"""Regression metric tests (MeanSquaredError, R2Score) vs the reference
oracle, via the shared MetricClassTester harness."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.ref_oracle import load_reference_metrics
from torcheval_tpu.metrics import MeanSquaredError, R2Score
from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
    assert_result_close,
)

REF_M, REF_F = load_reference_metrics()
RNG = np.random.default_rng(7)


def _ref_mse(inputs, targets, weights=None, **kwargs):
    ref = REF_M.MeanSquaredError(**kwargs)
    for i, (x, t) in enumerate(zip(inputs, targets)):
        sw = None if weights is None else torch.tensor(weights[i])
        ref.update(torch.tensor(x), torch.tensor(t), sample_weight=sw)
    return np.asarray(ref.compute())


class TestMeanSquaredError(MetricClassTester):
    def test_mse_1d(self):
        inputs = [RNG.uniform(size=(5,)).astype(np.float32) for _ in range(8)]
        targets = [RNG.uniform(size=(5,)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=MeanSquaredError(),
            state_names={"sum_squared_error", "sum_weight"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=_ref_mse(inputs, targets),
        )

    def test_mse_multioutput_raw_values(self):
        inputs = [RNG.uniform(size=(4, 3)).astype(np.float32) for _ in range(8)]
        targets = [RNG.uniform(size=(4, 3)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=MeanSquaredError(multioutput="raw_values"),
            state_names={"sum_squared_error", "sum_weight"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=_ref_mse(inputs, targets, multioutput="raw_values"),
        )

    def test_mse_sample_weight(self):
        inputs = [RNG.uniform(size=(6, 2)).astype(np.float32) for _ in range(8)]
        targets = [RNG.uniform(size=(6, 2)).astype(np.float32) for _ in range(8)]
        weights = [RNG.uniform(0.1, 1.0, size=(6,)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=MeanSquaredError(),
            state_names={"sum_squared_error", "sum_weight"},
            update_kwargs={
                "input": inputs,
                "target": targets,
                "sample_weight": [jnp.asarray(w) for w in weights],
            },
            compute_result=_ref_mse(inputs, targets, weights),
        )

    def test_mse_functional_vs_reference(self):
        x = RNG.uniform(size=(32, 4)).astype(np.float32)
        t = RNG.uniform(size=(32, 4)).astype(np.float32)
        w = RNG.uniform(0.1, 1.0, size=(32,)).astype(np.float32)
        for kwargs in (
            {},
            {"multioutput": "raw_values"},
        ):
            assert_result_close(
                F.mean_squared_error(jnp.asarray(x), jnp.asarray(t), **kwargs),
                np.asarray(REF_F.mean_squared_error(torch.tensor(x), torch.tensor(t), **kwargs)),
            )
        assert_result_close(
            F.mean_squared_error(
                jnp.asarray(x), jnp.asarray(t), sample_weight=jnp.asarray(w)
            ),
            np.asarray(
                REF_F.mean_squared_error(
                    torch.tensor(x), torch.tensor(t), sample_weight=torch.tensor(w)
                )
            ),
        )

    def test_mse_invalid_inputs(self):
        with pytest.raises(ValueError, match="multioutput"):
            F.mean_squared_error(jnp.ones(3), jnp.ones(3), multioutput="bogus")
        with pytest.raises(ValueError, match="same size"):
            F.mean_squared_error(jnp.ones(3), jnp.ones(4))
        with pytest.raises(ValueError, match="1D or 2D"):
            F.mean_squared_error(jnp.ones((2, 2, 2)), jnp.ones((2, 2, 2)))
        with pytest.raises(ValueError, match="sample_weight"):
            F.mean_squared_error(
                jnp.ones(3), jnp.ones(3), sample_weight=jnp.ones(4)
            )


def _ref_r2(inputs, targets, **kwargs):
    ref = REF_M.R2Score(**kwargs)
    for x, t in zip(inputs, targets):
        ref.update(torch.tensor(x), torch.tensor(t))
    return np.asarray(ref.compute())


class TestR2Score(MetricClassTester):
    def test_r2_1d(self):
        inputs = [RNG.uniform(size=(5,)).astype(np.float32) for _ in range(8)]
        targets = [RNG.uniform(size=(5,)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=R2Score(),
            state_names={
                "sum_squared_obs",
                "sum_obs",
                "sum_squared_residual",
                "num_obs",
            },
            update_kwargs={"input": inputs, "target": targets},
            compute_result=_ref_r2(inputs, targets),
            atol=1e-4,
            rtol=1e-4,
        )

    @pytest.mark.parametrize(
        "multioutput", ["uniform_average", "raw_values", "variance_weighted"]
    )
    def test_r2_multioutput(self, multioutput):
        inputs = [RNG.uniform(size=(4, 3)).astype(np.float32) for _ in range(8)]
        targets = [RNG.uniform(size=(4, 3)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=R2Score(multioutput=multioutput),
            state_names={
                "sum_squared_obs",
                "sum_obs",
                "sum_squared_residual",
                "num_obs",
            },
            update_kwargs={"input": inputs, "target": targets},
            compute_result=_ref_r2(inputs, targets, multioutput=multioutput),
            atol=1e-4,
            rtol=1e-4,
        )

    def test_r2_adjusted(self):
        x = RNG.uniform(size=(16,)).astype(np.float32)
        t = RNG.uniform(size=(16,)).astype(np.float32)
        assert_result_close(
            F.r2_score(jnp.asarray(x), jnp.asarray(t), num_regressors=3),
            np.asarray(
                REF_F.r2_score(torch.tensor(x), torch.tensor(t), num_regressors=3)
            ),
            atol=1e-4,
            rtol=1e-4,
        )

    def test_r2_invalid_inputs(self):
        with pytest.raises(ValueError, match="multioutput"):
            F.r2_score(jnp.ones(3), jnp.ones(3), multioutput="bogus")
        with pytest.raises(ValueError, match="num_regressors"):
            F.r2_score(jnp.ones(3), jnp.ones(3), num_regressors=-1)
        with pytest.raises(ValueError, match="no enough data"):
            F.r2_score(jnp.ones(1), jnp.ones(1))
        with pytest.raises(ValueError, match="smaller than n_samples"):
            F.r2_score(jnp.ones(4), jnp.ones(4), num_regressors=3)
        with pytest.raises(ValueError, match="same size"):
            F.r2_score(jnp.ones(3), jnp.ones(4))
