"""FAST-tier multi-process sync smoke (VERDICT r4 weak #3).

The full spawned-process archetype matrix is slow-marked; without this
smoke a default ``pytest -q`` run would never cross a real OS process
boundary and could green-light a broken ``MultiHostGroup``. One nproc=2
spawn, two metrics: counter state (batched psum-style sum) and buffered
state (padded ragged gather), compared against in-process ``merge_state``
oracles. Budget: well under 20 s.
"""

from __future__ import annotations

import os

import numpy as np

from tests.metrics.test_multihost import parse_result_lines

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER = os.path.join(REPO, "tests", "metrics", "_multihost_smoke_worker.py")


def _oracle():
    """Replay both ranks' updates into single-process metrics."""
    from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy

    acc = MulticlassAccuracy()
    auroc = BinaryAUROC()
    for rank in range(2):
        rng = np.random.default_rng(100 + rank)
        n = 8 + 4 * rank
        acc.update(rng.uniform(size=(n, 4)).astype(np.float32),
                   rng.integers(0, 4, size=n))
        auroc.update(rng.uniform(size=n).astype(np.float32),
                     (rng.random(n) < 0.5).astype(np.float32))
    return float(acc.compute()), float(auroc.compute())


def test_two_process_sync_smoke():
    from torcheval_tpu.launcher import launch

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    outputs = launch(WORKER, nproc=2, timeout=120.0, env=env)
    results = parse_result_lines(outputs)

    exp_acc, exp_auroc = _oracle()
    for rank, r in enumerate(results):
        assert r["nproc"] == 2 and r["rank"] == rank
        np.testing.assert_allclose(r["accuracy"], exp_acc, rtol=1e-6)
        np.testing.assert_allclose(r["auroc"], exp_auroc, rtol=1e-6)
