"""Random dataset generators for tests and examples.

Parity: reference torcheval/utils/random_data.py:12-161
(`get_rand_data_binary/multiclass/multilabel/binned_binary`), re-based on
``jax.random`` keys instead of the torch global RNG.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def get_rand_data_binary(
    num_updates: int,
    num_tasks: int,
    batch_size: int,
    *,
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Random (input, target) pairs for binary metrics.

    Returns input scores in [0, 1) and integer 0/1 targets, each shaped
    (num_updates, num_tasks, batch_size) — squeezed to
    (num_updates, batch_size) when num_tasks == 1.
    """
    key = key if key is not None else jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    shape = (num_updates, num_tasks, batch_size)
    input = jax.random.uniform(k1, shape)
    targets = jax.random.randint(k2, shape, 0, 2)
    if num_tasks == 1:
        input, targets = input.squeeze(1), targets.squeeze(1)
    return input, targets


def get_rand_data_multiclass(
    num_updates: int,
    num_classes: int,
    batch_size: int,
    *,
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Random (input, target) for multiclass metrics: scores shaped
    (num_updates, batch_size, num_classes), integer class targets."""
    key = key if key is not None else jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    input = jax.random.uniform(k1, (num_updates, batch_size, num_classes))
    targets = jax.random.randint(k2, (num_updates, batch_size), 0, num_classes)
    return input, targets


def get_rand_data_multilabel(
    num_updates: int,
    num_labels: int,
    batch_size: int,
    *,
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Random (input, target) for multilabel metrics: scores and 0/1 targets
    shaped (num_updates, batch_size, num_labels)."""
    key = key if key is not None else jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    shape = (num_updates, batch_size, num_labels)
    input = jax.random.uniform(k1, shape)
    targets = jax.random.randint(k2, shape, 0, 2)
    return input, targets


def get_rand_data_binned_binary(
    num_updates: int,
    num_tasks: int,
    batch_size: int,
    num_bins: int,
    *,
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Random binary data plus a sorted threshold tensor in [0, 1]."""
    key = key if key is not None else jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    input, targets = get_rand_data_binary(
        num_updates, num_tasks, batch_size, key=k1
    )
    thresholds = jnp.sort(jax.random.uniform(k2, (num_bins,)))
    thresholds = thresholds.at[0].set(0.0).at[-1].set(1.0)
    return input, targets, thresholds
