"""Lockstep checker acceptance (ISSUE 7): statically flags a deliberately
rank-divergent collective program, passes the library's existing
sharded/subgroup sync programs, and diffs eager synclib call plans.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 jax keeps it under experimental
    from jax.experimental.shard_map import shard_map

from torcheval_tpu import metrics as M
from torcheval_tpu.analysis import (
    check_eager_lockstep,
    check_program_lockstep,
    collective_plan,
    eager_sync_plan,
    verify_rank_lockstep,
)
from torcheval_tpu.metrics.metric import MergeKind
from torcheval_tpu.metrics.sharded import sync_states_in_jit

RNG = np.random.default_rng(11)


def _rules(report):
    return sorted({f.rule for f in report.findings if not f.suppressed})


@pytest.fixture(scope="module")
def mesh():
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    return Mesh(np.array(cpus[:8]), ("dp",))


@pytest.fixture(scope="module")
def mesh2d():
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    return Mesh(np.array(cpus[:8]).reshape(4, 2), ("dp", "sp"))


X8 = jax.ShapeDtypeStruct((8,), jnp.float32)


# --------------------------------------------------- rank-divergent programs


def test_rank_divergent_program_is_flagged(mesh):
    """The acceptance fixture: a leader-only extra reduction. Every rank
    but 0 would block forever in the leader's pmax — caught statically,
    with the offending op's provenance in the finding."""

    def build(rank):
        @partial(shard_map, mesh=mesh, in_specs=(P("dp"),), out_specs=P())
        def step(xs):
            total = jax.lax.psum(xs.sum(), "dp")
            if rank == 0:  # deliberate: rank-dependent program structure
                total = total + jax.lax.pmax(xs.max(), "dp")
            return total

        return step

    report = verify_rank_lockstep(build, range(4), X8, name="leader-extra")
    assert not report.ok
    findings = [
        f for f in report.findings if f.rule == "rank-divergent-collective"
    ]
    assert len(findings) == 3  # ranks 1..3 each diverge from rank 0
    assert "deadlock" in findings[0].message
    assert "pmax" in findings[0].message


def test_rank_uniform_spmd_program_passes(mesh):
    def build(rank):  # rank ignored: true SPMD
        @partial(shard_map, mesh=mesh, in_specs=(P("dp"),), out_specs=P())
        def step(xs):
            return jax.lax.psum(xs.sum(), "dp")

        return step

    report = verify_rank_lockstep(build, range(8), X8)
    assert report.ok, report.format_text()
    assert report.checked == 8


def test_reordered_collectives_are_divergence(mesh):
    """Equal counts, different order — the case a bare census misses."""

    def build(rank):
        @partial(shard_map, mesh=mesh, in_specs=(P("dp"),), out_specs=P())
        def step(xs):
            if rank % 2 == 0:
                return jax.lax.psum(xs.sum(), "dp") + jax.lax.pmax(
                    xs.max(), "dp"
                )
            return jax.lax.pmax(xs.max(), "dp") + jax.lax.psum(
                xs.sum(), "dp"
            )

        return step

    report = verify_rank_lockstep(build, range(2), X8)
    assert "rank-divergent-collective" in _rules(report)


# ------------------------------------------------ structural hazards (1 prog)


def test_branch_dependent_collective_is_flagged(mesh):
    @partial(shard_map, mesh=mesh, in_specs=(P("dp"), P()), out_specs=P("dp"))
    def step(xs, flag):
        return jax.lax.cond(
            flag[0] > 0,
            lambda v: jax.lax.psum(v, "dp"),
            lambda v: v * 2.0,
            xs,
        )

    report = check_program_lockstep(
        step, X8, jax.ShapeDtypeStruct((1,), jnp.float32)
    )
    assert _rules(report) == ["branch-dependent-collective"]
    assert "deadlock" in report.findings[0].message


def test_symmetric_branches_pass(mesh):
    @partial(shard_map, mesh=mesh, in_specs=(P("dp"), P()), out_specs=P())
    def step(xs, flag):
        return jax.lax.cond(
            flag[0] > 0,
            lambda v: jax.lax.psum(v.sum(), "dp"),
            lambda v: jax.lax.psum(v.max(), "dp") * 0.5,
            xs,
        )

    report = check_program_lockstep(
        step, X8, jax.ShapeDtypeStruct((1,), jnp.float32)
    )
    # both arms psum over 'dp': the ranks rendezvous either way
    assert report.ok, report.format_text()


def test_collective_in_while_is_a_warning(mesh):
    @partial(
        shard_map, mesh=mesh, in_specs=(P("dp"),), out_specs=P(),
        check_rep=False,  # jax 0.4.37 has no while replication rule
    )
    def step(xs):
        def body(carry):
            i, acc = carry
            return i + 1, acc + jax.lax.psum(xs.sum(), "dp")

        return jax.lax.while_loop(lambda c: c[0] < 3, body, (0, jnp.zeros(())))[1]

    report = check_program_lockstep(step, X8)
    assert report.ok  # warning-severity: rank-uniform trip counts are fine
    assert _rules(report) == ["collective-in-loop"]
    assert all(f.severity == "warning" for f in report.findings)


# ------------------------------------------- existing library sync programs


def test_library_sync_programs_pass(mesh):
    """sync_states_in_jit over every merge kind is lockstep-clean, and
    its plan is the declared one: one gather per EXTEND state, fused
    reductions per kind."""

    @partial(shard_map, mesh=mesh, in_specs=(P("dp"),), out_specs=P())
    def sync(xs):
        return sync_states_in_jit(
            {"a": xs.sum(), "b": xs.max(), "c": xs.min(), "buf": xs},
            "dp",
            {
                "a": MergeKind.SUM,
                "b": MergeKind.MAX,
                "c": MergeKind.MIN,
                "buf": MergeKind.EXTEND,
            },
        )

    report = check_program_lockstep(sync, X8)
    assert report.ok, report.format_text()
    plan = collective_plan(sync, X8)
    assert sorted(op.name for op in plan) == [
        "all_gather",
        "pmax",
        "pmin",
        "psum2",
    ]
    assert all(op.axes == ("dp",) for op in plan)
    # SPMD: the same builder at any rank yields the identical plan
    assert verify_rank_lockstep(lambda r: sync, range(8), X8).ok


def test_composed_axes_sync_is_lockstep_clean(mesh2d):
    """The subgroup-scoped hierarchical form from PR 3: reductions and
    gathers spanning a composed ("dp", "sp") axis tuple."""

    @partial(
        shard_map, mesh=mesh2d, in_specs=(P(("dp", "sp")),), out_specs=P()
    )
    def sync(xs):
        return sync_states_in_jit(
            {"n": xs.sum(), "buf": xs},
            ("dp", "sp"),
            {"n": MergeKind.SUM, "buf": MergeKind.EXTEND},
        )

    report = check_program_lockstep(sync, X8)
    assert report.ok, report.format_text()
    plan = collective_plan(sync, X8)
    assert all(op.axes == ("dp", "sp") for op in plan)
    assert verify_rank_lockstep(lambda r: sync, range(8), X8).ok


# ------------------------------------------------------- eager call plans


def _collection():
    coll = {
        "acc": M.MulticlassAccuracy(),
        "mse": M.MeanSquaredError(),
        "auroc": M.BinaryAUROC(),
    }
    x2 = jnp.asarray(RNG.random((16, 5)).astype(np.float32))
    t1 = jnp.asarray(RNG.integers(0, 5, 16))
    xb = jnp.asarray(RNG.random(16).astype(np.float32))
    tb = jnp.asarray(RNG.integers(0, 2, 16).astype(np.float32))
    coll["acc"].update(x2, t1)
    coll["mse"].update(xb, tb)
    coll["auroc"].update(xb, tb)
    return coll


def test_identical_collections_have_lockstep_plans():
    coll = _collection()
    plans = {
        rank: eager_sync_plan(coll, world_size=4, rank=rank)
        for rank in range(4)
    }
    report = check_eager_lockstep(plans)
    assert report.ok, report.format_text()
    assert report.checked == 4
    # the plan is the pinned constant-collective-count protocol: one
    # metadata exchange + one payload gather, any number of metrics
    assert len(plans[0]) == 2
    assert plans[0][0].startswith("allgather_object[")
    assert plans[0][1] == "allgather_array"


def test_mismatched_collections_diverge():
    """One rank constructed an extra metric (the classic init-order bug):
    its metadata framing differs — flagged as would-deadlock before any
    collective is issued."""
    coll = _collection()
    partial_coll = {k: v for k, v in coll.items() if k != "auroc"}
    report = check_eager_lockstep(
        {
            0: eager_sync_plan(coll, world_size=2, rank=0),
            1: eager_sync_plan(partial_coll, world_size=2, rank=1),
        }
    )
    assert _rules(report) == ["eager-plan-divergence"]
    assert "deadlock" in report.findings[0].message


def test_fill_level_does_not_fake_divergence():
    """Rank B buffered fewer samples than rank A — the real protocol pads
    payloads to the global max, so the plans must still match (the check
    is structural, not byte-count)."""
    a = _collection()
    b = _collection()
    xb = jnp.asarray(RNG.random(64).astype(np.float32))
    tb = jnp.asarray(RNG.integers(0, 2, 64).astype(np.float32))
    b["auroc"].update(xb, tb)  # different fill, same structure
    report = check_eager_lockstep(
        {
            0: eager_sync_plan(a, world_size=2, rank=0),
            1: eager_sync_plan(b, world_size=2, rank=1),
        }
    )
    assert report.ok, report.format_text()


def test_hand_recorded_plans_ignore_local_payload_sizes():
    """PlanRecordingGroup annotates array gathers with the LOCAL byte
    count; the padded protocol makes fill level rank-local, so
    check_eager_lockstep strips the sizes before diffing (review
    finding: ranks differing only in fill read as would-deadlock). A
    genuine op-kind mismatch must still fire."""
    from torcheval_tpu.analysis import PlanRecordingGroup

    g0 = PlanRecordingGroup(world_size=2, rank=0)
    g1 = PlanRecordingGroup(world_size=2, rank=1)
    for group, n in ((g0, 10), (g1, 20)):
        group.allgather_object({"m": ["s"]})
        group.allgather_array(np.zeros(n, np.float32))
    assert g0.calls != g1.calls  # raw records keep the forensic sizes
    assert check_eager_lockstep({0: g0.calls, 1: g1.calls}).ok

    g1.allgather_object({"m": ["s"]})  # extra op: genuine divergence
    report = check_eager_lockstep({0: g0.calls, 1: g1.calls})
    assert _rules(report) == ["eager-plan-divergence"]


def test_subgroup_scoped_plans_are_lockstep():
    """Member subsets sync over subgroup-relative worlds; the plan for a
    given collection is world-size-independent, so subgroup members stay
    in lockstep with each other by construction — pinned here."""
    coll = _collection()
    whole = eager_sync_plan(coll, world_size=4, rank=0)
    sub = eager_sync_plan(coll, world_size=2, rank=1)
    assert whole == sub
    assert check_eager_lockstep({0: whole, 2: sub, 3: sub}).ok


def test_eager_plan_does_not_consume_the_metrics():
    coll = _collection()
    before = float(coll["auroc"].compute())
    eager_sync_plan(coll, world_size=2)
    assert float(coll["auroc"].compute()) == before


def test_all_empty_collection_plans_stay_uniform():
    """Buffered metrics synced before any update pack zero bytes on
    every rank; the real protocol then skips the payload gather by
    GLOBAL agreement. The static plan deliberately over-approximates
    (lists the gather) — what matters is that it does so uniformly:
    no false divergence, and the dry run still completes."""
    empty = {"auroc": M.BinaryAUROC()}
    plans = {
        rank: eager_sync_plan(empty, world_size=2, rank=rank)
        for rank in range(2)
    }
    assert plans[0] == plans[1]
    assert check_eager_lockstep(plans).ok
