from torcheval_tpu.utils.test_utils.dummy_metric import (
    DummySumDictStateMetric,
    DummySumListStateMetric,
    DummySumMetric,
)
from torcheval_tpu.utils.test_utils.fault_injection import (
    ChaosLinkTransport,
    FaultInjectionGroup,
    FaultSpec,
    InjectedCrash,
    LinkFaultSpec,
    SnapshotCrashPlan,
    corrupt_manifest_digest,
    corrupt_shard,
    truncate_shard,
)
from torcheval_tpu.utils.test_utils.kill_schedule import (
    KILL_POINTS,
    KillGroup,
    KillSchedule,
    KillSpec,
)
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
)
from torcheval_tpu.utils.test_utils.overload import (
    OverloadBatch,
    OverloadPhase,
    OverloadSchedule,
)
from torcheval_tpu.utils.test_utils.schedule import (
    DeadlockError,
    DeterministicScheduler,
    ScheduleResult,
)
from torcheval_tpu.utils.test_utils.thread_world import (
    ThreadRankGroup,
    ThreadWorld,
)

__all__ = [
    "ChaosLinkTransport",
    "DeadlockError",
    "DeterministicScheduler",
    "ScheduleResult",
    "DummySumMetric",
    "DummySumListStateMetric",
    "DummySumDictStateMetric",
    "FaultInjectionGroup",
    "FaultSpec",
    "InjectedCrash",
    "KILL_POINTS",
    "KillGroup",
    "KillSchedule",
    "KillSpec",
    "LinkFaultSpec",
    "SnapshotCrashPlan",
    "corrupt_manifest_digest",
    "corrupt_shard",
    "truncate_shard",
    "MetricClassTester",
    "OverloadBatch",
    "OverloadPhase",
    "OverloadSchedule",
    "ThreadRankGroup",
    "ThreadWorld",
]
