"""Recall class metrics.

Parity: reference torcheval/metrics/classification/recall.py
(BinaryRecall :26, MulticlassRecall :117) — O(1) counter states.
"""

from __future__ import annotations

from typing import Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.recall import (
    _binary_recall_update_input_check,
    _binary_recall_update_jit,
    _binary_recall_update_masked,
    _recall_compute,
    _recall_param_check,
    _recall_update_input_check,
    _recall_update_jit,
    _recall_update_masked,
)
from torcheval_tpu.metrics.functional.tensor_utils import nan_safe_divide
from torcheval_tpu.metrics.metric import MergeKind, Metric, UpdatePlan

TRecall = TypeVar("TRecall", bound="MulticlassRecall")


class MulticlassRecall(Metric[jax.Array]):
    """Recall for multiclass classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassRecall
        >>> metric = MulticlassRecall()
        >>> metric.update(jnp.array([0, 2, 1, 3]), jnp.array([0, 1, 2, 3]))
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _recall_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        shape = () if average == "micro" else (num_classes,)
        self._add_state("num_tp", jnp.zeros(shape), merge=MergeKind.SUM)
        self._add_state("num_labels", jnp.zeros(shape), merge=MergeKind.SUM)
        self._add_state("num_predictions", jnp.zeros(shape), merge=MergeKind.SUM)

    # plans carry mask-aware kernel twins (metrics/_bucket.py)
    _bucketed_update = True

    def _update_plan(self: TRecall, input, target):
        input, target = self._input(input), self._input(target)
        _recall_update_input_check(input, target, self.num_classes)
        # one fused dispatch: kernel + the three counter adds
        return UpdatePlan(
            _recall_update_jit,
            ("num_tp", "num_labels", "num_predictions"),
            (input, target),
            (self.num_classes, self.average),
            masked_kernel=_recall_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self: TRecall, input, target) -> TRecall:
        return self._apply_update_plan(self._update_plan(input, target))

    def compute(self) -> jax.Array:
        return _recall_compute(
            self.num_tp, self.num_labels, self.num_predictions, self.average
        )


class BinaryRecall(Metric[jax.Array]):
    """Binary recall with thresholded score inputs.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryRecall
        >>> metric = BinaryRecall()
        >>> metric.update(jnp.array([0.9, 0.2, 0.6, 0.1]), jnp.array([1, 0, 1, 1]))
        >>> metric.compute()
        Array(0.6667, dtype=float32)
    """

    def __init__(self, *, threshold: float = 0.5, device=None) -> None:
        super().__init__(device=device)
        self.threshold = threshold
        self._add_state("num_tp", jnp.zeros(()), merge=MergeKind.SUM)
        self._add_state("num_true_labels", jnp.zeros(()), merge=MergeKind.SUM)

    _bucketed_update = True

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _binary_recall_update_input_check(input, target)
        return UpdatePlan(
            _binary_recall_update_jit,
            ("num_tp", "num_true_labels"),
            (input, target),
            (float(self.threshold),),
            masked_kernel=_binary_recall_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self, input, target) -> "BinaryRecall":
        return self._apply_update_plan(self._update_plan(input, target))

    def compute(self) -> jax.Array:
        return jnp.nan_to_num(
            nan_safe_divide(self.num_tp, self.num_true_labels)
        )
