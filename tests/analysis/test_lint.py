"""AST-lint rule registry: every rule FIRES on a seeded violation and
passes CLEAN over the shipped library (ISSUE 7 acceptance) — plus the
suppression grammar, the scope model, the CLI, and the obs bridge.

Stdlib-only on the library side: none of the lint tests import jax.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import torcheval_tpu
from torcheval_tpu.analysis import RULES, lint_file, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE_DIR = os.path.dirname(os.path.abspath(torcheval_tpu.__file__))


def _lint_source(tmp_path, source, name="fixture.py", rules=None):
    path = tmp_path / name
    path.write_text(source)
    return lint_file(str(path), rules=rules)


def _active_rules(report):
    return sorted({f.rule for f in report.active})


# ------------------------------------------------- seeded-violation fixtures

SEEDED = {
    "ffi-import": "import jax.ffi\n",
    "env-truthy": (
        "import os\n"
        'flag = os.environ.get("X", "").lower() in ("1", "true", "yes")\n'
    ),
    "host-sync": (
        "# tev: scope=jit\n"
        "import numpy as np\n"
        "def f(arr):\n"
        "    return np.asarray(arr) + arr.item()\n"
    ),
    "time-in-jit": (
        "# tev: scope=jit\n"
        "import time\n"
        "def kernel(x):\n"
        "    return x * time.time()\n"
    ),
    "shard-map-import": "from jax import shard_map\n",
    "bare-lock": (
        "import threading\n"
        "_HELPER_LOCK = threading.Lock()\n"
    ),
}


@pytest.mark.parametrize("rule", sorted(SEEDED))
def test_rule_fires_on_seeded_violation(rule, tmp_path):
    report = _lint_source(tmp_path, SEEDED[rule])
    assert rule in _active_rules(report), (
        f"rule {rule} did not fire on its seeded violation:\n"
        + report.format_text()
    )
    assert not report.ok


def test_every_registered_rule_has_a_seeded_fixture():
    """New rules must land with a firing fixture (the acceptance bullet
    is per-rule, so this meta-test keeps the table honest)."""
    assert set(SEEDED) == set(RULES)


@pytest.mark.parametrize(
    "source",
    [
        "from jax.extend import ffi\n",
        "from jax.extend.ffi import ffi_call\n",
        "import jax\nx = jax.ffi.register_ffi_target\n",
        "import jax\nx = jax.extend.ffi.ffi_call\n",
    ],
)
def test_ffi_import_spellings(source, tmp_path):
    assert "ffi-import" in _active_rules(_lint_source(tmp_path, source))


def test_ffi_shim_itself_is_exempt(tmp_path):
    (tmp_path / "torcheval_tpu").mkdir()
    path = tmp_path / "torcheval_tpu" / "_ffi.py"
    path.write_text("from jax.extend import ffi\n")
    assert lint_file(str(path)).ok


def test_host_sync_needs_jit_scope(tmp_path):
    """The scope model: the same idiom is clean in a host-side module and
    a violation under `# tev: scope=jit` (or a jit-reachable path)."""
    body = "import numpy as np\ndef f(a):\n    return np.asarray(a)\n"
    assert _lint_source(tmp_path, body).ok
    assert not _lint_source(tmp_path, "# tev: scope=jit\n" + body).ok
    # ...and scope=host overrides a jit-reachable path classification
    (tmp_path / "torcheval_tpu" / "ops").mkdir(parents=True)
    forced = tmp_path / "torcheval_tpu" / "ops" / "thing.py"
    forced.write_text("# tev: scope=host\n" + body)
    assert lint_file(str(forced)).ok
    unforced = tmp_path / "torcheval_tpu" / "ops" / "other.py"
    unforced.write_text(body)
    assert not lint_file(str(unforced)).ok


def test_guarded_shard_map_import_is_clean(tmp_path):
    report = _lint_source(
        tmp_path,
        "try:\n"
        "    from jax import shard_map\n"
        "except ImportError:\n"
        "    from jax.experimental.shard_map import shard_map\n",
    )
    assert report.ok, report.format_text()


def test_bool_spellings_mirror_config():
    """lint.py keeps a literal copy of the accepted boolean spellings (it
    must stay importable without the package root's jax deps on some
    paths); this pins the mirror to config's source of truth."""
    from torcheval_tpu import config
    from torcheval_tpu.analysis import lint

    assert lint._BOOL_SPELLINGS == frozenset(config._TRUTHY) | frozenset(
        config._FALSY
    )


def test_env_truthy_rule_ignores_non_boolean_tuples(tmp_path):
    report = _lint_source(
        tmp_path, 'x = mode in ("warn", "raise", "off")\n'
    )
    assert report.ok, report.format_text()


# ------------------------------------------------------------- suppressions


def test_suppression_with_reason_is_honored_and_auditable(tmp_path):
    report = _lint_source(
        tmp_path,
        "# tev: scope=jit\n"
        "import numpy as np\n"
        "x = np.asarray([1])  # tev: disable=host-sync -- fixture reason\n",
    )
    assert report.ok
    [finding] = report.findings
    assert finding.suppressed and finding.suppress_reason == "fixture reason"
    # suppressed findings stay in the JSON report, flagged
    payload = json.loads(report.to_json())
    assert payload["counts"]["suppressed"] == 1
    assert payload["findings"][0]["suppressed"] is True


def test_reasonless_suppression_is_itself_a_finding(tmp_path):
    report = _lint_source(
        tmp_path,
        "# tev: scope=jit\n"
        "import numpy as np\n"
        "x = np.asarray([1])  # tev: disable=host-sync\n",
    )
    assert not report.ok
    assert "bad-suppression" in _active_rules(report)
    assert "host-sync" in _active_rules(report)  # and does NOT suppress


def test_suppression_naming_unknown_rule_is_flagged(tmp_path):
    report = _lint_source(
        tmp_path, "x = 1  # tev: disable=no-such-rule -- because\n"
    )
    assert "bad-suppression" in _active_rules(report)


# --------------------------------------------------- clean run + CLI + obs


def test_shipped_library_and_examples_are_clean():
    """The acceptance run: zero unsuppressed errors over everything we
    ship, and every suppression carries its audit reason."""
    report = lint_paths(
        [
            PACKAGE_DIR,
            os.path.join(REPO, "examples"),
            os.path.join(REPO, "bench.py"),
            os.path.join(REPO, "scripts"),
        ]
    )
    assert report.checked > 100  # the walk actually covered the tree
    assert report.ok, "\n" + report.format_text(include_suppressed=False)
    for finding in report.findings:
        if finding.suppressed:
            assert finding.suppress_reason


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "torcheval_tpu.analysis", *argv],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )


def test_cli_json_report_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(SEEDED["ffi-import"])
    proc = _run_cli(str(bad), "--report", "json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is False
    assert payload["findings"][0]["rule"] == "ffi-import"
    assert payload["schema_version"] == 1

    out = tmp_path / "report.json"
    clean = _run_cli(PACKAGE_DIR, "--report", "json", "--output", str(out))
    assert clean.returncode == 0, clean.stdout[-2000:] + clean.stderr[-2000:]
    assert json.loads(out.read_text())["ok"] is True


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in RULES:
        assert rule in proc.stdout


def test_cli_rule_selection(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(SEEDED["ffi-import"] + SEEDED["shard-map-import"])
    only = _run_cli(str(bad), "--rules", "shard-map-import", "--report", "json")
    payload = json.loads(only.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"shard-map-import"}


def test_findings_bridge_to_obs_events(tmp_path):
    """Active findings mirror into the observability recorder as
    AnalysisEvents while it is on (CI forensics), and a disabled
    recorder drops them (the off contract)."""
    from torcheval_tpu import obs

    seeded = tmp_path / "seeded.py"
    seeded.write_text(SEEDED["ffi-import"])
    rec = obs.recorder()
    prev = rec.enabled
    rec.reset()
    rec.enable()
    try:
        lint_paths([str(seeded)])  # the recording entry point
        events = [e for e in rec.log.tail() if e.kind == "analysis"]
        assert events and events[-1].rule == "ffi-import"
        assert events[-1].tool == "lint"
        assert events[-1].path.endswith("seeded.py")
    finally:
        if not prev:
            rec.disable()
    rec.reset()
    lint_paths([str(seeded)])
    assert not [e for e in rec.log.tail() if e.kind == "analysis"]


def test_missing_path_is_a_loud_error(tmp_path):
    """A mistyped/renamed path must fail the gate, never lint nothing
    and report OK (review finding: the CI job would go permanently
    green)."""
    report = lint_paths([str(tmp_path / "no_such_dir")])
    assert not report.ok
    assert "missing-path" in {f.rule for f in report.active}
    # CLI twin: exit code is a usage error, not a green report
    proc = _run_cli(str(tmp_path / "no_such_dir"))
    assert proc.returncode == 2, (proc.returncode, proc.stdout, proc.stderr)


def test_explicit_non_py_file_is_a_loud_error(tmp_path):
    """An explicitly-named existing file the walker would skip (e.g. a
    .sh passed instead of its directory) must fail the gate, not read
    as linted (review finding: checked>0 from a sibling .py arg kept
    the zero-checked guard from tripping)."""
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    script = tmp_path / "tool.sh"
    script.write_text("echo hi\n")
    report = lint_paths([str(good), str(script)])
    assert not report.ok
    assert "unlinted-path" in _active_rules(report)


def test_host_sync_device_get_requires_jax_base_name(tmp_path):
    """`store.device_get(key)` on a non-jax object is not a host sync;
    `jax.device_get(x)` is (review finding: the rule fired on any
    attribute spelled device_get)."""
    clean = _lint_source(
        tmp_path,
        "# tev: scope=jit\n"
        "def f(store, key):\n"
        "    return store.device_get(key)\n",
    )
    assert "host-sync" not in _active_rules(clean), clean.format_text()
    seeded = _lint_source(
        tmp_path,
        "# tev: scope=jit\n"
        "import jax\n"
        "def f(x):\n"
        "    return jax.device_get(x)\n",
        name="seeded.py",
    )
    assert "host-sync" in _active_rules(seeded)


def test_cli_rejects_unknown_rules_and_tolerates_spaces(tmp_path):
    bad = tmp_path / "f.py"
    bad.write_text(SEEDED["ffi-import"])
    typo = _run_cli(str(bad), "--rules", "no-such-rule")
    assert typo.returncode == 2
    assert "unknown rule" in typo.stderr
    spaced = _run_cli(str(bad), "--rules", "ffi-import, shard-map-import")
    assert spaced.returncode == 1  # ran, found the seeded violation
    assert "KeyError" not in spaced.stderr


def test_cli_refuses_to_check_nothing():
    """--no-lint without --programs disables both arms; that must be a
    usage error, never a green '0 checked -> OK' (review finding: the
    CI gate could pass while analyzing nothing)."""
    proc = _run_cli("--no-lint")
    assert proc.returncode == 2, (proc.returncode, proc.stdout, proc.stderr)
    assert "nothing was checked" in proc.stderr


def test_api_rejects_unknown_rule_ids(tmp_path):
    """lint_file/lint_paths are documented API: an unknown rule id must
    raise a named ValueError, not a bare KeyError (review finding) —
    and lint_paths rejects it even when no file matches."""
    f = tmp_path / "f.py"
    f.write_text("x = 1\n")
    with pytest.raises(ValueError, match="unknown rule"):
        lint_file(str(f), rules=["no-such-rule"])
    with pytest.raises(ValueError, match="no-such-rule"):
        lint_paths([str(tmp_path / "empty")], rules=["no-such-rule"])


def test_findings_record_to_obs_exactly_once():
    """Composite verifiers pass the same Finding objects through several
    set_last_report layers; each finding must land in the event log
    exactly once (review finding: double-mirrored forensics)."""
    from torcheval_tpu import obs
    from torcheval_tpu.analysis import Finding, Report, set_last_report

    rec = obs.recorder()
    prev = rec.enabled
    rec.reset()
    rec.enable()
    try:
        sub = Report(tool="lint")
        sub.findings.append(
            Finding(tool="lint", rule="ffi-import", path="x.py", message="m")
        )
        set_last_report(sub)
        parent = Report(tool="lint")
        parent.extend(sub)  # same Finding objects, new report
        set_last_report(parent)
        set_last_report(parent)  # and once more for good measure
        events = [e for e in rec.log.tail() if e.kind == "analysis"]
        assert len(events) == 1
    finally:
        if not prev:
            rec.disable()
