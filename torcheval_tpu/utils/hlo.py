"""Optimized-HLO inspection helpers.

Used by the sync-structure regression test and ``bench.py`` to prove the
north-star property (BASELINE.md): in-jit metric sync adds ZERO collectives
to a step, because XLA's all-reduce combiner merges the metric-state psum
into the step's existing reduction.
"""

from __future__ import annotations

# Synchronous opcodes and their async -start forms (TPU/GPU lowerings emit
# start/done pairs; counting -done too would double-count an op).
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "collective-permute",
    "all-to-all",
    "reduce-scatter",
)


def collective_count(compiled) -> int:
    """Number of collective ops in a ``jax.stages.Compiled``'s optimized HLO."""
    hlo = compiled.as_text()
    return sum(
        hlo.count(f"{op}(") + hlo.count(f"{op}-start(")
        for op in COLLECTIVE_OPS
    )
