"""FrechetInceptionDistance class metric.

Parity: reference torcheval/metrics/image/fid.py:53-284. Streaming
sufficient statistics (feature sum + uncentered covariance sum per
distribution), SUM-merged — distributed sync is a single psum of
O(feature_dim^2) state regardless of image count.

TPU-native differences from the reference:

- The Frechet term ``tr sqrt(S1 S2)`` is computed via the real-symmetric
  reformulation ``tr sqrt(sqrt(S1) S2 sqrt(S1))`` using two ``eigh`` calls,
  because the reference's complex ``torch.linalg.eigvals`` (fid.py:221) has
  no TPU lowering. For PSD covariance matrices the two are mathematically
  identical.
- The default feature extractor is the Flax InceptionV3 port
  (``torcheval_tpu.models.inception``) wrapped with the same bilinear
  299x299 resize as the reference's ``FIDInceptionV3`` (fid.py:45-50);
  pretrained torchvision weights are imported when available. Any callable
  ``images (N, 3, H, W) -> activations (N, feature_dim)`` is accepted in
  its place.
- Activation extraction + state accumulation is one jitted program; images
  arrive NCHW (reference layout) and are transposed to NHWC for TPU convs.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Iterable, Optional, TypeVar, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.config import debug_validation_enabled
from torcheval_tpu.metrics.metric import MergeKind, Metric

TFrechetInceptionDistance = TypeVar(
    "TFrechetInceptionDistance", bound="FrechetInceptionDistance"
)

FeatureExtractor = Callable[[jax.Array], jax.Array]


class FIDInceptionV3:
    """The Flax InceptionV3 port wrapped for FID: NCHW input, bilinear
    299x299 resize, 2048-d pooled features (reference fid.py:28-50)."""

    def __init__(self) -> None:
        from torcheval_tpu.models.inception import (
            InceptionV3,
            load_torchvision_inception_params,
        )

        try:
            self.variables = load_torchvision_inception_params()
        except ImportError as e:
            raise ImportError(
                "You must have torchvision installed to use FID with "
                "pretrained InceptionV3 weights; pass a custom `model` "
                "callable otherwise."
            ) from e
        self._module = InceptionV3()
        self._apply = jax.jit(
            lambda variables, x: self._module.apply(variables, x)
        )

    def __call__(self, images: jax.Array) -> jax.Array:
        x = jnp.transpose(images, (0, 2, 3, 1))  # NCHW -> NHWC for TPU convs
        # antialias=False matches the reference's F.interpolate(...,
        # mode='bilinear', align_corners=False), which does not antialias
        # when downscaling (jax.image.resize antialiases by default).
        x = jax.image.resize(
            x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear",
            antialias=False,
        )
        return self._apply(self.variables, x)

    def to(self, device: jax.Device) -> "FIDInceptionV3":
        self.variables = jax.device_put(self.variables, device)
        return self


@jax.jit
def _fid_accumulate(activations: jax.Array):
    return (
        jnp.sum(activations, axis=0),
        jnp.matmul(activations.T, activations),
        jnp.int32(activations.shape[0]),
    )


@jax.jit
def _frechet_distance(
    real_sum: jax.Array,
    real_cov_sum: jax.Array,
    num_real: jax.Array,
    fake_sum: jax.Array,
    fake_cov_sum: jax.Array,
    num_fake: jax.Array,
) -> jax.Array:
    num_real = num_real.astype(jnp.float32)
    num_fake = num_fake.astype(jnp.float32)
    real_mean = real_sum / num_real
    fake_mean = fake_sum / num_fake
    real_cov = (
        real_cov_sum - num_real * jnp.outer(real_mean, real_mean)
    ) / (num_real - 1)
    fake_cov = (
        fake_cov_sum - num_fake * jnp.outer(fake_mean, fake_mean)
    ) / (num_fake - 1)

    mean_diff_squared = jnp.sum(jnp.square(real_mean - fake_mean))
    trace_sum = jnp.trace(real_cov) + jnp.trace(fake_cov)

    # tr sqrt(S1 S2) == tr sqrt(sqrt(S1) S2 sqrt(S1)) for PSD S1, S2 —
    # all-real eigh path (TPU has no complex eigvals kernel).
    evals1, evecs1 = jnp.linalg.eigh(real_cov)
    sqrt_real = (evecs1 * jnp.sqrt(jnp.maximum(evals1, 0.0))) @ evecs1.T
    inner = sqrt_real @ fake_cov @ sqrt_real
    inner = (inner + inner.T) / 2  # symmetrize numerical noise
    inner_evals = jnp.linalg.eigvalsh(inner)
    sqrt_eigenvals_sum = jnp.sum(jnp.sqrt(jnp.maximum(inner_evals, 0.0)))

    return mean_diff_squared + trace_sum - 2 * sqrt_eigenvals_sum


class FrechetInceptionDistance(Metric[jax.Array]):
    """Frechet Inception Distance between real and generated image
    distributions (https://arxiv.org/pdf/1706.08500.pdf).

    Args:
        model: callable mapping images ``(N, 3, H, W)`` to activations
            ``(N, feature_dim)``. If ``None``, the Flax InceptionV3 port
            with torchvision pretrained weights is used.
        feature_dim: activation dimensionality (2048 for InceptionV3).

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import FrechetInceptionDistance
        >>> def extractor(images):  # (N, 3, H, W) -> (N, 4)
        ...     pooled = images.mean(axis=(2, 3))
        ...     spread = images.var(axis=(1, 2, 3))[:, None]
        ...     return jnp.concatenate([pooled, spread], axis=1)
        >>> metric = FrechetInceptionDistance(model=extractor, feature_dim=4)
        >>> real = jnp.stack([jnp.full((3, 4, 4), 0.1 * i) for i in range(1, 9)])
        >>> metric.update(real, is_real=True)
        >>> metric.update(real * 0.8, is_real=False)
        >>> metric.compute()
        Array(0.03144199, dtype=float32)
    """

    def __init__(
        self,
        model: Optional[FeatureExtractor] = None,
        feature_dim: int = 2048,
        device: Optional[jax.Device] = None,
    ) -> None:
        super().__init__(device=device)
        self._FID_parameter_check(model=model, feature_dim=feature_dim)
        if model is None:
            model = FIDInceptionV3()
        self.model = model
        if hasattr(self.model, "to"):
            self.model.to(self._device)

        self._add_state(
            "real_sum", jnp.zeros(feature_dim), merge=MergeKind.SUM
        )
        self._add_state(
            "real_cov_sum",
            jnp.zeros((feature_dim, feature_dim)),
            merge=MergeKind.SUM,
        )
        self._add_state(
            "fake_sum", jnp.zeros(feature_dim), merge=MergeKind.SUM
        )
        self._add_state(
            "fake_cov_sum",
            jnp.zeros((feature_dim, feature_dim)),
            merge=MergeKind.SUM,
        )
        self._add_state(
            "num_real_images", jnp.zeros((), dtype=jnp.int32),
            merge=MergeKind.SUM,
        )
        self._add_state(
            "num_fake_images", jnp.zeros((), dtype=jnp.int32),
            merge=MergeKind.SUM,
        )

    def update(
        self: TFrechetInceptionDistance, images, is_real: bool
    ) -> TFrechetInceptionDistance:
        """Accumulate a batch of real or generated images (N, 3, H, W)."""
        # dtype-preserving conversion FIRST so the float32 check below sees
        # the caller's dtype (uint8 images must fail, reference fid.py:266).
        images = self._input(images)
        self._FID_update_input_check(images=images, is_real=is_real)
        images = images.astype(jnp.float32)
        # one fused dispatch for the stats: sum/cov/count kernel + the
        # three counter adds (the model forward stays its own program)
        activations = self.model(images)
        names = (
            ("real_sum", "real_cov_sum", "num_real_images")
            if is_real
            else ("fake_sum", "fake_cov_sum", "num_fake_images")
        )
        return self._apply_update_plan(
            (_fid_accumulate, names, (activations,), ())
        )

    def compute(self) -> jax.Array:
        """FID on the accumulated statistics; 0.0 (with a warning) until at
        least one real and one fake image have been seen."""
        num_real = int(self.num_real_images)
        num_fake = int(self.num_fake_images)
        if num_real == 0 or num_fake == 0:
            warnings.warn(
                "Computing FID requires at least 1 real image and 1 fake "
                f"image, but currently running with {num_real} real images "
                f"and {num_fake} fake images. Returning 0.0",
                RuntimeWarning,
            )
            return jnp.zeros(())
        # The eigendecompositions run on host CPU: feature_dim^2 state is
        # tiny next to the accumulation traffic, compute() is the rare path,
        # and TPU eigh lowering is slow for these shapes (same division as
        # the reference, whose torch.linalg.eigvals is a host LAPACK call
        # on CPU tensors, fid.py:221).
        try:
            # local_devices, not devices: in a multi-process job the global
            # first CPU device belongs to rank 0 and is non-addressable
            # from every other rank
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # JAX_PLATFORMS excludes cpu
            cpu = self._device
        return _frechet_distance(
            jax.device_put(self.real_sum, cpu),
            jax.device_put(self.real_cov_sum, cpu),
            jax.device_put(self.num_real_images, cpu),
            jax.device_put(self.fake_sum, cpu),
            jax.device_put(self.fake_cov_sum, cpu),
            jax.device_put(self.num_fake_images, cpu),
        )

    def _FID_parameter_check(
        self, model: Optional[FeatureExtractor], feature_dim: int
    ) -> None:
        if feature_dim is None or feature_dim <= 0:
            raise RuntimeError("feature_dim has to be a positive integer")
        if model is None and feature_dim != 2048:
            raise RuntimeError(
                "When the default Inception v3 model is used, feature_dim "
                "needs to be set to 2048"
            )

    def _FID_update_input_check(self, images: jax.Array, is_real: bool) -> None:
        if images.ndim != 4:
            raise ValueError(
                f"Expected 4D tensor as input. But input has {images.ndim} "
                "dimenstions."
            )
        if images.shape[1] != 3:
            raise ValueError(
                f"Expected 3 channels as input. Got {images.shape[1]}."
            )
        if type(is_real) != bool:  # noqa: E721 — parity with reference
            raise ValueError(
                f"Expected 'real' to be of type bool but got {type(is_real)}.",
            )
        if isinstance(self.model, FIDInceptionV3):
            if images.dtype != jnp.float32:
                raise ValueError(
                    "When default inception-v3 model is used, images expected "
                    f"to be `float32`, but got {images.dtype}."
                )
            if debug_validation_enabled():
                # value range check forces a device sync; debug-mode only
                # (the reference does it eagerly, fid.py:271-274)
                if float(jnp.min(images)) < 0 or float(jnp.max(images)) > 1:
                    raise ValueError(
                        "When default inception-v3 model is used, images are "
                        "expected to be in the [0, 1] interval"
                    )

    def to(
        self: TFrechetInceptionDistance,
        device: Union[str, jax.Device],
        *args: Any,
        **kwargs: Any,
    ) -> TFrechetInceptionDistance:
        super().to(device, *args, **kwargs)
        if hasattr(self.model, "to"):
            self.model.to(self._device)
        return self
