"""Global configuration for torcheval_tpu.

The reference library performs eager, value-dependent input validation (e.g.
``torch.max(target)`` range checks, reference
torcheval/metrics/functional/classification/confusion_matrix.py:267-281).
On TPU, reading a value off the device forces a host<->device sync in the hot
``update()`` path, which would blow the <1% step-overhead budget. We therefore
split validation into two tiers:

- *shape/dtype checks*: free under JAX (shapes are static metadata) — always on.
- *value checks*: require device->host readback — gated behind
  ``debug_validation`` (env ``TORCHEVAL_TPU_DEBUG``), default off.

The second knob is *shape bucketing* (env ``TORCHEVAL_TPU_SHAPE_BUCKETING``,
default off): variable-batch eval loops retrace/recompile the fused update
program once per distinct input shape. With bucketing on, batch axes are
padded up to power-of-two buckets and a validity mask keeps padded rows out
of every state, so a whole ragged stream compiles O(log max_batch) programs
total (see ``torcheval_tpu/metrics/_bucket.py`` and
docs/variable-shape-eval.md).

The third knob family is *sync resilience* (docs/fault-tolerance.md):
``sync_timeout`` / ``sync_retries`` / ``sync_degradation`` / ``sync_quorum``
set the process-wide defaults for ``resilience.ResilientGroup``, and the
toolkit auto-wraps the default process group when any of them departs from
the all-ranks-alive default (so a dead host degrades a metrics sync instead
of hanging the pod). The fourth is ``validate_inputs`` (``off``/``warn``/
``raise``): a NaN/Inf finite-check at the ``Metric.update`` front door —
value-level, so it forces a device readback per update and defaults off,
same budget reasoning as ``debug_validation``. The fifth family is
*elastic evaluation* (docs/fault-tolerance.md, "Elastic evaluation"):
``snapshot_interval`` / ``snapshot_retention`` default the
``elastic.ElasticSession`` snapshot cadence and on-disk generation count,
and ``sync_reform_after`` sets the persistent-failure escalation threshold
at which a quorum-degrading ``ResilientGroup`` re-forms onto survivors.

There is deliberately no config-file/flag system beyond these: the reference
uses plain constructor kwargs (SURVEY.md section 5.6) and so do we.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Iterator, Optional

# Accepted spellings for boolean env knobs, shared by every
# TORCHEVAL_TPU_* flag (here, ops.native, obs.recorder). The `env-truthy`
# lint rule (torcheval_tpu/analysis/lint.py) forbids inline copies of
# these tuples elsewhere; its jax-free mirror of the spellings is
# drift-guarded against this file by tests/analysis/test_lint.py.
_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off")


def env_truthy(name: str) -> bool:
    """True when env var ``name`` is set to a truthy spelling."""
    return os.environ.get(name, "").lower() in _TRUTHY


_debug_validation: bool = env_truthy("TORCHEVAL_TPU_DEBUG")


def debug_validation_enabled() -> bool:
    """True when value-level (device-sync-forcing) input validation is on."""
    return _debug_validation


def set_debug_validation(enabled: bool) -> None:
    global _debug_validation
    _debug_validation = bool(enabled)


@contextmanager
def debug_validation(enabled: bool = True) -> Iterator[None]:
    """Context manager enabling value-level input validation.

    >>> with debug_validation():
    ...     metric.update(inputs, targets)   # raises on out-of-range values
    """
    global _debug_validation
    prev = _debug_validation
    _debug_validation = enabled
    try:
        yield
    finally:
        _debug_validation = prev


_shape_bucketing: bool = env_truthy("TORCHEVAL_TPU_SHAPE_BUCKETING")


def shape_bucketing_enabled() -> bool:
    """True when variable-shape updates are padded to power-of-two buckets."""
    return _shape_bucketing


def set_shape_bucketing(enabled: bool) -> None:
    global _shape_bucketing
    _shape_bucketing = bool(enabled)


# -------------------------------------------------------- update donation

# None = not yet resolved (env, else backend default at first use)
_update_donation: Optional[bool] = None


def _resolve_update_donation() -> bool:
    raw = os.environ.get("TORCHEVAL_TPU_UPDATE_DONATION", "").lower()
    if raw in _TRUTHY:
        return True
    if raw in _FALSY:
        return False
    # Backend-dependent default, measured not assumed: on TPU the donated
    # buffer aliases in HBM and dispatch stays fully async — donation is
    # a pure win (zero realloc per step). On the CPU PJRT runtime,
    # acquiring exclusive ownership of the donated buffer WAITS on its
    # pending producer, serializing back-to-back updates (+70-150 us/step
    # on the bench box whenever the kernel has real compute — see the
    # bench `donation` arm's paired-differences numbers). CPU therefore
    # defaults off; the zero-realloc machinery stays available behind the
    # knob on every backend.
    import jax

    return jax.default_backend() == "tpu"


def update_donation_enabled() -> bool:
    """True when fusable metric updates DONATE their state buffers into
    the jitted step, so XLA writes the new state in place — zero per-step
    realloc — instead of allocating a fresh buffer every ``update()``
    (docs/benchmarks.md, "Donation fast path"). Default: on for TPU,
    off for CPU (see ``_resolve_update_donation`` for the measured why);
    env ``TORCHEVAL_TPU_UPDATE_DONATION`` overrides either way.

    Consequence when on (the ``_buffer.py`` donated-append discipline,
    extended to every accumulator family): state arrays handed out by
    ``state_dict()`` / snapshots are COPIES, and a raw state attribute
    captured before an update must not be read after it (the donated
    buffer is consumed). Meant as a one-time process-level choice:
    flipping it between an update and a snapshot re-exposes the aliasing
    the snapshot copies exist to prevent.
    """
    global _update_donation
    if _update_donation is None:
        _update_donation = _resolve_update_donation()
    return _update_donation


def set_update_donation(enabled: bool) -> None:
    global _update_donation
    _update_donation = bool(enabled)


@contextmanager
def update_donation(enabled: bool = True) -> Iterator[None]:
    """Scoped override of :func:`update_donation_enabled` (bench arms and
    tests; see the one-time-choice caveat on the getter)."""
    global _update_donation
    prev = _update_donation
    _update_donation = bool(enabled)
    try:
        yield
    finally:
        _update_donation = prev


# --------------------------------------------------------- sync resilience

_SYNC_POLICIES = ("raise", "local", "quorum")


def _env_invalid(name: str, raw: str, why: str, default) -> None:
    import warnings

    warnings.warn(
        f"ignoring env {name}={raw!r}: {why}; using default {default!r}",
        RuntimeWarning,
    )


def _check_timeout(seconds: float) -> float:
    import math

    seconds = float(seconds)
    if not math.isfinite(seconds) or seconds <= 0:
        # a 0/negative/NaN deadline would silently disable the deadline —
        # re-creating the unbounded hang the knob exists to prevent
        raise ValueError(
            f"sync_timeout must be a positive finite number of seconds "
            f"(or None for no deadline), got {seconds}"
        )
    return seconds


def _env_timeout(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return _check_timeout(float(raw))
    except ValueError:
        _env_invalid(name, raw, "not a positive finite number", None)
        return None


def _env_int(name: str, default: int, minimum: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        _env_invalid(name, raw, "not an integer", default)
        return default
    if value < minimum:
        _env_invalid(name, raw, f"must be >= {minimum}", default)
        return default
    return value


def _env_choice(name: str, default: str, choices) -> str:
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    if raw not in choices:
        # env values ride the SAME validation as the setters: a typo must
        # not silently flip semantics (e.g. an unknown validate_inputs
        # policy being treated as "warn" when the user meant "raise")
        _env_invalid(name, raw, f"must be one of {choices}", default)
        return default
    return raw


def _env_fraction(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        _env_invalid(name, raw, "not a number", default)
        return default
    if not 0.0 < value <= 1.0:
        _env_invalid(name, raw, "must be a fraction in (0, 1]", default)
        return default
    return value


_sync_timeout: Optional[float] = _env_timeout("TORCHEVAL_TPU_SYNC_TIMEOUT")
_SYNC_RETRIES_DEFAULT = 2
_sync_retries: int = _env_int(
    "TORCHEVAL_TPU_SYNC_RETRIES", _SYNC_RETRIES_DEFAULT, minimum=0
)
_sync_degradation: str = _env_choice(
    "TORCHEVAL_TPU_SYNC_DEGRADATION", "raise", _SYNC_POLICIES
)
_sync_quorum: float = _env_fraction("TORCHEVAL_TPU_SYNC_QUORUM", 0.5)


def sync_timeout() -> Optional[float]:
    """Per-collective metric-sync deadline in seconds (``None`` = wait
    forever, the reference's behavior). Env ``TORCHEVAL_TPU_SYNC_TIMEOUT``."""
    return _sync_timeout


def set_sync_timeout(seconds: Optional[float]) -> None:
    global _sync_timeout
    _sync_timeout = None if seconds is None else _check_timeout(seconds)


def sync_retries() -> int:
    """Extra attempts after a transient sync failure or timeout (default 2).
    Env ``TORCHEVAL_TPU_SYNC_RETRIES``."""
    return _sync_retries


def set_sync_retries(retries: int) -> None:
    global _sync_retries
    if retries < 0:
        raise ValueError(f"sync_retries must be >= 0, got {retries}")
    _sync_retries = int(retries)


def sync_degradation() -> str:
    """What a failed sync degrades to: ``"raise"`` (typed error — default),
    ``"local"`` (unsynced local state, flagged stale), or ``"quorum"``
    (merge the surviving ranks). Env ``TORCHEVAL_TPU_SYNC_DEGRADATION``."""
    return _sync_degradation


def check_sync_policy(policy: str) -> str:
    """The ONE validator for degradation-policy names, shared by the
    setter here and ``resilience.ResilientGroup``."""
    if policy not in _SYNC_POLICIES:
        raise ValueError(
            f"sync degradation policy must be one of {_SYNC_POLICIES}, "
            f"got {policy!r}"
        )
    return policy


def set_sync_degradation(policy: str) -> None:
    global _sync_degradation
    _sync_degradation = check_sync_policy(policy)


def sync_quorum() -> float:
    """Minimum participating fraction of world size for the ``quorum``
    policy (default 0.5). Env ``TORCHEVAL_TPU_SYNC_QUORUM``."""
    return _sync_quorum


def set_sync_quorum(fraction: float) -> None:
    global _sync_quorum
    if not 0.0 < fraction <= 1.0:
        raise ValueError(f"quorum must be in (0, 1], got {fraction}")
    _sync_quorum = float(fraction)


def sync_resilience_configured() -> bool:
    """True when a behavior-bearing sync-resilience knob departs from the
    all-ranks-alive default — the toolkit then wraps the process group in
    a ``ResilientGroup`` automatically. (``sync_quorum`` alone does not
    trigger wrapping: it only tunes the ``quorum`` policy.)"""
    return (
        _sync_timeout is not None
        or _sync_degradation != "raise"
        or _sync_retries != _SYNC_RETRIES_DEFAULT
    )


@contextmanager
def sync_resilience(
    *,
    timeout: Optional[float] = None,
    retries: Optional[int] = None,
    degradation: Optional[str] = None,
    quorum: Optional[float] = None,
) -> Iterator[None]:
    """Context manager scoping the sync-resilience defaults.

    >>> with sync_resilience(timeout=30.0, degradation="quorum"):
    ...     value = sync_and_compute(metric)   # survives a dead host
    """
    global _sync_timeout, _sync_retries, _sync_degradation, _sync_quorum
    prev = (_sync_timeout, _sync_retries, _sync_degradation, _sync_quorum)
    try:
        # setters run INSIDE the try: a validation error on a later knob
        # must not leak the earlier ones past the context
        if timeout is not None:
            set_sync_timeout(timeout)
        if retries is not None:
            set_sync_retries(retries)
        if degradation is not None:
            set_sync_degradation(degradation)
        if quorum is not None:
            set_sync_quorum(quorum)
        yield
    finally:
        (_sync_timeout, _sync_retries, _sync_degradation, _sync_quorum) = prev


# ------------------------------------------------------ elastic evaluation

_SNAPSHOT_INTERVAL_DEFAULT = 100
_snapshot_interval: int = _env_int(
    "TORCHEVAL_TPU_SNAPSHOT_INTERVAL", _SNAPSHOT_INTERVAL_DEFAULT, minimum=1
)
_SNAPSHOT_RETENTION_DEFAULT = 2
_snapshot_retention: int = _env_int(
    "TORCHEVAL_TPU_SNAPSHOT_RETENTION", _SNAPSHOT_RETENTION_DEFAULT, minimum=1
)
_sync_reform_after: int = _env_int(
    "TORCHEVAL_TPU_SYNC_REFORM_AFTER", 0, minimum=0
)


def snapshot_interval() -> int:
    """Default steps between ``elastic.ElasticSession`` snapshots
    (default 100). Env ``TORCHEVAL_TPU_SNAPSHOT_INTERVAL``."""
    return _snapshot_interval


def set_snapshot_interval(steps: int) -> None:
    global _snapshot_interval
    if int(steps) < 1:
        raise ValueError(f"snapshot_interval must be >= 1 step, got {steps}")
    _snapshot_interval = int(steps)


def snapshot_retention() -> int:
    """Default number of committed snapshot generations an
    ``elastic.ElasticSession`` keeps on disk (default 2 — the newest plus
    one fallback for torn-write recovery). Env
    ``TORCHEVAL_TPU_SNAPSHOT_RETENTION``."""
    return _snapshot_retention


def set_snapshot_retention(generations: int) -> None:
    global _snapshot_retention
    if int(generations) < 1:
        raise ValueError(
            f"snapshot_retention must keep >= 1 generation, got {generations}"
        )
    _snapshot_retention = int(generations)


def sync_reform_after() -> int:
    """Persistent-failure escalation threshold for
    ``resilience.ResilientGroup``: after this many CONSECUTIVE
    quorum-degraded syncs missing the SAME ranks, the group re-forms onto
    a survivors-only subgroup so later syncs run undegraded. ``0``
    (default) disables re-formation. Requires a long-lived, explicitly
    constructed group — the streak lives on the group object
    (docs/fault-tolerance.md, "Survivor re-formation"). Env
    ``TORCHEVAL_TPU_SYNC_REFORM_AFTER``."""
    return _sync_reform_after


def set_sync_reform_after(syncs: int) -> None:
    global _sync_reform_after
    if int(syncs) < 0:
        raise ValueError(
            f"sync_reform_after must be >= 0 (0 disables), got {syncs}"
        )
    _sync_reform_after = int(syncs)


# ------------------------------------------------------ rank-loss failover

_FAILOVER_DETECT_AFTER_DEFAULT = 2
_failover_detect_after: int = _env_int(
    "TORCHEVAL_TPU_FAILOVER_DETECT_AFTER",
    _FAILOVER_DETECT_AFTER_DEFAULT,
    minimum=1,
)


def failover_detect_after() -> int:
    """Consecutive missing-rank syncs before ``failover.FailureDomain``
    confirms a rank loss and arms the recovery epoch (default 2 — one
    missed sync is routinely a transient; a tripped stall watchdog
    alongside a missing streak escalates immediately regardless).
    Env ``TORCHEVAL_TPU_FAILOVER_DETECT_AFTER``."""
    return _failover_detect_after


def set_failover_detect_after(syncs: int) -> None:
    global _failover_detect_after
    if int(syncs) < 1:
        raise ValueError(
            f"failover_detect_after must be >= 1 sync, got {syncs}"
        )
    _failover_detect_after = int(syncs)


_tenant_staleness: int = _env_int(
    "TORCHEVAL_TPU_TENANT_STALENESS", 0, minimum=0
)


def tenant_staleness_epochs() -> int:
    """Default per-tenant staleness budget (in drain epochs) stamped on
    tables constructed WITHOUT an explicit ``staleness_epochs=``:
    ``Federation.exchange_interval`` honors the tightest armed budget,
    so one latency-sensitive tenant pulls exchanges forward for the
    whole region. ``0`` (default) means unbudgeted — only the global
    shed rung governs. Env ``TORCHEVAL_TPU_TENANT_STALENESS``."""
    return _tenant_staleness


def set_tenant_staleness_epochs(epochs: int) -> None:
    global _tenant_staleness
    if int(epochs) < 0:
        raise ValueError(
            f"tenant staleness budget must be >= 0 (0 disables), got {epochs}"
        )
    _tenant_staleness = int(epochs)


# -------------------------------------------------- cross-region federation

_FEDERATION_STALENESS_DEFAULT = 4
_federation_staleness: int = _env_int(
    "TORCHEVAL_TPU_FEDERATION_STALENESS",
    _FEDERATION_STALENESS_DEFAULT,
    minimum=1,
)


def federation_staleness_epochs() -> int:
    """Default staleness bound (in exchange rounds) for
    ``federation.Federation``: a remote region whose snapshot has not
    merged for more than this many rounds is declared DARK (partition
    detection; the federated read degrades to the surviving regions),
    and — unless the federation overrides ``staleness_503`` — the
    ``/healthz`` probe degrades to 503 past the same bound
    (docs/fault-tolerance.md, "Cross-region federation"). Env
    ``TORCHEVAL_TPU_FEDERATION_STALENESS``."""
    return _federation_staleness


def set_federation_staleness_epochs(rounds: int) -> None:
    global _federation_staleness
    if int(rounds) < 1:
        raise ValueError(
            f"federation staleness bound must be >= 1 round, got {rounds}"
        )
    _federation_staleness = int(rounds)


# -------------------------------------------------- quantized wire ladder

# Least -> most lossy; mirrored (and lint-/drift-guarded) by
# torcheval_tpu/wire.py RUNGS. "off" is the legacy sync_compression
# spelling of the exact rung.
_WIRE_RUNGS = ("exact", "bf16", "int8")
_LEGACY_RUNGS = {"off": "exact", "bf16": "bf16", "int8": "int8"}

_WIRE_BLOCK_DEFAULT = 32
_wire_block: int = _env_int(
    "TORCHEVAL_TPU_WIRE_BLOCK", _WIRE_BLOCK_DEFAULT, minimum=2
)


def _coerce_rung(rung: str) -> str:
    rung = str(rung).strip().lower()
    rung = _LEGACY_RUNGS.get(rung, rung)
    if rung not in _WIRE_RUNGS:
        raise ValueError(
            f"wire rung must be one of {_WIRE_RUNGS} (or legacy 'off'), "
            f"got {rung!r}"
        )
    return rung


def _parse_wire_ladder(raw: str) -> "dict[str, str]":
    """``"int8"`` (default rung) or ``"*=bf16,MulticlassAUROC=int8"``
    (per-family overrides; families are metric CLASS names)."""
    raw = raw.strip()
    if "=" not in raw:
        return {"*": _coerce_rung(raw)}
    policy: "dict[str, str]" = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        family, _, rung = part.partition("=")
        policy[family.strip()] = _coerce_rung(rung)
    policy.setdefault("*", "exact")
    return policy


def _env_wire_ladder() -> "dict[str, str]":
    # legacy env keeps working as the default-family rung
    legacy = _env_choice(
        "TORCHEVAL_TPU_SYNC_COMPRESSION", "off", ("off", "bf16", "int8")
    )
    default = {"*": _LEGACY_RUNGS[legacy]}
    raw = os.environ.get("TORCHEVAL_TPU_WIRE_LADDER", "").strip()
    if not raw:
        return default
    try:
        return _parse_wire_ladder(raw)
    except ValueError:
        _env_invalid(
            "TORCHEVAL_TPU_WIRE_LADDER",
            raw,
            f"rungs must be one of {_WIRE_RUNGS}",
            default,
        )
        return default


_wire_ladder: "dict[str, str]" = _env_wire_ladder()


def wire_ladder() -> "dict[str, str]":
    """The CONFIGURED per-family wire-compression ladder policy:
    ``{"*": default_rung, family: rung, ...}`` with rungs from
    ``exact | bf16 | int8`` (least -> most lossy; see
    ``torcheval_tpu/wire.py`` and docs/distributed.md, "Quantized wire
    ladder"). Families are metric CLASS names
    (``type(metric).__name__``). The EFFECTIVE rung a family actually
    rides is this, capped by any measured drift-budget fallback —
    read it via ``wire.effective_rung(family)``. Env
    ``TORCHEVAL_TPU_WIRE_LADDER`` (``"int8"`` or
    ``"*=bf16,MulticlassAUROC=int8"``); legacy
    ``TORCHEVAL_TPU_SYNC_COMPRESSION`` still sets the default rung.

    Scope caveat (unchanged from sync_compression): the EAGER and
    federation tiers read the policy per sync call; the IN-JIT tier
    reads it at TRACE time, baking the rung into the compiled step —
    pass ``compression=`` to ``sync_states_in_jit`` explicitly to be
    unambiguous under jit.
    """
    return dict(_wire_ladder)


def wire_rung_for(family: str) -> str:
    """``family``'s configured rung (its entry, else the ``"*"``
    default). Fallback caps are NOT applied here — use
    ``wire.effective_rung``."""
    return _wire_ladder.get(family, _wire_ladder.get("*", "exact"))


def set_wire_ladder(policy) -> None:
    """Set the ladder policy: a single rung name (``"int8"`` — applies
    to every family), a ``family=rung`` spec string, or a mapping
    ``{family: rung}`` (missing ``"*"`` defaults to ``exact``)."""
    global _wire_ladder
    if isinstance(policy, str):
        _wire_ladder = _parse_wire_ladder(policy)
        return
    parsed = {str(k): _coerce_rung(v) for k, v in dict(policy).items()}
    parsed.setdefault("*", "exact")
    _wire_ladder = parsed


@contextmanager
def wire_ladder_mode(policy) -> Iterator[None]:
    """Context manager scoping the wire-ladder policy.

    >>> with wire_ladder_mode("int8"):
    ...     value = sync_and_compute(metric)   # ~3.6x fewer float bytes
    """
    global _wire_ladder
    prev = _wire_ladder
    set_wire_ladder(policy)
    try:
        yield
    finally:
        _wire_ladder = prev


def wire_block_size() -> int:
    """int8-rung quantization block: elements sharing one f32 scale
    (default 32 — wire is ``size * (1 + 4/block)`` bytes vs ``4*size``
    exact, i.e. ~3.6x smaller, with max error ``amax(block)/254``).
    Env ``TORCHEVAL_TPU_WIRE_BLOCK``."""
    return _wire_block


def set_wire_block_size(block: int) -> None:
    global _wire_block
    if int(block) < 2:
        raise ValueError(f"wire block size must be >= 2, got {block}")
    _wire_block = int(block)


# Legacy single-policy views of the ladder (pre-ISSUE-18 API): the
# compression policy IS the ladder's default-family rung now.
_COMPRESSION_POLICIES = ("off", "bf16", "int8")


def sync_compression() -> str:
    """Legacy view of the ladder's DEFAULT-family rung (``"off"`` for
    ``exact``). Prefer :func:`wire_ladder` — this survives for callers
    of the pre-ladder single-policy API."""
    rung = _wire_ladder.get("*", "exact")
    return "off" if rung == "exact" else rung


def set_sync_compression(policy: str) -> None:
    """Legacy setter: sets the ladder's ``"*"`` default rung, keeping
    any per-family overrides."""
    global _wire_ladder
    if policy not in _COMPRESSION_POLICIES:
        raise ValueError(
            f"sync_compression must be one of {_COMPRESSION_POLICIES}, "
            f"got {policy!r}"
        )
    ladder = dict(_wire_ladder)
    ladder["*"] = _LEGACY_RUNGS[policy]
    _wire_ladder = ladder


@contextmanager
def sync_compression_mode(policy: str = "bf16") -> Iterator[None]:
    """Context manager scoping the legacy default-rung policy.

    >>> with sync_compression_mode("bf16"):
    ...     value = sync_and_compute(metric)   # halved float payloads
    """
    global _wire_ladder
    prev = _wire_ladder
    set_sync_compression(policy)
    try:
        yield
    finally:
        _wire_ladder = prev


# -------------------------------------------------------- input guardrails

_VALIDATE_POLICIES = ("off", "warn", "raise")

_validate_inputs: str = _env_choice(
    "TORCHEVAL_TPU_VALIDATE_INPUTS", "off", _VALIDATE_POLICIES
)


def validate_inputs_policy() -> str:
    """NaN/Inf guard at the ``Metric.update`` front door: ``"off"``
    (default — value checks force a device readback), ``"warn"``, or
    ``"raise"``. Env ``TORCHEVAL_TPU_VALIDATE_INPUTS``."""
    return _validate_inputs


def set_validate_inputs(policy: str) -> None:
    global _validate_inputs
    if policy not in _VALIDATE_POLICIES:
        raise ValueError(
            f"validate_inputs policy must be one of {_VALIDATE_POLICIES}, "
            f"got {policy!r}"
        )
    _validate_inputs = policy


@contextmanager
def validate_inputs(policy: str = "raise") -> Iterator[None]:
    """Context manager enabling the NaN/Inf input guard.

    >>> with validate_inputs():
    ...     metric.update(inputs, targets)   # raises on NaN/Inf inputs
    """
    global _validate_inputs
    prev = _validate_inputs
    set_validate_inputs(policy)
    try:
        yield
    finally:
        _validate_inputs = prev


# ---------------------------------------------------------- observability

def observability_enabled() -> bool:
    """True when the process-global event recorder
    (``torcheval_tpu.obs``) is recording. Off by default — when off, the
    instrumented hot paths cost one attribute read and add zero host
    syncs / zero collectives (docs/observability.md). Env
    ``TORCHEVAL_TPU_OBSERVABILITY`` (truthy enables at import; a value
    ending in ``.jsonl`` also attaches the JSONL writer)."""
    from torcheval_tpu.obs.recorder import RECORDER

    return RECORDER.enabled


def set_observability(enabled: bool) -> None:
    """Turn the global event recorder on/off process-wide. Prefer the
    scoped :func:`observability` context manager in eval code."""
    from torcheval_tpu.obs.recorder import RECORDER

    if enabled:
        RECORDER.enable()
    else:
        RECORDER.disable()


@contextmanager
def observability(
    enabled: bool = True,
    *,
    jsonl: Optional[str] = None,
    capacity: Optional[int] = None,
    chrome_trace: Optional[str] = None,
    watchdog: Optional[float] = None,
    serve: Optional[int] = None,
    slos=None,
) -> Iterator[None]:
    """Context manager scoping structured event recording
    (docs/observability.md).

    Inside the context the global recorder (``torcheval_tpu.obs``)
    collects typed lifecycle events — updates, computes, syncs (with
    provenance + wire bytes), resilience retries/degradations, elastic
    snapshots/restores, XLA compiles — into a bounded ring buffer, with
    causal trace/span ids connecting them into per-step trees
    (docs/observability.md, "Causal tracing"), and optionally streams
    them to ``jsonl`` via the async line writer (drained and closed on
    exit). ``chrome_trace`` additionally writes the scope's retained
    events as Chrome trace-event JSON (``obs.export_chrome_trace``,
    loadable in Perfetto) when the scope exits — including an exit by
    exception, so a crashed eval leaves its timeline behind.

    Live-diagnosis layer (docs/observability.md, "Flight recorder &
    watchdog" / "Health endpoint"): ``watchdog=<seconds>`` arms the
    stall watchdog (``obs.watchdog``) for the scope — a collective
    in-flight past that deadline dumps every thread's flight ring, the
    stalled thread's span path, and a ``StallEvent`` before the process
    dies; ``serve=<port>`` runs the background health server
    (``obs.server``: ``/metrics``, ``/healthz``, ``/flight``,
    ``/report``; port 0 = ephemeral — read it off
    ``obs.server.current_server().port``); ``slos=[SloSpec, ...]`` arms
    the SLO/anomaly monitor (``obs.monitor``; pass ``[]`` for
    drift-detection-only). All three are torn down at scope exit —
    watchdog disarmed, server stopped, monitor disarmed — exit by
    exception included.

    >>> with observability(jsonl="/tmp/eval-events.jsonl"):
    ...     value = sync_and_compute(metric)
    >>> # obs.format_report() / obs.read_jsonl(...) to inspect
    """
    from torcheval_tpu.obs.flight import FLIGHT
    from torcheval_tpu.obs.recorder import RECORDER

    prev_enabled = RECORDER.enabled
    prev_writer = RECORDER._writer
    # enable() adds the flight recorder's "recorder" source; the scope
    # restores RECORDER.enabled by attribute (pause semantics), so the
    # source must be restored the same way or flight recording leaks
    # past the scope
    prev_flight = "recorder" in FLIGHT._sources
    # pre-existing process-global live-diagnosis instances: the scope
    # must hand them BACK at exit (an operator's env-armed watchdog may
    # not be silently stripped by a narrower scoped one)
    scoped_watchdog = scoped_server = False
    scoped_monitor = False
    prev_watchdog = prev_monitor = None
    prev_server_addr = None
    # NOT sys.exc_info(): inside an outer `except` handler that call
    # reports the already-HANDLED exception, which would both mask a
    # chrome-trace export error after a fully successful scope and
    # mislabel a clean exit as a crash — only an exception escaping the
    # scope BODY counts
    propagating: Optional[BaseException] = None
    events_before = RECORDER.log.total
    try:
        # arming INSIDE the try: a failed start (e.g. the serve port is
        # already bound) still runs the teardown below, so an armed
        # watchdog/monitor cannot leak past a scope that never opened
        if watchdog is not None:
            from torcheval_tpu.obs import watchdog as _wd_mod

            prev_watchdog = _wd_mod._WATCHDOG
            _wd_mod.arm_watchdog(watchdog)
            scoped_watchdog = True
        if slos is not None:
            from torcheval_tpu.obs import monitor as _mon_mod

            prev_monitor = _mon_mod._MONITOR
            _mon_mod.arm_monitor(slos=tuple(slos))
            scoped_monitor = True
        if serve is not None:
            from torcheval_tpu.obs.server import current_server, start_server

            running = current_server()
            if running is not None:
                prev_server_addr = (running.port, running.host)
            start_server(serve)
            scoped_server = True
        if enabled:
            if jsonl is not None:
                # detach (don't close) any writer attached OUTSIDE this
                # scope before enable() installs this scope's — the outer
                # stream must keep working after the scope exits
                RECORDER._writer = None
            RECORDER.enable(jsonl=jsonl, capacity=capacity)
        else:
            # pause recording only — a writer attached OUTSIDE this scope
            # must survive the scope (full disable() would close it)
            RECORDER.enabled = False
        yield
    except BaseException as e:
        propagating = e
        raise
    finally:
        # live-diagnosis teardown first (the server reads the monitor
        # and watchdog, so it stops before they disarm); each RESTORES
        # any process-global instance that pre-existed the scope. None
        # of these raise by design, and the nested finally guarantees
        # the recorder/writer restore below runs regardless
        try:
            if scoped_server:
                from torcheval_tpu.obs.server import start_server, stop_server

                stop_server()
                if prev_server_addr is not None:
                    start_server(*prev_server_addr)
            if scoped_monitor:
                from torcheval_tpu.obs.monitor import _restore_monitor

                _restore_monitor(prev_monitor)
            if scoped_watchdog:
                from torcheval_tpu.obs.watchdog import _restore_watchdog

                _restore_watchdog(prev_watchdog)
        finally:
            export_error: Optional[BaseException] = None
            if enabled and chrome_trace is not None:
                # write the timeline even when the scope exits by
                # exception (a crashed eval leaves its trace behind); an
                # unwritable path surfaces — but only after the
                # recorder/writer state below is restored, and never
                # MASKING a propagating error. Only THIS SCOPE's events
                # (the documented contract): the ring is process-global
                # and may hold an earlier eval's events — export the
                # suffix recorded since entry. (Events beyond the ring
                # capacity are gone either way; tail(0) would mean ALL
                # retained, hence the explicit [] branch.)
                from torcheval_tpu.obs.export import export_chrome_trace

                new = RECORDER.log.total - events_before
                scope_events = RECORDER.log.tail(new) if new > 0 else []
                try:
                    export_chrome_trace(scope_events, path=chrome_trace)
                except Exception as e:  # noqa: BLE001 — re-raised below
                    export_error = e
            # restore recorder state FIRST (close may raise a ferried
            # writer error to the caller), then close ONLY the writer
            # THIS scope attached — never one inherited from outside
            scoped = RECORDER._writer
            RECORDER._writer = prev_writer
            RECORDER.enabled = prev_enabled
            if prev_flight:
                FLIGHT.enable("recorder")
            else:
                FLIGHT.disable("recorder")
            if scoped is not None and scoped is not prev_writer:
                scoped.close()
            if export_error is not None and propagating is None:
                raise export_error


@contextmanager
def shape_bucketing(enabled: bool = True) -> Iterator[None]:
    """Context manager enabling retrace-proof shape bucketing.

    Inside the context, bucket-aware metrics pad ragged batch axes up to
    power-of-two buckets and thread a validity mask into the kernel, so a
    streaming eval loop with a ragged tail compiles O(log max_batch)
    programs instead of one per distinct shape. Padded rows contribute
    exactly zero to every state, so ``compute()`` results match the
    unbucketed path.

    >>> with shape_bucketing():
    ...     for batch in loader:           # ragged last batch is fine
    ...         metric.update(batch.scores, batch.labels)
    """
    global _shape_bucketing
    prev = _shape_bucketing
    _shape_bucketing = enabled
    try:
        yield
    finally:
        _shape_bucketing = prev
