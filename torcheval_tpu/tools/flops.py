"""FLOP counting for Flax modules and jittable functions.

Parity: reference torcheval/tools/flops.py:147-335 (`flop_mapping`,
`FlopTensorDispatchMode`). The reference intercepts aten calls with a
``TorchDispatchMode`` and estimates FLOPs from a 7-op lookup table
(mm/bmm/addmm/matmul/convolution + backwards). The TPU-native design asks
the compiler instead: every captured (sub)module is lowered with XLA and
``compiled.cost_analysis()`` returns the exact post-fusion FLOP count —
covering every op, not just matmul/conv. Per-module attribution uses Flax
method interceptors (``nn.intercept_methods``) the way the reference uses
forward hooks + a module-name stack (reference flops.py:243-311).

Semantics notes (differences from the reference, both favorable):
- counts are exact program FLOPs after XLA fusion/simplification;
- backward counts are ``flops(grad(fn)) - flops(fn)`` — the reference
  instead tags its 7 op kinds during an eager ``.mean().backward()``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _cost_analysis(lowered) -> Dict[str, float]:
    ca = lowered.compile().cost_analysis()
    if isinstance(ca, list):  # older jax returns one dict per device program
        ca = ca[0]
    return ca or {}


def count_flops(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> float:
    """Exact XLA FLOP count of one call of a jittable function.

    Args may be arrays or ``jax.ShapeDtypeStruct`` avals — nothing is
    executed, only lowered and compiled.

    >>> import jax, jax.numpy as jnp
    >>> from torcheval_tpu.tools import count_flops
    >>> count_flops(lambda a, b: a @ b,
    ...             jax.ShapeDtypeStruct((128, 64), jnp.float32),
    ...             jax.ShapeDtypeStruct((64, 32), jnp.float32))
    524288.0
    """
    lowered = jax.jit(fn).lower(*args, **kwargs)
    return float(_cost_analysis(lowered).get("flops", 0.0))


def count_flops_backward(
    fn: Callable[..., Any], *args: Any, **kwargs: Any
) -> float:
    """FLOPs of the backward pass of ``fn`` w.r.t. all array arguments.

    Defined as ``flops(grad(mean(fn))) − flops(fn)`` — the gradient program
    re-runs the primal, so the difference is the backward work. The mean
    reduction mirrors the reference's ``res.mean().backward()``
    (reference tools/module_summary.py:266-269).
    """

    def scalar_fn(*a: Any, **k: Any) -> jax.Array:
        out = fn(*a, **k)
        leaves = [
            x for x in jax.tree_util.tree_leaves(out)
            if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.inexact)
        ]
        return sum(jnp.mean(x) for x in leaves)

    def _is_diffable(a: Any) -> bool:
        kinds = (jax.Array, jax.ShapeDtypeStruct, np.ndarray)
        if isinstance(a, kinds):
            return True
        if isinstance(a, (dict, list, tuple)):
            # a pytree qualifies if it holds at least one array AND nothing
            # grad can't trace (shape tuples of python ints must stay static)
            leaves = jax.tree_util.tree_leaves(a)
            return any(isinstance(x, kinds) for x in leaves) and all(
                isinstance(x, (*kinds, float, int)) for x in leaves
            )
        return False

    diffable = tuple(i for i, a in enumerate(args) if _is_diffable(a))
    if not diffable:
        return 0.0
    grad_fn = jax.grad(scalar_fn, argnums=diffable, allow_int=True)
    total = count_flops(grad_fn, *args, **kwargs)
    fwd = count_flops(fn, *args, **kwargs)
    return max(total - fwd, 0.0)


class ModuleCall(NamedTuple):
    """One captured submodule invocation."""

    path: Tuple[str, ...]
    type_name: str
    module: Any  # unbound flax module clone
    in_avals: Tuple[Any, ...]
    in_arrays: Tuple[Any, ...]
    out_avals: Tuple[Any, ...]
    kwargs: Dict[str, Any]


def capture_module_calls(
    module, variables, *args: Any, keep_arrays: bool = False, **kwargs: Any
) -> Tuple[List[ModuleCall], Any]:
    """Run one forward of a Flax module, recording every submodule call
    (path, unbound clone, input/output avals). Returns ``(calls, output)``.

    ``keep_arrays=True`` additionally stores each call's concrete input
    arrays (needed for per-module timing); left off by default so captured
    activations don't stay device-resident.

    The JAX analogue of the reference's forward pre/post hook
    instrumentation (reference flops.py:243-311 / module_summary.py:668-725).
    """
    import flax.linen as nn

    calls: List[ModuleCall] = []

    def _aval(x: Any) -> Any:
        if isinstance(x, (jax.Array, np.ndarray)):
            return jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype)
        return x

    def interceptor(next_fun, f_args, f_kwargs, context):
        if context.method_name != "__call__":
            return next_fun(*f_args, **f_kwargs)
        out = next_fun(*f_args, **f_kwargs)
        out_leaves = tuple(
            _aval(x)
            for x in jax.tree_util.tree_leaves(out)
            if isinstance(x, (jax.Array, np.ndarray))
        )
        calls.append(
            ModuleCall(
                path=tuple(context.module.path),
                type_name=type(context.module).__name__,
                module=context.module.clone(parent=None),
                in_avals=tuple(_aval(a) for a in f_args),
                in_arrays=tuple(f_args) if keep_arrays else (),
                out_avals=out_leaves,
                kwargs=dict(f_kwargs),
            )
        )
        return out

    with nn.intercept_methods(interceptor):
        out = module.apply(variables, *args, **kwargs)
    return calls, out


def _subtree(variables: Dict[str, Any], path: Tuple[str, ...]) -> Dict[str, Any]:
    """Restrict a variables dict to one submodule's subtree."""
    sub: Dict[str, Any] = {}
    for collection, tree in variables.items():
        node = tree
        ok = True
        for key in path:
            if not isinstance(node, dict) or key not in node:
                ok = False
                break
            node = node[key]
        if ok:
            sub[collection] = node
    return sub


def module_flops(
    call: ModuleCall, variables: Dict[str, Any], backward: bool = False
) -> float:
    """FLOPs of one captured submodule call (forward, or backward-only)."""
    sub_vars = _subtree(variables, call.path)
    sub_avals = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype)
        if isinstance(x, (jax.Array, np.ndarray))
        else x,
        sub_vars,
    )

    def apply_fn(v, *a):
        return call.module.apply(v, *a, **call.kwargs)

    if backward:
        return count_flops_backward(apply_fn, sub_avals, *call.in_avals)
    return count_flops(apply_fn, sub_avals, *call.in_avals)


class FlopCounter:
    """Per-module FLOP counts for a Flax module forward (+ backward).

    The reference analogue is ``FlopTensorDispatchMode`` (flops.py:173-335):
    ``flop_counts`` maps the dotted module path (``""`` for the root) to its
    exact XLA FLOP count, parents inclusive of children — the same
    attribution the reference's module-stack produces.

    >>> fc = FlopCounter(module, variables)
    >>> out = fc.run(x)
    >>> fc.flop_counts[""], fc.flop_counts["encoder"]
    """

    def __init__(self, module, variables) -> None:
        self.module = module
        self.variables = variables
        self.flop_counts: Dict[str, float] = {}
        self.flop_counts_backward: Dict[str, float] = {}
        self._calls: List[ModuleCall] = []

    def run(self, *args: Any, backward: bool = False, **kwargs: Any) -> Any:
        """Forward the wrapped module, populating ``flop_counts`` (and
        ``flop_counts_backward`` when requested)."""
        self._calls, out = capture_module_calls(
            self.module, self.variables, *args, **kwargs
        )
        self.flop_counts = {}
        self.flop_counts_backward = {}
        for call in self._calls:
            name = ".".join(call.path)
            try:
                self.flop_counts[name] = (
                    self.flop_counts.get(name, 0.0)
                    + module_flops(call, self.variables)
                )
            except Exception:
                self.flop_counts[name] = -1.0  # not independently lowerable
            if backward:
                try:
                    self.flop_counts_backward[name] = (
                        self.flop_counts_backward.get(name, 0.0)
                        + module_flops(call, self.variables, backward=True)
                    )
                except Exception:
                    self.flop_counts_backward[name] = -1.0
        return out

    def reset(self) -> None:
        self.flop_counts = {}
        self.flop_counts_backward = {}
