"""WindowedMeanSquaredError.

Parity: reference torcheval/metrics/window/mean_squared_error.py:23-265.
Note the reference's windowed-MSE task layout is (num_samples, num_tasks)
columns (reference :255-264), unlike CTR/NE's (num_tasks, num_samples) rows.
"""

from __future__ import annotations

from typing import Optional, Tuple, TypeVar, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.regression.mean_squared_error import (
    _mean_squared_error_compute,
    _mean_squared_error_param_check,
    _mean_squared_error_update_input_check,
    _update_unweighted,
    _update_weighted,
)
from torcheval_tpu.utils.convert import to_jax_float
from torcheval_tpu.metrics.window._base import WindowedTaskCounterMetric

TWindowedMeanSquaredError = TypeVar(
    "TWindowedMeanSquaredError", bound="WindowedMeanSquaredError"
)


class WindowedMeanSquaredError(WindowedTaskCounterMetric):
    """MSE over the last ``max_num_updates`` updates (+ optional lifetime).

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import WindowedMeanSquaredError
        >>> metric = WindowedMeanSquaredError(max_num_updates=2)
        >>> metric.update(jnp.array([0.9, 0.5]), jnp.array([0.5, 0.8]))
        >>> metric.update(jnp.array([0.3, 0.5]), jnp.array([0.2, 0.8]))
        >>> metric.compute()
        (Array(0.0875, dtype=float32), Array(0.0875, dtype=float32))
    """

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        max_num_updates: int = 100,
        enable_lifetime: bool = True,
        multioutput: str = "uniform_average",
        device: Optional[jax.Device] = None,
    ) -> None:
        super().__init__(device=device)
        _mean_squared_error_param_check(multioutput)
        self.multioutput = multioutput
        self._init_window_states(
            ("sum_squared_error", "sum_weight"),
            num_tasks=num_tasks,
            max_num_updates=max_num_updates,
            enable_lifetime=enable_lifetime,
            # scalar lifetime defaults: broadcast-promote to per-output
            # vectors on first multioutput update (reference :92-96, 141-145)
            lifetime_defaults=(jnp.zeros(()), jnp.zeros(())),
        )

    def _window_input_check(self, input: jax.Array) -> None:
        if self.num_tasks == 1:
            if input.ndim > 1:
                raise ValueError(
                    "`num_tasks = 1`, `input` is expected to be "
                    f"one-dimensional tensor, but got shape ({input.shape})."
                )
        elif input.ndim == 1 or input.shape[1] != self.num_tasks:
            raise ValueError(
                f"`num_tasks = {self.num_tasks}`, `input`'s shape is expected "
                f"to be (num_samples, {self.num_tasks}), but got shape "
                f"({input.shape})."
            )

    def update(
        self: TWindowedMeanSquaredError,
        input,
        target,
        *,
        sample_weight: Optional[jax.Array] = None,
    ) -> TWindowedMeanSquaredError:
        """Accumulate one batch's squared-error sums into the window — one
        fused dispatch (MSE kernel + lifetime + ring write)."""
        return self._apply_update_plan(
            self._update_plan(input, target, sample_weight=sample_weight)
        )

    def _update_plan(self, input, target, *, sample_weight=None):
        input, target = self._input_float(input), self._input_float(target)
        _mean_squared_error_update_input_check(input, target, sample_weight)
        self._window_input_check(input)
        if sample_weight is None:
            return self._window_plan(_update_unweighted, (input, target))
        return self._window_plan(
            _update_weighted,
            (input, target, to_jax_float(sample_weight)),
        )

    def compute(self) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
        """Windowed (and lifetime) MSE; empty before any update."""
        if self.total_updates == 0:
            return self._empty_result()
        sse_sum, weight_sum = self._windowed_counter_sums()
        windowed = _mean_squared_error_compute(
            sse_sum, self.multioutput, weight_sum
        ).squeeze()
        if self.enable_lifetime:
            lifetime = _mean_squared_error_compute(
                self.sum_squared_error, self.multioutput, self.sum_weight
            ).squeeze()
            return lifetime, windowed
        return windowed
