"""FID parity with the PUBLISHED torchvision InceptionV3 checkpoint.

The composite attestation VERDICT r4 asked for: the Flax port + weight
mapping must reproduce, under the REAL pretrained weights, the pooled
features and final FID captured from the reference pipeline
(``scripts/capture_fid_realweights_golden.py``). Both legs need
torchvision (this image has neither it nor egress), so the module skips
cleanly here and runs wherever the weights exist — the fid_golden CI
workflow executes capture + this test on every push.

The in-image mitigations stay in force regardless: wiring parity per
Mixed block against an independent torch mirror
(test_inception_golden.py) and value-checked weight placement
(test_inception_weight_mapping.py).
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)
NPZ = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "golden_fid_realweights.npz")

tv = pytest.importorskip(
    "torchvision",
    reason="real-weights golden needs torchvision (absent in this image; "
    "runs in the fid_golden CI workflow)",
)


@pytest.fixture(scope="module")
def golden():
    if not os.path.exists(NPZ):
        pytest.skip(
            "golden_fid_realweights.npz not captured yet — run "
            "scripts/capture_fid_realweights_golden.py on a machine with "
            "torchvision"
        )
    with np.load(NPZ) as f:
        return {k: f[k] for k in f.files}


@pytest.fixture(scope="module")
def variables(golden):
    """Flax params imported from the same checkpoint the golden used."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from capture_fid_realweights_golden import state_dict_sha256
    finally:
        sys.path.pop(0)

    from torchvision import models

    from torcheval_tpu.models.inception import (
        load_torchvision_inception_params,
    )

    sd = {
        k: v.detach().numpy()
        for k, v in models.inception_v3(weights="DEFAULT").state_dict().items()
    }
    sha = state_dict_sha256(sd)
    want = bytes(golden["weight_sha256"]).decode()
    assert sha == want, (
        f"local torchvision checkpoint {sha[:16]}… differs from the "
        f"captured one {want[:16]}… — re-run the capture script"
    )
    return load_torchvision_inception_params(sd)


def _features(variables, u8):
    import jax
    import jax.numpy as jnp

    from torcheval_tpu.models.inception import InceptionV3

    x = jnp.asarray(u8.astype(np.float32) / 255.0)
    x = jnp.transpose(x, (0, 2, 3, 1))
    x = jax.image.resize(
        x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear",
        antialias=False,
    )
    return np.asarray(InceptionV3().apply(variables, x))


def test_pooled_features_match_published_checkpoint(golden, variables):
    for leg in ("real", "fake"):
        ours = _features(variables, golden[f"{leg}_images"])
        ref = golden[f"{leg}_features"]
        # f32 conv stacks on different backends: compare with a feature-
        # scale tolerance; any wiring/mapping error moves features by O(1)
        np.testing.assert_allclose(ours, ref, rtol=5e-3, atol=5e-3)


def test_fid_matches_published_checkpoint(golden, variables):
    from torcheval_tpu.metrics import FrechetInceptionDistance
    from torcheval_tpu.models.inception import InceptionV3

    import jax
    import jax.numpy as jnp

    module = InceptionV3()

    def extractor(images):  # (N, 3, H, W) float in [0, 1]
        x = jnp.transpose(images, (0, 2, 3, 1))
        x = jax.image.resize(
            x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear",
            antialias=False,
        )
        return module.apply(variables, x)

    m = FrechetInceptionDistance(model=extractor)
    m.update(jnp.asarray(golden["real_images"].astype(np.float32) / 255.0),
             is_real=True)
    m.update(jnp.asarray(golden["fake_images"].astype(np.float32) / 255.0),
             is_real=False)
    got = float(m.compute())
    want = float(golden["fid"])
    assert got == pytest.approx(want, rel=0.02), (got, want)
