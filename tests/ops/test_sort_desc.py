"""Native radix argsort vs XLA: bit-exact order parity.

The curve metrics' CPU lowering swaps ``jnp.argsort(-x, stable=True)`` for
the FFI radix sort (``ops/native/sort_desc.cc``); these tests pin the exact
comparator semantics — stability under ties, NaN-last, and XLA CPU's
flush-to-zero tie class for subnormals/±0.
"""

import jax
import jax.experimental
import jax.numpy as jnp
import numpy as np
import pytest

from torcheval_tpu.metrics.functional.classification._curve_kernels import (
    _sort_desc_xla,
    sort_desc,
)


@pytest.fixture(autouse=True)
def _require_native():
    from torcheval_tpu.ops import native

    if not native.ensure_registered():
        pytest.skip("native toolchain unavailable")


def _assert_matches_xla(x):
    jx = jnp.asarray(x)
    s_n, o_n = jax.jit(sort_desc)(jx)
    s_x, o_x = _sort_desc_xla(jx)
    np.testing.assert_array_equal(np.asarray(o_n), np.asarray(o_x))
    np.testing.assert_array_equal(
        np.asarray(s_n), np.asarray(s_x), strict=True
    )


def test_ties_stable():
    rng = np.random.default_rng(0)
    x = rng.uniform(size=501).astype(np.float32)
    x[::3] = x[0]
    x[1::7] = x[1]
    _assert_matches_xla(x)


def test_special_values_order():
    _assert_matches_xla(
        np.array(
            [0.5, np.nan, -np.inf, np.inf, 0.5, -np.nan, 0.0, 1e-38,
             -1e-38, -0.0, -1.5, 3e38, -3e38],
            dtype=np.float32,
        )
    )


def test_batched_and_vmap():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(5, 64)).astype(np.float32)
    _assert_matches_xla(x)
    jx = jnp.asarray(x)
    o_v = jax.jit(jax.vmap(lambda r: sort_desc(r)[1]))(jx)
    o_e = jax.vmap(lambda r: _sort_desc_xla(r)[1])(jx)
    np.testing.assert_array_equal(np.asarray(o_v), np.asarray(o_e))


def test_wide_range_fuzz():
    rng = np.random.default_rng(2)
    for trial in range(10):
        n = int(rng.integers(1, 4097))
        x = (rng.normal(size=n) * float(10.0 ** rng.integers(-6, 7))).astype(
            np.float32
        )
        x[rng.random(n) < 0.25] = np.float32(rng.choice(x))
        _assert_matches_xla(x)


def test_non_f32_falls_back_to_xla():
    # bfloat16 input must not reach the f32-only kernel
    x = jnp.asarray(np.random.default_rng(3).normal(size=33), jnp.bfloat16)
    s, o = jax.jit(sort_desc)(x)
    s_x, o_x = _sort_desc_xla(x)
    np.testing.assert_array_equal(np.asarray(o), np.asarray(o_x))
    np.testing.assert_array_equal(
        np.asarray(s.astype(jnp.float32)), np.asarray(s_x.astype(jnp.float32))
    )


def test_empty_input():
    for shape in [(0,), (3, 0), (0, 5)]:
        s, o = jax.jit(sort_desc)(jnp.zeros(shape, jnp.float32))
        assert s.shape == shape and o.shape == shape


def test_x64_mode_curve_metric():
    # jax_enable_x64 flips argsort's dtype to int64; the dispatch must
    # still produce equal branch types (reproduces a trace-time crash)
    with jax.experimental.enable_x64():
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.uniform(size=64).astype(np.float32))
        s, o = jax.jit(sort_desc)(x)
        s_x, o_x = _sort_desc_xla(x)
        np.testing.assert_array_equal(
            np.asarray(o), np.asarray(o_x).astype(np.int32)
        )
