from torcheval_tpu.ops.fused_auc import (
    fused_auc,
    fused_auc_histogram,
    fused_auc_histogram_accumulate,
)

__all__ = [
    "fused_auc",
    "fused_auc_histogram",
    "fused_auc_histogram_accumulate",
]
