"""Exporters: JSONL event stream, Prometheus exposition, human report,
Chrome/Perfetto traces, and the cross-rank gathers.

Ways out of the recorder/registry, matched to their consumers:

- :class:`JsonlWriter` — an async bounded-queue line writer for log
  shippers (one JSON object per event, ``events.event_from_dict`` reads
  them back). Same background-writer discipline as the elastic snapshot
  writer it is modeled on: a daemon thread does the I/O, ``write`` blocks
  only when the queue is full (backpressure, never silent drops), errors
  are ferried to the caller and re-raised at ``drain``/``close``, and
  ``close`` drains cleanly.
- :func:`render_prometheus` — a text-exposition snapshot of the counter
  registry (label values escaped, names sanitized) PLUS the latency
  digests as proper ``# TYPE ... histogram`` families with cumulative
  ``_bucket`` / ``_sum`` / ``_count`` series.
- :func:`format_report` — a human-readable table (counters + latency
  p50/p99 + recent events) for terminals and bug reports; the
  failure-dump pytest hook in ``conftest.py`` prints this.
- :func:`export_chrome_trace` — the recorded events as Chrome
  trace-event JSON, loadable in Perfetto / ``chrome://tracing``:
  per-rank process lanes, per-thread tracks, complete ``X`` slices for
  duration events, instants for point events, and flow arrows linking
  the same sync across ranks (via ``SyncEvent.flow``).
- :func:`gather_observability` / :func:`gather_traces` — ONE collective
  each over a ``ProcessGroup`` merging every rank's counters+events
  (respectively events+latency digests) into a single report, so the
  leader can answer "which rank stalled which sync?" without ssh'ing
  around. Rides the existing group machinery (``allgather_object``), so
  it works over ``MultiHostGroup``, subgroups, ``ResilientGroup``
  wrappers, and the in-process ``ThreadWorld`` test world alike.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Any, Dict, List, Optional, Union

from torcheval_tpu.obs import hist as _hist
from torcheval_tpu.obs.events import Event, event_from_dict
from torcheval_tpu.obs.recorder import RECORDER, EventLog

__all__ = [
    "JsonlWriter",
    "export_chrome_trace",
    "format_report",
    "gather_observability",
    "gather_traces",
    "read_jsonl",
    "render_prometheus",
]


class JsonlWriter:
    """Append events to ``path`` as JSON lines, off the caller's thread.

    ``write`` appends to a bounded in-memory batch (blocking only when
    ``depth`` events are already pending — the backpressure contract;
    never a silent drop); a daemon thread wakes every
    ``flush_interval`` seconds, swaps the whole batch out, and
    serializes + appends it in one write. Batched hand-off, not a
    per-event queue: waking the writer on every event puts a GIL/context
    switch on the step path (measured ~100µs/event in rehearsal), while
    an append under a lock is sub-µs — the step path must not pay for
    telemetry I/O.

    I/O errors never surface inside ``write`` (an eval step must not die
    because a log disk filled) — they are ferried and re-raised at
    :meth:`drain` / :meth:`close`, after which the writer is inert.
    ``close`` drains, stops the thread, and closes the file.
    """

    def __init__(
        self, path: str, *, depth: int = 4096, flush_interval: float = 0.05
    ) -> None:
        self.path = path
        self.depth = int(depth)
        self.flush_interval = float(flush_interval)
        self.error: Optional[BaseException] = None  # tev: disable=unguarded-state -- single-writer error ferry: only the writer thread sets it, the caller reads/clears it at drain/close; a reference swap is atomic under the GIL
        self._lock = threading.Lock()
        self._buf: List[dict] = []  # tev: guarded-by=_lock
        self._writing = False  # tev: guarded-by=_lock
        self._stop = False  # tev: guarded-by=_lock
        self._closed = False  # tev: disable=unguarded-state -- caller-thread-only lifecycle flag (close() is caller API; the writer thread never reads it)
        self._kick = threading.Event()  # "flush now" (drain/backpressure)
        # open on the caller's thread so a bad path fails at construction,
        # not silently inside the daemon
        self._f = open(path, "a", encoding="utf-8")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="torcheval-obs-jsonl"
        )
        self._thread.start()

    def _loop(self) -> None:  # tev: scope=writer
        while True:
            self._kick.wait(self.flush_interval)
            self._kick.clear()
            with self._lock:
                batch, self._buf = self._buf, []
                self._writing = bool(batch)
                stop = self._stop
            if batch and self.error is None:
                try:
                    self._f.write(
                        "".join(json.dumps(d) + "\n" for d in batch)
                    )
                    self._f.flush()
                except Exception as e:  # noqa: BLE001 — ferried
                    if self.error is None:
                        self.error = e
            with self._lock:
                self._writing = False
                if stop and not self._buf:
                    return

    def write(self, event: Event) -> None:
        """Buffer one event (never raises; see class docstring)."""
        if self._closed or self.error is not None:
            return
        payload = event.as_dict()
        while True:
            with self._lock:
                if len(self._buf) < self.depth or self.error is not None:
                    self._buf.append(payload)
                    return
            # backpressure: the writer is behind — flush now and wait
            self._kick.set()
            time.sleep(0.001)

    def _idle(self) -> bool:
        with self._lock:
            return not self._buf and not self._writing

    def drain(self) -> None:
        """Block until every buffered event is on disk (flushed);
        re-raise any ferried writer error."""
        while not self._idle() and self.error is None:
            self._kick.set()
            time.sleep(0.002)
        if self.error is not None:
            error, self.error = self.error, None
            raise error

    def close(self) -> None:
        """Drain, stop the writer thread, close the file; re-raise any
        ferried error (after the file is closed)."""
        if self._closed:
            return
        try:
            self.drain()
        finally:
            self._closed = True
            with self._lock:
                self._stop = True
            self._kick.set()
            self._thread.join(timeout=30.0)
            try:
                self._f.close()
            except Exception:  # noqa: BLE001 — best-effort on teardown
                pass


def read_jsonl(path: str) -> List[Event]:
    """Read a :class:`JsonlWriter` file back into typed events (the
    round-trip contract: ``read_jsonl(p) == the events written``)."""
    out: List[Event] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(event_from_dict(json.loads(line)))
    return out


_PROM_NAME = re.compile(r"[^a-zA-Z0-9_]")

# counters that only ever move up -> `counter`; everything else `gauge`
_PROM_COUNTER_HINTS = (
    "attempts", "retries", "timeouts", "errors", "gathers", "payloads",
    "syncs", "reforms", "programs", "compiles", "hits", "written", "total",
    "restores", "kind_", "recorded", "trips",
)


def _prom_name(raw: str) -> str:
    """Sanitize to the Prometheus metric-name grammar
    (``[a-zA-Z_][a-zA-Z0-9_]*``): every forbidden character becomes
    ``_``, and a leading digit gets a ``_`` prefix — a counter key like
    ``update/MulticlassAccuracy`` or ``99p`` must never emit an
    unparseable exposition line."""
    name = _PROM_NAME.sub("_", raw)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _prom_label_value(value: Any) -> str:
    """Escape a label VALUE per the exposition format: backslash, double
    quote, and newline are the three characters the grammar requires
    escaped (in that order — escaping the escapes first)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _prom_le(upper_us: float) -> str:
    """A bucket's ``le`` label value in SECONDS (``+Inf`` for the last)."""
    if upper_us == float("inf"):
        return "+Inf"
    return format(upper_us / 1e6, ".12g")


def _render_histograms(histograms, prefix: str) -> List[str]:
    """The latency digests as Prometheus ``histogram`` families:
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``, one
    labeled series set per digest key (``op=<key>``)."""
    family = _prom_name(f"{prefix}_latency_seconds")
    lines: List[str] = []
    if histograms:
        lines.append(f"# TYPE {family} histogram")
    bounds = _hist.bucket_upper_bounds_us()
    for key in sorted(histograms):
        h = histograms[key]
        op = _prom_label_value(key)
        cumulative = 0
        for upper, count in zip(bounds, h.counts):
            cumulative += count
            lines.append(
                f'{family}_bucket{{op="{op}",le="{_prom_le(upper)}"}} '
                f"{cumulative}"
            )
        lines.append(f'{family}_sum{{op="{op}"}} {h.sum}')
        lines.append(f'{family}_count{{op="{op}"}} {h.count}')
    return lines


def _render_quality_histograms(prefix: str) -> List[str]:
    """The armed quality watches' value sketches as Prometheus
    ``histogram`` families: one labeled series set per watched input
    (``input=<series>``), cumulative ``_bucket{le=<edge>}`` over the
    sketch's value-space edges with the below-range lane folded into
    every bucket and ``+Inf`` covering below + bins + above (finite,
    binnable observations; NaN/Inf ride the ``quality`` gauge source).
    ``_sum`` is reconstructed from the streaming moments (mean x count
    over the finite samples — exact up to the moments' f32 precision).
    Reads the (small) sketch states off-device — scrape cadence by
    construction, never the step path."""
    from torcheval_tpu.obs import quality as _quality
    from torcheval_tpu.obs.sketch import _CNT_ABOVE, _CNT_BELOW

    watches = _quality.active_watches()
    if not watches:
        return []
    family = _prom_name(f"{prefix}_quality_value")
    lines: List[str] = [f"# TYPE {family} histogram"]
    emitted = False
    for watch in watches:
        edges = watch.config.edges()
        for series in watch.series:
            states = watch._states(series)
            label = _prom_label_value(series)
            below = float(states["cnt"][_CNT_BELOW])
            above = float(states["cnt"][_CNT_ABOVE])
            cumulative = below
            for edge, count in zip(edges[1:], states["hist"]):
                cumulative += float(count)
                lines.append(
                    f'{family}_bucket{{input="{label}",'
                    f'le="{format(float(edge), ".9g")}"}} '
                    f"{format(cumulative, '.12g')}"
                )
            total = cumulative + above
            lines.append(
                f'{family}_bucket{{input="{label}",le="+Inf"}} '
                f"{format(total, '.12g')}"
            )
            mom = states["mom"]
            lines.append(
                f'{family}_sum{{input="{label}"}} '
                f"{float(mom[0]) * float(mom[1])}"
            )
            lines.append(
                f'{family}_count{{input="{label}"}} {format(total, ".12g")}'
            )
            emitted = True
    return lines if emitted else []


def render_prometheus(
    registry=None,
    *,
    prefix: str = "torcheval_tpu",
    histograms: Optional[Dict[str, "_hist.LatencyHistogram"]] = None,
) -> str:
    """Prometheus text-exposition snapshot of a counter registry
    (default: ``counters.default_registry()``) plus the latency digests
    (default: the process-global ``obs.hist`` registry; pass ``{}`` to
    suppress).

    Numeric counters only — strings, rank lists, and None values are
    skipped (Prometheus has no representation for them; they remain
    available via :func:`format_report` and the JSONL stream). Booleans
    export as 0/1 gauges. Names are sanitized to the exposition grammar
    and label values escaped (backslash/quote/newline) — every emitted
    line parses (pinned by tests/metrics/test_tracing.py's grammar
    test).
    """
    from torcheval_tpu.obs.counters import default_registry

    if registry is None:
        registry = default_registry()
    if histograms is None:
        histograms = _hist.snapshot()
    lines: List[str] = []
    for source, counters in sorted(registry.read().items()):
        for counter, value in sorted(counters.items()):
            if isinstance(value, bool):
                value = int(value)
                kind = "gauge"
            elif isinstance(value, (int, float)):
                kind = (
                    "counter"
                    if any(h in counter for h in _PROM_COUNTER_HINTS)
                    else "gauge"
                )
            else:
                continue
            name = _prom_name(f"{prefix}_{source}_{counter}")
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {value}")
    lines.extend(_render_histograms(histograms, prefix))
    lines.extend(_render_quality_histograms(prefix))
    return "\n".join(lines) + "\n"


def format_report(
    registry=None,
    log: Optional[EventLog] = None,
    *,
    tail: int = 20,
    histograms: Optional[Dict[str, "_hist.LatencyHistogram"]] = None,
) -> str:
    """Human-readable observability report: one counter table per
    source, the latency digests (count / mean / approximate p50 / p99
    per key), then the newest ``tail`` events (oldest-first)."""
    from torcheval_tpu.obs.counters import default_registry

    if registry is None:
        registry = default_registry()
    if log is None:
        log = RECORDER.log
    if histograms is None:
        histograms = _hist.snapshot()
    lines: List[str] = ["torcheval_tpu observability report", "=" * 34]
    for source, counters in sorted(registry.read().items()):
        lines.append(f"\n[{source}]")
        width = max((len(k) for k in counters), default=0)
        for counter, value in sorted(counters.items()):
            lines.append(f"  {counter:<{width}}  {value}")
    if histograms:
        lines.append("\n[latency] (approximate quantiles, log2 buckets)")
        width = max(len(k) for k in histograms)
        for key in sorted(histograms):
            h = histograms[key]
            if not h.count:
                continue
            mean_us = h.sum / h.count * 1e6
            p50 = (h.quantile(0.5) or 0.0) * 1e6
            p99 = (h.quantile(0.99) or 0.0) * 1e6
            lines.append(
                f"  {key:<{width}}  n={h.count}  mean={mean_us:.1f}us"
                f"  p50<={p50:.1f}us  p99<={p99:.1f}us"
            )
    lines.extend(_quality_report_lines())
    events = log.tail(tail)
    lines.append(f"\n[events] newest {len(events)} of {log.total} recorded")
    for ev in events:
        payload = {
            k: v
            for k, v in ev.as_dict().items()
            if k not in ("kind", "schema", "t_mono", "t_wall", "tid", "trace")
            and v not in (None, "")
        }
        fields = " ".join(f"{k}={v}" for k, v in payload.items())
        lines.append(f"  {ev.t_mono:14.3f}  {ev.kind:<9} {fields}")
    return "\n".join(lines) + "\n"


def _quality_report_lines() -> List[str]:
    """The ``format_report`` input-quality table: one line per watched
    input with count / mean±std / range / conservative p50/p99 /
    NaN-zero tallies / distinct estimate, plus the last drift scores
    with their breach flags. Empty when nothing is watched. Reads the
    sketch states off-device (scrape cadence — this report is never on
    the step path)."""
    import math as _math

    from torcheval_tpu.obs import quality as _quality

    watches = _quality.active_watches()
    if not watches:
        return []
    lines = ["\n[quality] (input sketches; p50/p99 conservative bin edges)"]
    for watch in watches:
        for series in watch.series:
            sk = watch.sketch(series)
            summary = sk.compute()
            std = _math.sqrt(summary.var) if summary.count else 0.0
            p50 = sk.quantile(0.5)
            p99 = sk.quantile(0.99)
            q = (
                f"p50<={p50:.4g} p99<={p99:.4g}"
                if p50 is not None
                else "p50/p99=n/a"
            )
            lines.append(
                f"  {series}  n={summary.count:.0f}"
                f"  mean={summary.mean:.4g}±{std:.4g}"
                f"  range=[{summary.min:.4g}, {summary.max:.4g}]  {q}"
                f"  nan={summary.nan} inf={summary.posinf + summary.neginf}"
                f" zero={summary.zero} neg={summary.negative}"
                f"  distinct~{summary.distinct:.0f}"
            )
            scores = watch._scores.get(series)
            if scores:
                lines.append(
                    f"    drift: psi={scores['psi']:.4g}"
                    f" ks={scores['ks']:.4g} z={scores['z']:.4g}"
                    f" (window n={scores['count']:.0f}"
                    f" vs ref n={scores['ref_count']:.0f})"
                )
    return lines


def _check_rank_scoped(group, what: str) -> Optional[Dict[str, Any]]:
    """Shared entry checks for the cross-rank gathers: reject groups
    without per-rank observability state, and short-circuit non-members
    (they issue no collective). Returns the non-member result, or None
    when the caller should proceed with the gather."""
    from torcheval_tpu.distributed import LocalReplicaGroup

    if isinstance(group.unwrap(), LocalReplicaGroup):
        raise TypeError(
            f"{what} needs a rank-per-process group; a "
            "LocalReplicaGroup's replicas share one process-global "
            "recorder — read it directly with format_report()"
        )
    if not group.is_member:
        return {
            "world_size": group.world_size,
            "ranks": [],
            "per_rank": {},
        }
    return None


def _rank_events(me: int, tail: int) -> List[Dict[str, Any]]:
    """This rank's contribution to a gather: the newest ``tail`` events
    that are THIS rank's (events whose ``rank`` field is this rank, or
    rank-less process-local events), as plain dicts."""
    return [
        ev.as_dict()
        for ev in RECORDER.log.tail(tail)
        if ev.rank is None or ev.rank == me
    ]


def gather_observability(
    group,
    *,
    registry=None,
    tail: int = 50,
) -> Dict[str, Any]:
    """Merge every rank's observability summary through ``group``.

    Every member rank calls this in step (it issues ONE
    ``allgather_object`` on ``group`` — never on the metric-sync path);
    each contributes its counter-registry snapshot plus the newest
    ``tail`` events that are THIS rank's (events whose ``rank`` field is
    this rank, or rank-less process-local events). All members receive
    the same merged report; rank 0 conventionally prints or ships it.

    Returns ``{"world_size", "ranks", "per_rank": {rank: {"counters",
    "events"}}}`` — events as plain dicts (``event_from_dict`` restores
    them). Requires a rank-per-process group (``MultiHostGroup``,
    ``ThreadWorld`` views, subgroups); a ``LocalReplicaGroup`` has no
    per-rank observability state to gather.
    """
    from torcheval_tpu.obs.counters import default_registry

    non_member = _check_rank_scoped(group, "gather_observability")
    if non_member is not None:
        return non_member
    if registry is None:
        registry = default_registry()
    me = group.rank
    contribution = {
        "rank": me,
        "counters": registry.read(),
        "events": _rank_events(me, tail),
    }
    gathered = group.allgather_object(contribution)
    per_rank = {int(c["rank"]): c for c in gathered}
    return {
        "world_size": group.world_size,
        "ranks": sorted(per_rank),
        "per_rank": {
            r: {"counters": c["counters"], "events": c["events"]}
            for r, c in sorted(per_rank.items())
        },
    }


def gather_traces(
    group,
    *,
    tail: int = 200,
) -> Dict[str, Any]:
    """Merge every rank's trace events AND latency digests through
    ``group`` in ONE ``allgather_object`` (the ``gather_observability``
    discipline: every member calls it in step, never on the metric-sync
    path; works over ``MultiHostGroup``, ``ThreadWorld`` views,
    subgroups, and ``ResilientGroup`` wrappers).

    Returns ``{"world_size", "ranks", "per_rank": {rank: {"events":
    [...], "hist": {key: snapshot}}}, "latency": {key:
    LatencyHistogram}}`` — ``latency`` is the cross-rank merge of every
    rank's digests, folded in ascending rank order, so every member
    computes the same bits (the histogram merge-oracle property). Feed
    the whole result to :func:`export_chrome_trace` for a merged
    Perfetto timeline with per-rank lanes and cross-rank sync flows.
    """
    non_member = _check_rank_scoped(group, "gather_traces")
    if non_member is not None:
        non_member["latency"] = {}
        return non_member
    me = group.rank
    contribution = {
        "rank": me,
        "events": _rank_events(me, tail),
        "hist": {k: h.as_dict() for k, h in _hist.snapshot().items()},
    }
    gathered = group.allgather_object(contribution)
    per_rank = {int(c["rank"]): c for c in gathered}
    merged: Dict[str, _hist.LatencyHistogram] = {}
    for rank in sorted(per_rank):  # fixed fold order -> bit-identical
        for key, snap in sorted(per_rank[rank]["hist"].items()):
            h = _hist.LatencyHistogram.from_dict(snap)
            if key in merged:
                merged[key].merge(h)
            else:
                merged[key] = h
    return {
        "world_size": group.world_size,
        "ranks": sorted(per_rank),
        "per_rank": {
            r: {"events": c["events"], "hist": c["hist"]}
            for r, c in sorted(per_rank.items())
        },
        "latency": merged,
    }


# ------------------------------------------------------------ chrome trace

# kinds whose `seconds` is a true duration: they become complete "X"
# slices spanning [t_mono - seconds, t_mono]; everything else is an
# instant ("i") at t_mono
_DURATION_KINDS = frozenset(
    {"update", "compute", "sync", "snapshot", "restore", "span", "compile"}
)
_ENVELOPE_KEYS = frozenset(
    {"kind", "schema", "t_mono", "t_wall", "tid", "rank"}
)


def _chrome_label(d: Dict[str, Any]) -> str:
    kind = d.get("kind", "event")
    for key in ("metric", "name", "reason", "rule"):
        value = d.get(key)
        if value:
            return f"{kind}/{value}"
    if kind == "compile" and d.get("site"):
        return f"compile @ {d['site']}"
    return kind


def export_chrome_trace(
    events: Union[None, List[Any], Dict[str, Any]] = None,
    *,
    path: Optional[str] = None,
) -> Dict[str, Any]:
    """The event stream as Chrome trace-event JSON (Perfetto /
    ``chrome://tracing`` / ``ui.perfetto.dev`` all load it).

    ``events`` may be a list of :class:`~torcheval_tpu.obs.events.Event`
    (or their dicts) — default: the global recorder's retained ring — or
    a :func:`gather_traces` result for a merged multi-rank timeline.

    Layout: one PROCESS lane per rank (``pid`` = rank; rank-less
    process-local events land in lane 0 unless the event carries a
    rank), one TRACK per emitting thread (``tid``), complete ``X``
    slices for duration events (update/compute/sync/snapshot/restore/
    span/compile — ``ts`` = start, ``dur`` = seconds), instants
    (``ph="i"``) for point events (retry/memory/analysis), and flow
    arrows (``ph`` s/t/f sharing ``id``) binding the SAME sync's slices
    across every contributing rank via ``SyncEvent.flow``. Payload
    fields ride in ``args``; span/parent ids ride there too, so a
    Perfetto query can rebuild the causal tree.

    Timestamps are each rank's monotonic clock in µs — within a rank
    they order exactly; across ranks/hosts the clocks are not aligned
    (lanes are still side-by-side and flows still link).

    Returns the ``{"traceEvents": [...]}`` dict; ``path`` additionally
    writes it as JSON. Grammar (required ``ph``/``ts``/``pid``/``tid``,
    complete-X-or-matched-B/E) is pinned by
    tests/metrics/test_tracing.py.
    """
    if events is None:
        events = RECORDER.log.tail()
    if isinstance(events, dict) and "per_rank" in events:
        per_rank = {
            int(rank): list(contrib["events"])
            for rank, contrib in events["per_rank"].items()
        }
    else:
        per_rank = {}
        for ev in events:
            d = ev if isinstance(ev, dict) else ev.as_dict()
            rank = d.get("rank")
            per_rank.setdefault(0 if rank is None else int(rank), []).append(d)

    trace_events: List[Dict[str, Any]] = []
    # flow id -> [(pid, tid, ts_us_midslice)] of the sync slices sharing it
    flows: Dict[int, List] = {}
    for rank in sorted(per_rank):
        trace_events.append(
            {
                "ph": "M", "name": "process_name", "pid": rank, "tid": 0,
                "ts": 0, "args": {"name": f"rank {rank}"},
            }
        )
        for raw in per_rank[rank]:
            d = raw if isinstance(raw, dict) else raw.as_dict()
            kind = d.get("kind", "event")
            tid = d.get("tid") or 0
            t_end_us = float(d.get("t_mono", 0.0)) * 1e6
            args = {
                k: v
                for k, v in d.items()
                if k not in _ENVELOPE_KEYS and v is not None
            }
            record: Dict[str, Any] = {
                "name": _chrome_label(d),
                "cat": kind,
                "pid": rank,
                "tid": tid,
                "args": args,
            }
            if kind in _DURATION_KINDS:
                dur_us = max(float(d.get("seconds", 0.0)), 0.0) * 1e6
                record.update(
                    ph="X", ts=t_end_us - dur_us, dur=dur_us
                )
                if kind == "sync" and d.get("flow"):
                    flows.setdefault(int(d["flow"]), []).append(
                        (rank, tid, t_end_us - dur_us / 2)
                    )
            else:
                record.update(ph="i", ts=t_end_us, s="t")
            trace_events.append(record)
    # flow arrows: one start ("s") on the earliest slice, steps ("t")
    # through the middles, a finish ("f") on the latest — only when the
    # flow actually spans more than one slice. Ordered by TIMESTAMP, not
    # rank: the trace-event contract binds same-id flow events in ts
    # order, and a rank-major sequence whose ts runs backwards (rank 1
    # entered the sync first) makes Perfetto drop or mis-bind the arrow.
    for flow_id, slices in sorted(flows.items()):
        if len(slices) < 2:
            continue
        slices.sort(key=lambda s: (s[2], s[0], s[1]))
        for i, (pid, tid, ts) in enumerate(slices):
            ph = "s" if i == 0 else ("f" if i == len(slices) - 1 else "t")
            record = {
                "ph": ph, "name": "sync", "cat": "sync-flow",
                "id": flow_id, "pid": pid, "tid": tid, "ts": ts,
            }
            if ph == "f":
                record["bp"] = "e"
            trace_events.append(record)
    out = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(out, f)
    return out
