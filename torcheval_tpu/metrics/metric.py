"""TPU-native ``Metric`` base class.

Behavioral parity with the reference ABC (reference
torcheval/metrics/metric.py:29-281) — same surface:
``update / compute / merge_state / reset / state_dict / load_state_dict / to /
device`` and the ``_add_state`` registry — redesigned for JAX:

- Metric state is a **pytree of ``jax.Array`` leaves** (plus Python int/float
  and the list/dict containers of the reference's ``TState`` union,
  reference metric.py:18). Arrays live in device HBM; ``update`` launches
  asynchronous XLA ops and never syncs the host.
- Each state declares a **merge kind** (sum / max / min / extend / custom) at
  registration. This replaces the reference's ~40 bespoke ``merge_state``
  method bodies with declarative metadata, and — crucially — lets the sync
  layer (torcheval_tpu/metrics/synclib.py) lower counter-state merges to a
  single fused ``lax.psum`` on ICI instead of the reference's pickle-based
  ``all_gather_object`` (reference toolkit.py:388).
- ``to(device)`` is ``jax.device_put``; ``state_dict`` returns a picklable
  snapshot (jax.Arrays are immutable, so snapshots are free).

The class layer is a thin OO shell: all math lives in pure, jitted functions
under ``torcheval_tpu/metrics/functional/`` (same single-source-of-truth split
as the reference, SURVEY.md section 1).
"""

from __future__ import annotations

import copy
import enum
import functools
import time
import types
from abc import ABC, abstractmethod
from typing import (
    Any,
    Dict,
    Generic,
    Iterable,
    List,
    NamedTuple,
    Optional,
    TypeVar,
    Union,
)

import jax
import jax.numpy as jnp

from torcheval_tpu import config
from torcheval_tpu.metrics._fuse import fused_accumulate
from torcheval_tpu.obs.recorder import RECORDER as _OBS
from torcheval_tpu.utils.convert import (
    canonicalize_device,
    device_descriptor,
    resolve_device_descriptor,
    to_host,
    to_host_float,
    to_jax,
    to_jax_float,
)

TState = Union[jax.Array, List[jax.Array], Dict[Any, jax.Array], int, float]
TComputeReturn = TypeVar("TComputeReturn")
TSelf = TypeVar("TSelf", bound="Metric")


class UpdatePlan(NamedTuple):
    """A fusable metric update (see :meth:`Metric._update_plan`).

    ``transform=False``: ``states += kernel(*dynamic, *config)``.
    ``transform=True``: ``states = kernel(states, *dynamic, *config)``.
    ``kernel`` and ``config`` must be hashable (they key the jit caches);
    ``finalize`` (host-side, optional) runs after the device step and is
    never part of a cache key.

    ``masked_kernel`` + ``batch_axes`` opt the plan into shape bucketing
    (torcheval_tpu/metrics/_bucket.py): under
    ``config.shape_bucketing()``, batch axes are padded to power-of-two
    buckets and ``masked_kernel(*padded_dynamic, valid_sizes, *config)``
    is dispatched instead — it must make padded rows contribute exactly
    zero to every state. ``batch_axes`` names the ragged axes of each
    dynamic argument: one tuple of dim labels per argument (positional
    from axis 0; ``None``/empty for arguments with no ragged axis).
    """

    kernel: Any
    state_names: tuple
    dynamic: tuple
    config: tuple = ()
    transform: bool = False
    finalize: Any = None
    masked_kernel: Any = None
    batch_axes: tuple = ()


class MergeKind(enum.Enum):
    """Declarative cross-replica merge semantics for one state.

    Extracted from the per-metric ``merge_state`` bodies of the reference
    (e.g. sum: reference classification/accuracy.py:143-148; max:
    aggregation/max.py merge; extend: classification/auroc.py list states;
    slowest-rank max: aggregation/throughput.py:94-103). Encoding them as
    metadata is what lets the distributed layer choose ``lax.psum`` vs padded
    ``all_gather`` per state without inspecting Python code.
    """

    SUM = "sum"  # elementwise add (tensor / int / float / dict-of-tensor)
    MAX = "max"  # elementwise max
    MIN = "min"  # elementwise min
    EXTEND = "extend"  # list state: concatenate the per-replica lists
    CUSTOM = "custom"  # subclass overrides merge_state / _merge_custom_state


class DefaultStateDict(dict):
    """Picklable defaultdict-of-zero-scalars for dict states.

    The reference resets dict states to ``defaultdict(lambda: tensor(0.0))``
    (reference metric.py:136-140), which cannot be pickled; since our sync
    path snapshots states for cross-host transfer we use an equivalent that
    can.
    """

    def __init__(self, device_str: str, *args: Any) -> None:
        super().__init__(*args)
        self._device_str = device_str

    def __missing__(self, key: Any) -> jax.Array:
        value = jax.device_put(
            jnp.zeros((), dtype=jnp.float32),
            resolve_device_descriptor(self._device_str),
        )
        self[key] = value
        return value

    def __reduce__(self):
        return (DefaultStateDict, (self._device_str, dict(self)))


def _is_array(x: Any) -> bool:
    return isinstance(x, jax.Array)


def _shield_compute_output(metric: "Metric", out: Any) -> Any:
    """Copy array leaves of a ``compute()`` result while donation is
    active: several computes return a STATE array itself (confusion
    matrix with ``normalize=None``, Sum/Min/Max), and the next donated
    update would consume it out from under the caller. Off the donation
    path this is a no-op (computes stay zero-copy)."""
    if not metric._donation_active():
        return out
    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if _is_array(x) else x, out
    )


def _instrumented(fn, phase: str, cls_name: str):
    """Wrap a subclass's ``update``/``compute`` with observability (and,
    for ``compute``, the donation output shield — see
    ``_shield_compute_output``).

    Recorder OFF (the default): one attribute read, then the original
    function — no host sync, no allocation (the recorder-ON/OFF parity is
    pinned by tests/metrics/test_no_host_sync.py and the observability
    bench). Recorder ON: the call is timed, annotated into the XLA trace
    (``jax.profiler.TraceAnnotation``), wrapped in a causal-tracing span
    frame (``obs/trace.py`` — a compile or retry fired inside parents to
    this update, and the event carries trace/span/parent ids), fed into
    the per-family latency digest (``obs/hist.py``), and recorded as an
    ``UpdateEvent``/``ComputeEvent``; updates also stamp ``obs_step``
    (the recorder's step cursor) on the metric — cleared by ``reset()``
    and ``load_state_dict`` like ``sync_provenance``. All of it is
    host-side bookkeeping: zero host syncs, zero collectives (pinned by
    the recorder-ON tier-1 variants).
    """
    from torcheval_tpu.obs import hist as _obs_hist
    from torcheval_tpu.obs import trace as _obs_trace
    from torcheval_tpu.obs.events import ComputeEvent, UpdateEvent

    label = f"torcheval.{phase}/{cls_name}"
    is_compute = phase == "compute"

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        if not _OBS.enabled:
            out = fn(self, *args, **kwargs)
            return _shield_compute_output(self, out) if is_compute else out
        # inline frame management (not trace.Scope): this is THE hot
        # instrumented path, and on a saturated box every µs of host
        # python here is amplified by core competition with async XLA
        # (see the bench `tracing` config's capture notes)
        frame = _obs_trace.push(label)
        t0 = time.monotonic()
        try:
            with jax.profiler.TraceAnnotation(label):
                out = fn(self, *args, **kwargs)
        except BaseException as e:
            _obs_trace.capture_error(e)
            raise
        finally:
            _obs_trace.pop(frame)
        seconds = time.monotonic() - t0
        name = type(self).__name__
        _obs_hist.observe(f"{phase}/{name}", seconds)
        if phase == "update":
            self.obs_step = _OBS.step_cursor
            _OBS.record(
                UpdateEvent(
                    metric=name,
                    seconds=seconds,
                    trace=frame.trace_id,
                    span=frame.span_id,
                    parent=frame.parent_id,
                )
            )
        else:
            out = _shield_compute_output(self, out)
            _OBS.record(
                ComputeEvent(
                    metric=name,
                    seconds=seconds,
                    trace=frame.trace_id,
                    span=frame.span_id,
                    parent=frame.parent_id,
                )
            )
        return out

    wrapper._obs_instrumented = True
    return wrapper


class Metric(Generic[TComputeReturn], ABC):
    """Base class for all torcheval_tpu metrics.

    Subclasses register states with ``_add_state`` in ``__init__`` and
    implement ``update``/``compute``; ``merge_state`` is derived from the
    registered merge kinds unless overridden.
    """

    # Discontinuity counter: bumped by ``reset()`` and ``load_state_dict``
    # (the two operations that REPLACE state rather than accumulate into
    # it). A published sync-plane snapshot records the epoch it was
    # captured at; a mismatch at read time means the snapshot describes
    # state the metric no longer holds, so the plane must discard it
    # instead of serving pre-reset merged values (ISSUE 16 satellite).
    # Class-level default so pickles/clones from before this field simply
    # read 0; updates never touch it (zero-cost on the serving path).
    _state_epoch: int = 0

    def __init__(
        self,
        *,
        device: Optional[Union[jax.Device, str]] = None,
        shard: Optional["ShardContext"] = None,
    ) -> None:
        self._state_name_to_default: Dict[str, TState] = {}
        self._state_name_to_merge_kind: Dict[str, MergeKind] = {}
        self._device: jax.Device = canonicalize_device(device)
        # sharded-state layer (metrics/shardspec.py): `shard` names where
        # this instance's sharded states live (eager rank/world or a mesh
        # axis); `_sharded_states` records the ShardInfo per state name;
        # `_routed_states` the outbox bookkeeping of scatter-routed states
        self._shard_ctx = shard
        self._sharded_states: Dict[str, Any] = {}
        self._routed_states: Dict[str, Any] = {}
        self._shard_bookkeeping_registered = False

    def __init_subclass__(cls, **kwargs: Any) -> None:
        """Instrument concrete ``update``/``compute`` overrides with the
        observability recorder (``torcheval_tpu.obs``) — see
        ``_instrumented`` for the off-by-default cost contract. Only
        functions defined on THIS class are wrapped (inherited ones were
        wrapped when their defining class was created), abstract stubs
        are left alone, and wrapping is idempotent."""
        super().__init_subclass__(**kwargs)
        for name in ("update", "compute"):
            fn = cls.__dict__.get(name)
            if (
                fn is None
                or not callable(fn)
                or getattr(fn, "__isabstractmethod__", False)
                or getattr(fn, "_obs_instrumented", False)
            ):
                continue
            setattr(cls, name, _instrumented(fn, name, cls.__name__))

    # ------------------------------------------------------------------ state

    @property
    def device(self) -> jax.Device:
        return self._device

    def _add_state(
        self,
        name: str,
        default: TState,
        *,
        merge: MergeKind = MergeKind.CUSTOM,
        shard: Optional["ShardSpec"] = None,
    ) -> None:
        """Register a state variable (reference metric.py:49-65).

        ``default`` must be a jax.Array, a list of jax.Arrays, a dict with
        jax.Array values, an int, or a float. It is snapshotted for
        ``reset()`` and the live value is placed on ``self.device``.

        ``shard`` (a :class:`~torcheval_tpu.metrics.shardspec.ShardSpec`)
        declares the state partitioned across the metric's shard context
        (``Metric(shard=...)``): under an EAGER context the registered
        default becomes this rank's contiguous slice along ``shard.axis``
        (the per-rank shard IS the persisted state — snapshots, syncs and
        the elastic on-disk layout all ship ``size/world`` bytes); under a
        MESH context the state keeps its logical shape but is placed with
        a ``NamedSharding`` over the mesh axis (per-device bytes drop to
        ``size/world``; the fused update jits pin ``out_shardings`` so
        updates never silently re-replicate it). Ignored without a shard
        context, so one metric class serves replicated and sharded use.
        """
        if shard is not None and self._shard_ctx is not None:
            if not isinstance(default, jax.Array):
                raise TypeError(
                    f"sharded state {name!r} requires an array default"
                )
            if not self._shard_ctx.is_mesh and shard.axis != 0:
                raise ValueError(
                    "eager sharding currently partitions axis 0 only "
                    f"(state {name!r} declared axis {shard.axis})"
                )
            default, info = self._shard_ctx.prepare_state(name, default, shard)
            self._sharded_states[name] = info
            self._ensure_shard_bookkeeping()
        self._check_state_variable_type(name, default)
        self._state_name_to_default[name] = self._clone_state(default)
        self._state_name_to_merge_kind[name] = merge
        # the LIVE state must NEVER alias the registered default
        # (device_put to the same device is a no-copy identity): a
        # donated update consumes the live buffer, and if donation is
        # enabled at ANY point in the metric's life — including via the
        # config knob AFTER construction — an aliased default would die
        # with it, permanently breaking reset(). One unconditional copy
        # per state at construction buys that out.
        setattr(
            self, name, self._place_named(name, self._clone_state(default, force_copy=True))
        )

    def _ensure_shard_bookkeeping(self) -> None:
        """Register the carried-shard descriptor states once per eager
        sharded metric: ``_shard_rank``/``_shard_world`` describe which
        shard the LIVE arrays currently hold (normally this rank's own;
        ``-1``/``0`` after a reassembling merge desharded the instance to
        the logical state). They are ordinary int states, so snapshots,
        syncs and checkpoints are self-describing — a restore knows which
        slice it is looking at without any side channel."""
        ctx = self._shard_ctx
        if ctx is None or ctx.is_mesh or self._shard_bookkeeping_registered:
            return
        self._shard_bookkeeping_registered = True
        self._add_state("_shard_rank", int(ctx.rank), merge=MergeKind.CUSTOM)
        self._add_state("_shard_world", int(ctx.world), merge=MergeKind.CUSTOM)

    # Donation fast path (ROADMAP item 4): when True — and the process
    # knob ``config.update_donation`` is on (TPU default; see its measured
    # CPU caveat) — this metric's fusable update plans run through jitted
    # steps with ``donate_argnums``, so XLA writes each new state into the
    # OLD state's buffer (zero realloc per step). Ownership consequence
    # (the ``_buffer.py`` donated-append discipline, generalized): state
    # array objects must never escape the metric — ``_clone_state``
    # therefore COPIES arrays while donation is in effect, which makes
    # ``state_dict()`` / ``reset()`` / ``load_state_dict`` hand out and
    # take in independent buffers. Subclasses whose states intentionally
    # alias external arrays opt out by setting this False.
    _donated_update: bool = True

    # class-level fallbacks so instances restored from pre-sharding
    # pickles (and lightweight test doubles skipping __init__) behave as
    # replicated metrics. READ-ONLY mappings: a write through an
    # instance that skipped __init__ must raise, never land on the
    # class and turn every Metric in the process into a "sharded" one.
    _shard_ctx = None
    _sharded_states: Dict[str, Any] = types.MappingProxyType({})
    _routed_states: Dict[str, Any] = types.MappingProxyType({})
    _shard_bookkeeping_registered = False

    # CUSTOM-kind states that must ALSO merge through the sharded
    # reassembling merge (which by the owner-partitioned contract keeps
    # CUSTOM non-sharded states at self's value — rank-identical config
    # scalars). Instrumentation that attaches genuinely mergeable CUSTOM
    # states to arbitrary metrics (obs/quality.py's input sketches)
    # lists them here so `_merge_sharded` routes them through
    # `_merge_custom_state` like the default merge does.
    _custom_mergeable_states: frozenset = frozenset()

    def _donation_active(self) -> bool:
        return self._donated_update and config.update_donation_enabled()

    def _clone_state(self, value: TState, *, force_copy: bool = False) -> TState:
        if _is_array(value):
            if force_copy or self._donation_active():
                # a later donated update CONSUMES the live buffer; a
                # snapshot sharing it would die with it
                return jnp.copy(value)
            return value  # jax.Arrays are immutable; no copy needed
        if isinstance(value, list):
            # clone leaves too: a shallow container copy would share the
            # inner arrays with the live state, which a donated update
            # consumes — the same invariant as the bare-array branch
            return [self._clone_state(v, force_copy=force_copy) for v in value]
        if isinstance(value, DefaultStateDict):
            return DefaultStateDict(
                value._device_str,
                {k: self._clone_state(v, force_copy=force_copy) for k, v in value.items()},
            )
        if isinstance(value, dict):
            return {k: self._clone_state(v, force_copy=force_copy) for k, v in value.items()}
        return copy.deepcopy(value)

    def _place_state(self, value: TState, device: Optional[jax.Device] = None) -> TState:
        device = device or self._device
        if _is_array(value):
            return jax.device_put(value, device)
        if isinstance(value, list):
            return [jax.device_put(v, device) for v in value]
        if isinstance(value, dict):
            placed = DefaultStateDict(device_descriptor(device))
            for k, v in value.items():
                placed[k] = jax.device_put(v, device)
            return placed
        return value

    def _place_named(
        self, name: str, value: TState, device: Optional[jax.Device] = None
    ) -> TState:
        """``_place_state`` that preserves a mesh-sharded state's
        ``NamedSharding`` placement (a plain ``device_put`` to one device
        would silently gather the shards back into a replica)."""
        info = self._sharded_states.get(name) if self._sharded_states else None
        if (
            info is not None
            and getattr(info, "sharding", None) is not None
            and device is None
            and _is_array(value)
        ):
            return jax.device_put(value, info.sharding)
        return self._place_state(value, device)

    def _check_state_variable_type(self, name: str, value: TState) -> None:
        """Runtime TState validation (reference metric.py:260-281)."""
        if _is_array(value) or isinstance(value, (int, float)):
            return
        if isinstance(value, list):
            if all(_is_array(v) for v in value):
                return
            raise TypeError(
                f"The value of state variable `{name}` must be a list of "
                f"jax.Array, got {value!r}."
            )
        if isinstance(value, dict):
            if all(_is_array(v) for v in value.values()):
                return
            raise TypeError(
                f"The values of state variable dict `{name}` must be "
                f"jax.Array, got {value!r}."
            )
        raise TypeError(
            "The value of state variable must be a jax.Array, a list of "
            "jax.Array, a dict with jax.Array values, an int, or a float; "
            f"got `{name}` = {value!r}."
        )

    # --------------------------------------------------------- input boundary

    # True on metrics whose ``_update_plan`` carries a masked kernel: under
    # shape bucketing their host inputs must STAY on the host (numpy) until
    # padded to the bucket — a device pad of the ragged shape would compile
    # per shape, which is the retrace bucketing exists to kill.
    _bucketed_update: bool = False

    def _input_placement(self):
        """Where ``update()`` inputs (and array-valued config attributes
        like binned thresholds) are committed: ``self._device`` normally;
        REPLICATED over the mesh for a mesh-sharded metric — a state
        distributed over 8 devices cannot be jitted together with a batch
        committed to one of them."""
        ctx = self._shard_ctx
        if ctx is not None and ctx.is_mesh:
            from jax.sharding import NamedSharding, PartitionSpec

            return NamedSharding(ctx.mesh, PartitionSpec())
        return self._device

    def _input(self, x: Any, *, dtype: Any = None) -> jax.Array:
        """Coerce an update() argument onto ``self.device``.

        The analogue of the reference's ``input.to(self.device)`` at the top
        of every update (e.g. reference classification/accuracy.py:124-125):
        accepts jax/numpy/torch/scalars, H2D-copies only when needed. Under
        shape bucketing, bucket-aware metrics keep host inputs on the host
        (the fused dispatch device-puts the padded array once).

        Under ``config.validate_inputs`` (off by default — the finite check
        forces a device readback) every float input is guarded against
        NaN/Inf here, the one front door all updates share.
        """
        if (
            self._bucketed_update
            and config.shape_bucketing_enabled()
            and not isinstance(x, jax.Array)
        ):
            return self._guard_finite(to_host(x, dtype=dtype))
        # jax.Array inputs keep the documented `input.to(self.device)` hop
        # even under bucketing (the device pad then runs on self.device)
        return self._guard_finite(to_jax(x, dtype=dtype, device=self._input_placement()))

    def _input_float(self, x: Any) -> jax.Array:
        if (
            self._bucketed_update
            and config.shape_bucketing_enabled()
            and not isinstance(x, jax.Array)
        ):
            return self._guard_finite(to_host_float(x))
        return self._guard_finite(to_jax_float(x, device=self._input_placement()))

    def _guard_finite(self, x: Any) -> Any:
        """NaN/Inf guardrail (``config.validate_inputs``: off/warn/raise).

        Value-level, so it syncs the device — which is exactly why it is a
        policy knob and not always-on (<1% step-overhead budget, module
        docstring of ``torcheval_tpu.config``). Integer and bool inputs
        pass through untouched.
        """
        policy = config.validate_inputs_policy()
        if policy == "off":
            return x
        if isinstance(x, jax.Array):
            if not jnp.issubdtype(x.dtype, jnp.inexact):
                return x
            finite = bool(jnp.all(jnp.isfinite(x)))
        else:
            import numpy as np

            arr = np.asarray(x)
            if not np.issubdtype(arr.dtype, np.inexact):
                return x
            finite = bool(np.all(np.isfinite(arr)))
        if not finite:
            message = (
                f"{type(self).__name__}.update received non-finite values "
                "(NaN/Inf) in a float input "
                "(config.validate_inputs guardrail)"
            )
            if policy == "raise":
                raise ValueError(message)
            import warnings

            warnings.warn(message, RuntimeWarning, stacklevel=4)
        return x

    # ------------------------------------------------------- abstract surface

    @abstractmethod
    def update(self: TSelf, *_: Any, **__: Any) -> TSelf:
        """Accumulate a batch into metric state. Async, no host sync."""

    # --------------------------------------------------------- fusable update

    def _update_plan(self, *args: Any, **kwargs: Any):
        """The fusable factorization of ``update(*args, **kwargs)`` — or
        ``None`` when this metric's update cannot be expressed as one
        (buffered appends with donation, host-side text processing).

        Two forms:

        - a plain tuple ``(kernel, state_names, dynamic[, config])``:
          the update is exactly ``states += kernel(*dynamic, *config)``;
        - an :class:`UpdatePlan` with ``transform=True``: the update is
          ``states = kernel(states, *dynamic, *config)`` (ring-buffer
          column writes, running min/max — anything non-additive), with an
          optional host-side ``finalize`` callback run after the device
          step (cursor advances, host counters).

        Implementations run their input validation eagerly here, so a plan
        that is returned is safe to execute. ``toolkit.update_collection``
        executes many metrics' plans as ONE jitted dispatch; a metric's own
        ``update`` runs its plan through :meth:`_apply_update_plan`.
        """
        return None

    def _apply_update_plan(self: TSelf, plan) -> TSelf:
        """Execute one fusable update plan against this metric's states.
        The trailing ``config`` element may be omitted (defaults to ``()``).
        """
        from torcheval_tpu.metrics._bucket import apply_bucketing
        from torcheval_tpu.metrics._fuse import fused_transform

        donate = self._donation_active()
        if isinstance(plan, UpdatePlan):
            plan = apply_bucketing(plan)
            states = tuple(getattr(self, n) for n in plan.state_names)
            shardings = self._mesh_out_shardings(plan.state_names)
            if plan.transform:
                new_states = fused_transform(
                    plan.kernel, states, plan.dynamic, plan.config,
                    donate=donate, out_shardings=shardings,
                )
            else:
                new_states = fused_accumulate(
                    plan.kernel, states, plan.dynamic, plan.config,
                    donate=donate, out_shardings=shardings,
                )
            for name, value in zip(plan.state_names, new_states):
                setattr(self, name, value)
            if plan.finalize is not None:
                plan.finalize()
            return self
        kernel, state_names, dynamic, *rest = plan
        config = rest[0] if rest else ()
        states = tuple(getattr(self, name) for name in state_names)
        new_states = fused_accumulate(
            kernel, states, dynamic, config, donate=donate,
            out_shardings=self._mesh_out_shardings(state_names),
        )
        for name, value in zip(state_names, new_states):
            setattr(self, name, value)
        return self

    def _mesh_out_shardings(self, state_names) -> Optional[tuple]:
        """Output shardings pinning a mesh-sharded metric's state layout
        through the fused update jits: sharded states keep their
        ``NamedSharding``, the rest stay replicated over the same mesh.
        Without the pin XLA is free to pick a replicated output layout —
        silently gathering the state back to a full per-device copy and
        defeating the size/world memory contract. ``None`` (no
        constraint) off the mesh path."""
        ctx = self._shard_ctx
        if ctx is None or not ctx.is_mesh or not self._sharded_states:
            return None
        if not any(n in self._sharded_states for n in state_names):
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        replicated = NamedSharding(ctx.mesh, PartitionSpec())
        return tuple(
            self._sharded_states[n].sharding
            if n in self._sharded_states
            else replicated
            for n in state_names
        )

    @abstractmethod
    def compute(self) -> TComputeReturn:
        """Finalize the metric value from state. Idempotent."""

    def _prepare_for_merge_state(self) -> None:
        """Pre-sync hook (reference metric.py:109-118).

        List-state metrics override this to concatenate their buffers into a
        single array, cutting the number of collectives issued during sync.
        """

    # ------------------------------------------------------------------ merge

    def merge_state(self: TSelf, metrics: Iterable[TSelf]) -> TSelf:
        """Merge peer replicas' states into self (reference metric.py:99-107).

        Default implementation is driven by the merge kinds registered in
        ``_add_state``; metrics with bespoke semantics (e.g. windowed ring
        buffers, reference window/normalized_entropy.py:232-296) override
        this method or individual kinds via ``_merge_custom_state``.

        Sharded instances (``Metric(shard=...)`` with eager-sharded
        states) route to :meth:`_merge_sharded`: peers are shard CARRIERS
        (each holding one rank's slice plus its routed outbox), and the
        merge REASSEMBLES the logical state instead of reducing replicas.
        """
        metrics = list(metrics)
        if self._sharded_states and self._is_shard_carrier():
            return self._merge_sharded(metrics)
        for other in metrics:
            for name, kind in self._state_name_to_merge_kind.items():
                mine = getattr(self, name)
                theirs = self._place_state(getattr(other, name))
                setattr(self, name, self._merge_one(name, kind, mine, theirs))
        return self

    def _merge_one(
        self, name: str, kind: MergeKind, mine: TState, theirs: TState
    ) -> TState:
        if kind is MergeKind.SUM:
            if isinstance(mine, dict):
                for k, v in theirs.items():
                    mine[k] = mine[k] + v if k in mine else v
                return mine
            return mine + theirs
        if kind is MergeKind.MAX:
            if isinstance(mine, (int, float)):
                return max(mine, theirs)
            return jnp.maximum(mine, theirs)
        if kind is MergeKind.MIN:
            if isinstance(mine, (int, float)):
                return min(mine, theirs)
            return jnp.minimum(mine, theirs)
        if kind is MergeKind.EXTEND:
            mine.extend(theirs)
            return mine
        return self._merge_custom_state(name, mine, theirs)

    def _merge_custom_state(self, name: str, mine: TState, theirs: TState) -> TState:
        raise NotImplementedError(
            f"{type(self).__name__} registered state `{name}` with "
            "MergeKind.CUSTOM but does not override merge_state or "
            "_merge_custom_state."
        )

    # ---------------------------------------------------------- sharded state

    def _is_shard_carrier(self) -> bool:
        """True while the live sharded states hold ONE rank's slice (the
        steady state of an eager sharded metric). False on replicated
        and mesh instances, and after a reassembling merge desharded the
        instance to the logical state."""
        return bool(self._sharded_states) and int(
            getattr(self, "_shard_world", 0)
        ) > 0

    def _own_shard_active(self) -> bool:
        """True when the live states hold exactly this rank's configured
        shard — the precondition for the sharded (routing) update plans.
        A carrier of a FOREIGN rank's shard (a transient sync/restore
        clone) must not be updated; a desharded (logical) instance
        updates through the dense plans instead."""
        if not self._is_shard_carrier():
            return False
        ctx = self._shard_ctx
        if ctx is None or ctx.is_mesh:
            return False
        rk = int(getattr(self, "_shard_rank"))
        wd = int(getattr(self, "_shard_world"))
        if rk == ctx.rank and wd == ctx.world:
            return True
        raise RuntimeError(
            f"{type(self).__name__} holds shard {rk} of world {wd} but is "
            f"configured as rank {ctx.rank} of world {ctx.world}; foreign "
            "shard carriers are merge/sync intermediates and cannot be "
            "updated"
        )

    def _route_active(self, name: str) -> bool:
        """Whether ``update()`` should take the sharded scatter-route
        plan for ``name``: the state is routed, the live shard is this
        rank's own, and the world is > 1 (at world 1 every cell is owned
        — the dense plans are strictly better than filling the outbox
        with dropped slots)."""
        return (
            name in self._routed_states
            and self._shard_ctx is not None
            and self._shard_ctx.world > 1
            and self._own_shard_active()
        )

    def _merge_sharded(self: TSelf, metrics: List[TSelf]) -> TSelf:
        """Reassemble the logical state from shard carriers.

        ``self`` plus every peer is a carrier of one rank's slice (the
        carried rank/world ride the ``_shard_rank``/``_shard_world``
        states, so clones loaded from any rank's payload self-describe).
        Per sharded state: place every carrier's slice into a fresh
        logical array (scatter-ADD, so two carriers of the same rank
        merge like replicas), then apply every carrier's routed outbox
        entries in ascending carried-rank order. Routed states are
        integer counters, so the result is bit-identical to the
        replicated merge oracle regardless of interleaving. Non-sharded
        states merge by their declared kinds; CUSTOM non-sharded scalars
        keep ``self``'s value (sharded families require them
        rank-identical — the owner-partitioned update contract).

        Afterwards ``self`` is DESHARDED (``_shard_rank == -1``): it
        carries the logical state, ``compute()`` works locally, and
        loading its ``state_dict`` back into a sharded working metric
        re-slices to that rank's shard.
        """
        from torcheval_tpu.metrics import shardspec

        carriers = sorted(
            [self] + list(metrics),
            key=lambda c: int(getattr(c, "_shard_rank", -1)),
        )
        worlds = {
            int(getattr(c, "_shard_world", 0)) for c in carriers
        } - {0}
        if len(worlds) > 1:
            raise RuntimeError(
                f"cannot merge shard carriers from different worlds {sorted(worlds)}"
            )
        merged: Dict[str, jax.Array] = {}
        for name, info in self._sharded_states.items():
            names = self._routed_states.get(name)
            if names is not None and names.is_value_lane:
                # FLOAT-value lane: fold per-carrier contributions S_q
                # in carried-rank order — each S_q is the carrier's
                # shard slice plus its per-batch outbox folds, so the
                # addition order equals the replicated oracle's exactly
                # (see shardspec.RoutedInfo)
                merged[name] = self._merge_value_routed_state(
                    name, info, names, carriers
                )
                continue
            logical = jnp.zeros(info.logical_shape, info.dtype)
            for c in carriers:
                value = self._place_state(getattr(c, name))
                rk = int(getattr(c, "_shard_rank", -1))
                wd = int(getattr(c, "_shard_world", 0))
                if rk < 0 or wd <= 0:
                    # an already-logical carrier folds in whole (only
                    # meaningful for SUM-kind counters)
                    logical = logical + value
                    continue
                start, stop = self._shard_ctx.shard_range(
                    info.logical_shape[0], rk, wd
                )
                logical = logical.at[start:stop].add(value)
            if names is not None:
                flat = logical.reshape(-1)
                for c in carriers:
                    cnt = int(getattr(c, names.obh, 0))
                    entries = getattr(c, names.obi)[:cnt]
                    flat = shardspec.apply_outbox_counts(
                        flat, self._place_state(entries)
                    )
                logical = flat.reshape(info.logical_shape)
            merged[name] = logical
        skip = set(self._sharded_states) | self._routed_aux_names()
        skip.update(("_shard_rank", "_shard_world"))
        for other in carriers:
            if other is self:
                continue
            for name, kind in self._state_name_to_merge_kind.items():
                if name in skip or (
                    kind is MergeKind.CUSTOM
                    and name not in self._custom_mergeable_states
                ):
                    continue
                mine = getattr(self, name)
                theirs = self._place_state(getattr(other, name))
                setattr(self, name, self._merge_one(name, kind, mine, theirs))
        for name, value in merged.items():
            setattr(self, name, value)
        self._clear_outboxes()
        self._shard_rank = -1
        self._shard_world = 0
        return self

    def _merge_value_routed_state(
        self, name: str, info, names, carriers
    ) -> jax.Array:
        """One float-value-routed state's reassembling merge (see
        :meth:`_merge_sharded`): ``sum_q S_q`` in carried-rank order,
        ``S_q`` = carrier q's shard slice placed into a fresh logical
        array plus its outbox folded one batch at a time."""
        import numpy as np

        from torcheval_tpu.metrics import shardspec

        col = names.states.index(name)
        logical = jnp.zeros(info.logical_shape, info.dtype)
        for c in carriers:
            value = self._place_state(getattr(c, name))
            rk = int(getattr(c, "_shard_rank", -1))
            wd = int(getattr(c, "_shard_world", 0))
            if rk < 0 or wd <= 0:
                contrib = value
            else:
                start, stop = self._shard_ctx.shard_range(
                    info.logical_shape[0], rk, wd
                )
                contrib = (
                    jnp.zeros(info.logical_shape, info.dtype)
                    .at[start:stop]
                    .set(value)
                )
                cnt = int(getattr(c, names.obh, 0))
                if cnt:
                    nb = int(getattr(c, names.obbh, 0))
                    bounds = shardspec.complete_bounds(
                        np.asarray(getattr(c, names.obb)[:nb]), cnt
                    )
                    contrib = shardspec.apply_outbox_values(
                        contrib.reshape(-1),
                        self._place_state(getattr(c, names.obi))[:cnt],
                        self._place_state(getattr(c, names.obv))[:cnt, col],
                        bounds,
                    ).reshape(info.logical_shape)
            logical = logical + contrib
        return logical

    def _routed_aux_names(self) -> set:
        out = set()
        for names in self._routed_states.values():
            out.update((names.obi, names.obn, names.obh))
            if names.is_value_lane:
                out.update((names.obv, names.obb, names.obc, names.obbh))
        return out

    def _clear_outboxes(self) -> None:
        for names in self._routed_states.values():
            setattr(self, names.obi, jnp.zeros((0,), jnp.int32))
            setattr(
                self,
                names.obn,
                self._place_state(jnp.zeros((), jnp.int32)),
            )
            setattr(self, names.obh, 0)
            if names.is_value_lane:
                setattr(
                    self,
                    names.obv,
                    jnp.zeros((0, len(names.states))),
                )
                setattr(self, names.obb, jnp.zeros((0,), jnp.int32))
                setattr(
                    self,
                    names.obc,
                    self._place_state(jnp.zeros((), jnp.int32)),
                )
                setattr(self, names.obbh, 0)

    def _logical_state(self, name: str) -> jax.Array:
        """The logically-full view of one state.

        Replicated, mesh-sharded (the global array IS logical — XLA holds
        it distributed), and desharded instances return the live state
        untouched. A shard carrier assembles a LOCAL logical view: its
        slice placed at the carried range plus its own outbox entries —
        exactly the contributions this rank observed, so a sharded
        metric's un-synced ``compute()`` equals a replicated metric's
        local compute bit-for-bit (integer counters). Transient: the
        assembled array is not retained.
        """
        value = getattr(self, name)
        info = self._sharded_states.get(name) if self._sharded_states else None
        if info is None or not self._is_shard_carrier():
            return value
        from torcheval_tpu.metrics import shardspec

        rk = int(getattr(self, "_shard_rank"))
        wd = int(getattr(self, "_shard_world"))
        start, stop = self._shard_ctx.shard_range(
            info.logical_shape[0], rk, wd
        )
        logical = (
            jnp.zeros(info.logical_shape, info.dtype).at[start:stop].set(value)
        )
        names = self._routed_states.get(name)
        if names is not None and names.is_value_lane:
            import numpy as np

            cnt = int(getattr(self, names.obh, 0))
            if cnt:
                nb = int(getattr(self, names.obbh, 0))
                bounds = shardspec.complete_bounds(
                    np.asarray(getattr(self, names.obb)[:nb]), cnt
                )
                col = names.states.index(name)
                logical = shardspec.apply_outbox_values(
                    logical.reshape(-1),
                    getattr(self, names.obi)[:cnt],
                    getattr(self, names.obv)[:cnt, col],
                    bounds,
                ).reshape(info.logical_shape)
        elif names is not None:
            cnt = int(getattr(self, names.obh, 0))
            logical = shardspec.apply_outbox_counts(
                logical.reshape(-1), getattr(self, names.obi)[:cnt]
            ).reshape(info.logical_shape)
        return logical

    def _reshard_to_own(self: TSelf) -> TSelf:
        """Re-slice a DESHARDED (logical-carrying) instance back to this
        rank's configured shard — the tail step of a world-size-change
        restore: the elastic merge reassembles the full logical state
        from every old rank's shard + outbox, and each new rank keeps
        only its slice (slices partition the cells, so globally every
        contribution survives exactly once)."""
        ctx = self._shard_ctx
        if not self._sharded_states or ctx is None or ctx.is_mesh:
            return self
        rk = int(getattr(self, "_shard_rank", -1))
        wd = int(getattr(self, "_shard_world", 0))
        if rk == ctx.rank and wd == ctx.world:
            return self
        if rk >= 0 and wd == 1:
            # a world-1 carrier's shard IS the logical state, and its
            # outboxes are structurally empty (every cell was owned) —
            # safe to re-slice like a desharded instance
            if any(
                int(getattr(self, names.obh, 0)) != 0
                for names in self._routed_states.values()
            ):
                raise RuntimeError(
                    "world-1 shard carrier has pending outbox entries; "
                    "refusing to reshard"
                )
        elif rk >= 0:
            raise RuntimeError(
                "reshard requires a desharded (merged) logical state or "
                f"this rank's own shard; live state carries shard {rk} of "
                f"world {wd}"
            )
        for name, info in self._sharded_states.items():
            start, stop = ctx.shard_range(info.logical_shape[0])
            setattr(
                self,
                name,
                jax.lax.slice_in_dim(getattr(self, name), start, stop, axis=0),
            )
        self._clear_outboxes()
        self._shard_rank = ctx.rank
        self._shard_world = ctx.world
        return self

    def _adopt_shard_payload(
        self, state_dict: Dict[str, TState]
    ) -> Dict[str, TState]:
        """Normalize an incoming snapshot for a sharded instance.

        A payload carrying ``_shard_rank >= 0`` is adopted verbatim (the
        live states become that rank's carrier — how sync clones and
        same-world restores work). A LOGICAL payload (``_shard_rank ==
        -1``, or legacy/in-jit dicts whose arrays have the logical
        shapes) is re-sliced to this rank's configured shard with empty
        outboxes — how a merged result or a world-size-change restore
        lands back in a working metric."""
        import numpy as np

        ctx = self._shard_ctx
        # world-1 contexts skip routing entirely (shardspec.enable_routing),
        # so their payloads carry no outbox states; fill empty ones so a
        # strict load into a routed multi-world instance accepts them
        for names in self._routed_states.values():
            state_dict.setdefault(names.obi, jnp.zeros((0,), jnp.int32))
            state_dict.setdefault(names.obn, jnp.zeros((), jnp.int32))
            state_dict.setdefault(names.obh, 0)
            if names.is_value_lane:
                state_dict.setdefault(
                    names.obv, jnp.zeros((0, len(names.states)))
                )
                state_dict.setdefault(names.obb, jnp.zeros((0,), jnp.int32))
                state_dict.setdefault(names.obc, jnp.zeros((), jnp.int32))
                state_dict.setdefault(names.obbh, 0)
        rk = state_dict.get("_shard_rank")
        logical = rk is not None and int(np.asarray(rk)) < 0
        if rk is None:
            # no descriptor: infer from shapes (all-or-nothing)
            shapes = []
            for name, info in self._sharded_states.items():
                value = state_dict.get(name)
                if value is None:
                    continue
                shapes.append(
                    tuple(np.shape(value)) == tuple(info.logical_shape)
                    and tuple(info.logical_shape)
                    != tuple(np.shape(getattr(self, name)))
                )
            logical = bool(shapes) and all(shapes)
            if not logical:
                return state_dict
        if not logical:
            return state_dict
        for name, info in self._sharded_states.items():
            value = state_dict.get(name)
            if value is None:
                continue
            start, stop = ctx.shard_range(info.logical_shape[0])
            state_dict[name] = jnp.asarray(value)[start:stop]
        state_dict["_shard_rank"] = ctx.rank
        state_dict["_shard_world"] = ctx.world
        for names in self._routed_states.values():
            state_dict[names.obi] = jnp.zeros((0,), jnp.int32)
            state_dict[names.obn] = jnp.zeros((), jnp.int32)
            state_dict[names.obh] = 0
            if names.is_value_lane:
                state_dict[names.obv] = jnp.zeros((0, len(names.states)))
                state_dict[names.obb] = jnp.zeros((0,), jnp.int32)
                state_dict[names.obc] = jnp.zeros((), jnp.int32)
                state_dict[names.obbh] = 0
        return state_dict

    # ------------------------------------------------------------------ reset

    def reset(self: TSelf) -> TSelf:
        """Restore every state to its registered default on ``self.device``
        (reference metric.py:120-147). Dict states become auto-zero dicts."""
        for name, default in self._state_name_to_default.items():
            if isinstance(default, dict):
                setattr(
                    self, name, DefaultStateDict(device_descriptor(self._device))
                )
            else:
                # force_copy for the same reason _add_state does: the live
                # state must never alias the registered default, even when
                # donation only gets enabled AFTER this reset
                setattr(
                    self,
                    name,
                    self._place_named(
                        name, self._clone_state(default, force_copy=True)
                    ),
                )
        # a provenance left by a prior (possibly degraded) sync — and the
        # observability step cursor stamped by the last recorded update —
        # describe state this reset just discarded; they must not outlive
        # it (same stale-attribute class as the PR 4 sync_provenance fix);
        # admission-ladder provenance describes the discarded stream too
        self.__dict__.pop("sync_provenance", None)
        self.__dict__.pop("obs_step", None)
        self.__dict__.pop("admission_provenance", None)
        # ... and any PUBLISHED snapshot of it is now a lie: bump the
        # state epoch so a sync plane discards pre-reset merged values
        self._state_epoch = self._state_epoch + 1
        return self

    # ---------------------------------------------------------- serialization

    def state_dict(self) -> Dict[str, TState]:
        """Snapshot of all states (reference metric.py:149-166).

        jax.Arrays are immutable, so the snapshot shares buffers safely —
        the moral equivalent of the reference's ``detach().clone()``.
        """
        return {name: self._clone_state(getattr(self, name)) for name in
                self._state_name_to_default}

    def _sync_state_dict(self) -> Dict[str, TState]:
        """State snapshot for a SYNC payload (``toolkit`` -> ``synclib``).

        Like :meth:`state_dict` — and defaults to it — but free to TRIM
        regions that are provably padding (valid-prefix payload trimming):
        growable example buffers ship their covering power-of-2 bucket
        instead of full capacity (``_buffer.BufferedExamplesMetric``), and
        pre-wrap ring windows ship only their filled prefix
        (``window.WindowedBinaryAUROC``). Contract: loading a trimmed
        snapshot into a fresh clone and merging must be bit-identical to
        doing the same with the full :meth:`state_dict` (pinned by
        tests/metrics/test_payload_trimming.py). Checkpoints always use
        the untrimmed :meth:`state_dict`.

        Sharded metrics inherit the discipline for their routed outboxes:
        the sync ships each outbox sliced to the power-of-2 bucket
        covering its entry count — so the sharded sync wire is
        ``shard (size/world) + O(entries)`` per rank, never the buffer
        capacity, and never the full logical state.
        """
        sd = self.state_dict()
        if self._routed_states:
            for names in self._routed_states.values():
                cnt = int(getattr(self, names.obh, 0))
                keep = 1 << (cnt - 1).bit_length() if cnt > 0 else 0
                buf = sd.get(names.obi)
                if _is_array(buf) and buf.shape[0] > keep:
                    sd[names.obi] = buf[:keep]
                if not names.is_value_lane:
                    continue
                vbuf = sd.get(names.obv)
                if _is_array(vbuf) and vbuf.shape[0] > keep:
                    sd[names.obv] = vbuf[:keep]
                nb = int(getattr(self, names.obbh, 0))
                bkeep = 1 << (nb - 1).bit_length() if nb > 0 else 0
                bbuf = sd.get(names.obb)
                if _is_array(bbuf) and bbuf.shape[0] > bkeep:
                    sd[names.obb] = bbuf[:bkeep]
        return sd

    def load_state_dict(
        self, state_dict: Dict[str, TState], strict: bool = True
    ) -> None:
        """Load a snapshot (reference metric.py:168-210).

        Sharded instances accept two payload forms (see
        :meth:`_adopt_shard_payload`): a self-describing shard carrier's
        snapshot (adopted verbatim) or a logical-full snapshot (re-sliced
        to this rank's configured shard).
        """
        state_dict = dict(state_dict)
        if self._sharded_states and self._shard_ctx is not None and not self._shard_ctx.is_mesh:
            state_dict = self._adopt_shard_payload(state_dict)
        registered = set(self._state_name_to_default)
        provided = set(state_dict)
        if strict and registered != provided:
            missing = registered - provided
            unexpected = provided - registered
            raise RuntimeError(
                "Error(s) in loading state_dict for "
                f"{type(self).__name__}: "
                f"missing keys: {sorted(missing)}, "
                f"unexpected keys: {sorted(unexpected)}."
            )
        for name in registered & provided:
            value = state_dict[name]
            self._check_state_variable_type(name, value)
            # force_copy: the caller keeps its snapshot arrays — the live
            # state must not alias them, or a donated update issued after
            # donation gets enabled would consume the caller's snapshot
            setattr(
                self,
                name,
                self._place_named(
                    name, self._clone_state(value, force_copy=True)
                ),
            )
        # restored state replaces whatever a prior sync produced: drop the
        # stale provenance (the sync path re-attaches its own afterwards)
        # and the stale observability step cursor alike — and invalidate
        # any published sync-plane snapshot of the replaced state. The
        # admission-ladder provenance is stamped per compute() on the
        # stream the restored state replaces, so it goes too.
        self.__dict__.pop("sync_provenance", None)
        self.__dict__.pop("obs_step", None)
        self.__dict__.pop("admission_provenance", None)
        self._state_epoch = self._state_epoch + 1

    # ---------------------------------------------------------------- devices

    # array-valued config attributes (e.g. binned metrics' `threshold`) that
    # must travel with the states on to(); subclasses append names here.
    _extra_device_attrs: tuple = ()

    def to(self: TSelf, device: Union[jax.Device, str], *args: Any, **kwargs: Any) -> TSelf:
        """Move all array states to ``device`` (reference metric.py:212-248)."""
        target = canonicalize_device(device)
        for name in self._state_name_to_default:
            setattr(self, name, self._place_state(getattr(self, name), target))
        for name in self._extra_device_attrs:
            value = getattr(self, name, None)
            if isinstance(value, jax.Array):
                setattr(self, name, jax.device_put(value, target))
        self._device = target
        return self

    # --------------------------------------------------------------- pickling

    def __getstate__(self) -> Dict[str, Any]:
        state = dict(self.__dict__)
        state["_device"] = device_descriptor(self._device)
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        state["_device"] = resolve_device_descriptor(state["_device"])
        self.__dict__.update(state)
        # Unpickled arrays materialize on the process default backend; restore
        # the device invariant so cross-host sync keeps state where declared.
        for name in self._state_name_to_default:
            setattr(self, name, self._place_named(name, getattr(self, name)))
