"""Multi-process ``MultiHostGroup`` sync tests.

Spawns real OS processes that join one JAX distributed job over a localhost
coordinator (``jax.distributed.initialize``) and run the actual pod sync
path — ``multihost_utils.process_allgather`` over the collective backend —
with asymmetric per-rank states. This is the JAX analogue of the reference's
spawned-gloo-worker strategy (reference
utils/test_utils/metric_class_tester.py:292-341, tests/metrics/test_synclib.py).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER = os.path.join(REPO, "tests", "metrics", "_multihost_worker.py")


def parse_result_lines(outputs):
    """Per-rank 'RESULT {json}' payloads from worker outputs (rank order)."""
    results = []
    for rank, out in enumerate(outputs):
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"rank {rank} printed no RESULT line:\n{out[-2000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))
    return results


def _spawn_workers(nproc: int, timeout: float = 300.0):
    """Run the worker on nproc processes via the launcher (the library's own
    multi-process path); return per-rank RESULT dicts."""
    from torcheval_tpu.launcher import launch

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    outputs = launch(WORKER, nproc=nproc, timeout=timeout, env=env)
    return parse_result_lines(outputs)


@pytest.mark.parametrize("nproc", [2, 4])
def test_multihost_sync(nproc):
    results = _spawn_workers(nproc)

    # every rank must agree bit-for-bit on the synced values
    for r in range(1, nproc):
        assert results[r] == results[0], (
            f"rank {r} disagrees with rank 0:\n{results[r]}\nvs\n{results[0]}"
        )

    res = results[0]

    assert res["allgather_array"] == [[r, r + 1] for r in range(nproc)]
    assert res["allgather_object_ok"]

    # tensor state: sum over ranks of (rank+1)
    assert res["sum"] == sum(r + 1 for r in range(nproc))

    # list state with rank-0 empty: sum over ranks of sum(1..rank)
    assert res["list_sum"] == sum(
        i + 1 for r in range(nproc) for i in range(r)
    )

    # dict state: disjoint per-rank keys + one shared summed key
    expected_dict = {f"k{r}": 1.0 for r in range(nproc)}
    expected_dict["shared"] = float(sum(range(nproc)))
    assert res["dict"] == expected_dict

    # float states, slowest-rank merge: sum(10*(r+1)) / max(r+1)
    assert res["throughput"] == pytest.approx(
        sum(10 * (r + 1) for r in range(nproc)) / nproc
    )

    # collection exchange: accuracy over the concatenation of all ranks' data
    correct = total = 0
    for r in range(nproc):
        rng = np.random.default_rng(r)
        x = rng.uniform(size=(32, 5)).astype(np.float32)
        t = rng.integers(0, 5, size=(32,))
        correct += int(np.sum(np.argmax(x, axis=1) == t))
        total += 32
    assert res["coll_acc"] == pytest.approx(correct / total)
    assert res["coll_sum"] == float(sum(range(nproc)))

    assert res["synced_state_dict_sum"] == res["sum"]

    # buffered AUROC with ragged per-rank sample counts == pooled oracle
    import sklearn.metrics as skm

    xs, ts = [], []
    for r in range(nproc):
        rngb = np.random.default_rng(100 + r)
        n_r = 60 * r + 5
        xs.append(rngb.random(n_r).astype(np.float32))
        ts.append((rngb.random(n_r) < 0.5).astype(np.float32))
    expected = skm.roc_auc_score(np.concatenate(ts), np.concatenate(xs))
    assert res["auroc"] == pytest.approx(expected, abs=1e-5)

    # windowed MSE merge semantics == the reference's window-concat merge
    # (reference window/mean_squared_error.py via merge_state), replayed on
    # the reference metrics themselves
    import torch
    from tests.ref_oracle import load_reference_metrics

    REF_M, _ = load_reference_metrics()
    replicas = []
    for r in range(nproc):
        m = REF_M.WindowedMeanSquaredError(
            max_num_updates=4, enable_lifetime=True
        )
        for i in range(2 * r + 3):
            v = (r + 1) * 0.1 * (i + 1)
            m.update(torch.full((8,), v), torch.zeros(8))
        replicas.append(m)
    merged = replicas[0]
    merged.merge_state(replicas[1:])
    exp_life, exp_win = merged.compute()
    assert res["wmse_lifetime"] == pytest.approx(float(exp_life), rel=1e-5)
    assert res["wmse_windowed"] == pytest.approx(float(exp_win), rel=1e-5)
