"""Multi-process ``MultiHostGroup`` sync tests.

Spawns real OS processes that join one JAX distributed job over a localhost
coordinator (``jax.distributed.initialize``) and run the actual pod sync
path — ``multihost_utils.process_allgather`` over the collective backend —
with asymmetric per-rank states. This is the JAX analogue of the reference's
spawned-gloo-worker strategy (reference
utils/test_utils/metric_class_tester.py:292-341, tests/metrics/test_synclib.py).

``test_merge_archetype`` is the VERDICT-r2 matrix: every state/merge
archetype the library uses crosses a real process boundary (wire protocol:
pickle framing, padded ragged gathers, dtype preservation, key ordering),
named per archetype × nproc ∈ {2, 4}. One spawn per nproc is shared by the
whole matrix — each worker computes all legs in one distributed job.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _dump_events_on_failure(obs_recorder):
    """Flake forensics: recorder on for the spawned-process suite — the
    parent-side event tail (subgroup syncs, provenance) rides any failure
    report via the conftest hook."""
    yield

# slow tier: spawned-process sync matrix (~2-5 min); the per-class coverage
# enforcement in _sync_matrix.build_cases still fires at collection time
# in the fast tier
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER = os.path.join(REPO, "tests", "metrics", "_multihost_worker.py")


def parse_result_lines(outputs):
    """Per-rank 'RESULT {json}' payloads from worker outputs (rank order)."""
    results = []
    for rank, out in enumerate(outputs):
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"rank {rank} printed no RESULT line:\n{out[-2000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))
    return results


_CACHE = {}


def _results_for(nproc: int):
    """Spawn the worker matrix once per nproc; every test shares the run."""
    if nproc not in _CACHE:
        from torcheval_tpu.launcher import launch

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        outputs = launch(WORKER, nproc=nproc, timeout=300.0, env=env)
        _CACHE[nproc] = parse_result_lines(outputs)
    return _CACHE[nproc]


# --------------------------------------------------------------------------
# archetype oracles: replay every rank's updates into ONE in-process metric;
# the spawned result must match (update/merge order is immaterial for every
# archetype here)
# --------------------------------------------------------------------------


def _oracle_sum(nproc):
    return float(sum(r + 1 for r in range(nproc)))


def _oracle_list_extend(nproc):
    return float(sum(i + 1 for r in range(nproc) for i in range(r)))


def _oracle_dict_disjoint(nproc):
    d = {f"k{r}": 1.0 for r in range(nproc)}
    d["shared"] = float(sum(range(nproc)))
    return d


def _oracle_max(nproc):
    return float(max((r * 7) % (nproc + 2) for r in range(nproc)))


def _oracle_min(nproc):
    return float(min(-((r * 7) % (nproc + 2)) for r in range(nproc)))


def _oracle_throughput_float_max(nproc):
    # SUM(processed) / MAX(elapsed): the slowest rank bounds the pod
    return sum(10 * (r + 1) for r in range(nproc)) / nproc


def _oracle_buffered_auroc_extend(nproc):
    import sklearn.metrics as skm

    xs, ts = [], []
    for r in range(nproc):
        rngb = np.random.default_rng(100 + r)
        n_r = 60 * r + 5
        xs.append(rngb.random(n_r).astype(np.float32))
        ts.append((rngb.random(n_r) < 0.5).astype(np.float32))
    return float(skm.roc_auc_score(np.concatenate(ts), np.concatenate(xs)))


def _oracle_binned_counters(nproc):
    import jax.numpy as jnp

    from torcheval_tpu.metrics import BinaryBinnedAUPRC

    m = BinaryBinnedAUPRC(threshold=7)
    for r in range(nproc):
        rng = np.random.default_rng(200 + r)
        n = 40 + 10 * r
        m.update(
            jnp.asarray(rng.random(n).astype(np.float32)),
            jnp.asarray((rng.random(n) < 0.4).astype(np.float32)),
        )
    return float(m.compute())


def _oracle_retrieval_multiquery(nproc):
    import jax.numpy as jnp

    from torcheval_tpu.metrics import RetrievalPrecision

    m = RetrievalPrecision(k=2, num_queries=3, empty_target_action="neg")
    for r in range(nproc):
        rng = np.random.default_rng(300 + r)
        n = 6 + 2 * r
        scores = rng.random(n).astype(np.float32)
        labels = (rng.random(n) < 0.5).astype(np.float32)
        indexes = np.where(np.arange(n) % 2 == 0, r % 3, (r + 1) % 3)
        m.update(jnp.asarray(scores), jnp.asarray(labels), indexes=indexes)
    return [float(v) for v in m.compute()]


def _oracle_ne_per_task(nproc):
    import jax.numpy as jnp

    from torcheval_tpu.metrics import BinaryNormalizedEntropy

    m = BinaryNormalizedEntropy(num_tasks=2)
    for r in range(nproc):
        rng = np.random.default_rng(400 + r)
        n = 16 + 8 * r
        m.update(
            jnp.asarray(rng.uniform(0.01, 0.99, size=(2, n)).astype(np.float32)),
            jnp.asarray((rng.random((2, n)) < 0.5).astype(np.float32)),
        )
    return [float(v) for v in m.compute()]


def _oracle_window_custom(nproc):
    import torch

    from tests.ref_oracle import load_reference_metrics

    REF_M, _ = load_reference_metrics()
    replicas = []
    for r in range(nproc):
        m = REF_M.WindowedMeanSquaredError(
            max_num_updates=4, enable_lifetime=True
        )
        for i in range(2 * r + 3):
            v = (r + 1) * 0.1 * (i + 1)
            m.update(torch.full((8,), v), torch.zeros(8))
        replicas.append(m)
    merged = replicas[0]
    merged.merge_state(replicas[1:])
    life, win = merged.compute()
    return [float(life), float(win)]


# archetype -> (worker result key(s), oracle)
ARCHETYPES = {
    "scalar_sum": (("sum",), _oracle_sum),
    "scalar_max": (("max",), _oracle_max),
    "scalar_min": (("min",), _oracle_min),
    "list_extend_with_empty_rank": (("list_sum",), _oracle_list_extend),
    "dict_disjoint_keys": (("dict",), _oracle_dict_disjoint),
    "throughput_float_max": (("throughput",), _oracle_throughput_float_max),
    "buffered_extend_ragged": (("auroc",), _oracle_buffered_auroc_extend),
    "binned_sum_counters": (("binned_auprc",), _oracle_binned_counters),
    "retrieval_multiquery_custom": (
        ("retrieval_precision",), _oracle_retrieval_multiquery
    ),
    "per_task_vector_sum": (("normalized_entropy",), _oracle_ne_per_task),
    "window_ring_custom": (
        ("wmse_lifetime", "wmse_windowed"), _oracle_window_custom
    ),
}


@pytest.mark.parametrize("nproc", [2, 4])
@pytest.mark.parametrize("archetype", sorted(ARCHETYPES))
def test_merge_archetype(archetype, nproc):
    """Every merge archetype must survive the real spawned-process wire."""
    results = _results_for(nproc)
    keys, oracle = ARCHETYPES[archetype]
    expected = oracle(nproc)
    got = [results[0][k] for k in keys]
    if len(keys) == 1:
        got = got[0]
    else:
        got = [g for g in got]
        expected = list(expected)
    # every rank must agree bit-for-bit before comparing to the oracle
    for r in range(1, nproc):
        for k in keys:
            assert results[r][k] == results[0][k], (
                f"rank {r} disagrees on {archetype}/{k}"
            )
    if isinstance(expected, dict):
        assert got == expected
    else:
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("nproc", [2, 4])
def test_multihost_sync(nproc):
    """Raw collective legs + batched collection exchange + synced dicts."""
    results = _results_for(nproc)

    # every rank must agree bit-for-bit on every synced value
    for r in range(1, nproc):
        assert results[r] == results[0], (
            f"rank {r} disagrees with rank 0:\n{results[r]}\nvs\n{results[0]}"
        )

    res = results[0]
    assert res["allgather_array"] == [[r, r + 1] for r in range(nproc)]
    assert res["allgather_object_ok"]

    # collection exchange: accuracy over the concatenation of all ranks' data
    correct = total = 0
    for r in range(nproc):
        rng = np.random.default_rng(r)
        x = rng.uniform(size=(32, 5)).astype(np.float32)
        t = rng.integers(0, 5, size=(32,))
        correct += int(np.sum(np.argmax(x, axis=1) == t))
        total += 32
    assert res["coll_acc"] == pytest.approx(correct / total)
    assert res["coll_sum"] == float(sum(range(nproc)))
    assert res["synced_state_dict_sum"] == res["sum"]


SUBGROUP_WORKER = os.path.join(
    REPO, "tests", "metrics", "_multihost_subgroup_worker.py"
)


def test_subgroup_sync_over_the_wire():
    """ISSUE acceptance: ``sync_and_compute(metric, process_group=
    subgroup)`` over 2 of 4 SPAWNED ranks matches the reference's
    subgroup semantics — members gather member states (KV-store
    collectives, no whole-job XLA gather), non-members return their local
    metric untouched — exercised through sync-matrix metrics, under
    fault injection, and through the hierarchical two-level group."""
    from torcheval_tpu.launcher import launch

    from tests.metrics._sync_matrix import build_rank_replicas, to_jsonable

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    outputs = launch(SUBGROUP_WORKER, nproc=4, timeout=600.0, env=env)
    results = parse_result_lines(outputs)

    def oracle(name, ranks):
        replicas = build_rank_replicas(name, 4)
        merged = replicas[ranks[0]]
        merged.merge_state([replicas[r] for r in ranks[1:]])
        return to_jsonable(merged.compute())

    def close(a, b):
        if isinstance(a, list) and isinstance(b, list):
            return len(a) == len(b) and all(close(x, y) for x, y in zip(a, b))
        if isinstance(a, float) and isinstance(b, float):
            if np.isnan(a) and np.isnan(b):
                return True
            return bool(np.isclose(a, b, rtol=1e-4, atol=1e-5))
        return a == b

    for name in ("MulticlassAccuracy", "BinaryAUROC", "Throughput"):
        want_members = oracle(name, [1, 2])
        # both members agree bit-for-bit, and match the oracle
        assert results[1][f"sub12/{name}"] == results[2][f"sub12/{name}"]
        assert close(results[1][f"sub12/{name}"], want_members), name
        for r in (0, 3):  # non-members: local metric untouched
            local = to_jsonable(build_rank_replicas(name, 4)[r].compute())
            assert close(results[r][f"sub12/{name}"], local), (name, r)
    assert [results[r]["sub12/is_member"] for r in range(4)] == [
        False, True, True, False,
    ]

    want_comp = oracle("MulticlassAccuracy", [0, 3])
    assert results[0]["sub03/MulticlassAccuracy"] == results[3][
        "sub03/MulticlassAccuracy"
    ]
    assert close(results[0]["sub03/MulticlassAccuracy"], want_comp)

    # fault injection over the subgroup: scripted transient, retried
    want_members = oracle("MulticlassAccuracy", [1, 2])
    for r in (1, 2):
        assert close(results[r]["faulted/MulticlassAccuracy"], want_members)
        assert results[r]["faulted/retries"] >= 1

    # hierarchical == flat over all ranks; only leaders touch level 2
    want_all = oracle("MulticlassAccuracy", [0, 1, 2, 3])
    for r in range(4):
        assert close(results[r]["hier/MulticlassAccuracy"], want_all)
    assert [results[r]["hier/leader_collectives"] for r in range(4)] == [
        2, 0, 2, 0,
    ]


MATRIX_WORKER = os.path.join(
    REPO, "tests", "metrics", "_multihost_sync_matrix_worker.py"
)


def _matrix_results():
    if "matrix" not in _CACHE:
        from torcheval_tpu.launcher import launch

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        try:
            outputs = launch(MATRIX_WORKER, nproc=2, timeout=900.0, env=env)
            _CACHE["matrix"] = parse_result_lines(outputs)
        except Exception as e:  # cache the failure: don't respawn 58 times
            _CACHE["matrix"] = e
    if isinstance(_CACHE["matrix"], Exception):
        raise _CACHE["matrix"]
    return _CACHE["matrix"]


def _metric_case_names():
    from tests.metrics._sync_matrix import build_cases

    return sorted(build_cases())


@pytest.mark.parametrize("name", _metric_case_names())
def test_every_metric_class_syncs(name):
    """Reference bar: every metric class crosses a real process boundary
    (reference metric_class_tester.py:292-341 spawns gloo workers per
    metric). One spawned 2-rank job carries all ~58 classes; each synced
    result must equal the in-process merge_state oracle on the same data.
    """
    from tests.metrics._sync_matrix import build_cases, run_case, to_jsonable

    results = _matrix_results()
    got = results[0][name]
    assert results[1][name] == got, f"ranks disagree on {name}"
    assert not (isinstance(got, dict) and "error" in got), got

    factory, gen = build_cases()[name]
    replicas = [run_case(factory(), gen, r) for r in range(2)]
    replicas[0].merge_state(replicas[1:])
    expected = to_jsonable(replicas[0].compute())

    def close(a, b):
        if isinstance(a, list) and isinstance(b, list):
            return len(a) == len(b) and all(close(x, y) for x, y in zip(a, b))
        if isinstance(a, float) and isinstance(b, float):
            if np.isnan(a) and np.isnan(b):
                return True
            return bool(np.isclose(a, b, rtol=1e-4, atol=1e-5))
        return a == b

    assert close(got, expected), f"{name}: synced {got} != merged {expected}"
