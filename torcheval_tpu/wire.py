"""Quantized wire ladder: the ``exact | bf16 | int8`` payload codecs.

Every sync tier ships metric state over some wire — the eager packed
protocol (``metrics.synclib``), the in-jit EXTEND/reduce-scatter path
(``metrics.sharded``), and the cross-region federation deltas
(``federation.py``). This module is the ONE place that knows how a float
payload is narrowed for that wire, as a three-rung ladder:

- ``"exact"``  — raw bytes, bit-exact (the default; every sync is
  exactness-preserving unless a family opts down the ladder);
- ``"bf16"``   — dense bfloat16 cast, ~2x fewer bytes, ~3 significant
  decimal digits (the historical ``config.sync_compression`` policy);
- ``"int8"``   — EQuARX-style blockwise int8 (arxiv 2506.17615): values
  quantize to int8 against a PER-BLOCK float32 scale
  (``scale = amax(block) / 127``), ~3.6x fewer bytes at the default
  32-element block, with a HARD per-element error bound of
  ``amax(block) / 254`` (round-to-nearest of ``x / scale``).

Integer payloads NEVER quantize — pure-integer counter states are
bit-exact at every rung (the quantizer is a pass-through for them), so
only score/histogram-bearing float families pay any precision at all.

Rungs are chosen PER FAMILY (metric class name) via
``config.wire_ladder()``; the process-wide :data:`LADDER` registry then
caps each family's effective rung from MEASURED evidence: a
``DriftSpec`` budget breach (``obs/quality.py``) calls
:func:`note_budget_breach`, which steps the family one rung up the
ladder toward ``exact`` (int8 -> bf16 -> exact) and emits a typed
:class:`~torcheval_tpu.obs.events.WireTierEvent`. Lossiness is opt-in
and evidence-revoked — the EQuARX posture gated by PR 13's continuously
measured error budgets instead of assumed bounds.

The numpy codec here is the eager/federation wire; the ``jnp`` twins
(``quantize_blockwise_jit`` / ``pack_wire`` / ``unpack_wire``) are
traceable and live INSIDE the jitted step program so the in-jit tier
quantizes with zero added collectives (one uint8 gather replaces one
float gather — ``metrics/sharded.py``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "RUNGS",
    "LADDER",
    "WireLadder",
    "dequantize_blockwise",
    "effective_rung",
    "int8_error_bound",
    "int8_wire_bytes",
    "note_budget_breach",
    "quantize_blockwise",
    "rung_index",
]

# Least -> most lossy. "Falling back UP the ladder" means moving left.
RUNGS: Tuple[str, ...] = ("exact", "bf16", "int8")

_RUNG_INDEX = {rung: i for i, rung in enumerate(RUNGS)}
# legacy config.sync_compression spelling for the exact rung
_RUNG_INDEX["off"] = 0


def rung_index(rung: str) -> int:
    """Ladder position (0 = exact/lossless, higher = lossier).
    Accepts the legacy ``"off"`` spelling for ``"exact"``."""
    try:
        return _RUNG_INDEX[rung]
    except KeyError:
        raise ValueError(
            f"unknown wire rung {rung!r}; expected one of {RUNGS}"
        ) from None


def normalize_rung(rung: str) -> str:
    """Canonical rung name (maps legacy ``"off"`` -> ``"exact"``)."""
    return RUNGS[rung_index(rung)]


# ------------------------------------------------------------ int8 codec

def _nblocks(size: int, block: int) -> int:
    return -(-max(int(size), 1) // int(block))


def int8_wire_bytes(size: int, block: int) -> int:
    """Wire bytes the int8 rung ships for ``size`` elements: one int8
    per element (padded to a whole block) plus one f32 scale per block."""
    nb = _nblocks(size, block)
    return nb * int(block) + 4 * nb


# The codec's scale is defined as a MULTIPLY by this f32 constant (not
# a divide by 127): IEEE-754 pins a single multiply bit-exactly across
# numpy and XLA, whereas XLA strength-reduces division-by-constant into
# a reciprocal multiply that lands one ULP away from numpy's divide.
_RECIP127 = np.float32(1.0 / 127.0)


def quantize_blockwise(
    a: np.ndarray, block: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Blockwise int8 quantization of a float array (numpy, eager wire).

    Returns ``(q, scales)``: ``q`` int8 of shape ``[nblocks * block]``
    (the input flattened and zero-padded to whole blocks) and ``scales``
    float32 of shape ``[nblocks]`` with ``scale = amax(block) / 127``
    (0.0 for all-zero blocks). Dequantization is ``q * scale``; the
    per-element error is bounded by ``scale / 2 = amax / 254``.

    Quantized codes live on ``[-127, 127]``; ``-128`` is reserved as
    the NON-FINITE sentinel. A ``±inf`` slot (a buffer's neutral fill)
    or NaN quantizes to ``-128``, is excluded from the block's amax (one
    fill slot must not poison its block's scale), and its exact float32
    value travels in a scan-order side list
    (:func:`nonfinite_exceptions`) that
    :func:`dequantize_blockwise` splices back — non-finite payloads
    reconstruct EXACTLY at the int8 rung.
    """
    block = int(block)
    flat = np.ascontiguousarray(a, dtype=np.float32).reshape(-1)
    nb = _nblocks(flat.size, block)
    padded = np.zeros(nb * block, dtype=np.float32)
    padded[: flat.size] = flat
    blocks = padded.reshape(nb, block)
    finite = np.isfinite(blocks)
    amax = np.abs(np.where(finite, blocks, 0.0)).max(axis=1)
    scales = (amax * _RECIP127).astype(np.float32)
    safe = np.where(scales > 0, scales, np.float32(1.0))
    ratio = np.round(np.where(finite, blocks, 0.0) / safe[:, None])
    q = np.clip(ratio, -127, 127).astype(np.int8)
    q = np.where(finite, q, np.int8(-128))
    return q.reshape(-1), scales


def nonfinite_exceptions(a: np.ndarray) -> np.ndarray:
    """The scan-order float32 side list of ``a``'s non-finite elements —
    the values :func:`quantize_blockwise` marked ``-128``."""
    flat = np.ascontiguousarray(a, dtype=np.float32).reshape(-1)
    return flat[~np.isfinite(flat)]


def dequantize_blockwise(
    q: np.ndarray,
    scales: np.ndarray,
    size: int,
    dtype: Any = np.float32,
    exceptions: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Inverse of :func:`quantize_blockwise` — returns the first
    ``size`` elements as a flat array of ``dtype``. ``exceptions`` is
    the scan-order non-finite side list (``-128`` sentinels splice
    their exact values back; without it sentinels read NaN)."""
    nb = int(scales.size)
    block = q.size // nb if nb else 0
    out = (
        q.reshape(nb, block).astype(np.float32)
        * scales.astype(np.float32)[:, None]
    ).reshape(-1)[: int(size)]
    sentinel = np.asarray(q).reshape(-1)[: int(size)] == -128
    if sentinel.any():
        out[sentinel] = (
            np.asarray(exceptions, dtype=np.float32)
            if exceptions is not None and np.size(exceptions)
            else np.float32(np.nan)
        )
    return out.astype(dtype)


def int8_error_bound(a: np.ndarray, block: int) -> float:
    """The codec's hard max-abs-error bound for ``a``: the largest
    per-block ``amax / 254`` (what a round-to-nearest int8 grid with
    ``scale = amax / 127`` can be off by, per element)."""
    flat = np.ascontiguousarray(a, dtype=np.float32).reshape(-1)
    if flat.size == 0:
        return 0.0
    nb = _nblocks(flat.size, int(block))
    padded = np.zeros(nb * int(block), dtype=np.float32)
    padded[: flat.size] = flat
    blocks = padded.reshape(nb, int(block))
    # finite-masked, mirroring quantize_blockwise: the bound claims
    # nothing for non-finite elements (which never ride int8)
    amax = np.abs(np.where(np.isfinite(blocks), blocks, 0.0)).max(axis=1)
    return float(np.float64(amax.max()) / 254.0)


# ------------------------------------------------- in-jit (traceable) twins

def quantize_blockwise_jit(x, block: int):
    """Traceable twin of :func:`quantize_blockwise` (shapes static at
    trace time). Returns ``(q int8 [nb*block], scales f32 [nb])``."""
    import jax.numpy as jnp

    block = int(block)
    flat = jnp.reshape(x.astype(jnp.float32), (-1,))
    nb = _nblocks(flat.size, block)
    padded = jnp.pad(flat, (0, nb * block - flat.size))
    blocks = jnp.reshape(padded, (nb, block))
    # finite-masked like the numpy twin, but with no exceptions side
    # list (a traced shape cannot depend on the non-finite count): a
    # non-finite element quantizes to 0. In-jit int8 therefore wants
    # finite payloads — which EXTEND trim guarantees for the valid
    # prefix; only neutral-fill pad slots are affected.
    finite = jnp.isfinite(blocks)
    amax = jnp.max(jnp.abs(jnp.where(finite, blocks, 0.0)), axis=1)
    scales = amax * jnp.float32(_RECIP127)
    safe = jnp.where(scales > 0, scales, jnp.float32(1.0))
    ratio = jnp.round(jnp.where(finite, blocks, 0.0) / safe[:, None])
    q = jnp.clip(ratio, -127, 127).astype(jnp.int8)
    return jnp.reshape(q, (-1,)), scales


def pack_wire(q, scales):
    """Bit-pack ``(q int8 [n], scales f32 [nb])`` into ONE flat uint8
    buffer (``n + 4 * nb`` bytes) — the single-gather wire layout the
    in-jit tier ships, so quantization adds zero collectives."""
    from jax import lax
    import jax.numpy as jnp

    qb = lax.bitcast_convert_type(q, jnp.uint8)
    sb = jnp.reshape(lax.bitcast_convert_type(scales, jnp.uint8), (-1,))
    return jnp.concatenate([qb, sb])


def unpack_wire(wire, nblocks: int, block: int):
    """Inverse of :func:`pack_wire` for one replica's row. Returns the
    dequantized flat float32 array of ``nblocks * block`` elements."""
    from jax import lax
    import jax.numpy as jnp

    n = int(nblocks) * int(block)
    q = lax.bitcast_convert_type(wire[:n], jnp.int8)
    sb = jnp.reshape(wire[n : n + 4 * int(nblocks)], (int(nblocks), 4))
    scales = lax.bitcast_convert_type(sb, jnp.float32)
    return (
        jnp.reshape(q.astype(jnp.float32), (int(nblocks), int(block)))
        * scales[:, None]
    ).reshape(-1)


# -------------------------------------------------- the fallback registry

class WireLadder:
    """Process-wide per-family effective-rung registry.

    The CONFIGURED rung comes from ``config.wire_ladder()``; this
    registry holds the measured-evidence CAP a drift-budget breach
    imposes on top of it. ``effective_rung`` is the least lossy of the
    two — a family never rides a lossier wire than either its
    configuration or its error budget allows. Thread-safe: syncs read
    while the monitor's check hook writes.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._caps: Dict[str, int] = {}  # tev: guarded-by=_lock

    def effective_rung(self, family: str, configured: str) -> str:
        ci = rung_index(configured)
        with self._lock:
            cap = self._caps.get(family, len(RUNGS) - 1)
        return RUNGS[min(ci, cap)]

    def cap(self, family: str) -> Optional[str]:
        """The family's breach-imposed cap (None = never breached)."""
        with self._lock:
            idx = self._caps.get(family)
        return None if idx is None else RUNGS[idx]

    def note_budget_breach(
        self, family: str, *, series: str = "", breach: str = ""
    ) -> Optional[Tuple[str, str]]:
        """A measured error budget was breached for ``family``: step its
        effective rung one rung toward ``exact`` and emit a
        :class:`~torcheval_tpu.obs.events.WireTierEvent`. Returns
        ``(from_rung, to_rung)``, or None when already at ``exact``
        (nothing left to fall back to — no event)."""
        from torcheval_tpu import config

        configured = config.wire_rung_for(family)
        with self._lock:
            cur = min(
                rung_index(configured),
                self._caps.get(family, len(RUNGS) - 1),
            )
            if cur <= 0:
                return None
            self._caps[family] = cur - 1
        prev_rung, new_rung = RUNGS[cur], RUNGS[cur - 1]
        from torcheval_tpu.obs.events import WireTierEvent
        from torcheval_tpu.obs.recorder import RECORDER

        RECORDER.record(
            WireTierEvent(
                family=family,
                series=series,
                prev_tier=prev_rung,
                tier=new_rung,
                breach=breach,
            )
        )
        return prev_rung, new_rung

    def reset(self, family: Optional[str] = None) -> None:
        """Lift the breach cap for ``family`` (or every family) — e.g.
        after a re-baseline (``freeze_reference``) re-arms the budget."""
        with self._lock:
            if family is None:
                self._caps.clear()
            else:
                self._caps.pop(family, None)

    def counters(self) -> Dict[str, Any]:
        """The ``wire`` counter-source payload (flat, exporter-ready):
        the configured ladder plus every breach-imposed family cap."""
        from torcheval_tpu import config

        with self._lock:
            caps = dict(self._caps)
        out: Dict[str, Any] = {
            "default_rung": config.wire_rung_for("*"),
            "block_size": config.wire_block_size(),
            "fallback_families": len(caps),
        }
        for family, idx in sorted(caps.items()):
            out[f"cap_{family}"] = RUNGS[idx]
        return out


LADDER = WireLadder()


def effective_rung(family: str) -> str:
    """The rung ``family`` rides RIGHT NOW: its configured ladder rung
    (``config.wire_ladder()``) capped by any drift-breach fallback."""
    from torcheval_tpu import config

    return LADDER.effective_rung(family, config.wire_rung_for(family))


def note_budget_breach(
    family: str, *, series: str = "", breach: str = ""
) -> Optional[Tuple[str, str]]:
    """Module-level convenience for :meth:`WireLadder.note_budget_breach`
    on the process-wide :data:`LADDER`."""
    return LADDER.note_budget_breach(family, series=series, breach=breach)
