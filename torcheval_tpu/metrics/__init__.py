from torcheval_tpu.metrics import functional
from torcheval_tpu.metrics.aggregation import AUC, Cat, Max, Mean, Min, Sum, Throughput
from torcheval_tpu.metrics.classification import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.metrics.metric import Metric

__all__ = [
    # base interface
    "Metric",
    # functional metrics
    "functional",
    # class metrics
    "AUC",
    "BinaryAccuracy",
    "Cat",
    "Max",
    "Mean",
    "Min",
    "MulticlassAccuracy",
    "MultilabelAccuracy",
    "Sum",
    "Throughput",
    "TopKMultilabelAccuracy",
]
