from torcheval_tpu.ops.fused_auc import (
    fused_auc,
    fused_auc_histogram,
)

__all__ = ["fused_auc", "fused_auc_histogram"]
