"""Weighted calibration: sum(w * pred) / sum(w * label).

Parity: reference torcheval/metrics/functional/ranking/weighted_calibration.py
(`weighted_calibration` :12-57, `_weighted_calibration_update` :60-78,
`_weighted_calibration_input_check` :93-113).
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import resolve_weight, to_jax, to_jax_float


@jax.jit
def _wc_update_scalar(
    input: jax.Array, target: jax.Array, weight: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    return weight * jnp.sum(input, axis=-1), weight * jnp.sum(target, axis=-1)


@jax.jit
def _wc_update_tensor(
    input: jax.Array, target: jax.Array, weight: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    return jnp.sum(weight * input, axis=-1), jnp.sum(weight * target, axis=-1)


def _weighted_calibration_update(
    input,
    target,
    weight: Union[float, int, jax.Array],
    *,
    num_tasks: int,
) -> Tuple[jax.Array, jax.Array]:
    input, target = to_jax_float(input), to_jax_float(target)
    _weighted_calibration_input_check(input, target, weight, num_tasks)
    is_scalar, weight_arr = resolve_weight(weight, input)
    if is_scalar:
        return _wc_update_scalar(input, target, weight_arr)
    return _wc_update_tensor(input, target, weight_arr)


def _weighted_calibration_input_check(
    input: jax.Array,
    target: jax.Array,
    weight: Union[float, int, jax.Array],
    num_tasks: int,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            f"`input` shape ({input.shape}) is different from `target` shape "
            f"({target.shape})"
        )
    if num_tasks == 1:
        if input.ndim > 1:
            raise ValueError(
                "`num_tasks = 1`, `input` is expected to be one-dimensional "
                f"tensor, but got shape ({input.shape})."
            )
    elif input.ndim == 1 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`num_tasks = {num_tasks}`, `input`'s shape is expected to be "
            f"({num_tasks}, num_samples), but got shape ({input.shape})."
        )


def weighted_calibration(
    input,
    target,
    weight: Union[float, int, jax.Array] = 1.0,
    *,
    num_tasks: int = 1,
) -> jax.Array:
    """Weighted calibration = sum(input * weight) / sum(target * weight).

    Class version: ``torcheval_tpu.metrics.WeightedCalibration``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import weighted_calibration
        >>> weighted_calibration(jnp.array([0.8, 0.4, 0.3, 0.8, 0.7, 0.6]),
        ...                      jnp.array([1, 1, 0, 0, 1, 0]))
        Array(1.2, dtype=float32)
    """
    weighted_input_sum, weighted_target_sum = _weighted_calibration_update(
        input, target, weight, num_tasks=num_tasks
    )
    return weighted_input_sum / weighted_target_sum
