from torcheval_tpu.ops.fused_auc import (
    fused_auc,
    fused_auc_histogram,
    fused_auc_histogram_accumulate,
)
from torcheval_tpu.ops.histogram import bincount, histogram
from torcheval_tpu.ops.segment import segment_count, segment_max, segment_sum
from torcheval_tpu.ops.topk import topk

__all__ = [
    "bincount",
    "fused_auc",
    "fused_auc_histogram",
    "fused_auc_histogram_accumulate",
    "histogram",
    "segment_count",
    "segment_max",
    "segment_sum",
    "topk",
]
