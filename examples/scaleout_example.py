"""Evaluating with every scale-out axis: sp, pp, and ep in one script.

Beyond-parity workload (the reference is single-model-parallel only): a
long sequence evaluated with exact ring attention over a sequence-parallel
mesh, a deep MLP streamed through a GPipe pipeline, and an MoE block routed
over an expert-parallel mesh — with jitted metric updates consuming the
sharded outputs in the SAME compiled program each time. Runs on any device
count: a TPU slice, or the 8-device virtual CPU platform
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""


import os as _os
import sys as _sys

# file-relative fallback: `python -m examples.<name>` resolves imports from
# the CWD, not this directory, so `_backend` needs the examples dir on
# sys.path (direct `python examples/<name>.py` runs already have it)
_here = _os.path.dirname(_os.path.abspath(__file__))
_sys.path.append(_here)
_sys.path.append(_os.path.dirname(_here))  # repo root: uninstalled checkouts

from _backend import ensure_backend

ensure_backend()

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 jax keeps it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from torcheval_tpu.metrics import MeanSquaredError, MulticlassAccuracy, Perplexity
from torcheval_tpu.metrics.functional.classification.accuracy import (
    _multiclass_accuracy_update,
)
from torcheval_tpu.metrics.functional.text.perplexity import (
    _perplexity_update_jit,
)
from torcheval_tpu.parallel import moe_apply, pipeline_apply, ring_attention


def main() -> None:
    devices = jax.devices()
    if len(devices) == 1 and jax.devices("cpu"):
        devices = jax.devices("cpu")
    n = len(devices)
    devs = np.array(devices)
    rng = np.random.default_rng(0)
    print(f"devices: {n}")

    # ---- sp: ring attention over a sequence-sharded eval batch ----------
    sp_mesh = Mesh(devs, ("sp",))
    batch, seq, heads, dim, vocab = 2, 8 * n, 2, 16, 32
    q, k, v = (
        jnp.asarray(rng.normal(size=(batch, seq, heads, dim)), jnp.float32)
        for _ in range(3)
    )
    w_out = jnp.asarray(
        rng.normal(size=(heads * dim, vocab)) * 0.2, jnp.float32
    )
    targets = jnp.asarray(rng.integers(0, vocab, (batch, seq)))

    @jax.jit
    @partial(
        shard_map, mesh=sp_mesh,
        in_specs=(
            P(None, "sp", None, None), P(None, "sp", None, None),
            P(None, "sp", None, None), P(), P(None, "sp"),
        ),
        out_specs=P(),
    )
    def sp_eval(q, k, v, w_out, tg):
        attn = ring_attention(q, k, v, axis_name="sp", causal=True)
        logits = attn.reshape(*attn.shape[:2], -1) @ w_out
        nll, count = _perplexity_update_jit(logits, tg, None)
        return jax.lax.psum(
            jnp.stack([nll, count.astype(jnp.float32)]), "sp"
        )

    nll, count = np.asarray(sp_eval(q, k, v, w_out, targets))
    ppl = Perplexity()
    ppl.load_state_dict(
        {"sum_log_probs": jnp.asarray(nll), "num_total": jnp.asarray(count)}
    )
    print(f"sp ring-attention perplexity={float(ppl.compute()):.3f} "
          f"over {seq}-token sequences on {n} shards")

    # ---- pp: deep stack pipelined over all devices ----------------------
    pp_mesh = Mesh(devs, ("pp",))
    n_micro, mb, width = 4, 4, 16
    stage_params = {
        "w": jnp.asarray(
            rng.normal(size=(n, width, width)) * 0.5, jnp.float32
        ),
    }
    stage_fn = lambda p, h: jnp.tanh(h @ p["w"])  # noqa: E731
    xs = jnp.asarray(rng.normal(size=(n_micro, mb, width)), jnp.float32)
    cls_targets = jnp.asarray(rng.integers(0, width, (n_micro, mb)))

    @jax.jit
    @partial(
        shard_map, mesh=pp_mesh, in_specs=(P("pp"), P(), P()), out_specs=P()
    )
    def pp_eval(stacked, x, tg):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked)
        logits = pipeline_apply(stage_fn, local, x, axis_name="pp")
        nc, nt = _multiclass_accuracy_update(
            logits.reshape(-1, width), tg.reshape(-1), "micro", None, 1
        )
        return jnp.stack([nc, nt])

    nc, nt = np.asarray(pp_eval(stage_params, xs, cls_targets))
    acc = MulticlassAccuracy()
    acc.load_state_dict(
        {"num_correct": jnp.asarray(nc), "num_total": jnp.asarray(nt)}
    )
    print(f"pp pipeline accuracy={float(acc.compute()):.3f} "
          f"({n} stages, {n_micro} microbatches)")

    # ---- ep: MoE layer routed across all devices ------------------------
    ep_mesh = Mesh(devs, ("ep",))
    tok_per_shard, hid = 8, 32
    wg = jnp.asarray(rng.normal(size=(width, n)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(n, width, hid)) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(n, hid, width)) * 0.3, jnp.float32)
    toks = jnp.asarray(
        rng.normal(size=(n * tok_per_shard, width)), jnp.float32
    )
    clean = jnp.asarray(
        rng.normal(size=(n * tok_per_shard, width)), jnp.float32
    )

    @jax.jit
    @partial(
        shard_map, mesh=ep_mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep")), out_specs=P("ep"),
    )
    def ep_forward(x, wg, w1, w2):
        return moe_apply(
            x, wg, w1[0], w2[0], axis_name="ep", capacity=tok_per_shard
        )

    recon = ep_forward(toks, wg, w1, w2)
    mse = MeanSquaredError()
    mse.update(recon, clean)
    print(f"ep MoE reconstruction mse={float(mse.compute()):.3f} "
          f"({n} experts, all_to_all dispatch)")

    # ---- composed dp x sp: ring attention inside a data-parallel step ---
    # the realistic long-context eval topology: batch over dp, sequence
    # over sp, metric counters psum'd over BOTH axes in the same program
    if n >= 4 and n % 2 == 0:
        dp, sp = 2, n // 2
        dpsp_mesh = Mesh(devs.reshape(dp, sp), ("dp", "sp"))
        seq_c = 8 * sp
        qc, kc, vc = (
            jnp.asarray(
                rng.normal(size=(dp * 2, seq_c, heads, dim)),
                jnp.float32,
            )
            for _ in range(3)
        )
        spec_c = P("dp", "sp", None, None)

        @jax.jit
        @partial(
            shard_map, mesh=dpsp_mesh,
            in_specs=(spec_c,) * 3, out_specs=(spec_c, P()),
        )
        def dpsp_eval(q, k, v):
            attn = ring_attention(q, k, v, axis_name="sp", causal=True)
            pos_frac = jax.lax.psum(
                jnp.sum(attn > 0).astype(jnp.float32), ("dp", "sp")
            )
            return attn, pos_frac

        attn_c, pos = dpsp_eval(qc, kc, vc)
        print(f"dpxsp composed ring attention ok "
              f"(mesh {dp}x{sp}, seq {seq_c}, pos_frac="
              f"{float(pos) / attn_c.size:.3f})")
    else:
        print(f"dpxsp composed leg skipped (needs an even device count "
              f">= 4; have {n})")

    print("scaleout done")


if __name__ == "__main__":
    main()
