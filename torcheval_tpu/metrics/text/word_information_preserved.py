"""WordInformationPreserved class metric.

Parity: reference torcheval/metrics/text/word_information_preserved.py:22-106.
"""

from __future__ import annotations

from typing import List, Optional, TypeVar, Union

import jax

from torcheval_tpu.metrics.functional.text.word_information_preserved import (
    _word_information_preserved_compute,
    _word_information_preserved_update,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric

TWordInformationPreserved = TypeVar(
    "TWordInformationPreserved", bound="WordInformationPreserved"
)


class WordInformationPreserved(Metric[jax.Array]):
    """Word information preserved score over all updates (1 = perfect).

    Functional version:
    ``torcheval_tpu.metrics.functional.word_information_preserved``.

    Examples::

        >>> from torcheval_tpu.metrics import WordInformationPreserved
        >>> metric = WordInformationPreserved()
        >>> metric.update(["hello world", "welcome to the facebook"],
        ...               ["hello metaverse", "welcome to meta"])
        >>> metric.compute()
        Array(0.3, dtype=float32)
    """

    def __init__(self, *, device: Optional[jax.Device] = None) -> None:
        super().__init__(device=device)
        self._add_state("correct_total", 0.0, merge=MergeKind.SUM)
        self._add_state("input_total", 0.0, merge=MergeKind.SUM)
        self._add_state("target_total", 0.0, merge=MergeKind.SUM)

    def update(
        self: TWordInformationPreserved,
        input: Union[str, List[str]],
        target: Union[str, List[str]],
    ) -> TWordInformationPreserved:
        """Accumulate one batch of sentence pairs."""
        correct, target_total, input_total = (
            _word_information_preserved_update(input, target)
        )
        self.correct_total += correct
        self.target_total += target_total
        self.input_total += input_total
        return self

    def compute(self) -> jax.Array:
        """Running word information preserved score."""
        return _word_information_preserved_compute(
            self.correct_total, self.target_total, self.input_total
        )
