"""SLO/anomaly monitor + live health endpoint acceptance pins (ISSUE 11).

- **monitor**: EWMA/z-score drift over observed values and latency-digest
  deltas, threshold SLOs over counter/latency sources, burn-rate SLOs
  over error/total counter pairs, typed ``AlertEvent``s in the event
  envelope, cooldown + active-alert clearing;
- **server**: ``/metrics`` (grammar-checked Prometheus exposition),
  ``/healthz`` (200/503 semantics driven by watchdog + alerts),
  ``/flight``, ``/report`` — all served in-process, and the server
  thread shuts down cleanly on ``config.observability`` scope exit (the
  acceptance criterion);
- **satellites**: ``render_prometheus`` under concurrent writers (ring
  mutation during scrape), ``event_from_dict`` on schema-1 payloads of
  the new Stall/Alert kinds.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from torcheval_tpu import config, obs
from torcheval_tpu.obs import hist as obs_hist
from torcheval_tpu.obs import monitor as obs_monitor
from torcheval_tpu.obs import server as obs_server
from torcheval_tpu.obs.counters import CounterRegistry
from torcheval_tpu.obs.events import (
    AlertEvent,
    StallEvent,
    event_from_dict,
)
from torcheval_tpu.obs.monitor import EwmaStat, Monitor, SloSpec

# the exposition-format line grammar (same pin as test_tracing.py)
_PROM_LINE = re.compile(
    r"^(?:# (?:TYPE|HELP) [a-zA-Z_][a-zA-Z0-9_]* \w+$"
    r"|[a-zA-Z_][a-zA-Z0-9_]*"
    r"(?:\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\""
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*\})?"
    r" [0-9.eEinf+-]+(?:$|\s))"
)


@pytest.fixture
def rec():
    r = obs.recorder()
    prev = r.enabled
    r.reset()
    r.enable()
    try:
        yield r
    finally:
        r.reset()
        if not prev:
            r.disable()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode("utf-8")


# ----------------------------------------------------------------- monitor


def test_ewma_stat_warmup_and_zscore():
    s = EwmaStat(alpha=0.2, warmup=4)
    rng = np.random.default_rng(0)
    zs = [s.update(1.0 + float(rng.normal(0, 0.01))) for _ in range(4)]
    assert zs == [None] * 4  # warm-up reports nothing
    # steady noisy series: in-band z
    for _ in range(50):
        z = s.update(1.0 + float(rng.normal(0, 0.01)))
        assert z is not None and abs(z) < 6
    # a huge step is flagged
    assert abs(s.update(2.0)) > 6
    # a CONSTANT series that then moves reports +/-inf, not a crash
    c = EwmaStat(alpha=0.2, warmup=2)
    for _ in range(5):
        assert c.update(3.0) in (None, 0.0)
    assert c.update(4.0) == float("inf")


def test_drift_alert_raised_cleared_and_cooldown(rec):
    m = Monitor(z_threshold=3.0, warmup=4, cooldown=30.0)
    for _ in range(10):
        m.observe("ctr", 0.5)
    z = m.observe("ctr", 50.0)
    assert z is not None and abs(z) >= 3.0
    active = m.active_alerts()
    assert len(active) == 1 and active[0]["alert"] == "drift"
    events = [e for e in rec.log.tail() if e.kind == "alert"]
    assert events and events[-1].name == "ctr" and events[-1].z == z
    # cooldown: an immediate second breach records no second AlertEvent
    m.observe("ctr", 60.0)
    assert len([e for e in rec.log.tail() if e.kind == "alert"]) == len(events)
    # back in band (the EWMA absorbed some of the spike; feed values
    # near the new mean): the standing alert clears
    for _ in range(20):
        m.observe("ctr", m._series["ctr"].mean)
    assert m.active_alerts() == []


def test_threshold_slo_over_counter_and_latency_sources(rec):
    registry = CounterRegistry()
    registry.register("svc", lambda: {"errors": 12})
    obs_hist.reset()
    try:
        for _ in range(32):
            obs_hist.observe("sync", 0.5)  # p99 = 0.5-1s bucket
        m = Monitor(cooldown=0.0)
        m.add_slo(SloSpec("svc-errors", "svc.errors", kind="max", bound=10))
        m.add_slo(
            SloSpec("sync-p99", "latency/sync:p99", kind="max", bound=0.1)
        )
        m.add_slo(SloSpec("ok-floor", "svc.errors", kind="min", bound=1))
        raised = m.check(registry=registry)
        names = {a["name"] for a in raised}
        assert names >= {"svc-errors", "sync-p99"}
        assert "ok-floor" not in names  # 12 >= 1: in bounds
        counters = m.counters()
        assert counters["active_alerts"] >= 2
        assert counters["breach_svc_errors".replace("svc_errors", "svc-errors")] == 1
        assert counters["breach_ok-floor"] == 0
        alerts = [e for e in rec.log.tail() if e.kind == "alert"]
        assert {e.alert for e in alerts} == {"threshold"}
    finally:
        obs_hist.reset()


def test_burn_rate_slo(rec):
    state = {"err": 0, "tot": 0}
    registry = CounterRegistry()
    registry.register(
        "sync", lambda: {"timeouts": state["err"], "attempts": state["tot"]}
    )
    m = Monitor(cooldown=0.0)
    m.add_slo(
        SloSpec(
            "sync-budget", "sync.timeouts", kind="burn-rate", bound=2.0,
            total="sync.attempts", budget=0.01, window=300.0,
        )
    )
    m.check(registry=registry)  # baseline snapshot
    state.update(err=1, tot=100)  # 1% error rate = 1x budget: no alert
    assert not m.check(registry=registry)
    state.update(err=11, tot=200)  # +10 errors over +100: 10x budget
    raised = m.check(registry=registry)
    assert raised and raised[0]["alert"] == "burn-rate"
    assert raised[0]["value"] >= 2.0
    events = [e for e in rec.log.tail() if e.kind == "alert"]
    assert events[-1].name == "sync-budget"


def test_latency_drift_detected_from_digest_deltas(rec):
    obs_hist.reset()
    try:
        m = Monitor(z_threshold=3.0, warmup=4, cooldown=0.0)
        # 10 checks of ~1 ms traffic warm the EWMA
        for _ in range(10):
            for _ in range(8):
                obs_hist.observe("update/Acc", 1e-3)
            m.check(registry=CounterRegistry())
        # the service quietly becomes 100x slower
        for _ in range(8):
            obs_hist.observe("update/Acc", 0.1)
        raised = m.check(registry=CounterRegistry())
        assert any(
            a["name"] == "latency/update/Acc:p99" and a["alert"] == "drift"
            for a in raised
        )
    finally:
        obs_hist.reset()


def test_toolkit_feeds_monitor_with_host_scalar_computes(rec):
    """sync_and_compute auto-feeds the armed monitor when the computed
    value is ALREADY a host scalar — and never reads a device array."""
    from torcheval_tpu.distributed import SingleProcessGroup
    from torcheval_tpu.metrics import Throughput
    from torcheval_tpu.metrics.toolkit import sync_and_compute

    monitor = obs_monitor.arm_monitor()
    try:
        m = Throughput()
        m.update(64, 2.0)
        value = sync_and_compute(m, SingleProcessGroup())
        assert isinstance(value, float)
        key = "computed/Throughput"
        assert key in monitor._series
        assert monitor._series[key].n == 1
    finally:
        obs_monitor.disarm_monitor()
    assert obs_monitor.current_monitor() is None


# ------------------------------------------------------- event round-trips


def test_stall_and_alert_events_round_trip_schema_1():
    """Satellite: ``event_from_dict`` on schema-1 payloads of the new
    kinds — exact round-trip, and unknown future fields are ignored."""
    stall = StallEvent(
        rank=2, op="allgather_object", seq=7, age_seconds=12.5,
        deadline=5.0, span_path="torcheval.sync > torcheval.collective",
        detail="#7 allgather_object issued",
    )
    alert = AlertEvent(
        name="sync-p99", alert="threshold", value=0.5, bound=0.1,
        z=4.2, message="too slow",
    )
    for event in (stall, alert):
        payload = event.as_dict()
        assert payload["schema"] == 1
        restored = event_from_dict(json.loads(json.dumps(payload)))
        assert type(restored) is type(event)
        assert restored == event
        # a NEWER writer's extra field must not break this reader
        payload["future_field"] = {"x": 1}
        assert event_from_dict(payload) == event
    assert event_from_dict({"kind": "stall", "schema": 1, "seq": 3}).seq == 3
    assert event_from_dict({"kind": "alert", "name": "n"}).name == "n"


def test_retry_event_flight_field_round_trips():
    from torcheval_tpu.obs.events import RetryEvent

    e = RetryEvent(reason="timeout", flight="#3 allgather_object issued")
    restored = event_from_dict(e.as_dict())
    assert restored.flight == e.flight


# ------------------------------------- prometheus under concurrent writers


def test_render_prometheus_under_concurrent_writers(rec):
    """Satellite: a scrape racing live ring mutation and histogram
    inserts must neither crash nor emit an unparseable line."""
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        rng = np.random.default_rng(1)
        try:
            while not stop.is_set():
                rec.record(obs.UpdateEvent(metric=f"M{i % 7}", seconds=1e-4))
                obs_hist.observe(f"op{i % 3}", float(rng.uniform(1e-6, 1e-2)))
                i += 1
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 1.0
        scrapes = 0
        while time.monotonic() < deadline:
            text = obs.render_prometheus()
            for line in text.splitlines():
                assert _PROM_LINE.match(line), f"unparseable: {line!r}"
            scrapes += 1
    finally:
        stop.set()
        for t in threads:
            t.join(5.0)
        obs_hist.reset()
    assert not errors
    assert scrapes >= 3


# ------------------------------------------------------------------ server


def test_endpoints_serve_valid_responses_in_process(rec):
    """ISSUE 11 acceptance: /healthz and /metrics serve valid responses
    in-process (exposition grammar-checked), /flight and /report too."""
    with config.observability(watchdog=30.0, serve=0, slos=[]):
        srv = obs.current_server()
        assert srv is not None and srv.port > 0

        status, text = _get(srv.url + "/metrics")
        assert status == 200
        assert text.strip(), "exposition must not be empty"
        for line in text.splitlines():
            assert _PROM_LINE.match(line), f"unparseable: {line!r}"
        assert "torcheval_tpu_flight_enabled 1" in text
        assert "torcheval_tpu_watchdog_armed 1" in text

        status, body = _get(srv.url + "/healthz")
        assert status == 200
        payload = json.loads(body)
        assert payload["status"] == "ok" and payload["healthy"]
        assert payload["watchdog"]["armed"] == 1
        assert payload["flight"]["enabled"] == 1
        assert "sync" in payload and "alerts" in payload

        status, body = _get(srv.url + "/flight")
        assert status == 200
        json.loads(body)  # valid JSON

        status, text = _get(srv.url + "/report")
        assert status == 200
        assert "observability report" in text

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/nope")
        assert ei.value.code == 404
        assert srv.requests >= 5


def test_healthz_503_when_alerting_and_recovers(rec):
    with config.observability(serve=0, slos=[]):
        srv = obs.current_server()
        monitor = obs_monitor.current_monitor()
        monitor.cooldown = 0.0
        monitor.z_threshold = 3.0
        for _ in range(10):
            monitor.observe("ctr", 0.5)
        monitor.observe("ctr", 100.0)  # drift alert now active
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/healthz")
        assert ei.value.code == 503
        payload = json.loads(ei.value.read().decode())
        assert payload["status"] == "alerting"
        assert payload["alerts"]
        # recovery: series back in band clears the alert
        for _ in range(30):
            monitor.observe("ctr", monitor._series["ctr"].mean)
        status, body = _get(srv.url + "/healthz")
        assert status == 200


def test_healthz_503_when_watchdog_tripped(rec):
    from torcheval_tpu.obs.flight import FLIGHT

    FLIGHT.reset()
    with config.observability(watchdog=0.05, serve=0):
        srv = obs.current_server()
        wd = obs.current_watchdog()
        wd._sink = None  # keep the test log clean
        r = FLIGHT.start("allgather_object", rank=0, world_size=2)
        time.sleep(0.3)  # poll ticks past the deadline -> trip
        assert wd.tripped
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/healthz")
        assert ei.value.code == 503
        payload = json.loads(ei.value.read().decode())
        assert payload["status"] == "stalled"
        assert payload["watchdog"]["last_trip"]["op"] == "allgather_object"
        FLIGHT.complete(r, ranks=(0, 1))
    FLIGHT.reset()


def test_server_shuts_down_cleanly_on_scope_exit():
    """ISSUE 11 acceptance: the server thread stops on scope exit — the
    port refuses connections and the thread is joined."""
    with config.observability(serve=0):
        srv = obs.current_server()
        url = srv.url
        thread = srv._thread
        assert thread.is_alive()
        _get(url + "/healthz")
    assert obs.current_server() is None
    assert not thread.is_alive()
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(url + "/healthz", timeout=1)


def test_server_shuts_down_on_scope_exit_by_exception():
    with pytest.raises(RuntimeError, match="boom"):
        with config.observability(serve=0, watchdog=10.0, slos=[]):
            srv = obs.current_server()
            assert srv is not None
            raise RuntimeError("boom")
    assert obs.current_server() is None
    assert obs.current_watchdog() is None
    assert obs_monitor.current_monitor() is None


def test_healthz_payload_usable_without_server():
    payload = obs_server.healthz_payload()
    assert payload["status"] in ("ok", "degraded", "alerting", "stalled")
    assert "flight" in payload and "sync" in payload
