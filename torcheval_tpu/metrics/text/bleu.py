"""BLEUScore class metric.

Parity: reference torcheval/metrics/text/bleu.py:22-141. N-gram matching is
host-side (as in the reference); states are a fixed-size counter vector on
device plus host float lengths, all SUM-merged — so distributed sync is one
psum.
"""

from __future__ import annotations

from typing import Optional, Sequence, TypeVar, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.text.bleu import (
    _bleu_score_compute,
    _bleu_score_update,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric

TBLEUScore = TypeVar("TBLEUScore", bound="BLEUScore")


class BLEUScore(Metric[jax.Array]):
    """BLEU score over all updates.

    Functional version: ``torcheval_tpu.metrics.functional.bleu_score``.

    Args:
        n_gram: maximum n-gram order, in {1, 2, 3, 4}.
        weights: optional per-order weight distribution of length ``n_gram``.

    Examples::

        >>> from torcheval_tpu.metrics import BLEUScore
        >>> metric = BLEUScore(n_gram=4)
        >>> candidates = ["the squirrel is eating the nut",
        ...               "the cat is on the mat"]
        >>> references = [["a squirrel is eating a nut",
        ...                "the squirrel is eating a tasty nut"],
        ...               ["there is a cat on the mat",
        ...                "a cat is on the mat"]]
        >>> metric.update(candidates, references)
        >>> metric.compute()
        Array(0.65341892, dtype=float32)
    """

    _extra_device_attrs = ("weights",)

    def __init__(
        self,
        *,
        n_gram: int,
        weights: Optional[jax.Array] = None,
        device: Optional[jax.Device] = None,
    ) -> None:
        super().__init__(device=device)
        if n_gram not in (1, 2, 3, 4):
            raise ValueError(f"n_gram should be 1, 2, 3, or 4, got {n_gram}.")
        if weights is not None and n_gram != len(weights):
            raise ValueError(
                "the length of weights should equal n_gram, got "
                f"len(weights)={len(weights)}, n_gram={n_gram}"
            )
        self.weights = None if weights is None else jnp.asarray(weights)
        self.n_gram = n_gram
        self._add_state("input_len", 0.0, merge=MergeKind.SUM)
        self._add_state("target_len", 0.0, merge=MergeKind.SUM)
        self._add_state(
            "matches_by_order",
            jnp.zeros(n_gram, dtype=jnp.float32),
            merge=MergeKind.SUM,
        )
        self._add_state(
            "possible_matches_by_order",
            jnp.zeros(n_gram, dtype=jnp.float32),
            merge=MergeKind.SUM,
        )

    def update(
        self: TBLEUScore,
        input: Union[str, Sequence[str]],
        target: Sequence[Union[str, Sequence[str]]],
    ) -> TBLEUScore:
        """Accumulate one batch of translations + references."""
        (
            input_len,
            target_len,
            matches_by_order,
            possible_matches_by_order,
        ) = _bleu_score_update(input, target, self.n_gram)
        self.input_len += input_len
        self.target_len += target_len
        self.matches_by_order = self.matches_by_order + self._input_float(
            matches_by_order
        )
        self.possible_matches_by_order = (
            self.possible_matches_by_order
            + self._input_float(possible_matches_by_order)
        )
        return self

    def compute(self) -> jax.Array:
        """Running BLEU score; 0.0 before any update."""
        if float(jnp.sum(self.matches_by_order)) == 0.0:
            return jnp.zeros((), dtype=jnp.float32)
        return _bleu_score_compute(
            jnp.asarray(self.input_len),
            jnp.asarray(self.target_len),
            self.matches_by_order,
            self.possible_matches_by_order,
            self.n_gram,
            self.weights,
        )
