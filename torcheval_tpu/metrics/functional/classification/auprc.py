"""AUPRC (area under the precision-recall curve, Riemann integral).

Parity: reference torcheval/metrics/functional/classification/auprc.py
(binary :16-100 multi-task; multiclass :103-170 macro/None; multilabel
:173-236; compute :239-295 + tensor_utils `_riemann_integral`). Unlike the
reference — which loops tasks/classes in Python calling the compacting curve
kernel — the whole computation here is one jitted, vmapped, fixed-shape XLA
program (tie-run duplicates integrate to zero; see ``_curve_kernels``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification._curve_kernels import (
    binary_auprc_area,
)
from torcheval_tpu.utils.convert import to_jax


@jax.jit
def _binary_auprc_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    return binary_auprc_area(input, target)


def _binary_auprc_update_input_check(
    input: jax.Array, target: jax.Array, num_tasks: int
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if num_tasks == 1:
        if input.ndim == 2 and input.shape[0] > 1:
            raise ValueError(
                "`num_tasks = 1`, `input` and `target` are expected to be "
                "one-dimensional tensors or 1xN tensors, but got shape "
                f"input: {input.shape}, target: {target.shape}."
            )
        if input.ndim > 2:
            raise ValueError(
                f"input should be at most two-dimensional, got shape {input.shape}."
            )
    elif input.ndim != 2 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`num_tasks = {num_tasks}`, `input`'s shape is expected to be "
            f"({num_tasks}, num_samples), but got shape ({input.shape})."
        )


def binary_auprc(input, target, *, num_tasks: int = 1) -> jax.Array:
    """Compute AUPRC for binary classification.

    Class version: ``torcheval_tpu.metrics.BinaryAUPRC``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import binary_auprc
        >>> binary_auprc(jnp.array([0.1, 0.5, 0.7, 0.8]), jnp.array([1, 0, 1, 1]))
        Array(0.9167, dtype=float32)
    """
    input, target = to_jax(input), to_jax(target)
    _binary_auprc_update_input_check(input, target, num_tasks)
    return _binary_auprc_kernel(input, target)  # batches over rows if 2-D


def _multiclass_auprc_param_check(num_classes: int, average: Optional[str]) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if num_classes < 2:
        raise ValueError(f"`num_classes` has to be at least 2, got {num_classes}.")


def _multiclass_auprc_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: int
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.ndim != 2 or input.shape[1] != num_classes:
        raise ValueError(
            f"input should have shape of (num_sample, num_classes), "
            f"got {input.shape} and num_classes={num_classes}."
        )


@jax.jit
def _multiclass_auprc_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    num_classes = input.shape[1]
    scores = input.T
    pos = jnp.arange(num_classes)

    def per_class(s, c):
        return binary_auprc_area(s, (target == c).astype(jnp.int32))

    return jax.vmap(per_class)(scores, pos)


def multiclass_auprc(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "macro",
) -> jax.Array:
    """Compute one-vs-rest AUPRC for multiclass classification.

    Class version: ``torcheval_tpu.metrics.MulticlassAUPRC``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import multiclass_auprc
        >>> multiclass_auprc(jnp.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1],
        ...                  [0.1, 0.2, 0.7], [0.3, 0.5, 0.2]]), jnp.array([0, 1, 2, 1]), num_classes=3)
        Array(1., dtype=float32)
    """
    input, target = to_jax(input), to_jax(target)
    if num_classes is None and input.ndim == 2:
        num_classes = input.shape[1]
    _multiclass_auprc_param_check(num_classes, average)
    _multiclass_auprc_update_input_check(input, target, num_classes)
    auprcs = _multiclass_auprc_kernel(input, target)
    if average == "macro":
        return jnp.mean(auprcs)
    return auprcs


def _multilabel_auprc_param_check(num_labels: int, average: Optional[str]) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if num_labels < 1:
        raise ValueError(f"`num_labels` has to be at least 1, got {num_labels}.")


def _multilabel_auprc_update_input_check(
    input: jax.Array, target: jax.Array, num_labels: int
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "Expected both input.shape and target.shape to have the same shape"
            f" but got {input.shape} and {target.shape}."
        )
    if input.ndim != 2 or input.shape[1] != num_labels:
        raise ValueError(
            f"input should have shape of (num_sample, num_labels), "
            f"got {input.shape} and num_labels={num_labels}."
        )


@jax.jit
def _multilabel_auprc_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    def per_label(s, t):
        return binary_auprc_area(s, t)

    return jax.vmap(per_label)(input.T, target.T)


def multilabel_auprc(
    input,
    target,
    *,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
) -> jax.Array:
    """Compute per-label AUPRC for multilabel classification.

    Class version: ``torcheval_tpu.metrics.MultilabelAUPRC``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import multilabel_auprc
        >>> multilabel_auprc(jnp.array([[0.9, 0.2, 0.8], [0.1, 0.7, 0.3], [0.6, 0.5, 0.4]]), jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 1]]), num_labels=3)
        Array(1., dtype=float32)
    """
    input, target = to_jax(input), to_jax(target)
    if input.ndim != 2:
        raise ValueError(
            f"input should be a two-dimensional tensor, got shape {input.shape}."
        )
    if num_labels is None:
        num_labels = input.shape[1]
    _multilabel_auprc_param_check(num_labels, average)
    _multilabel_auprc_update_input_check(input, target, num_labels)
    auprcs = _multilabel_auprc_kernel(input, target)
    if average == "macro":
        return jnp.mean(auprcs)
    return auprcs
