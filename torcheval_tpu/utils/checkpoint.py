"""Metric checkpoint/resume helpers (Orbax-backed).

The reference's checkpoint story is ``Metric.state_dict()`` /
``load_state_dict(strict)`` plus ``get_synced_state_dict(_collection)`` for
rank-0-consistent snapshots (reference metrics/metric.py:149-210,
toolkit.py:110-179; setup.py:58 names "metric computations and
checkpointing" as a core capability). These helpers bind that surface to the
TPU ecosystem's checkpointing layer: Orbax writes the state pytree (device
arrays stay sharded-aware on multihost filesystems), and restore routes
through ``load_state_dict`` so device placement and TState validation apply.

Fault tolerance (docs/fault-tolerance.md):

- **Atomic publish**: ``save_metric_state`` writes to a temporary sibling
  path and renames it into place, so a crash mid-save leaves either the
  previous checkpoint or none — never a torn one at the published path.
- **Payload digest**: a sha256 over the canonical byte encoding of every
  state leaf travels inside the checkpoint; ``load_metric_state`` recomputes
  it and rejects corrupt or truncated checkpoints with a clear error
  instead of silently restoring garbage into a resumed eval.
- **Schema validation**: restored leaves are checked against the metric's
  REGISTERED state shapes/dtypes before anything is loaded, so a
  checkpoint from a differently-configured metric (e.g. another
  ``num_classes``) fails with an error naming the offending leaf instead
  of a cryptic downstream jax broadcast/dtype error.
- **Single-writer protocol**: the atomic-publish temp/aside sibling names
  (``<path>.tmp`` / ``<path>.old``) are deliberately FIXED (pid-less) so a
  restarted process can recognize and recover a crashed predecessor's
  leftovers — which means two live writers saving to the SAME path would
  silently clobber each other's siblings and interleave renames. A
  ``<path>.lock`` sentinel (created ``O_EXCL``) detects that race and
  fails the second writer loudly; a lock older than
  ``_LOCK_STALE_SECONDS`` is presumed to be a crashed writer's leftover
  and is broken with a warning. Writers on different paths never contend.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import time
import warnings
from typing import Any, Dict, Union

import jax

from torcheval_tpu.metrics.metric import Metric

MetricOrCollection = Union[Metric, Dict[str, Metric]]

# digest sidecar key inside the saved tree (reserved; not a metric name)
_DIGEST_KEY = "__digest__"


_CHECKPOINTER = None


def _checkpointer():
    global _CHECKPOINTER
    if _CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _CHECKPOINTER = ocp.PyTreeCheckpointer()
    return _CHECKPOINTER


def _to_plain(tree):
    """DefaultStateDict (our auto-zero dict) -> plain dict for Orbax.

    Device arrays are written as host numpy: metric state is tiny (sufficient
    statistics / bounded buffers), and numpy payloads restore on any topology
    without per-array sharding metadata (restore then routes through
    ``load_state_dict``, which re-places state on the metric's device).
    """
    import numpy as np

    if isinstance(tree, dict):
        return {k: _to_plain(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_to_plain(v) for v in tree]
    if isinstance(tree, jax.Array):
        tree = np.asarray(tree)
    if isinstance(tree, np.ndarray) and tree.size == 0:
        # Orbax refuses zero-size arrays (a fresh buffered metric's lazy
        # sentinel is shape (0,)); encode shape+dtype, rebuild on restore.
        return {
            "__empty_shape__": np.asarray(tree.shape, np.int64),
            "__empty_proto__": np.zeros((1,), tree.dtype),
        }
    return tree


def _from_plain(tree):
    """Inverse of :func:`_to_plain`'s empty-array encoding."""
    import numpy as np

    if isinstance(tree, dict):
        if set(tree) == {"__empty_shape__", "__empty_proto__"}:
            return np.zeros(
                tuple(int(d) for d in tree["__empty_shape__"]),
                tree["__empty_proto__"].dtype,
            )
        return {k: _from_plain(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_from_plain(v) for v in tree]
    return tree


def _digest(tree: Any) -> str:
    """sha256 over a canonical byte encoding of the plain state tree.

    Every leaf is canonicalized through ``np.asarray`` (python ints/floats
    and their numpy-scalar restore forms encode identically), and the key
    path, dtype, and shape are folded in so a corrupted, truncated, or
    transposed payload cannot collide with the original.
    """
    import numpy as np

    h = hashlib.sha256()

    def walk(node: Any, path: str) -> None:
        if isinstance(node, dict):
            for key in sorted(node, key=repr):
                walk(node[key], f"{path}/{key!r}")
        elif isinstance(node, (list, tuple)):
            for i, item in enumerate(node):
                walk(item, f"{path}[{i}]")
        else:
            arr = np.asarray(node)
            h.update(path.encode())
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(np.ascontiguousarray(arr).tobytes())

    walk(tree, "")
    return h.hexdigest()


def _leaf_desc(value: Any) -> str:
    import numpy as np

    arr = np.asarray(value)
    return f"{arr.dtype}[{', '.join(str(d) for d in arr.shape)}]"


def validate_state_dict(
    metric: Metric, state: Dict[str, Any], *, context: str, prefix: str = ""
) -> None:
    """Check a restored state tree against ``metric``'s REGISTERED states
    (``_add_state`` defaults) and raise a clear :class:`RuntimeError`
    naming the offending leaf path — instead of deferring to a cryptic
    downstream jax broadcast/dtype error when the mismatched value is
    first used.

    Rules per registered default:

    - array default with a real shape (``size > 0``): the restored leaf
      must be an array of the SAME dtype and shape (a checkpoint from a
      differently-configured metric — another ``num_classes``, window
      size, bin count — fails here);
    - array default that is a lazy 0-size sentinel (growable buffers fix
      dtype/row shape on first append): only array-ness is checked;
    - list / dict defaults: the restored leaf must be a list / dict
      (element types are validated by ``load_state_dict``);
    - int/float defaults: the restored leaf must be a scalar (python or
      0-d numpy number).

    Shared by :func:`load_metric_state` and
    ``elastic.ElasticSession.restore``.
    """
    import numpy as np

    what = type(metric).__name__
    for name, value in state.items():
        default = metric._state_name_to_default.get(name)
        if default is None:
            continue  # unknown names are strict-mode territory, not ours
        leaf = f"{prefix}{name}"
        if isinstance(default, (jax.Array, np.ndarray)):
            if not isinstance(value, (jax.Array, np.ndarray)):
                raise RuntimeError(
                    f"{context}: state '{leaf}' holds "
                    f"{type(value).__name__!r} but {what} registered an "
                    f"array state ({_leaf_desc(default)})"
                )
            if np.asarray(default).size == 0:
                continue  # lazy sentinel: dtype/shape fixed by first append
            d, v = np.asarray(default), np.asarray(value)
            info = (getattr(metric, "_sharded_states", None) or {}).get(name)
            if info is not None:
                # sharded state: the payload may be ANY world's slice of
                # the logical state (a world-size-change restore loads
                # old-world shards, a desharded merge result is logical)
                # — dtype, rank, and non-shard dims must match; the
                # shard dim may be any size up to the logical dim
                logical = tuple(info.logical_shape)
                ok = (
                    v.dtype == d.dtype
                    and v.ndim == len(logical)
                    and tuple(v.shape[1:]) == tuple(logical[1:])
                    and 0 < v.shape[0] <= logical[0]
                )
                if not ok:
                    raise RuntimeError(
                        f"{context}: sharded state '{leaf}' holds "
                        f"{_leaf_desc(value)} but {what} registered a "
                        f"state of logical shape {logical} "
                        f"({d.dtype}) — was the checkpoint written by a "
                        "differently-configured metric?"
                    )
                continue
            if v.dtype != d.dtype or v.shape != d.shape:
                raise RuntimeError(
                    f"{context}: state '{leaf}' holds {_leaf_desc(value)} "
                    f"but {what} registered {_leaf_desc(default)} — was "
                    "the checkpoint written by a differently-configured "
                    "metric?"
                )
        elif isinstance(default, list):
            if not isinstance(value, (list, tuple)):
                raise RuntimeError(
                    f"{context}: state '{leaf}' holds "
                    f"{type(value).__name__!r} but {what} registered a "
                    "list state"
                )
        elif isinstance(default, dict):
            if not isinstance(value, dict):
                raise RuntimeError(
                    f"{context}: state '{leaf}' holds "
                    f"{type(value).__name__!r} but {what} registered a "
                    "dict state"
                )
        elif isinstance(default, (int, float)):
            scalar = isinstance(value, (int, float)) or (
                isinstance(value, np.ndarray) and value.ndim == 0
            ) or isinstance(value, np.number)
            if not scalar:
                raise RuntimeError(
                    f"{context}: state '{leaf}' holds "
                    f"{type(value).__name__!r} but {what} registered a "
                    "scalar state"
                )


# A crashed writer's leftover lock is broken after this many seconds; a
# YOUNGER foreign lock means a concurrent live writer — a loud error
# (module-level so tests and long-save deployments can tune it).
_LOCK_STALE_SECONDS = 600.0


def _acquire_save_lock(path: str) -> str:
    """Single-writer guard for one checkpoint path (module docstring)."""
    lock = f"{path}.lock"
    for attempt in (0, 1):
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, f"pid={os.getpid()} t={time.time()}\n".encode())
            os.close(fd)
            return lock
        except FileExistsError:
            try:
                age = time.time() - os.path.getmtime(lock)
            except OSError:
                continue  # holder just released it — retry the O_EXCL
            if age > _LOCK_STALE_SECONDS and attempt == 0:
                warnings.warn(
                    f"breaking stale checkpoint lock {lock} "
                    f"({age:.0f}s old — presumed crashed writer)",
                    RuntimeWarning,
                )
                # break by ATOMIC RENAME to a unique name, not unlink:
                # with several contenders racing to break the same stale
                # lock, an unlink could remove a rival's FRESH lock
                # created a moment after the stale one vanished — rename
                # moves exactly the stale file, and exactly one contender
                # wins it (the losers fall through to the O_EXCL race)
                tomb = f"{lock}.stale-{os.getpid()}-{time.monotonic_ns()}"
                try:
                    os.rename(lock, tomb)
                    os.unlink(tomb)
                except OSError:
                    pass  # a rival broke it first; retry the O_EXCL
                continue
            raise RuntimeError(
                f"another save_metric_state writer holds {lock}: the "
                "atomic-publish protocol uses FIXED (pid-less) "
                f"'{os.path.basename(path)}.tmp'/'.old' siblings so a "
                "restarted process can recover a crashed save, which "
                "makes two CONCURRENT writers to the same path mutually "
                "destructive (silently interleaved renames). Serialize "
                "savers or give each its own path; a lock older than "
                f"{_LOCK_STALE_SECONDS:.0f}s is presumed stale and "
                "broken automatically."
            )
    raise RuntimeError(f"could not acquire checkpoint lock {lock}")


def save_metric_state(metric: MetricOrCollection, path: str) -> None:
    """Write a metric's (or a ``{name: Metric}`` collection's) state to
    ``path`` as an Orbax checkpoint — atomically, with an embedded payload
    digest (see module docstring).

    For a distributed eval loop, snapshot the *synced* state instead:
    ``save_metric_state(get_synced_metric(metric, pg), path)``.

    >>> save_metric_state(metric, "/ckpt/metrics/step_1000")
    >>> save_metric_state({"acc": acc, "auroc": auroc}, "/ckpt/metrics")
    """
    path = os.path.abspath(os.fspath(path))
    if isinstance(metric, Metric):
        tree = {"__single__": _to_plain(metric.state_dict())}
    else:
        if _DIGEST_KEY in metric:
            raise ValueError(
                f"{_DIGEST_KEY!r} is reserved for the checkpoint integrity "
                "digest and cannot be a metric name"
            )
        tree = {name: _to_plain(m.state_dict()) for name, m in metric.items()}
    import numpy as np

    # digest the LOGICAL tree (empty-array encodings decoded), which is
    # exactly what load recomputes over after restore
    tree[_DIGEST_KEY] = np.frombuffer(
        bytes.fromhex(_digest(_from_plain(tree))), dtype=np.uint8
    ).copy()
    # single-writer guard: the fixed sibling names below are only safe
    # with ONE live writer per path (module docstring)
    lock = _acquire_save_lock(path)
    try:
        # atomic publish: write a temp sibling, then rename into place — a
        # crash mid-save leaves the previous checkpoint (or nothing), never
        # a torn tree at the published path
        # fixed (pid-less) sibling names: a restarted process recognizes
        # and cleans up any leftovers from a crashed earlier save, and load
        # can recover the aside copy from a swap interrupted mid-way
        tmp = f"{path}.tmp"
        old = f"{path}.old"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        # a previous save may have crashed between its two renames, leaving
        # the last good snapshot ONLY at the aside name: recover it before
        # anything clobbers it (mirrors load_metric_state's recovery)
        if not os.path.exists(path) and os.path.exists(old):
            os.rename(old, path)
        _checkpointer().save(tmp, tree, force=True)
        # the previous checkpoint is renamed ASIDE (never deleted) until
        # the new one is in place, so no crash point destroys the last good
        # snapshot; the aside copy is removed only after the swap lands
        if os.path.exists(old):
            shutil.rmtree(old)
        had_old = os.path.exists(path)
        if had_old:
            os.rename(path, old)
        try:
            os.rename(tmp, path)
        except BaseException:
            if had_old:
                os.rename(old, path)  # roll the previous checkpoint back
            raise
        if had_old:
            shutil.rmtree(old, ignore_errors=True)
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


def load_metric_state(
    metric: MetricOrCollection, path: str, strict: bool = True
) -> MetricOrCollection:
    """Restore state saved by :func:`save_metric_state` into ``metric``
    in place (construct the metric(s) with the same config first, as with
    the reference's ``load_state_dict`` flow). Returns ``metric``.

    >>> metric = MulticlassAccuracy()
    >>> load_metric_state(metric, "/ckpt/metrics/step_1000")
    """
    from torcheval_tpu.metrics.toolkit import _restore_state_types

    path = os.path.abspath(os.fspath(path))
    if not os.path.exists(path):
        aside = f"{path}.old"
        if os.path.exists(aside):
            # a save crashed between its two renames: the last good
            # snapshot survives at the aside name — recover it rather
            # than telling the resume harness to start fresh
            os.rename(aside, path)
        else:
            # a missing checkpoint is NOT corruption: resume harnesses
            # branch on this distinction (start fresh vs alert)
            raise FileNotFoundError(f"no metric checkpoint at {path}")
    try:
        tree = _from_plain(_checkpointer().restore(path))
    except Exception as e:  # orbax raises backend-specific error types
        raise RuntimeError(
            f"checkpoint at {path} is corrupt or truncated "
            f"(restore failed: {type(e).__name__}: {e})"
        ) from e
    saved_digest = tree.pop(_DIGEST_KEY, None)
    if saved_digest is not None:
        want = bytes(bytearray(int(b) for b in saved_digest)).hex()
        got = _digest(tree)
        if got != want:
            raise RuntimeError(
                f"checkpoint at {path} is corrupt: payload digest mismatch "
                f"(stored {want[:16]}…, recomputed {got[:16]}…); refusing "
                "to restore garbage metric state"
            )
    if isinstance(metric, Metric):
        if "__single__" not in tree:
            raise RuntimeError(
                f"checkpoint at {path} holds a metric collection "
                f"({sorted(tree)}); pass the matching {{name: Metric}} dict."
            )
        validate_state_dict(
            metric, tree["__single__"], context=f"checkpoint at {path}"
        )
        metric.load_state_dict(
            _restore_state_types(tree["__single__"]), strict=strict
        )
        return metric
    if "__single__" in tree:
        raise RuntimeError(
            f"checkpoint at {path} holds a single metric's state; pass a "
            "Metric, not a collection."
        )
    missing = set(metric) - set(tree)
    unexpected = set(tree) - set(metric)
    if strict and (missing or unexpected):
        raise RuntimeError(
            f"checkpoint at {path} does not match the collection: "
            f"missing state for {sorted(missing)}, "
            f"unclaimed saved state for {sorted(unexpected)}."
        )
    for name, m in metric.items():
        if name in tree:
            validate_state_dict(
                m,
                tree[name],
                context=f"checkpoint at {path}",
                prefix=f"{name}.",
            )
            m.load_state_dict(_restore_state_types(tree[name]), strict=strict)
    return metric
