"""Parametrized parity sweep: every public functional op vs the reference
oracle on random data (the JAX analogue of the reference's per-op functional
unit-test tier, reference tests/metrics/functional/**, SURVEY.md section 4).

Class-metric behavior is covered by the per-family MetricClassTester suites;
this module pins the *stateless* surface, one comparison per op/config.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.ref_oracle import load_reference_metrics
from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    assert_result_close,
)

REF_M, REF_F = load_reference_metrics()
RNG = np.random.default_rng(47)

N = 64
C = 5
L = 4  # labels for multilabel


def _t(x):
    return torch.tensor(np.asarray(x))


# ------------------------------------------------------------ data builders

def binary():
    return (
        RNG.random(N).astype(np.float32),
        RNG.integers(0, 2, N).astype(np.float32),
    )


def binary_tasks(tasks=2):
    return (
        RNG.random((tasks, N)).astype(np.float32),
        RNG.integers(0, 2, (tasks, N)).astype(np.float32),
    )


def multiclass():
    return (
        RNG.random((N, C)).astype(np.float32),
        RNG.integers(0, C, N),
    )


def multilabel():
    return (
        RNG.random((N, L)).astype(np.float32),
        RNG.integers(0, 2, (N, L)).astype(np.float32),
    )


# Each case: (name, ours(...), ref(...)) — callables taking no args.
CASES = {}


def case(name):
    def deco(fn):
        CASES[name] = fn
        return fn
    return deco


@case("binary_accuracy")
def _():
    x, t = binary()
    return F.binary_accuracy(x, t), REF_F.binary_accuracy(_t(x), _t(t))


@case("binary_accuracy_threshold")
def _():
    x, t = binary()
    return (
        F.binary_accuracy(x, t, threshold=0.3),
        REF_F.binary_accuracy(_t(x), _t(t), threshold=0.3),
    )


@case("multiclass_accuracy_micro")
def _():
    x, t = multiclass()
    return F.multiclass_accuracy(x, t), REF_F.multiclass_accuracy(_t(x), _t(t))


@case("multiclass_accuracy_macro")
def _():
    x, t = multiclass()
    return (
        F.multiclass_accuracy(x, t, average="macro", num_classes=C),
        REF_F.multiclass_accuracy(_t(x), _t(t), average="macro", num_classes=C),
    )


@case("multiclass_accuracy_none_k2")
def _():
    x, t = multiclass()
    return (
        F.multiclass_accuracy(x, t, average=None, num_classes=C, k=2),
        REF_F.multiclass_accuracy(_t(x), _t(t), average=None, num_classes=C, k=2),
    )


@case("multilabel_accuracy_variants")
def _():
    x, t = multilabel()
    ours = [
        F.multilabel_accuracy(x, t, criteria=c)
        for c in ("exact_match", "hamming", "overlap", "contain", "belong")
    ]
    ref = [
        REF_F.multilabel_accuracy(_t(x), _t(t), criteria=c)
        for c in ("exact_match", "hamming", "overlap", "contain", "belong")
    ]
    return ours, ref


@case("topk_multilabel_accuracy")
def _():
    x, t = multilabel()
    return (
        F.topk_multilabel_accuracy(x, t, criteria="hamming", k=2),
        REF_F.topk_multilabel_accuracy(_t(x), _t(t), criteria="hamming", k=2),
    )


@case("binary_auroc")
def _():
    x, t = binary()
    return F.binary_auroc(x, t), REF_F.binary_auroc(_t(x), _t(t))


@case("binary_auroc_weighted_tasks")
def _():
    x, t = binary_tasks()
    w = RNG.random((2, N)).astype(np.float32)
    return (
        F.binary_auroc(x, t, num_tasks=2, weight=w),
        REF_F.binary_auroc(_t(x), _t(t), num_tasks=2, weight=_t(w)),
    )


@case("multiclass_auroc")
def _():
    x, t = multiclass()
    return (
        F.multiclass_auroc(x, t, num_classes=C),
        REF_F.multiclass_auroc(_t(x), _t(t), num_classes=C),
    )


@case("binary_auprc")
def _():
    x, t = binary()
    return F.binary_auprc(x, t), REF_F.binary_auprc(_t(x), _t(t))


@case("multiclass_auprc")
def _():
    x, t = multiclass()
    return (
        F.multiclass_auprc(x, t, num_classes=C, average=None),
        REF_F.multiclass_auprc(_t(x), _t(t), num_classes=C, average=None),
    )


@case("multilabel_auprc")
def _():
    x, t = multilabel()
    return (
        F.multilabel_auprc(x, t, num_labels=L),
        REF_F.multilabel_auprc(_t(x), _t(t), num_labels=L),
    )


@case("binary_precision_recall_curve")
def _():
    x, t = binary()
    return (
        F.binary_precision_recall_curve(x, t),
        REF_F.binary_precision_recall_curve(_t(x), _t(t)),
    )


@case("multiclass_precision_recall_curve")
def _():
    x, t = multiclass()
    return (
        F.multiclass_precision_recall_curve(x, t, num_classes=C),
        REF_F.multiclass_precision_recall_curve(_t(x), _t(t), num_classes=C),
    )


@case("multilabel_precision_recall_curve")
def _():
    x, t = multilabel()
    return (
        F.multilabel_precision_recall_curve(x, t, num_labels=L),
        REF_F.multilabel_precision_recall_curve(_t(x), _t(t), num_labels=L),
    )


@case("binary_binned_auroc")
def _():
    x, t = binary()
    return (
        F.binary_binned_auroc(x, t, threshold=50),
        REF_F.binary_binned_auroc(_t(x), _t(t), threshold=50),
    )


@case("multiclass_binned_auroc")
def _():
    # Deliberate divergence from the reference: its kernel reduces over the
    # class axis and yields one value per SAMPLE (reference
    # binned_auroc.py:186-213, visible in its own docstring example); ours
    # computes true per-class one-vs-rest. Pin internal consistency instead:
    # a dense threshold grid must converge to the exact multiclass AUROC.
    x, t = multiclass()
    binned, _th = F.multiclass_binned_auroc(x, t, num_classes=C, threshold=2000)
    exact = F.multiclass_auroc(x, t, num_classes=C)
    return binned, np.asarray(exact)


@case("binary_binned_auprc")
def _():
    x, t = binary()
    return (
        F.binary_binned_auprc(x, t, threshold=50),
        REF_F.binary_binned_auprc(_t(x), _t(t), threshold=50),
    )


@case("multiclass_binned_auprc")
def _():
    x, t = multiclass()
    return (
        F.multiclass_binned_auprc(x, t, num_classes=C, threshold=20),
        REF_F.multiclass_binned_auprc(_t(x), _t(t), num_classes=C, threshold=20),
    )


@case("multilabel_binned_auprc")
def _():
    x, t = multilabel()
    return (
        F.multilabel_binned_auprc(x, t, num_labels=L, threshold=20),
        REF_F.multilabel_binned_auprc(_t(x), _t(t), num_labels=L, threshold=20),
    )


@case("binary_binned_precision_recall_curve")
def _():
    x, t = binary()
    return (
        F.binary_binned_precision_recall_curve(x, t, threshold=20),
        REF_F.binary_binned_precision_recall_curve(_t(x), _t(t), threshold=20),
    )


@case("multiclass_binned_precision_recall_curve_both_kernels")
def _():
    x, t = multiclass()
    ours = [
        F.multiclass_binned_precision_recall_curve(
            x, t, num_classes=C, threshold=10, optimization=o
        )
        for o in ("vectorized", "memory")
    ]
    ref = [
        REF_F.multiclass_binned_precision_recall_curve(
            _t(x), _t(t), num_classes=C, threshold=10, optimization=o
        )
        for o in ("vectorized", "memory")
    ]
    return ours, ref


@case("multilabel_binned_precision_recall_curve")
def _():
    x, t = multilabel()
    return (
        F.multilabel_binned_precision_recall_curve(x, t, num_labels=L, threshold=10),
        REF_F.multilabel_binned_precision_recall_curve(
            _t(x), _t(t), num_labels=L, threshold=10
        ),
    )


@case("binary_confusion_matrix")
def _():
    x, t = binary()
    return (
        F.binary_confusion_matrix(x, t),
        REF_F.binary_confusion_matrix(_t(x), _t(t).long()),
    )


@case("multiclass_confusion_matrix_normalized")
def _():
    x, t = multiclass()
    ours = [
        F.multiclass_confusion_matrix(x, t, num_classes=C, normalize=n)
        for n in (None, "pred", "true", "all")
    ]
    ref = [
        REF_F.multiclass_confusion_matrix(_t(x), _t(t), num_classes=C, normalize=n)
        for n in (None, "pred", "true", "all")
    ]
    return ours, ref


@case("f1_scores")
def _():
    x, t = multiclass()
    bx, bt = binary()
    ours = [
        F.multiclass_f1_score(x, t, num_classes=C, average=a)
        for a in ("micro", "macro", "weighted", None)
    ] + [F.binary_f1_score(bx, bt)]
    ref = [
        REF_F.multiclass_f1_score(_t(x), _t(t), num_classes=C, average=a)
        for a in ("micro", "macro", "weighted", None)
    ] + [REF_F.binary_f1_score(_t(bx), _t(bt))]
    return ours, ref


@case("precision_recall")
def _():
    x, t = multiclass()
    bx, bt = binary()
    bt = bt.astype(np.int64)  # reference binary_recall requires int targets
    ours = [
        F.multiclass_precision(x, t, num_classes=C, average="macro"),
        F.multiclass_recall(x, t, num_classes=C, average="macro"),
        F.binary_precision(bx, bt),
        F.binary_recall(bx, bt),
    ]
    ref = [
        REF_F.multiclass_precision(_t(x), _t(t), num_classes=C, average="macro"),
        REF_F.multiclass_recall(_t(x), _t(t), num_classes=C, average="macro"),
        REF_F.binary_precision(_t(bx), _t(bt)),
        REF_F.binary_recall(_t(bx), _t(bt)),
    ]
    return ours, ref


@case("recall_at_fixed_precision")
def _():
    x, t = binary()
    mx, mt = multilabel()
    ours = [
        F.binary_recall_at_fixed_precision(x, t, min_precision=0.5),
        F.multilabel_recall_at_fixed_precision(mx, mt, num_labels=L, min_precision=0.5),
    ]
    ref = [
        REF_F.binary_recall_at_fixed_precision(_t(x), _t(t), min_precision=0.5),
        REF_F.multilabel_recall_at_fixed_precision(
            _t(mx), _t(mt), num_labels=L, min_precision=0.5
        ),
    ]
    return ours, ref


@case("binary_normalized_entropy")
def _():
    x = np.clip(RNG.random(N).astype(np.float64), 0.01, 0.99)
    t = RNG.integers(0, 2, N).astype(np.float64)
    ours = [
        F.binary_normalized_entropy(x, t),
        F.binary_normalized_entropy(
            np.log(x / (1 - x)), t, from_logits=True
        ),
    ]
    ref = [
        REF_F.binary_normalized_entropy(_t(x), _t(t)),
        REF_F.binary_normalized_entropy(
            torch.logit(_t(x)), _t(t), from_logits=True
        ),
    ]
    return ours, ref


@case("aggregation")
def _():
    x = RNG.random((N,)).astype(np.float32)
    w = RNG.random((N,)).astype(np.float32)
    ours = [
        F.mean(x, w),
        F.sum(x, w),
        F.throughput(100, 2.0),
        F.auc(np.sort(x)[:16], x[:16]),
    ]
    ref = [
        REF_F.mean(_t(x), _t(w)),
        REF_F.sum(_t(x), _t(w)),
        REF_F.throughput(100, 2.0),
        REF_F.auc(_t(np.sort(x)[:16]), _t(x[:16])),
    ]
    return ours, ref


@case("regression")
def _():
    x = RNG.random((N, 3)).astype(np.float32)
    t = RNG.random((N, 3)).astype(np.float32)
    ours = [
        F.mean_squared_error(x, t),
        F.mean_squared_error(x, t, multioutput="raw_values"),
        F.r2_score(x, t),
    ]
    ref = [
        REF_F.mean_squared_error(_t(x), _t(t)),
        REF_F.mean_squared_error(_t(x), _t(t), multioutput="raw_values"),
        REF_F.r2_score(_t(x), _t(t)),
    ]
    return ours, ref


@case("ranking")
def _():
    ks = RNG.integers(0, 2, (N,)).astype(np.float32)
    kw = RNG.random((N,)).astype(np.float32)
    scores = RNG.random((8, 10)).astype(np.float32)
    class_idx = RNG.integers(0, 10, 8)  # hit_rate/RR take class indices
    onehot = np.zeros(10, dtype=np.float32)
    onehot[class_idx[0]] = 1  # retrieval_precision takes binary relevance
    ids = RNG.integers(0, 100, 40)
    freq_in = RNG.random(20).astype(np.float32)
    ours = [
        F.click_through_rate(ks, kw),
        F.hit_rate(scores, class_idx, k=3),
        F.reciprocal_rank(scores, class_idx),
        F.weighted_calibration(ks, ks, kw),
        F.frequency_at_k(freq_in, k=0.5),
        F.num_collisions(ids),
        F.retrieval_precision(scores[0], onehot, k=4),
    ]
    ref = [
        REF_F.click_through_rate(_t(ks), _t(kw)),
        REF_F.hit_rate(_t(scores), _t(class_idx), k=3),
        REF_F.reciprocal_rank(_t(scores), _t(class_idx)),
        REF_F.weighted_calibration(_t(ks), _t(ks), _t(kw)),
        REF_F.frequency_at_k(_t(freq_in), k=0.5),
        REF_F.num_collisions(_t(ids)),
        REF_F.retrieval_precision(_t(scores[0]), _t(onehot), k=4),
    ]
    return ours, ref


@case("text")
def _():
    preds = ["the cat sat on the mat", "hello brave new world"]
    tgts = ["the cat sat on a mat", "hello brand new world"]
    logits = RNG.normal(size=(2, 6, 9)).astype(np.float32)
    toks = RNG.integers(0, 9, (2, 6))
    ours = [
        F.word_error_rate(preds, tgts),
        F.word_information_lost(preds, tgts),
        F.word_information_preserved(preds, tgts),
        F.perplexity(logits, toks),
        F.bleu_score(preds, [[t] for t in tgts], n_gram=2),
    ]
    ref = [
        REF_F.word_error_rate(preds, tgts),
        REF_F.word_information_lost(preds, tgts),
        REF_F.word_information_preserved(preds, tgts),
        REF_F.perplexity(_t(logits), _t(toks)),
        REF_F.bleu_score(preds, [[t] for t in tgts], n_gram=2),
    ]
    return ours, ref


@case("image")
def _():
    x = RNG.random((2, 3, 8, 8)).astype(np.float32)
    t = RNG.random((2, 3, 8, 8)).astype(np.float32)
    ours = [
        F.peak_signal_noise_ratio(x, t),
        F.peak_signal_noise_ratio(x, t, data_range=1.0),
    ]
    ref = [
        REF_F.peak_signal_noise_ratio(_t(x), _t(t)),
        REF_F.peak_signal_noise_ratio(_t(x), _t(t), data_range=1.0),
    ]
    return ours, ref


@pytest.mark.parametrize("name", sorted(CASES))
def test_functional_parity(name):
    ours, ref = CASES[name]()

    def to_np(x):
        if isinstance(x, torch.Tensor):
            return x.detach().cpu().numpy()
        if isinstance(x, (list, tuple)):
            return type(x)(to_np(v) for v in x)
        if x is None:
            return None
        return np.asarray(x)

    assert_result_close(to_np(ours), to_np(ref), atol=1e-4, rtol=1e-4)
