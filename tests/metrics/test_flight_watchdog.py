"""Flight recorder + stall watchdog acceptance pins (ISSUE 11).

The tentpole contracts:

- **flight rings**: every collective through the group wrapper layer
  leaves a per-thread :class:`FlightRecord` with live state transitions
  (``enqueued -> issued -> completed/failed``); a ``ResilientGroup``
  wrapping an instrumented plain group records ONE record per logical
  collective (worker-thread suppression), never two;
- **hang forensics** (the acceptance criterion): a
  ``FaultInjectionGroup``-delayed collective in a rendezvousing
  ThreadWorld-4 trips the watchdog DURING the stall, the dump includes
  all four ranks' flight rings, and ``diff_flight_rings()`` names the
  injected stalled rank and its last completed seq;
- **error forensics**: a sync that times out raises with the flight-ring
  tail attached (``e.flight_tail``) and the ``RetryEvent`` carries it
  too;
- **cost**: flight + watchdog + monitor ON add zero collectives (the
  extended pin lives in test_sync_collective_counts.py) and zero host
  syncs (test_no_host_sync.py).
"""

from __future__ import annotations

import io
import time

import numpy as np
import pytest

from torcheval_tpu import config, obs
from torcheval_tpu.metrics import Sum
from torcheval_tpu.metrics.toolkit import sync_and_compute
from torcheval_tpu.obs import flight as obs_flight
from torcheval_tpu.obs import watchdog as obs_watchdog
from torcheval_tpu.obs.flight import FLIGHT, diff_flight_rings
from torcheval_tpu.resilience import ResilientGroup, SyncTimeoutError
from torcheval_tpu.utils.test_utils import (
    FaultInjectionGroup,
    FaultSpec,
    ThreadWorld,
)


@pytest.fixture
def flight_on():
    """Flight recording enabled with clean rings; fully restored after."""
    FLIGHT.reset()
    FLIGHT.enable("test")
    try:
        yield FLIGHT
    finally:
        FLIGHT.disable("test")
        FLIGHT.reset()


# ------------------------------------------------------------ ring basics


def test_flight_record_lifecycle_and_counters(flight_on):
    r = FLIGHT.start(
        "allgather_object", payload_bytes=64, rank=1, world_size=4,
        state="enqueued",
    )
    assert r.state == "enqueued" and r.in_flight
    FLIGHT.issued(r)
    assert r.state == "issued" and r.attempts == 1
    FLIGHT.complete(r, ranks=(0, 1, 2, 3))
    assert r.state == "completed" and not r.in_flight
    assert r.ranks == (0, 1, 2, 3)
    counters = FLIGHT.counters()
    assert counters["completed_total"] == 1
    assert counters["in_flight"] == 0
    assert counters["enabled"] == 1
    snap = FLIGHT.snapshot()
    (ring,) = snap.values()
    assert ring["last_completed_seq"] == 1
    assert ring["records"][0]["op"] == "allgather_object"
    assert ring["records"][0]["payload_bytes"] == 64
    # wall timestamps were stamped at each transition
    rec = ring["records"][0]
    assert 0 < rec["t_enqueued"] <= rec["t_issued"] <= rec["t_done"]


def test_flight_ring_is_bounded(flight_on):
    FLIGHT.capacity = 8
    try:
        for _ in range(50):
            r = FLIGHT.start("allgather_object", rank=0, world_size=1)
            FLIGHT.complete(r, ranks=(0,))
    finally:
        FLIGHT.capacity = obs_flight.DEFAULT_RING_CAPACITY
    (ring,) = FLIGHT.rings().values()
    records = ring.tail()
    assert len(records) <= 8
    assert records[-1].seq == 50  # seq keeps counting past evictions


def test_disabled_flight_costs_one_attribute_read():
    FLIGHT.reset()
    assert not FLIGHT.enabled
    assert FLIGHT.start("allgather_object") is None
    FLIGHT.complete(None)  # no-ops, never raises
    FLIGHT.fail(None)
    FLIGHT.issued(None)
    assert FLIGHT.rings() == {}


def test_source_keyed_enable_survives_recorder_disable():
    """An armed watchdog's flight source outlives the event recorder:
    recorder on+off must not blind the watchdog."""
    FLIGHT.reset()
    rec = obs.recorder()
    prev = rec.enabled
    FLIGHT.enable("watchdog")
    try:
        rec.enable()
        assert FLIGHT.enabled
        rec.disable()
        assert FLIGHT.enabled  # the watchdog source holds it on
    finally:
        FLIGHT.disable("watchdog")
        if prev:
            rec.enable()
    assert not FLIGHT.enabled


def test_resilient_wrapper_records_one_record_per_collective(flight_on):
    """The resilient layer's record IS the collective's record: a worker
    thread running the inner gather must not add a second one (the
    suppression contract)."""
    world = ThreadWorld(2)

    def run(view):
        g = ResilientGroup(view, timeout=10.0, policy="quorum")
        g.allgather_object({"r": view.rank})
        g.allgather_object({"r": view.rank})
        return FLIGHT._ring().tail()

    results = world.run(run)
    for rank, records in enumerate(results):
        assert len(records) == 2, f"rank {rank}: one record per collective"
        assert [r.seq for r in records] == [1, 2]
        assert all(r.state == "completed" for r in records)
        assert all(r.attempts == 1 for r in records)


def test_retry_keeps_one_record_with_attempt_count(flight_on):
    """A transient wire glitch reissues the collective — the flight ring
    keeps ONE record whose ``attempts`` counts the reissues."""
    import copy

    class TwoRankFake:
        world_size = 2
        rank = 0
        is_member = True
        ranks = (0, 1)

        def unwrap(self):
            return self

        def allgather_object(self, obj):
            return [obj, copy.deepcopy(obj)]

        def allgather_array(self, x):
            x = np.asarray(x)
            return [x, x.copy()]

    g = ResilientGroup(
        FaultInjectionGroup(TwoRankFake(), [FaultSpec(0, "transient")]),
        timeout=10.0, retries=2, policy="quorum",
        backoff_base=0.001, backoff_max=0.002,
    )
    g.allgather_object({"r": 0})
    (record,) = FLIGHT._ring().tail()
    assert record.state == "completed"
    assert record.attempts == 2  # first attempt + one reissue


# ----------------------------------------------------------------- diffing


def _records(specs, rank):
    """specs: list of (seq, op, state)."""
    return [
        {
            "seq": seq, "op": op, "state": state, "rank": rank,
            "t_issued": time.time(), "payload_bytes": 0,
        }
        for seq, op, state in specs
    ]


def test_diff_names_stalled_rank_and_last_completed_seq():
    per_rank = {}
    for rank in range(4):
        if rank == 2:
            per_rank[rank] = _records(
                [(1, "allgather_object", "completed"),
                 (2, "allgather_object", "completed"),
                 (3, "allgather_object", "issued")],
                rank,
            )
        else:
            per_rank[rank] = _records(
                [(1, "allgather_object", "completed"),
                 (2, "allgather_object", "completed"),
                 (3, "allgather_object", "completed"),
                 (4, "allgather_object", "issued")],
                rank,
            )
    diff = diff_flight_rings(per_rank)
    assert not diff.ok
    assert diff.stalled_rank == 2
    assert diff.stalled_seq == 2  # its last COMPLETED ordinal
    assert diff.stalled_op == "allgather_object"
    assert diff.last_completed == {0: 3, 1: 3, 2: 2, 3: 3}
    assert "rank 2" in diff.format()


def test_diff_names_diverging_rank_via_collective_op_shapes():
    per_rank = {
        0: _records(
            [(1, "allgather_object", "completed"),
             (2, "allgather_array", "completed")], 0,
        ),
        1: _records(
            [(1, "allgather_object", "completed"),
             (2, "allgather_object", "completed")], 1,
        ),
    }
    diff = diff_flight_rings(per_rank)
    assert not diff.ok
    assert diff.diverged_rank == 1
    assert diff.divergence_seq == 2
    assert "would-deadlock" in diff.format()


def test_diff_consistent_rings_are_ok():
    per_rank = {
        r: _records([(1, "allgather_object", "completed")], r)
        for r in range(3)
    }
    diff = diff_flight_rings(per_rank)
    assert diff.ok and diff.findings == []


# ----------------------------------------------- hang forensics (acceptance)


def test_watchdog_trips_on_injected_stall_and_diff_names_the_rank():
    """ISSUE 11 acceptance: a FaultInjectionGroup-delayed collective in a
    ThreadWorld-4 trips the watchdog DURING the stall; the dump includes
    all ranks' flight rings; diff_flight_rings names the injected
    stalled rank and its last completed seq. Deterministic: the fault is
    scripted by call index, the watchdog deadline is far below the
    injected delay, and the delay is far below the collective timeout —
    the trip always lands inside the stall window."""
    sink = io.StringIO()
    FLIGHT.reset()
    rec = obs.recorder()
    prev = rec.enabled
    rec.reset()
    rec.enable()
    wd = obs_watchdog.arm_watchdog(0.25, poll=0.05, sink=sink)
    try:
        world = ThreadWorld(4, timeout=30.0)

        def run(view):
            # rank 2's third collective stalls 1.5 s >> 0.25 s deadline
            faults = (
                [FaultSpec(2, "delay", seconds=1.5)]
                if view.rank == 2 else []
            )
            g = ResilientGroup(
                FaultInjectionGroup(view, faults),
                timeout=20.0, policy="quorum",
            )
            for i in range(4):
                g.allgather_object({"rank": view.rank, "i": i})

        world.run(run)
        assert wd.trips >= 1
        trip = wd.last_trip
        assert trip["rank"] == 2
        assert trip["op"] == "allgather_object"
        assert trip["age_seconds"] >= 0.25

        # the dump carried ALL four ranks' rings
        assert sorted(trip["flight"]) == [0, 1, 2, 3]
        dump = sink.getvalue()
        assert "stall watchdog" in dump
        for rank in range(4):
            assert f"rank {rank}" in dump
        assert "IN FLIGHT" in dump

        # diff of the trip-time rings names the injected rank and its
        # last completed seq: rank 2 completed 2 collectives (seq 1-2)
        # and stalled in its 3rd, while peers completed 3 and block in
        # their 4th
        diff = diff_flight_rings(trip["flight"])
        assert not diff.ok
        assert diff.stalled_rank == 2
        assert diff.stalled_seq == 2
        assert diff.stalled_op == "allgather_object"
        assert max(diff.last_completed.values()) == 3

        # the StallEvent landed in the event ring, typed
        stalls = [e for e in rec.log.tail() if e.kind == "stall"]
        assert stalls, "watchdog trip must record a StallEvent"
        assert stalls[-1].op == "allgather_object"
        assert stalls[-1].rank == 2
        assert stalls[-1].deadline == 0.25
    finally:
        obs_watchdog.disarm_watchdog()
        rec.reset()
        if not prev:
            rec.disable()
        FLIGHT.reset()
    assert obs_watchdog.current_watchdog() is None


def test_watchdog_jsonl_dump_is_synchronous(tmp_path):
    """The forensics line is on disk when trip() returns — the process
    may be SIGKILLed the next instant."""
    import json

    path = tmp_path / "stalls.jsonl"
    FLIGHT.reset()
    FLIGHT.enable("test")
    wd = obs_watchdog.StallWatchdog(0.05, sink=None, jsonl=str(path))
    try:
        r = FLIGHT.start("allgather_object", rank=3, world_size=4)
        time.sleep(0.06)
        wd.trip(r, time.monotonic())
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 1
        payload = json.loads(lines[0])
        assert payload["kind"] == "stall"
        assert payload["op"] == "allgather_object"
        assert payload["rank"] == 3
        assert payload["schema"] == 1
        assert payload["flight"], "dump carries the flight snapshot"
        FLIGHT.complete(r, ranks=(0, 1, 2, 3))
    finally:
        FLIGHT.disable("test")
        FLIGHT.reset()


def test_watchdog_one_trip_per_stall_and_rearm():
    """A sustained stall logs ONE trip; after progress resumes a new
    stall trips again."""
    FLIGHT.reset()
    FLIGHT.enable("test")
    wd = obs_watchdog.StallWatchdog(0.08, poll=0.02, sink=None)
    wd.arm()
    try:
        r = FLIGHT.start("allgather_object", rank=0, world_size=2)
        time.sleep(0.4)  # several poll ticks past the deadline
        assert wd.trips == 1
        assert wd.tripped
        FLIGHT.complete(r, ranks=(0, 1))
        time.sleep(0.1)
        assert not wd.tripped  # progress cleared the stall
        r2 = FLIGHT.start("allgather_array", rank=0, world_size=2)
        time.sleep(0.2)
        assert wd.trips == 2
        FLIGHT.complete(r2, ranks=(0, 1))
    finally:
        wd.disarm()
        FLIGHT.disable("test")
        FLIGHT.reset()
    assert not wd.armed


def test_config_scope_arms_and_disarms_watchdog():
    with config.observability(watchdog=5.0):
        wd = obs_watchdog.current_watchdog()
        assert wd is not None and wd.armed
        assert wd.deadline == 5.0
        assert FLIGHT.enabled
        reg = obs.default_registry()
        assert "watchdog" in reg.sources
        assert reg.read()["watchdog"]["armed"] == 1
    assert obs_watchdog.current_watchdog() is None
    assert "watchdog" not in obs.default_registry().sources
    assert not FLIGHT.enabled


# --------------------------------------------------------- error forensics


def test_timeout_error_carries_flight_tail_and_retry_event():
    """ISSUE 11: on the ResilientGroup timeout path the raised error and
    the RetryEvent both carry the flight-ring tail."""
    FLIGHT.reset()
    rec = obs.recorder()
    prev = rec.enabled
    rec.reset()
    rec.enable()
    try:
        world = ThreadWorld(2, timeout=30.0)

        def run(view):
            if view.rank == 1:
                # rank 1 stays healthy: it deposits for rank 0's gather
                # so the delayed collective can eventually land (the
                # worker thread drains it late)
                view.allgather_object({"r": 1})
                return None
            faults = [FaultSpec(0, "delay", seconds=0.6, times=3)]
            g = ResilientGroup(
                FaultInjectionGroup(view, faults),
                timeout=0.1, retries=0, policy="raise",
            )
            with pytest.raises(SyncTimeoutError) as ei:
                g.allgather_object({"r": 0})
            return ei.value

        err = world.run(run)[0]
        assert hasattr(err, "flight_tail")
        assert "allgather_object" in err.flight_tail
        retry_events = [e for e in rec.log.tail() if e.kind == "retry"]
        timeouts = [e for e in retry_events if e.reason == "timeout"]
        assert timeouts
        assert any("allgather_object" in e.flight for e in timeouts)
    finally:
        rec.reset()
        if not prev:
            rec.disable()
        FLIGHT.reset()


# ------------------------------------------------------------ cross-rank IO


def test_gather_flight_merges_per_rank_rings():
    FLIGHT.reset()
    FLIGHT.enable("test")
    try:
        world = ThreadWorld(4)

        def run(view):
            g = ResilientGroup(view, timeout=20.0)
            g.allgather_object({"r": view.rank})
            return obs_flight.gather_flight(view)

        results = world.run(run)
        for merged in results:
            assert merged["world_size"] == 4
            assert merged["ranks"] == [0, 1, 2, 3]
            for rank in range(4):
                records = merged["per_rank"][rank]
                assert records, f"rank {rank} contributed records"
                assert records[0]["op"] == "allgather_object"
        # the gather itself was suppressed from the rings
        for ring in FLIGHT.rings().values():
            assert all(r.op != "kv_allgather" for r in ring.tail())
    finally:
        FLIGHT.disable("test")
        FLIGHT.reset()


def test_flight_rides_config_observability_and_eager_sync(tmp_path):
    """config.observability() alone (the PR 5 knob) now also leaves
    flight records for the eager sync's collectives — and restores the
    off state at scope exit."""

    class TwoRankGroup:
        world_size = 2
        rank = 0
        is_member = True
        ranks = (0, 1)

        def unwrap(self):
            return self

        def allgather_object(self, obj):
            import copy

            return [obj, copy.deepcopy(obj)]

        def allgather_array(self, x):
            x = np.asarray(x)
            return [x, x.copy()]

    FLIGHT.reset()
    m = Sum()
    m.update(np.float32([1.0, 2.0]))
    with config.observability():
        group = ResilientGroup(TwoRankGroup(), timeout=20.0)
        value = sync_and_compute(m, group)
        assert float(value) == pytest.approx(6.0)
        per_rank = FLIGHT.per_rank()
        assert 0 in per_rank
        ops = [r["op"] for r in per_rank[0]]
        assert "allgather_object" in ops
        assert all(r["state"] == "completed" for r in per_rank[0])
    assert not FLIGHT.enabled
    FLIGHT.reset()


def test_diff_flags_symmetric_hang_via_stall_age():
    """Review fix: a SYMMETRIC hang (every rank equally deep in a dead
    collective — same last-completed seq everywhere) must still be
    reported once the in-flight records age past ``stall_after``; a
    fresh snapshot of healthy ranks mid-collective must not."""
    def rings(issued_ago):
        return {
            r: [
                {"seq": 1, "op": "allgather_object", "state": "completed",
                 "rank": r, "t_issued": time.time() - issued_ago},
                {"seq": 2, "op": "allgather_object", "state": "issued",
                 "rank": r, "t_issued": time.time() - issued_ago},
            ]
            for r in range(4)
        }

    dead = diff_flight_rings(rings(issued_ago=60.0), stall_after=5.0)
    assert not dead.ok
    assert dead.stalled_rank == 0  # tie -> lowest rank named first
    assert dead.stalled_seq == 1
    assert dead.stalled_age >= 5.0
    assert "all ranks stalled" in dead.format()

    healthy = diff_flight_rings(rings(issued_ago=0.001), stall_after=5.0)
    assert healthy.ok  # a snapshot mid-collective is not a hang


def test_plain_group_issued_record_counts_its_attempt(flight_on):
    """Review fix: a record born in the issued state (plain groups — no
    queueing layer) carries attempts=1 and a real t_issued."""
    out = obs_flight.guarded_collective(
        "allgather_object", 16, 0, 2, lambda: ["a", "a"]
    )
    assert out == ["a", "a"]
    (record,) = FLIGHT._ring().tail()
    assert record.attempts == 1
    assert record.t_issued > 0.0


def test_scope_restores_preexisting_watchdog_and_monitor():
    """Review fix: a scoped watchdog/monitor must hand BACK whatever the
    process had armed before the scope, not strip it."""
    from torcheval_tpu.obs import monitor as obs_monitor

    outer_wd = obs_watchdog.arm_watchdog(120.0, sink=None)
    outer_mon = obs_monitor.arm_monitor()
    try:
        with config.observability(watchdog=5.0, slos=[]):
            inner = obs_watchdog.current_watchdog()
            assert inner is not None and inner.deadline == 5.0
            assert inner is not outer_wd
            assert obs_monitor.current_monitor() is not outer_mon
        restored = obs_watchdog.current_watchdog()
        assert restored is outer_wd and restored.armed
        assert restored.deadline == 120.0
        assert obs_monitor.current_monitor() is outer_mon
        assert "watchdog" in obs.default_registry().sources
        assert "slo" in obs.default_registry().sources
    finally:
        obs_watchdog.disarm_watchdog()
        obs_monitor.disarm_monitor()
    assert obs_watchdog.current_watchdog() is None


def test_failed_server_start_does_not_leak_armed_watchdog():
    """Review fix: arming happens INSIDE the scope's try — a serve port
    that fails to bind still tears down the already-armed watchdog and
    monitor."""
    import socket

    from torcheval_tpu.obs import monitor as obs_monitor

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        with pytest.raises(OSError):
            with config.observability(watchdog=5.0, slos=[], serve=port):
                raise AssertionError("scope must not open")
        assert obs_watchdog.current_watchdog() is None
        assert obs_monitor.current_monitor() is None
        assert obs.current_server() is None
        assert not FLIGHT.enabled
    finally:
        blocker.close()
