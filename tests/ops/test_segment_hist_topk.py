"""Per-kernel parity: Histogram / Bincount / TopK / SegmentSum /
SegmentCount native CPU kernels vs their pure-XLA twins.

Every dispatcher promises the native path is BIT-IDENTICAL to the XLA
twin (the fallback contract in docs/api.md). These tests drive the
public entry points — which route native when the library is loadable —
and compare against the twins called directly, across dtypes
(f32 native / bf16 and f64-disabled fallbacks), empty inputs, ties, and
NaN propagation — mirroring the cross_entropy non-finite parity pin.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torcheval_tpu.ops import (
    bincount,
    histogram,
    segment_count,
    segment_max,
    segment_sum,
    topk,
)
from torcheval_tpu.ops.histogram import _histogram_xla
from torcheval_tpu.ops.segment import (
    _segment_count_xla,
    _segment_max_xla,
    _segment_sum_xla,
)
from torcheval_tpu.ops.topk import _topk_xla

RNG = np.random.default_rng(41)


def _native_available():
    from torcheval_tpu.ops import native

    return native.ensure_registered()


# ------------------------------------------------------------ segment_sum


@pytest.mark.parametrize("n,segments", [(1, 1), (257, 16), (4096, 100)])
def test_segment_sum_parity(n, segments):
    data = jnp.asarray(RNG.normal(size=n).astype(np.float32))
    # includes out-of-range ids on BOTH sides: dropped on both paths
    ids = jnp.asarray(
        RNG.integers(-3, segments + 3, size=n).astype(np.int32)
    )
    got = segment_sum(data, ids, segments)
    want = _segment_sum_xla(data, ids, segments)
    assert got.dtype == want.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_segment_sum_nan_propagates():
    """A NaN datum poisons exactly its segment, nothing else."""
    data = jnp.asarray(np.array([1.0, np.nan, 2.0], np.float32))
    ids = jnp.asarray(np.array([0, 1, 2], np.int32))
    got = np.asarray(segment_sum(data, ids, 3))
    assert got[0] == 1.0 and np.isnan(got[1]) and got[2] == 2.0


def test_segment_sum_empty_and_f64_fallback():
    empty = segment_sum(
        jnp.zeros((0,), jnp.float32), jnp.zeros((0,), jnp.int32), 4
    )
    np.testing.assert_array_equal(np.asarray(empty), np.zeros(4, np.float32))
    # non-f32 data falls back to the XLA twin (same values)
    data = jnp.asarray(RNG.normal(size=64).astype(np.float32))
    ids = jnp.asarray(RNG.integers(0, 4, size=64).astype(np.int32))
    got16 = segment_sum(data.astype(jnp.bfloat16), ids, 4)
    want16 = _segment_sum_xla(data.astype(jnp.bfloat16), ids, 4)
    np.testing.assert_array_equal(
        np.asarray(got16, np.float32), np.asarray(want16, np.float32)
    )


def test_segment_sum_grad_matches_twin():
    data = jnp.asarray(RNG.normal(size=64).astype(np.float32))
    ids = jnp.asarray(RNG.integers(-1, 5, size=64).astype(np.int32))
    g = jax.grad(lambda d: segment_sum(d, ids, 4)[2])(data)
    gw = jax.grad(lambda d: _segment_sum_xla(d, ids, 4)[2])(data)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(gw))


# ---------------------------------------------------------- segment_count


@pytest.mark.parametrize("mask", [None, "with_mask"])
def test_segment_count_parity(mask):
    ids = jnp.asarray(RNG.integers(-2, 12, size=999).astype(np.int32))
    m = (
        None
        if mask is None
        else jnp.asarray(RNG.integers(0, 3, size=999).astype(np.int32))
    )
    got = segment_count(ids, 10, mask=m)
    want = _segment_count_xla(ids, 10, m)
    assert got.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_segment_count_float_mask_parity_and_native():
    """The house-standard validity mask is float32 (valid_mask's default):
    the dispatcher normalizes it via ``!= 0`` rather than falling back, so
    fractional values count as nonzero exactly like the XLA twin."""
    ids = jnp.asarray(RNG.integers(-2, 12, size=999).astype(np.int32))
    m = jnp.asarray(RNG.choice([0.0, 0.5, 1.0], size=999).astype(np.float32))
    got = segment_count(ids, 10, mask=m)
    want = _segment_count_xla(ids, 10, m)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    if _native_available():
        text = (
            jax.jit(lambda i, mm: segment_count(i, 10, mask=mm))
            .lower(ids, m)
            .compile()
            .as_text()
        )
        assert "torcheval_segment_count" in text


def test_segment_count_empty():
    got = segment_count(jnp.zeros((0,), jnp.int32), 3)
    np.testing.assert_array_equal(np.asarray(got), np.zeros(3, np.int32))


# ---------------------------------------------------------- segment_max


@pytest.mark.parametrize("identity", [0, -5])
def test_segment_max_parity(identity):
    """Native vs dense-twin vs jax scatter-max: identical maxima, with
    empty segments holding the caller's identity and out-of-range ids
    dropped on every path."""
    ids = jnp.asarray(
        RNG.integers(-2, 12, size=256).astype(np.int32)
    )  # some dropped
    data = jnp.asarray(RNG.integers(-3, 30, size=256).astype(np.int32))
    got = segment_max(data, ids, 16, identity=identity)
    twin = _segment_max_xla(data, ids, 16, identity)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(twin))
    # vs the scatter reference where segments are hit
    ref = np.full(16, identity, np.int32)
    for d, i in zip(np.asarray(data), np.asarray(ids)):
        if 0 <= i < 16:
            ref[i] = max(ref[i], d)
    np.testing.assert_array_equal(np.asarray(got), ref)
    # empty segments (10..15 unhit at size-16 with ids < 12) hold identity
    assert np.asarray(got)[
        np.setdiff1d(np.arange(16), np.asarray(ids))
    ].tolist() == [
        identity
    ] * len(np.setdiff1d(np.arange(16), np.asarray(ids)))


def test_segment_max_empty_and_fallback_dtypes():
    # empty input: identity everywhere (XLA twin path — size 0 skips
    # the native dispatch)
    out = segment_max(
        jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.int32), 4,
        identity=7,
    )
    np.testing.assert_array_equal(np.asarray(out), np.full(4, 7, np.int32))


def test_segment_max_under_jit_matches_eager():
    ids = jnp.asarray(RNG.integers(0, 8, size=64).astype(np.int32))
    data = jnp.asarray(RNG.integers(0, 100, size=64).astype(np.int32))
    eager = segment_max(data, ids, 8)
    jitted = jax.jit(lambda d, i: segment_max(d, i, 8))(data, ids)
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(jitted))


# -------------------------------------------------------------- histogram


@pytest.mark.parametrize(
    "bounds", [(0.0, 1.0), (0.1, 0.3), (-2.5, 7.0)]
)
@pytest.mark.parametrize("weighted", [False, True])
def test_histogram_parity(bounds, weighted):
    """Bit-identical across awkward (non-ULP-exact) bounds — the edge
    constants must be narrowed identically on both paths."""
    lo, hi = bounds
    v = jnp.asarray(
        RNG.uniform(lo - 1.0, hi + 1.0, size=4096).astype(np.float32)
    )
    w = (
        jnp.asarray(RNG.uniform(size=4096).astype(np.float32))
        if weighted
        else None
    )
    got = histogram(v, 37, bounds=bounds, weights=w)
    want = _histogram_xla(v, w, 37, lo, hi)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_histogram_nan_and_range_drops():
    v = jnp.asarray(
        np.array([0.5, np.nan, -np.inf, np.inf, -0.1, 1.1, 0.0, 1.0],
                 np.float32)
    )
    got = np.asarray(histogram(v, 4, bounds=(0.0, 1.0)))
    # kept: 0.5 (bin 2), 0.0 (bin 0), 1.0 (last bin, closed right edge)
    np.testing.assert_array_equal(got, [1.0, 0.0, 1.0, 1.0])
    # NaN WEIGHT on a valid sample propagates into its bin (both paths)
    w = jnp.asarray(np.array([np.nan, 1, 1, 1, 1, 1, 1, 1], np.float32))
    got = np.asarray(histogram(v, 4, bounds=(0.0, 1.0), weights=w))
    want = np.asarray(_histogram_xla(v, w, 4, 0.0, 1.0))
    np.testing.assert_array_equal(got, want)
    assert np.isnan(got[2])


def test_histogram_empty_and_dtype_fallback():
    got = histogram(jnp.zeros((0,), jnp.float32), 5, bounds=(0.0, 1.0))
    np.testing.assert_array_equal(np.asarray(got), np.zeros(5, np.float32))
    v = jnp.asarray(RNG.uniform(size=256).astype(np.float32))
    got16 = histogram(v.astype(jnp.bfloat16), 8, bounds=(0.0, 1.0))
    want16 = _histogram_xla(
        v.astype(jnp.bfloat16).astype(jnp.float32), None, 8, 0.0, 1.0
    )
    np.testing.assert_array_equal(np.asarray(got16), np.asarray(want16))


def test_histogram_rejects_bad_bounds():
    with pytest.raises(ValueError, match="hi > lo"):
        histogram(jnp.zeros(4), 4, bounds=(1.0, 1.0))


def test_histogram_weight_grad_matches_twin():
    v = jnp.asarray(RNG.uniform(size=128).astype(np.float32))
    w = jnp.asarray(RNG.uniform(size=128).astype(np.float32))
    g = jax.grad(
        lambda w: histogram(v, 8, bounds=(0.0, 1.0), weights=w)[3]
    )(w)
    gw = jax.grad(lambda w: _histogram_xla(v, w, 8, 0.0, 1.0)[3])(w)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(gw))


# --------------------------------------------------------------- bincount


def test_bincount_counts_and_weights():
    ids = jnp.asarray(RNG.integers(-1, 12, size=500).astype(np.int32))
    got = bincount(ids, 10)
    want = _segment_count_xla(ids, 10, None)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    w = jnp.asarray(RNG.uniform(size=500).astype(np.float32))
    goww = bincount(ids, 10, weights=w)
    waww = _segment_sum_xla(w, ids, 10)
    np.testing.assert_array_equal(np.asarray(goww), np.asarray(waww))


def test_bincount_int64_ids_do_not_wrap():
    """An int64 id past 2^31 must be dropped, not wrapped into range by
    the int32 cast (possible only under jax_enable_x64 — x64-disabled
    jax never materializes an int64 array in the first place)."""
    import jax.experimental

    with jax.experimental.enable_x64():
        ids = jnp.asarray(
            np.array([0, 2**31 + 1, 2**33 + 2, -5], np.int64)
        )
        assert ids.dtype == jnp.int64
        got = np.asarray(bincount(ids, 8))
    want = np.zeros(8, got.dtype)
    want[0] = 1
    np.testing.assert_array_equal(got, want)

    with pytest.raises(ValueError, match="integers"):
        bincount(jnp.zeros(4, jnp.float32), 8)


# ------------------------------------------------------------------- topk


@pytest.mark.parametrize("shape,k", [((100,), 5), ((7, 257), 17),
                                     ((3, 64), 64), ((2, 5), 1)])
def test_topk_parity(shape, k):
    x = jnp.asarray(RNG.normal(size=shape).astype(np.float32))
    got_v, got_i = topk(x, k)
    want_v, want_i = _topk_xla(x, k)
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    assert got_i.dtype == want_i.dtype


def test_topk_ties_and_specials():
    """Ties keep ascending index; NaN / ±inf / ±0 follow lax.top_k's
    descending totalOrder exactly (NaN first, -0 below +0)."""
    rows = np.array(
        [
            [1.0, 3.0, 3.0, 2.0, 3.0, -1.0],
            [np.nan, 1.0, -np.inf, np.inf, np.nan, 0.5],
            [0.0, -0.0, 5.0, -5.0, 0.0, -0.0],
            [2.0, 2.0, 2.0, 2.0, 2.0, 2.0],
        ],
        np.float32,
    )
    x = jnp.asarray(rows)
    for k in (1, 3, 6):
        got_v, got_i = topk(x, k)
        want_v, want_i = _topk_xla(x, k)
        np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
        np.testing.assert_array_equal(
            np.asarray(got_v), np.asarray(want_v)
        )  # NaN positions already pinned by the index equality


def test_topk_empty_k0_and_dtype_fallback():
    v, i = topk(jnp.zeros((2, 4), jnp.float32), 0)
    assert v.shape == (2, 0) and i.shape == (2, 0)
    x = jnp.asarray(RNG.normal(size=(3, 9)).astype(np.float32))
    got = topk(x.astype(jnp.bfloat16), 4)
    want = _topk_xla(x.astype(jnp.bfloat16), 4)
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))
    with pytest.raises(ValueError, match="k must be"):
        topk(x, 10)


def test_topk_grad_matches_twin():
    x = jnp.asarray(RNG.normal(size=(4, 32)).astype(np.float32))
    g = jax.grad(lambda x: topk(x, 5)[0].sum())(x)
    gw = jax.grad(lambda x: _topk_xla(x, 5)[0].sum())(x)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(gw))


def test_topk_vmap_parity():
    x = jnp.asarray(RNG.normal(size=(6, 40)).astype(np.float32))
    got = jax.vmap(lambda r: topk(r, 3))(x)
    want = jax.vmap(lambda r: _topk_xla(r, 3))(x)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))


# ------------------------------------------------- f64-disabled behavior


def test_f64_disabled_int64_guard():
    """Under default (x64-disabled) jax, int inputs canonicalize to
    int32 and the native path engages; the parity above covers it. This
    pin documents that the dispatch NEVER routes raw int64 ids to the
    int32 kernel (the bincount wrap test is the value-level proof)."""
    ids = jnp.asarray(np.arange(10, dtype=np.int64))
    assert ids.dtype == jnp.int32  # canonicalized by x64-disabled jax
    got = segment_count(ids, 10)
    np.testing.assert_array_equal(np.asarray(got), np.ones(10, np.int32))
