"""Varying-manual-axes helpers (shard_map vma/replication bookkeeping).

Three consumers:

- scan-carrying parallel primitives (ring attention, GPipe): a
  ``lax.scan`` carry inside ``shard_map`` must be typed varying over every
  manual axis the step outputs vary over — the union of the inputs'
  varying axes plus the primitive's own collective axis, not just the
  latter. Under a composed mesh (e.g. dp x sp) the inputs are also
  dp-varying, so a carry pcast only over the ring/pipeline axis trips a
  trace-time carry-type mismatch
  (pinned by tests/parallel/test_composed_mesh.py);
- native-kernel outputs (``metrics/functional/tensor_utils._match_vma``):
  ffi_call results come back unmarked and must re-acquire their
  reference operand's vma;
- the in-jit EXTEND state sync (``metrics/sharded.py``): a true
  ``lax.all_gather`` produces a value that IS identical on every shard of
  the gathered axes, but shard_map's replication checker does not know
  that, so an unpartitioned ``out_specs`` rejects it.
  :func:`gather_replicated` performs the gather AND makes the checker
  accept the result, choosing the best mechanism the running jax offers.
"""

from __future__ import annotations

from typing import Any, Tuple, Union

import jax
from jax import lax

# vma typing landed after jax 0.4.x; older shard_map has no varying-axes
# bookkeeping, so on those versions both helpers reduce to no-ops (there is
# no carry-type mismatch to repair when nothing is tracked).
_HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pcast")

AxisNames = Union[str, Tuple[str, ...]]


def _leaf_vma(leaf: Any) -> Tuple[str, ...]:
    try:
        return tuple(jax.typeof(leaf).vma)
    except Exception:
        return ()


def union_vary_axes(*values: Any, axis_name: str) -> Tuple[str, ...]:
    """The union of every leaf's varying manual axes plus ``axis_name``,
    in first-seen order."""
    axes = []
    if _HAS_VMA:
        for value in values:
            for leaf in jax.tree_util.tree_leaves(value):
                axes.extend(_leaf_vma(leaf))
    axes.append(axis_name)
    return tuple(dict.fromkeys(axes))


def pcast_varying(x: jax.Array, vary_axes: Tuple[str, ...]) -> jax.Array:
    """Mark ``x`` varying over the axes in ``vary_axes`` it does not
    already vary over (``lax.pcast`` rejects re-marking a varying axis)."""
    if not _HAS_VMA:
        return x
    missing = tuple(a for a in vary_axes if a not in _leaf_vma(x))
    return lax.pcast(x, missing, to="varying") if missing else x


# ------------------------------------------------- replicated all_gather

# Tri-state: None = not probed yet; True = the running jax's shard_map
# rule tables accepted the all_gather replication rule; False = no table
# to patch (use the psum fallback unless all_gather_invariant exists).
_AG_RULE_INSTALLED = None


def _axis_tuple(axis_name: AxisNames) -> Tuple[str, ...]:
    return axis_name if isinstance(axis_name, tuple) else (axis_name,)


def _install_all_gather_replication_rule() -> bool:
    """Teach pre-vma shard_map that a (full-group, tiled or stacked)
    ``all_gather`` output is replicated over the gathered axes.

    jax <= 0.4.x ships shard_map with only the varying->varying "standard
    collective" rule for ``all_gather_p`` — mathematically too weak (the
    gathered value IS equal on every shard of the axis), which is why the
    in-jit EXTEND sync historically used a gather-as-psum (O(world x size)
    wire) instead. Registering the missing-but-correct rule in both of
    shard_map's rule tables (the jaxpr replication check and the
    efficient-transpose rewrite) lets the true all_gather through. Gathers
    over ``axis_index_groups`` subsets keep the conservative old behavior:
    a subgroup gather is NOT globally replicated.
    """
    global _AG_RULE_INSTALLED
    if _AG_RULE_INSTALLED is not None:
        return _AG_RULE_INSTALLED
    try:
        from jax.experimental import shard_map as _sm
        from jax._src.lax import parallel as _par

        ag_p = _par.all_gather_p
        check_rules = _sm._check_rules
        rewrite_rules = _sm._rewrite_rules
    except (ImportError, AttributeError):
        _AG_RULE_INSTALLED = False
        return False

    def _ag_check(mesh, x_rep, *, axis_name, axis_index_groups=None, **params):
        del mesh, params
        names = _axis_tuple(axis_name)
        if axis_index_groups is not None or x_rep is None:
            return x_rep
        return set(x_rep) | set(names)

    def _ag_rewrite(mesh, in_rep, x, *, axis_name,
                    axis_index_groups=None, **params):
        del mesh
        names = _axis_tuple(axis_name)
        (x_rep,) = in_rep
        out = ag_p.bind(
            x, axis_name=axis_name, axis_index_groups=axis_index_groups,
            **params,
        )
        if axis_index_groups is not None:
            return [out], [set(x_rep)]
        return [out], [set(x_rep) | set(names)]

    check_rules[ag_p] = _ag_check
    rewrite_rules[ag_p] = _ag_rewrite
    _AG_RULE_INSTALLED = True
    return True


def gather_replicated(x: jax.Array, axis_name: AxisNames) -> jax.Array:
    """``lax.all_gather(x, axis_name, tiled=True)`` whose result passes
    shard_map's replication checker — concatenation along axis 0, shards
    ordered by the axes' row-major linear index.

    Wire cost is the all-gather's O(size) per hop, not the historical
    psum trick's O(world x size) zero-buffer all-reduce (pinned by
    tests/metrics/test_sync_collective_structure.py). Mechanism, best
    first: native ``lax.all_gather_invariant`` (vma-capable jax), the
    installed replication rule (pre-vma jax, see
    :func:`_install_all_gather_replication_rule`), else the psum trick as
    a correctness fallback on jax versions with neither.
    """
    if hasattr(lax, "all_gather_invariant"):
        return lax.all_gather_invariant(x, axis_name, tiled=True)
    if _install_all_gather_replication_rule():
        return lax.all_gather(x, axis_name, tiled=True)
    # fallback: scatter into a zero [world, ...] buffer and all-reduce —
    # psum output is statically known replicated on every jax version
    names = _axis_tuple(axis_name)
    world = 1
    idx = 0
    for name in names:  # row-major linearization matches all_gather order
        size = lax.psum(1, name)
        world = world * size
        idx = idx * size + lax.axis_index(name)
    import jax.numpy as jnp

    buf = jnp.zeros((world,) + x.shape, x.dtype).at[idx].set(x)
    gathered = lax.psum(buf, names)
    return jnp.reshape(gathered, (-1,) + tuple(x.shape[1:]))
