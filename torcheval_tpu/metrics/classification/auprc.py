"""AUPRC class metrics.

Parity: reference torcheval/metrics/classification/auprc.py (BinaryAUPRC :31,
MulticlassAUPRC :154, MultilabelAUPRC :296) — example-buffering states.
"""

from __future__ import annotations

from typing import Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.auprc import (
    _binary_auprc_kernel,
    _binary_auprc_update_input_check,
    _multiclass_auprc_kernel,
    _multiclass_auprc_param_check,
    _multiclass_auprc_update_input_check,
    _multilabel_auprc_kernel,
    _multilabel_auprc_param_check,
    _multilabel_auprc_update_input_check,
)
from torcheval_tpu.metrics._buffer import BufferedExamplesMetric

T = TypeVar("T")


class _BufferedPairMetric(BufferedExamplesMetric):
    """Shared buffered (inputs, targets) plumbing for curve metrics.

    Fixed-shape power-of-2 device buffers + valid count (see
    ``torcheval_tpu.metrics._buffer``), replacing the reference's Python
    list-append states (reference classification/auprc.py:87-89-style).
    Score padding is ``-inf`` (sorts after every real score); target padding
    is ``-1`` (matches no class / no positive label), so curve kernels can
    consume the full padded buffer and compile only O(log n) times.
    """

    _concat_axis = 0   # sample axis of update batches
    _target_fill = -1.0

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_buffer("inputs", fill=-jnp.inf, axis=self._concat_axis)
        self._add_buffer(
            "targets", fill=self._target_fill, axis=self._concat_axis
        )

    def _append(self, input: jax.Array, target: jax.Array) -> None:
        BufferedExamplesMetric._append(self, inputs=input, targets=target)

    def _concat(self):
        """Exact-size (count-length) views for kernels that are not
        pad-neutral; pad-neutral kernels should use ``_padded()``."""
        return self._valid()


class BinaryAUPRC(_BufferedPairMetric):
    """AUPRC (average precision by Riemann sum) for binary classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryAUPRC
        >>> metric = BinaryAUPRC()
        >>> metric.update(jnp.array([0.1, 0.5, 0.7, 0.8]),
        ...               jnp.array([1, 0, 1, 1]))
        >>> metric.compute()
        Array(0.9167, dtype=float32)
    """

    _concat_axis = -1

    def __init__(self, *, num_tasks: int = 1, device=None) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.num_tasks = num_tasks

    def update(self, input, target) -> "BinaryAUPRC":
        input, target = self._input(input), self._input(target)
        _binary_auprc_update_input_check(input, target, self.num_tasks)
        self._append(input, target)
        return self

    def compute(self) -> jax.Array:
        # pad-neutral kernel: padded entries (score -inf, target -1) add no
        # true positives and only trailing zero-width Riemann segments
        inputs, targets = self._padded()
        return _binary_auprc_kernel(inputs, targets)


class MulticlassAUPRC(_BufferedPairMetric):
    """One-vs-rest AUPRC for multiclass classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassAUPRC
        >>> metric = MulticlassAUPRC(num_classes=3)
        >>> metric.update(jnp.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1],
        ...                  [0.1, 0.2, 0.7], [0.3, 0.5, 0.2]]), jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(
        self,
        *,
        num_classes: int,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multiclass_auprc_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average

    def update(self, input, target) -> "MulticlassAUPRC":
        input, target = self._input(input), self._input(target)
        _multiclass_auprc_update_input_check(input, target, self.num_classes)
        self._append(input, target)
        return self

    def compute(self) -> jax.Array:
        inputs, targets = self._padded()
        auprcs = _multiclass_auprc_kernel(inputs, targets)
        if self.average == "macro":
            return jnp.mean(auprcs)
        return auprcs


class MultilabelAUPRC(_BufferedPairMetric):
    """Per-label AUPRC for multilabel classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MultilabelAUPRC
        >>> metric = MultilabelAUPRC(num_labels=3)
        >>> metric.update(jnp.array([[0.9, 0.2, 0.8], [0.1, 0.7, 0.3], [0.6, 0.5, 0.4]]), jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 1]]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(
        self,
        *,
        num_labels: int,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multilabel_auprc_param_check(num_labels, average)
        self.num_labels = num_labels
        self.average = average

    def update(self, input, target) -> "MultilabelAUPRC":
        input, target = self._input(input), self._input(target)
        _multilabel_auprc_update_input_check(input, target, self.num_labels)
        self._append(input, target)
        return self

    def compute(self) -> jax.Array:
        inputs, targets = self._padded()
        auprcs = _multilabel_auprc_kernel(inputs, targets)
        if self.average == "macro":
            return jnp.mean(auprcs)
        return auprcs
