"""Device-cost accounting: what does a metric panel cost to keep?

The reference paper's ``tools/`` layer answers this for MODELS (module
summaries, FLOP counts); this module answers it for METRIC STATE and the
programs that update it — the question a serving-scale eval panel has to
answer before it can be scheduled: *how many device bytes does each
metric's state pin, and what does one update program cost to run?*

Three layers, all static — nothing here executes a step:

- :func:`state_bytes` / :func:`memory_report` — per-metric state bytes
  from a host-side walk of the REGISTERED state leaves (``jax.Array``
  ``nbytes`` is shape×dtype metadata; int/float scalars count as 8).
  Works on any constructed metric, fed or not.
- :func:`program_costs` — per-program ``peak``/``temp``/``argument``
  bytes via ``compiled.memory_analysis()`` and FLOPs via the
  ``cost_analysis()`` path ``tools/flops.py`` established. Both APIs are
  backend/version-dependent, so every field degrades to ``None`` rather
  than raising (the jax-version posture of ``_ffi.py``).
- :func:`metric_update_costs` — :func:`program_costs` of a metric's own
  fused update program, lowered from its ``_update_plan`` with the
  CURRENT state avals (the same program ``_apply_update_plan``
  dispatches; compile-cached by jit, so repeated calls are cheap).

:func:`track_metrics` federates the state-bytes walk into the
``CounterRegistry`` as a pull-based source, so one Prometheus scrape
answers "what does this metric panel cost" next to the sync/compile/
snapshot counters (ISSUE 8 tentpole d).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Optional

__all__ = [
    "logical_state_bytes",
    "memory_report",
    "metric_update_costs",
    "per_rank_state_bytes",
    "program_costs",
    "state_bytes",
    "track_metrics",
]


def _leaf_bytes(value: Any) -> int:
    """Device bytes of one TState leaf (metadata only — no device sync).

    int/float scalar states count as 8 (one 64-bit host word): they live
    on the host, but they are part of the state a sync ships and a
    snapshot persists, so the report includes them rather than hiding
    them at 0.
    """
    import jax

    if isinstance(value, jax.Array):
        return int(value.nbytes)
    if isinstance(value, (int, float)):
        return 8
    if isinstance(value, (list, tuple)):
        return sum(_leaf_bytes(v) for v in value)
    if isinstance(value, dict):
        return sum(_leaf_bytes(v) for v in value.values())
    return 0


def state_bytes(metric) -> Dict[str, int]:
    """Per-state device bytes of one metric: ``{state_name: bytes}``
    over the states registered via ``Metric._add_state`` (the same
    registry ``state_dict``/sync/snapshot traverse)."""
    return {
        name: _leaf_bytes(getattr(metric, name))
        for name in metric._state_name_to_default
    }


def _shard_count(value: Any) -> int:
    """How many equal shards a mesh-distributed array splits into (1 for
    replicated / single-device arrays) — metadata only, from the
    sharding's shard shape."""
    import jax

    if not isinstance(value, jax.Array):
        return 1
    sharding = getattr(value, "sharding", None)
    if sharding is None or getattr(sharding, "is_fully_replicated", True):
        return 1
    try:
        shard_shape = sharding.shard_shape(value.shape)
    except Exception:  # noqa: BLE001 — exotic shardings degrade to replicated
        return 1
    full = 1
    for a, b in zip(value.shape, shard_shape):
        if b:
            full *= -(-int(a) // int(b))  # ceil-div per partitioned dim
    return max(int(full), 1)


def per_rank_state_bytes(metric) -> Dict[str, int]:
    """Per-state bytes THIS rank/device actually pins.

    Eager-sharded states already live as this rank's slice, so the live
    walk is the answer; mesh-sharded states report ``nbytes / shards``
    (the per-device block, from sharding metadata — no device sync).
    Replicated states equal :func:`state_bytes`.
    """
    out: Dict[str, int] = {}
    for name in metric._state_name_to_default:
        value = getattr(metric, name)
        out[name] = _leaf_bytes(value) // _shard_count(value)
    return out


def logical_state_bytes(metric) -> Dict[str, int]:
    """Per-state bytes of the LOGICAL (unsharded) state — what one
    replica would pin. Sharded states report their registered logical
    shape (``Metric._sharded_states``); hash-partitioned metrics (the
    keyed ``table.MetricTable``) supply their own accounting via the
    ``_logical_state_nbytes`` hook (per-key rows x the last-known
    global key count); everything else equals the live walk. Routed
    outbox buffers are per-rank overhead and count as-is (the ``small
    constant`` in the size/world contract)."""
    import numpy as np

    hook = getattr(metric, "_logical_state_nbytes", None)
    if hook is not None:
        return dict(hook())
    sharded = getattr(metric, "_sharded_states", None) or {}
    out: Dict[str, int] = {}
    for name in metric._state_name_to_default:
        info = sharded.get(name)
        if info is not None:
            out[name] = int(
                info.logical_size * np.dtype(info.dtype).itemsize
            )
        else:
            out[name] = _leaf_bytes(getattr(metric, name))
    return out


def memory_report(
    metrics: Mapping[str, Any],
) -> Dict[str, Dict[str, Any]]:
    """Per-metric state-byte accounting for a ``{name: Metric}`` panel.

    Returns ``{name: {"metric": class-name, "state_bytes": total,
    "logical_bytes": ..., "per_rank_bytes": ..., "sharded": bool,
    "states": {state: bytes}}}``. ``logical_bytes`` is what one
    unsharded replica would pin; ``per_rank_bytes`` is what THIS
    rank/device pins (equal for replicated families; ``~logical/world +
    outbox`` for sharded ones — the ISSUE 9 acceptance measurement).
    Pure metadata walk — no step executes, no device sync, no collective
    (pinned by the transfer-guard variant in
    tests/metrics/test_tracing.py). When the observability recorder is
    on, one :class:`~torcheval_tpu.obs.events.MemoryEvent` per metric
    lands in the event stream.
    """
    from torcheval_tpu.obs.recorder import RECORDER

    report: Dict[str, Dict[str, Any]] = {}
    for name, metric in metrics.items():
        per_state = state_bytes(metric)
        total = sum(per_state.values())
        logical = sum(logical_state_bytes(metric).values())
        per_rank = sum(per_rank_state_bytes(metric).values())
        report[name] = {
            "metric": type(metric).__name__,
            "state_bytes": total,
            "logical_bytes": logical,
            "per_rank_bytes": per_rank,
            "sharded": per_rank != logical,
            "states": per_state,
        }
        if RECORDER.enabled:
            from torcheval_tpu.obs.events import MemoryEvent

            RECORDER.record(
                MemoryEvent(
                    metric=name,
                    state_bytes=total,
                    states=len(per_state),
                    logical_bytes=logical,
                    per_rank_bytes=per_rank,
                )
            )
    return report


def program_costs(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Dict[str, Optional[float]]:
    """Compile-time cost sheet of one jittable call: FLOPs (the
    ``tools/flops.py`` cost-analysis path) and bytes from
    ``compiled.memory_analysis()``. Args may be arrays or
    ``jax.ShapeDtypeStruct`` avals — nothing executes.

    Returns ``{"flops", "argument_bytes", "output_bytes", "temp_bytes",
    "peak_bytes", "generated_code_bytes"}``; any field the jax version
    or backend cannot supply is ``None`` (never raises for a missing
    API). ``peak_bytes`` is the buffer-liveness upper bound
    ``argument + output + temp`` when XLA does not report a tighter
    peak directly.
    """
    import jax

    out: Dict[str, Optional[float]] = {
        "flops": None,
        "argument_bytes": None,
        "output_bytes": None,
        "temp_bytes": None,
        "peak_bytes": None,
        "generated_code_bytes": None,
    }
    try:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    except Exception:  # noqa: BLE001 — a non-lowerable fn costs None, not a crash
        return out
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — version/backend-dependent API
        ma = None
    if ma is not None:
        for field, attr in (
            ("argument_bytes", "argument_size_in_bytes"),
            ("output_bytes", "output_size_in_bytes"),
            ("temp_bytes", "temp_size_in_bytes"),
            ("generated_code_bytes", "generated_code_size_in_bytes"),
        ):
            value = getattr(ma, attr, None)
            if value is not None:
                out[field] = int(value)
        peak = getattr(ma, "peak_memory_in_bytes", None)
        if peak is None and None not in (
            out["argument_bytes"], out["output_bytes"], out["temp_bytes"]
        ):
            peak = out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]  # type: ignore[operator]
        if peak is not None:
            out["peak_bytes"] = int(peak)
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):  # older jax: one dict per device program
            ca = ca[0] if ca else None
        if ca and "flops" in ca:
            out["flops"] = float(ca["flops"])
    except Exception:  # noqa: BLE001 — version/backend-dependent API
        pass
    return out


def metric_update_costs(metric, *args: Any, **kwargs: Any) -> Optional[Dict[str, Optional[float]]]:
    """:func:`program_costs` of ``metric``'s fused update program for
    one example batch — the program ``_apply_update_plan`` actually
    dispatches, lowered with the metric's live state avals. Returns
    ``None`` for metrics without a fusable plan (host-side text
    processing, buffered appends)."""
    from torcheval_tpu.metrics import _fuse
    from torcheval_tpu.metrics.metric import UpdatePlan

    plan = metric._update_plan(*args, **kwargs)
    if plan is None:
        return None
    if isinstance(plan, UpdatePlan):
        kernel, names, dynamic, config = (
            plan.kernel, plan.state_names, plan.dynamic, plan.config
        )
        transform = plan.transform
    else:
        kernel, names, dynamic, *rest = plan
        config = rest[0] if rest else ()
        transform = False
    states = tuple(getattr(metric, n) for n in names)
    apply_fn = _fuse._apply_transform if transform else _fuse._apply_kernel

    def fused(states, *dyn):
        return apply_fn(kernel, config, states, dyn)

    return program_costs(fused, states, *dynamic)


def track_metrics(
    metrics: Mapping[str, Any],
    *,
    source: str = "memory",
    registry=None,
) -> Callable[[], Dict[str, Any]]:
    """Register a pull-based ``{metric}_state_bytes`` counter source for
    a metric panel, so ``render_prometheus()`` / ``format_report()`` /
    ``gather_observability()`` carry the panel's device-byte cost next
    to the existing counters. The MAPPING is captured, not a snapshot:
    every scrape re-walks the live metrics (zero cost between scrapes —
    the ``CounterRegistry`` supplier contract). Returns the supplier;
    unregister with ``registry.unregister(source)``."""
    from torcheval_tpu.obs.counters import default_registry

    if registry is None:
        registry = default_registry()

    def supplier() -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        total = 0
        total_rank = 0
        for name, metric in metrics.items():
            n = sum(state_bytes(metric).values())
            pr = sum(per_rank_state_bytes(metric).values())
            out[f"{name}_state_bytes"] = n
            out[f"{name}_per_rank_bytes"] = pr
            total += n
            total_rank += pr
        out["total_state_bytes"] = total
        out["total_per_rank_bytes"] = total_rank
        return out

    registry.register(source, supplier)
    return supplier
