"""ClickThroughRate class metric.

Parity: reference torcheval/metrics/ranking/click_through_rate.py:23-113.
Per-task counters sync with one psum. The reference holds float64 counters;
we keep float32 on TPU (see SURVEY.md section 7 "hard parts") — CTR counters
are bounded by event counts, well within f32 for realistic streams.
"""

from __future__ import annotations

from typing import Optional, TypeVar, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.ranking.click_through_rate import (
    _click_through_rate_compute,
    resolve_ctr_weights,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric

TClickThroughRate = TypeVar("TClickThroughRate", bound="ClickThroughRate")


class ClickThroughRate(Metric[jax.Array]):
    """Weighted click-through rate, optionally multi-task.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import ClickThroughRate
        >>> metric = ClickThroughRate()
        >>> metric.update(jnp.array([0, 1, 0, 1, 1, 0, 0, 1]))
        >>> metric.compute()
        Array([0.5], dtype=float32)
    """

    def __init__(
        self, *, num_tasks: int = 1, device: Optional[jax.Device] = None
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.num_tasks = num_tasks
        self._add_state(
            "click_total", jnp.zeros(num_tasks), merge=MergeKind.SUM
        )
        self._add_state(
            "weight_total", jnp.zeros(num_tasks), merge=MergeKind.SUM
        )

    def update(
        self: TClickThroughRate,
        input,
        weights: Union[jax.Array, float, int] = 1.0,
    ) -> TClickThroughRate:
        """Accumulate click events (and optional per-event weights)."""
        # one fused dispatch: CTR kernel + the two counter adds
        return self._apply_update_plan(self._update_plan(input, weights))

    def _update_plan(self, input, weights=1.0):
        kernel, args = resolve_ctr_weights(
            self._input(input),
            weights,
            num_tasks=self.num_tasks,
            convert=self._input_float,
        )
        return (kernel, ("click_total", "weight_total"), args, ())

    def compute(self) -> jax.Array:
        """CTR per task; 0.0 for tasks with no updates."""
        return _click_through_rate_compute(self.click_total, self.weight_total)
