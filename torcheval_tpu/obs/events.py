"""Typed lifecycle events for the observability subsystem.

One dataclass per event the eval stack emits (docs/observability.md has the
full schema table). Every event carries the same timing envelope:

- ``t_mono``: ``time.monotonic()`` at record time — orders events and
  yields durations immune to wall-clock steps;
- ``t_wall``: ``time.time()`` — correlates with external logs/dashboards;
- ``step``: the recorder's step cursor (``Recorder.set_step``;
  ``elastic.ElasticSession`` advances it automatically), ``None`` when no
  loop is driving one;
- ``rank``: the emitting rank for group-scoped events (sync, retry,
  snapshot, restore); ``None`` for process-local events (update, compute,
  compile, span);
- ``tid``: the emitting thread's identifier (stamped by
  ``Recorder.record`` — the Chrome exporter's per-thread tracks);
- ``trace``/``span``/``parent``: the causal-tracing ids
  (``obs/trace.py``) — duration events carry their OWN span id (+ the
  parent they nest under); point events recorded inside a span carry
  the trace id and that span as ``parent``. ``None`` everywhere when no
  span is open.

Events are plain data: construct them anywhere, compare them with ``==``,
serialize with :meth:`Event.as_dict` (JSON-safe: tuples become lists, and
every dict carries ``"schema": SCHEMA_VERSION`` so readers can detect
future layout changes) and reconstruct with :func:`event_from_dict` (the
JSONL exporter's round-trip contract, pinned by
tests/metrics/test_observability.py; unknown fields from newer writers
are ignored, pinned by tests/metrics/test_tracing.py).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, ClassVar, Dict, Optional, Tuple, Type

__all__ = [
    "SCHEMA_VERSION",
    "AlertEvent",
    "AnalysisEvent",
    "CompileEvent",
    "ComputeEvent",
    "DriftEvent",
    "Event",
    "FailoverEvent",
    "MemoryEvent",
    "PlaneSyncEvent",
    "RegionSyncEvent",
    "RestoreEvent",
    "RetryEvent",
    "SnapshotEvent",
    "SpanEvent",
    "StallEvent",
    "SyncEvent",
    "UpdateEvent",
    "WireTierEvent",
    "event_from_dict",
]

# Bumped only on an incompatible layout change; new OPTIONAL fields do
# not bump it (readers ignore unknown keys by contract).
SCHEMA_VERSION = 1


@dataclass
class Event:
    """Common timing envelope; see the module docstring for field
    semantics. ``Recorder.record`` stamps the envelope when unset, so
    instrumentation only fills the payload fields."""

    kind: ClassVar[str] = "event"

    t_mono: float = 0.0
    t_wall: float = 0.0
    step: Optional[int] = None
    rank: Optional[int] = None
    tid: Optional[int] = None
    trace: Optional[int] = None
    span: Optional[int] = None
    parent: Optional[int] = None

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe dict (``kind`` and ``schema`` included, tuples
        become lists)."""
        out: Dict[str, Any] = {"kind": self.kind, "schema": SCHEMA_VERSION}
        for f in dataclasses.fields(self):
            value = getattr(self, f.name)
            if isinstance(value, tuple):
                value = list(value)
            out[f.name] = value
        return out


@dataclass
class UpdateEvent(Event):
    """One ``Metric.update`` (or one fused ``toolkit.update_collection``
    dispatch covering ``fused`` metrics)."""

    kind: ClassVar[str] = "update"

    metric: str = ""
    seconds: float = 0.0
    fused: int = 1


@dataclass
class ComputeEvent(Event):
    """One ``Metric.compute``."""

    kind: ClassVar[str] = "compute"

    metric: str = ""
    seconds: float = 0.0


@dataclass
class SyncEvent(Event):
    """One whole eager state sync (``toolkit.get_synced_metric*``).

    ``ranks``/``world_size``/``degraded``/``policy``/``reformed`` mirror
    the :class:`~torcheval_tpu.resilience.SyncProvenance` attached to the
    synced metrics — bit-identical, pinned under fault injection by
    tests/metrics/test_observability.py. ``sent_bytes``/``recv_bytes``
    are the packed wire payload this rank shipped / the surviving ranks'
    payloads it received (``synclib.SyncedStates``).
    """

    kind: ClassVar[str] = "sync"

    ranks: Tuple[int, ...] = ()
    world_size: int = 0
    degraded: bool = False
    policy: str = "raise"
    reformed: bool = False
    sent_bytes: int = 0
    recv_bytes: int = 0
    metrics: int = 0
    seconds: float = 0.0
    # cross-rank flow ordinal (obs/trace.py next_flow_id): the N-th sync
    # issued from this thread — identical on every rank by lockstep, so
    # merged traces can link the same collective across ranks with zero
    # communication. 0 = no flow recorded.
    flow: int = 0
    # lossiest quantized-wire-ladder rung any metric in this sync rode
    # (wire.py: "exact" | "bf16" | "int8"); per-metric rungs ride each
    # metric's SyncProvenance.wire_tier. New OPTIONAL field — schema 1.
    wire_tier: str = "exact"


@dataclass
class RetryEvent(Event):
    """One resilience-layer lifecycle event (``ResilientGroup``): a retry
    cause (``timeout`` / ``transient`` / ``partial-gather``), a
    degradation outcome (``degraded-local`` / ``degraded-quorum`` /
    ``failed``), or a survivor re-formation (``reform``).

    ``flight`` carries the formatted flight-ring tail (``obs/flight.py``)
    on timeout/failure events while the flight recorder is on — *which*
    collective in the sequence stalled, not just that one did."""

    kind: ClassVar[str] = "retry"

    reason: str = ""
    attempt: int = 0
    policy: str = "raise"
    detail: str = ""
    flight: str = ""


@dataclass
class SnapshotEvent(Event):
    """One committed (or attempted) elastic snapshot generation on this
    rank (``elastic.ElasticSession``)."""

    kind: ClassVar[str] = "snapshot"

    generation: int = -1
    seconds: float = 0.0
    shard_bytes: int = 0
    async_writer: bool = False


@dataclass
class RestoreEvent(Event):
    """One successful ``ElasticSession.restore`` on this rank."""

    kind: ClassVar[str] = "restore"

    generation: int = -1
    restored_step: int = 0
    old_world: int = 0
    new_world: int = 0
    seconds: float = 0.0


@dataclass
class CompileEvent(Event):
    """One XLA program demand (bridged from ``utils.CompileCounter``'s
    jax.monitoring listeners): a backend compile / persistent-cache load
    (``cache_hit=False``, ``seconds`` = time inside compile-or-load), or
    a persistent-cache hit notification (``cache_hit=True``)."""

    kind: ClassVar[str] = "compile"

    seconds: float = 0.0
    cache_hit: bool = False
    # causal attribution (obs/trace.py): the innermost open span at the
    # moment the compile fired — e.g. "torcheval.update/MulticlassAccuracy"
    # names the metric family that demanded the program — and the shape
    # bucket length of the bucketed dispatch that triggered it (0 when
    # the compile happened outside a bucketed dispatch). Ends the era of
    # anonymous compile events from the CompileCounter bridge.
    site: str = ""
    bucket: int = 0


@dataclass
class SpanEvent(Event):
    """One user-named phase closed by ``Recorder.span`` (the phase also
    appears in XLA traces via ``jax.profiler.TraceAnnotation``)."""

    kind: ClassVar[str] = "span"

    name: str = ""
    seconds: float = 0.0


@dataclass
class MemoryEvent(Event):
    """One per-metric device-cost accounting snapshot
    (``obs.memory.memory_report``): the bytes this metric's registered
    state leaves pin in device memory, from a host-side metadata walk —
    no step executes, no device sync."""

    kind: ClassVar[str] = "memory"

    metric: str = ""
    state_bytes: int = 0
    states: int = 0
    # sharded-state accounting (ISSUE 9): what the state would cost
    # replicated vs what THIS rank/device actually pins. Equal on
    # replicated families; per_rank_bytes ~= logical/world on sharded.
    logical_bytes: int = 0
    per_rank_bytes: int = 0


@dataclass
class AnalysisEvent(Event):
    """One active static-analysis finding (``torcheval_tpu.analysis``),
    mirrored from :class:`~torcheval_tpu.analysis.report.Finding` when an
    analyzer runs while the recorder is on — so a CI failure's event tail
    carries the forensics that explain it (which rule, where, why)."""

    kind: ClassVar[str] = "analysis"

    tool: str = ""
    rule: str = ""
    path: str = ""
    line: int = 0
    severity: str = "error"
    message: str = ""


@dataclass
class StallEvent(Event):
    """One stall-watchdog trip (``obs/watchdog.py``): a collective sat in
    the flight ring past the deadline with no flight progress anywhere in
    the process. Emitted (and dumped to stderr/JSONL) *before* the
    process dies or an operator kills it — the hang forensics record.

    ``op``/``seq`` identify the stuck collective on this thread's flight
    ring (``seq`` is the per-thread collective ordinal — comparable
    across ranks by lockstep); ``span_path`` is the innermost open span
    path of the stalled thread at trip time."""

    kind: ClassVar[str] = "stall"

    op: str = ""
    seq: int = 0
    age_seconds: float = 0.0
    deadline: float = 0.0
    span_path: str = ""
    detail: str = ""


@dataclass
class DriftEvent(Event):
    """One data-quality drift scoring of a watched input series
    (``obs/quality.py``), emitted per ``Monitor.check`` while the
    recorder is on: the post-freeze window size vs the frozen
    reference, the PSI / histogram-KS / Welch-z scores, and which
    bounds (if any) the scoring breached (comma-joined, ``""`` when
    in-bounds). Breaches additionally raise monitor ``AlertEvent``s
    (cooldown-guarded); this event is the continuous score record."""

    kind: ClassVar[str] = "drift"

    series: str = ""
    count: float = 0.0
    ref_count: float = 0.0
    psi: float = 0.0
    ks: float = 0.0
    z: float = 0.0
    breach: str = ""


@dataclass
class RegionSyncEvent(Event):
    """One inter-region federation link action (``federation.py``):
    a posted snapshot (``send-delta``/``send-full``), an applied merge
    (``merge``), an acknowledged epoch (``ack``), an idempotently
    discarded re-delivery (``duplicate``), an anti-entropy trigger
    (``resync``/``base-mismatch``/``crc-failure``), or a link
    state change (``partition``/``heal``).

    ``region``/``peer`` name the directed link; ``epoch`` is the
    message's epoch stamp, ``local_epoch`` this region's exchange round,
    ``peer_epoch`` the peer's highest merged epoch in the ledger after
    the action; ``nbytes`` the wire payload (delta or full);
    ``staleness_epochs`` the staleness that tripped a ``partition``."""

    kind: ClassVar[str] = "region_sync"

    region: str = ""
    peer: str = ""
    action: str = ""
    epoch: int = 0
    local_epoch: int = 0
    peer_epoch: int = 0
    nbytes: int = 0
    staleness_epochs: int = 0


@dataclass
class PlaneSyncEvent(Event):
    """One background sync-plane round (``syncplane.py``).

    ``version`` is the merged snapshot version the round produced,
    ``generation`` the publish generation it consumed;
    ``ranks``/``world_size``/``degraded``/``policy``/``reformed`` mirror
    the round's :class:`~torcheval_tpu.resilience.SyncProvenance` (the
    round's inner eager sync additionally records its own
    :class:`SyncEvent` with wire-byte accounting). A FAILED round
    records ``error`` with version 0 — the plane keeps serving the
    previous snapshot."""

    kind: ClassVar[str] = "plane_sync"

    version: int = 0
    generation: int = 0
    ranks: Tuple[int, ...] = ()
    world_size: int = 0
    degraded: bool = False
    policy: str = "raise"
    reformed: bool = False
    metrics: int = 0
    seconds: float = 0.0
    error: str = ""


@dataclass
class AlertEvent(Event):
    """One SLO/anomaly monitor alert (``obs/monitor.py``): a streaming
    drift detection (``alert="drift"``, EWMA z-score over observed metric
    values or latency-digest quantiles), a threshold breach
    (``alert="threshold"``), or an error-budget burn
    (``alert="burn-rate"``). ``name`` is the SLO/series name; ``value``
    the observed quantity; ``bound`` the configured limit; ``z`` the
    z-score for drift alerts."""

    kind: ClassVar[str] = "alert"

    name: str = ""
    alert: str = ""
    value: float = 0.0
    bound: float = 0.0
    z: float = 0.0
    message: str = ""


@dataclass
class WireTierEvent(Event):
    """One quantized-wire-ladder fallback (``torcheval_tpu/wire.py``): a
    MEASURED drift-budget breach (``obs/quality.py`` ``DriftSpec``)
    stepped ``family``'s effective wire rung one rung toward exact
    (``prev_tier -> tier``, e.g. ``int8 -> bf16``). ``series`` names the
    watched input series whose scoring breached; ``breach`` the
    comma-joined breached bound kinds (``psi``/``ks``/``z``). Later
    syncs of the family ride the new rung until
    ``wire.LADDER.reset()`` lifts the cap (e.g. after a re-baseline)."""

    kind: ClassVar[str] = "wire_tier"

    family: str = ""
    series: str = ""
    prev_tier: str = ""
    tier: str = ""
    breach: str = ""


@dataclass
class AdmissionEvent(Event):
    """One admission-ladder rung transition (``table._admission``): the
    drain-time controller stepped ``prev_rung → rung`` on merged
    pressure. ``sampled_fraction`` is the NEW rung's admission
    probability; ``epoch`` the drain epoch at which it takes effect.
    Recorded once per transition per rank (transitions are computed on
    merged state, so every rank records the same step)."""

    kind: ClassVar[str] = "admission"

    table: str = ""
    prev_rung: int = 0
    rung: int = 0
    rung_name: str = "full"
    pressure: float = 0.0
    sampled_fraction: float = 1.0
    epoch: int = 0


@dataclass
class FailoverEvent(Event):
    """One phase of a ``failover.FailureDomain`` rank-loss recovery:
    ``action`` walks ``detected`` (loss confirmed from local signals) →
    ``reconstructed`` (dead ranks' partitioned state rebuilt over the
    survivors, loss bound declared) → ``reformed`` (every communicator
    re-formed to the survivor world) → ``rejoined`` (live re-entry at
    the full world, no process restart). ``world_size`` is the world the
    domain serves AFTER the phase; ``loss_steps``/``loss_epochs`` and
    the source ``generation`` mirror the declared ``LossBound``."""

    kind: ClassVar[str] = "failover"

    action: str = ""
    dead_ranks: Tuple[int, ...] = ()
    survivors: Tuple[int, ...] = ()
    world_size: int = 0
    generation: int = -1
    loss_steps: int = 0
    loss_epochs: int = 0
    seconds: float = 0.0


_EVENT_TYPES: Dict[str, Type[Event]] = {
    cls.kind: cls
    for cls in (
        AdmissionEvent,
        AlertEvent,
        DriftEvent,
        FailoverEvent,
        WireTierEvent,
        AnalysisEvent,
        MemoryEvent,
        PlaneSyncEvent,
        RegionSyncEvent,
        StallEvent,
        UpdateEvent,
        ComputeEvent,
        SyncEvent,
        RetryEvent,
        SnapshotEvent,
        RestoreEvent,
        CompileEvent,
        SpanEvent,
        Event,
    )
}


def event_from_dict(data: Dict[str, Any]) -> Event:
    """Inverse of :meth:`Event.as_dict` — the JSONL read side.

    Unknown keys are ignored (a newer writer's extra fields must not
    break an older reader); lists are restored to tuples (the only
    sequence type events use).
    """
    kind = data.get("kind", "event")
    cls = _EVENT_TYPES.get(kind, Event)
    names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {
        k: (tuple(v) if isinstance(v, list) else v)
        for k, v in data.items()
        if k in names
    }
    return cls(**kwargs)
