"""Causal tracing: trace/span context underneath the event recorder.

PR 5's event stream answers *what happened*; this module answers *what
caused what*. Every span gets a *span id* and a *parent span id*, and
every root span opens a *trace id* — so one eval step (update panel →
bucketed dispatch → XLA compile → sync → retries → snapshot) is a
connected tree instead of a flat timeline. The machinery is a plain
thread-local stack of :class:`SpanFrame`\\ s:

- **Instrumented sites push a frame** for the duration of the phase
  (``Metric.update``/``compute`` wrappers, the toolkit sync, elastic
  snapshot/restore, user ``obs.span()`` phases) via :class:`Scope`.
- **Point events inherit the current frame**: ``Recorder.record`` stamps
  ``trace``/``parent`` from :func:`current` onto any event that does not
  carry its own span — a ``RetryEvent`` emitted during a sync parents to
  the sync span, a ``CompileEvent`` fired inside an update parents to
  that update (and names it, see ``site`` attribution in the recorder's
  compile sink).
- **Flow ids link the same collective across ranks**
  (:func:`next_flow_id`): collectives run in lockstep, so "this rank's
  N-th eager sync" IS the same sync on every rank — a per-thread ordinal
  needs ZERO communication to agree across ranks (the same reasoning
  that makes the lockstep checker's per-rank plans comparable). The
  Chrome exporter turns shared flow ids into Perfetto flow arrows.

Cost contract (the PR 5 discipline, extended): everything here is
host-side list/int work guarded by the recorder's single ``enabled``
attribute read at the instrumented sites — tracing-ON adds zero host
syncs and zero collectives to any step path (pinned by the recorder-ON
variants in tests/metrics/test_no_host_sync.py and
test_sync_collective_counts.py), and < 2%/step wall overhead (the bench
``tracing`` config, drift-guarded by tests/test_perf_claims.py).
"""

from __future__ import annotations

import contextlib as _contextlib
import itertools
import os
import threading
from typing import Any, Dict, List, Optional

__all__ = [
    "Scope",
    "SpanFrame",
    "active_stack",
    "annotate",
    "capture_error",
    "clear_error_stack",
    "current",
    "last_error_stack",
    "next_flow_id",
    "pop",
    "push",
    "scope_or_null",
    "thread_paths",
    "trace_path",
]

_TLS = threading.local()

# Cross-thread view of the per-thread span stacks, for the stall watchdog
# (obs/watchdog.py): a watchdog thread diagnosing a hang must name the
# span path of the STALLED thread, which thread-local state alone cannot
# answer. Each thread registers its (mutable) stack list on first use;
# entries are tiny and thread counts bounded, so stale tids are harmless.
_ALL_STACKS: Dict[int, List["SpanFrame"]] = {}

# Span ids are process-unique (itertools.count.__next__ is atomic under
# the GIL); trace ids additionally carry a random 32-bit process prefix
# so traces merged from several ranks/processes never collide.
_SPAN_IDS = itertools.count(1)
_TRACE_IDS = itertools.count(1)
_TRACE_PREFIX = int.from_bytes(os.urandom(4), "big")


class SpanFrame:
    """One live span on a thread's context stack.

    ``annotations`` is a scratch dict instrumented code deeper in the
    call can stamp context onto (e.g. the bucketed dispatch notes its
    bucket length so a compile fired under it is attributed to the
    shape bucket that demanded it). The frame dies when the phase exits,
    so annotations can never go stale across calls.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "annotations")

    def __init__(
        self,
        trace_id: int,
        span_id: int,
        parent_id: Optional[int],
        name: str,
    ) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.annotations: Dict[str, Any] = {}


def _stack() -> List[SpanFrame]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
        _ALL_STACKS[threading.get_ident()] = stack
    return stack


def push(name: str) -> SpanFrame:
    """Open a span: child of the current frame, or a new trace root.
    (Hot when the recorder is on — one try/except TLS read, one
    :class:`SpanFrame` allocation, two counter bumps.)"""
    try:
        stack = _TLS.stack
    except AttributeError:
        stack = _TLS.stack = []
        _ALL_STACKS[threading.get_ident()] = stack
    if stack:
        top = stack[-1]
        frame = SpanFrame(top.trace_id, next(_SPAN_IDS), top.span_id, name)
    else:
        trace_id = (_TRACE_PREFIX << 32) | next(_TRACE_IDS)
        frame = SpanFrame(trace_id, next(_SPAN_IDS), None, name)
    stack.append(frame)
    return frame


def pop(frame: SpanFrame) -> None:
    """Close a span. Tolerates a corrupted stack (pops through to the
    given frame) so one mismatched site cannot poison a whole thread."""
    try:
        stack = _TLS.stack
    except AttributeError:
        return
    if stack and stack[-1] is frame:  # the overwhelmingly common case
        stack.pop()
        return
    while stack:
        if stack.pop() is frame:
            return


def capture_error(exc: BaseException) -> None:
    """Capture the CURRENT span path as this thread's error stack —
    called by instrumented sites from an ``except`` block, BEFORE their
    ``finally`` pops the failing frame. Identity-keyed on the exception
    so only the innermost site's capture survives the unwind (outer
    sites see the same exception and leave the deeper path in place)."""
    if getattr(_TLS, "error_for", None) is not exc:
        _TLS.error_for = exc
        _TLS.error_stack = [f.name for f in getattr(_TLS, "stack", ())]


def current() -> Optional[SpanFrame]:
    """The innermost open span on this thread, or None."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def active_stack() -> List[SpanFrame]:
    """Snapshot of this thread's open spans, outermost first."""
    return list(getattr(_TLS, "stack", ()))


def trace_path(frames: Optional[List[SpanFrame]] = None) -> str:
    """Human-readable span path, outermost first: ``"a > b > c"``."""
    if frames is None:
        frames = active_stack()
    return " > ".join(f.name for f in frames)


def thread_paths() -> Dict[int, str]:
    """Every thread's current span path (``{tid: "a > b"}``), threads
    with no open span omitted — the watchdog's "where was each thread"
    answer. List append/pop is atomic under the GIL and the snapshot
    copies before formatting, so no locking is needed."""
    out: Dict[int, str] = {}
    for tid, stack in list(_ALL_STACKS.items()):
        frames = list(stack)
        if frames:
            out[tid] = " > ".join(f.name for f in frames)
    return out


def annotate(**kwargs: Any) -> None:
    """Stamp context onto the current frame (no-op outside any span)."""
    frame = current()
    if frame is not None:
        frame.annotations.update(kwargs)


class Scope:
    """Context manager opening one span frame for a code region.

    On an exception the full span path (this frame included) is captured
    as the thread's *error stack* before unwinding pops it — the
    conftest failure hook appends it to test reports ("the trace path to
    the failing site"). Identity-keyed on the exception, so only the
    INNERMOST frame's capture survives the unwind.
    """

    __slots__ = ("name", "frame")

    def __init__(self, name: str) -> None:
        self.name = name
        self.frame: Optional[SpanFrame] = None

    def __enter__(self) -> SpanFrame:
        self.frame = push(self.name)
        return self.frame

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            capture_error(exc)
        if self.frame is not None:
            pop(self.frame)
        return False


_NULL_SCOPE = _contextlib.nullcontext()


def scope_or_null(name: str, enabled: bool):
    """A :class:`Scope` when ``enabled``, else a shared ``nullcontext``
    (which yields ``None``) — the one-liner every conditionally-traced
    site uses::

        with trace.scope_or_null("torcheval.sync", _OBS.enabled) as frame:
            ...  # frame is the SpanFrame, or None when disabled

    Using the ``with`` protocol (rather than try/finally +
    ``sys.exc_info()``) matters: inside an outer ``except`` handler,
    ``sys.exc_info()`` reports the already-HANDLED exception, and a
    scope exited with it would capture a bogus error stack for a
    perfectly clean call. Disabled cost: one call + a shared, stateless
    context manager — no allocation.
    """
    return Scope(name) if enabled else _NULL_SCOPE


def last_error_stack() -> Optional[List[str]]:
    """The span path captured at the most recent exception that escaped
    a :class:`Scope` on this thread (outermost first), or None."""
    stack = getattr(_TLS, "error_stack", None)
    return list(stack) if stack else None


def clear_error_stack() -> None:
    _TLS.error_for = None
    _TLS.error_stack = None


# ------------------------------------------------------------------- flows

def next_flow_id() -> int:
    """The next cross-rank flow ordinal for THIS thread (1-based).

    Collectives are issued in lockstep, so every rank's N-th call from
    its sync path refers to the SAME logical collective — a per-thread
    counter agrees across ranks (including ThreadWorld, where each rank
    is a thread of one process) without any communication. Stamped into
    ``SyncEvent.flow``; the Chrome exporter draws the arrows.
    """
    n = getattr(_TLS, "flow", 0) + 1
    _TLS.flow = n
    return n
