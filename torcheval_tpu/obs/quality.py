# tev: scope=host — drift scoring, counter scrapes, and reference
# freezing are host-side, scrape-cadence surfaces by design; the only
# jit-reachable code here is the combined-kernel factory, whose bodies
# are the sketch fold kernels (obs/sketch.py) plus the watched metric's
# own kernel.
"""Input-quality watching & drift scoring (data-quality telemetry).

:func:`watch_inputs` arms DATA observability on an existing metric (or a
``{name: Metric}`` collection): the four sketch state families of
:class:`~torcheval_tpu.obs.sketch.InputSketch` are registered as
ordinary states ON the watched metric (``_add_state`` — so they ride
sync / merge / elastic snapshots / subgroup scoping / the sharded merge
for free), and the metric's fusable update plan is extended so sketch
accumulation happens INSIDE the same fused update program:

- **zero extra dispatches**: the combined kernel traces the metric's own
  kernel plus the sketch folds into one XLA program (``_fuse.py``);
- **zero collectives, zero host syncs**: statically verified by the
  ``analysis --programs`` ``_quality_smoke`` and pinned at runtime by
  the quality-armed variants in tests/metrics/test_no_host_sync.py and
  test_sync_collective_counts.py;
- **one attribute-read off-guard**: accumulation is gated on
  ``QUALITY.enabled`` — paused, a watched metric's ``_update_plan``
  costs one attribute read over the unwatched path (and an UNwatched
  metric pays literally nothing);
- **bucketed masked twins**: when the watched plan declares a masked
  kernel, the combined plan does too — padded rows contribute exactly
  zero to every sketch state, so a warmed watched metric stays
  retrace-proof under ``config.shape_bucketing()``.

:class:`DriftSpec` scores the live sketches against a frozen reference
window at the PR 10 ``Monitor.check()`` cadence (the health server runs
it per ``/healthz`` probe): population-stability index (PSI) and
histogram-KS over the quantile histogram (below/above-range lanes
included), and a Welch z on the streaming means — all computed on the
POST-FREEZE window (SUM states subtract exactly; the moments window is
the exact Chan-merge inverse), so the reference does not dilute the
signal. Breaches raise cooldown-guarded monitor alerts (degrading
``/healthz`` to 503 like any SLO breach) and every scored check emits a
typed :class:`~torcheval_tpu.obs.events.DriftEvent` while the recorder
is on. The ``quality`` counter source publishes per-input gauges
(count, NaN/zero/negative totals, mean/std, distinct estimate, drift
scores, per-spec breach flags) and ``render_prometheus`` adds the value
histograms as proper ``histogram`` families.

Cost contract: the step path never reads a device value — scoring,
scraping, and ``freeze_reference`` force readbacks of the (small)
sketch states at check/scrape cadence only, the documented exception
shared with ``MetricTable.scrape_values``.
"""

from __future__ import annotations

import math
import threading
import types
import weakref
from functools import lru_cache
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.metric import Metric, MergeKind, UpdatePlan
from torcheval_tpu.obs.sketch import (
    CNT_FIELDS,
    InputSketch,
    SketchConfig,
    chan_merge,
    default_config,
    hll_estimate,
    moment_default,
    moments_window,
    _fold_fns,
)

__all__ = [
    "DriftSpec",
    "QUALITY",
    "QualityWatch",
    "active_watches",
    "watch_inputs",
]

_STATE_SUFFIXES = ("hist", "cnt", "mom", "reg")

# per-metric extended-plan memo (see _watched_update_plan); weak keys so
# a dropped metric never pins its plan (or the kernels it closes over)
_PLAN_MEMO: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()  # tev: disable=unguarded-state -- keyed by the metric instance whose own (single-threaded) update call populates it; no cross-thread sharing by contract


def _q_names(i: int) -> Tuple[str, ...]:
    return tuple(f"_q{i}_{s}" for s in _STATE_SUFFIXES)


class _QualityState:
    """The one-attribute-read accumulation gate (the ``FLIGHT.enabled``
    idiom): watched metrics extend their update plans only while
    ``enabled`` is True. Watching is the explicit per-metric opt-in, so
    the gate defaults ON; pause it to measure or bypass accumulation
    without un-watching."""

    __slots__ = ("enabled",)

    def __init__(self) -> None:
        self.enabled = True


QUALITY = _QualityState()


class DriftSpec(NamedTuple):
    """Drift bounds for one watched input (or ``series="*"`` for all).

    Scores are computed on the post-freeze window vs the frozen
    reference: ``psi`` bounds the population-stability index over the
    histogram lanes (industry rule of thumb: 0.1 moderate, 0.2
    significant shift), ``ks`` the max CDF distance, ``z`` the absolute
    Welch z-statistic of the window mean vs the reference mean.
    ``min_count`` gates scoring until the window holds that many finite
    samples (a cold window cannot drift).
    """

    series: str = "*"
    psi: float = 0.2
    ks: float = 0.2
    z: float = 6.0
    min_count: int = 256


class _WatchSpec(NamedTuple):
    """Per-metric instrumentation record (hashable core only)."""

    args: Tuple[int, ...]
    sketch: SketchConfig


# ------------------------------------------------------ combined kernels


def _normalize(out: Any, n: int, kernel: Any) -> Tuple:
    if not isinstance(out, tuple):
        out = (out,)
    if len(out) != n:
        raise ValueError(
            f"kernel {getattr(kernel, '__name__', kernel)} returned "
            f"{len(out)} values for {n} states"
        )
    return out


@lru_cache(maxsize=None)
def _combined_kernels(
    orig_kernel,
    orig_masked,
    transform: bool,
    n_orig: int,
    orig_config: Tuple,
    arg_indices: Tuple[int, ...],
    cfg: SketchConfig,
    mask_pos: Tuple[int, ...],
):
    """(plain, masked) transform kernels running the watched metric's
    own kernel plus one sketch fold per watched dynamic argument, as ONE
    traced body. Cached per (kernel, config, watch geometry) so repeated
    updates key the same jit entries — the cache-key discipline of
    ``_fuse.py``. ``mask_pos[k]`` is the index of watched arg k's batch
    label in the bucketed valid-extent vector (-1: no ragged axis — all
    rows valid)."""
    fold = _fold_fns(cfg)

    def _orig_part(states, dyn, kernel):
        orig_states = states[:n_orig]
        if transform:
            return _normalize(
                kernel(orig_states, *dyn, *orig_config), n_orig, kernel
            )
        deltas = _normalize(kernel(*dyn, *orig_config), n_orig, kernel)
        return tuple(s + d for s, d in zip(orig_states, deltas))

    def _sketch_part(states, dyn, weights):
        out = []
        for k, i in enumerate(arg_indices):
            s4 = states[n_orig + 4 * k : n_orig + 4 * (k + 1)]
            out.extend(fold(s4, dyn[i], weights[k]))
        return tuple(out)

    def plain(states, *dyn):
        ones = tuple(jnp.float32(1.0) for _ in arg_indices)
        return _orig_part(states, dyn, orig_kernel) + _sketch_part(
            states, dyn, ones
        )

    masked = None
    if orig_masked is not None:

        def masked(states, *args):
            dyn, valid = args[:-1], args[-1]
            weights = []
            for k, i in enumerate(arg_indices):
                pos = mask_pos[k]
                if pos < 0:
                    weights.append(jnp.float32(1.0))
                    continue
                x = jnp.asarray(dyn[i])
                row = jnp.arange(x.shape[0], dtype=jnp.int32) < valid[pos]
                w = row.astype(jnp.float32).reshape(
                    (x.shape[0],) + (1,) * (x.ndim - 1)
                )
                weights.append(jnp.broadcast_to(w, x.shape))
            # the original masked kernel keeps its own (*dyn, valid)
            # signature; the sketch folds consume the same valid vector
            if transform:
                orig_states = states[:n_orig]
                new_orig = _normalize(
                    orig_masked(orig_states, *dyn, valid, *orig_config),
                    n_orig,
                    orig_masked,
                )
            else:
                deltas = _normalize(
                    orig_masked(*dyn, valid, *orig_config), n_orig, orig_masked
                )
                new_orig = tuple(
                    s + d for s, d in zip(states[:n_orig], deltas)
                )
            return new_orig + _sketch_part(states, dyn, tuple(weights))

        masked.__name__ = f"{getattr(orig_masked, '__name__', 'kernel')}_q"

    plain.__name__ = f"{getattr(orig_kernel, '__name__', 'kernel')}_q"
    return plain, masked


def _extend_plan(plan, spec: _WatchSpec):
    """Rewrite one fusable update plan into its quality-watched twin:
    same dynamic arguments, original states first, four sketch states
    per watched argument appended, one combined transform kernel (and
    masked twin when the original declares one)."""
    if not isinstance(plan, UpdatePlan):
        kernel, names, dynamic, *rest = plan
        plan = UpdatePlan(kernel, names, dynamic, rest[0] if rest else ())
    bad = [i for i in spec.args if i >= len(plan.dynamic)]
    if bad:
        raise ValueError(
            f"watch_inputs args {bad} are out of range for this "
            f"metric's update plan ({len(plan.dynamic)} dynamic "
            "argument(s)) — watched indices name positional update "
            "arguments"
        )
    order: List[str] = []
    for labels in plan.batch_axes:
        for label in labels or ():
            if label not in order:
                order.append(label)
    mask_pos = []
    for i in spec.args:
        labels = (
            plan.batch_axes[i] if i < len(plan.batch_axes) else ()
        ) or ()
        mask_pos.append(order.index(labels[0]) if labels else -1)
    combined, combined_masked = _combined_kernels(
        plan.kernel,
        plan.masked_kernel,
        plan.transform,
        len(plan.state_names),
        plan.config,
        spec.args,
        spec.sketch,
        tuple(mask_pos),
    )
    state_names = plan.state_names + tuple(
        name for i in spec.args for name in _q_names(i)
    )
    return UpdatePlan(
        combined,
        state_names,
        plan.dynamic,
        (),
        transform=True,
        finalize=plan.finalize,
        masked_kernel=combined_masked,
        batch_axes=plan.batch_axes if combined_masked is not None else (),
    )


# module-level functions (not closures) so bound-method instance
# attributes survive deepcopy (clone rebinds to the copy) and pickling
def _watched_update_plan(self, *args: Any, **kwargs: Any):
    plan = type(self)._update_plan(self, *args, **kwargs)
    if plan is None or not QUALITY.enabled:  # the one-attribute-read gate
        return plan
    # steady-state fast path: a metric's plan shape (kernel/states/
    # masked twin/config/axes) is stable across updates — memoize the
    # rewrite per metric and only swap the per-call dynamic tuple.
    # Keyed on every field the rewrite depends on, so a metric that
    # switches plans (e.g. routed vs dense) still rewrites correctly.
    # The memo lives OFF the instance (weak-keyed module table): the
    # rewritten plan holds unpicklable kernel closures, and instance
    # state must stay deepcopy/pickle-clean (clones just re-memoize).
    if isinstance(plan, UpdatePlan):
        memo_key = (
            plan.kernel,
            plan.masked_kernel,
            plan.state_names,
            plan.config,
            plan.transform,
            plan.batch_axes,
        )
        memo = _PLAN_MEMO.get(self)
        if memo is not None and memo[0] == memo_key:
            return memo[1]._replace(
                dynamic=plan.dynamic, finalize=plan.finalize
            )
        extended = _extend_plan(plan, self._quality_spec)
        # memoize WITHOUT the per-call fields (dynamic pins a batch's
        # device arrays; finalize may close over per-call state)
        _PLAN_MEMO[self] = (
            memo_key,
            extended._replace(dynamic=(), finalize=None),
        )
        return extended
    return _extend_plan(plan, self._quality_spec)


def _watched_merge_custom(self, name: str, mine, theirs):
    if name.startswith("_q") and name.endswith("_mom"):
        # pairwise in carrier (ascending-rank) order — the toolkit merge
        # left-folds peers per state, so this IS Chan's
        # pairwise-in-rank-order merge (obs/sketch.py)
        return chan_merge(mine, theirs)
    return type(self)._merge_custom_state(self, name, mine, theirs)


def _validate_watchable(metric: Metric) -> None:
    """The pre-instrumentation checks, separated so a COLLECTION watch
    validates every member BEFORE instrumenting any — a TypeError on
    the third member must not leave the first two permanently
    instrumented with no handle to close or re-watch them."""
    if getattr(metric, "_quality_spec", None) is not None:
        raise ValueError(
            f"{type(metric).__name__} is already quality-watched"
        )
    if type(metric)._update_plan is Metric._update_plan:
        raise TypeError(
            f"watch_inputs requires a metric with a fusable update plan "
            f"({type(metric).__name__} has none — buffered/host-side "
            "updates cannot fuse sketch accumulation)"
        )


def _instrument(metric: Metric, spec: _WatchSpec) -> None:
    _validate_watchable(metric)
    cfg = spec.sketch
    for i in spec.args:
        h, c, m, r = _q_names(i)
        metric._add_state(
            h, jnp.zeros((cfg.num_bins,), jnp.float32), merge=MergeKind.SUM
        )
        metric._add_state(c, jnp.zeros((8,), jnp.int32), merge=MergeKind.SUM)
        metric._add_state(m, moment_default(), merge=MergeKind.CUSTOM)
        metric._add_state(
            r, jnp.zeros((cfg.registers,), jnp.int32), merge=MergeKind.MAX
        )
    metric._quality_spec = spec
    # the moments state must ALSO merge through the sharded reassembly
    # path, which by contract keeps CUSTOM non-sharded states at self's
    # value unless they are declared custom-mergeable (metric.py)
    metric._custom_mergeable_states = frozenset(
        metric._custom_mergeable_states
    ) | {_q_names(i)[2] for i in spec.args}
    metric._update_plan = types.MethodType(_watched_update_plan, metric)
    metric._merge_custom_state = types.MethodType(
        _watched_merge_custom, metric
    )


# --------------------------------------------------------------- watching

_WATCHES: "Dict[int, QualityWatch]" = {}  # tev: guarded-by=_WATCH_LOCK
_WATCH_LOCK = threading.Lock()
_WATCH_SEQ = [0]  # tev: guarded-by=_WATCH_LOCK


def active_watches() -> List["QualityWatch"]:
    """The live :class:`QualityWatch` handles (exporters iterate this)."""
    with _WATCH_LOCK:
        return list(_WATCHES.values())


def _quality_counters() -> Dict[str, Any]:
    out: Dict[str, Any] = {"watched_inputs": 0}
    for watch in active_watches():
        counters = watch.counters()
        out["watched_inputs"] += counters.pop("watched_inputs", 0)
        out.update(counters)
    return out


def _check_watches(monitor) -> List[Dict[str, Any]]:
    raised: List[Dict[str, Any]] = []
    for watch in active_watches():
        raised.extend(watch.check(monitor))
    return raised


def _register_global_hooks() -> None:
    from torcheval_tpu.obs.counters import default_registry
    from torcheval_tpu.obs.monitor import register_check_hook

    default_registry().register("quality", _quality_counters)
    register_check_hook("quality", _check_watches)


def _unregister_global_hooks() -> None:
    from torcheval_tpu.obs.counters import default_registry
    from torcheval_tpu.obs.monitor import unregister_check_hook

    default_registry().unregister("quality")
    unregister_check_hook("quality")


def watch_inputs(
    metric_or_collection,
    *,
    args: Tuple[int, ...] = (0,),
    num_bins: Optional[int] = None,
    bounds: Optional[Tuple[float, float]] = None,
    log2_bounds: Tuple[int, int] = (-24, 24),
    registers: int = 64,
    label: Optional[str] = None,
) -> "QualityWatch":
    """Arm input-quality sketches on a metric or ``{name: Metric}``
    collection (module docstring has the cost/fusion contract).

    ``args`` names the watched positional update arguments (default: the
    first — conventionally the prediction/input tensor); sketch geometry
    knobs mirror :class:`~torcheval_tpu.obs.sketch.InputSketch`. Each
    watched input becomes a series ``<label>/<arg index>`` (collection
    members use their collection key as label).

    Returns a :class:`QualityWatch` — the handle for reference freezing,
    drift specs, sketch snapshots, and teardown (``close()``).
    """
    cfg = default_config(num_bins, bounds, log2_bounds, registers)
    args = tuple(sorted(set(int(i) for i in args)))
    if not args or any(i < 0 for i in args):
        raise ValueError(f"args must be non-negative indices, got {args!r}")
    spec = _WatchSpec(args=args, sketch=cfg)
    if isinstance(metric_or_collection, dict):
        members = list(metric_or_collection.items())
        if not members:
            raise ValueError("watch_inputs: empty collection")
    else:
        members = [
            (label or type(metric_or_collection).__name__,
             metric_or_collection)
        ]
    entries = []
    for name, metric in members:
        _validate_watchable(metric)
        for i in args:
            entries.append((f"{name}/{i}", metric, i))
    # series names must be unique ACROSS watches: a collision silently
    # merges two inputs' gauges in the quality counter source, emits
    # duplicate Prometheus series, and lets one watch's in-bounds check
    # clear the other's standing drift alert
    with _WATCH_LOCK:
        taken = {
            series
            for other in _WATCHES.values()
            for series in other.series
        }
    clashes = sorted({s for s, _, _ in entries} & taken)
    if clashes:
        raise ValueError(
            f"watch series {clashes} already exist on an active watch; "
            "pass label= (or distinct collection keys) to disambiguate"
        )
    for name, metric in members:
        _instrument(metric, spec)
    watch = QualityWatch(entries, cfg)
    with _WATCH_LOCK:
        _WATCH_SEQ[0] += 1
        watch._id = _WATCH_SEQ[0]
        _WATCHES[watch._id] = watch
        _register_global_hooks()
    return watch


class QualityWatch:
    """Handle over a set of watched inputs (one per (metric, arg)).

    ``series`` keys are ``<label>/<arg index>``. Reading methods
    (``sketch``, ``summary``, ``counters``, ``check``) force a device
    readback of the sketch states — scrape/check cadence only.
    """

    def __init__(self, entries, config: SketchConfig) -> None:
        self._entries: Dict[str, Tuple[Metric, int]] = {
            series: (metric, arg) for series, metric, arg in entries
        }
        self.config = config
        self._id = 0
        self._lock = threading.Lock()
        self._refs: Dict[str, Dict[str, np.ndarray]] = {}  # tev: guarded-by=_lock
        self._specs: List[DriftSpec] = []  # tev: guarded-by=_lock
        self._scores: Dict[str, Dict[str, float]] = {}  # tev: guarded-by=_lock

    @property
    def series(self) -> Tuple[str, ...]:
        return tuple(sorted(self._entries))

    def _states(self, series: str) -> Dict[str, np.ndarray]:
        metric, arg = self._entries[series]
        h, c, m, r = _q_names(arg)
        return {
            "hist": np.asarray(getattr(metric, h)),
            "cnt": np.asarray(getattr(metric, c)),
            "mom": np.asarray(getattr(metric, m)),
            "reg": np.asarray(getattr(metric, r)),
        }

    def sketch(self, series: str) -> InputSketch:
        """A standalone :class:`InputSketch` loaded from the live sketch
        states of one watched input (an independent snapshot)."""
        metric, arg = self._entries[series]
        cfg = self.config
        if cfg.log2:
            sk = InputSketch(
                num_bins=cfg.num_bins,
                log2_bounds=(int(cfg.lo), int(cfg.hi)),
                registers=cfg.registers,
            )
        else:
            sk = InputSketch(
                num_bins=cfg.num_bins,
                bounds=(cfg.lo, cfg.hi),
                registers=cfg.registers,
            )
        h, c, m, r = _q_names(arg)
        sk.load_state_dict(
            {
                "hist": getattr(metric, h),
                "counts": getattr(metric, c),
                "moments": getattr(metric, m),
                "registers": getattr(metric, r),
            }
        )
        return sk

    # ----------------------------------------------------------- drift

    def freeze_reference(self) -> None:
        """Snapshot every watched input's live sketch as the drift
        reference window. Scoring compares the POST-freeze window
        against this snapshot; call it after the reference traffic has
        been observed (and again to re-baseline)."""
        refs = {series: self._states(series) for series in self._entries}
        with self._lock:
            self._refs = refs

    def add_drift(self, *specs: DriftSpec) -> None:
        """Arm drift scoring: freezes a reference now if none exists and
        registers the specs (``series="*"`` applies to every watched
        input). Scoring runs inside ``Monitor.check()`` — whichever
        monitor instance runs the check (the armed global one at
        ``/healthz`` cadence, or a test-local instance)."""
        specs = specs or (DriftSpec(),)
        for spec in specs:
            if spec.series != "*" and spec.series not in self._entries:
                raise KeyError(
                    f"DriftSpec series {spec.series!r} is not watched "
                    f"(watched: {sorted(self._entries)})"
                )
        with self._lock:
            need_ref = not self._refs
            self._specs.extend(specs)
        if need_ref:
            self.freeze_reference()

    def _series_specs(self) -> Dict[str, DriftSpec]:
        with self._lock:
            specs = list(self._specs)
        out: Dict[str, DriftSpec] = {}
        for spec in specs:
            if spec.series == "*":
                for series in self._entries:
                    out.setdefault(series, spec)
            else:
                out[spec.series] = spec
        return out

    def score(self, series: str) -> Optional[Dict[str, float]]:
        """PSI / KS / z of the post-freeze window vs the frozen
        reference (None when no reference is frozen for ``series``)."""
        with self._lock:
            ref = self._refs.get(series)
        if ref is None:
            return None
        live = self._states(series)
        return _drift_scores(live, ref)

    def check(self, monitor) -> List[Dict[str, Any]]:
        """Score every specced series; raise cooldown-guarded monitor
        alerts for breaches and emit a DriftEvent per scored series
        (recorder-gated). Called by ``Monitor.check`` via the quality
        check hook."""
        from torcheval_tpu.obs.events import DriftEvent
        from torcheval_tpu.obs.recorder import RECORDER

        raised: List[Dict[str, Any]] = []
        for series, spec in sorted(self._series_specs().items()):
            scores = self.score(series)
            if scores is None:
                continue
            with self._lock:
                self._scores[series] = scores
            if scores["count"] < spec.min_count:
                # a re-baseline (freeze_reference / reset) shrinks the
                # window below the gate: standing alerts from the OLD
                # window must clear, or /healthz stays 503 until the
                # new window warms (forever, if the stream stopped)
                for kind in ("psi", "ks", "z"):
                    monitor._clear(f"quality/{series}", f"drift-{kind}")
                continue
            breaches = []
            for kind, bound in (
                ("psi", spec.psi),
                ("ks", spec.ks),
                ("z", spec.z),
            ):
                value = abs(scores[kind])
                name = f"quality/{series}"
                if bound > 0 and value >= bound:
                    breaches.append(kind)
                    alert = monitor._alert(
                        name,
                        f"drift-{kind}",
                        scores[kind],
                        bound,
                        f"{series} input drift: {kind}={scores[kind]:.4g} "
                        f"breaches bound {bound:g} over a "
                        f"{scores['count']:.0f}-sample window "
                        f"(ref {scores['ref_count']:.0f})",
                    )
                    if alert:
                        raised.append(alert)
                else:
                    monitor._clear(name, f"drift-{kind}")
            if breaches:
                # measured-error-budget gate for the quantized wire
                # ladder (ISSUE 18): a drifting input family forfeits
                # its lossy wire rung — step it toward exact and emit a
                # WireTierEvent (no-op once already exact)
                from torcheval_tpu import wire

                metric, _arg = self._entries[series]
                wire.note_budget_breach(
                    type(metric).__name__,
                    series=series,
                    breach=",".join(breaches),
                )
            RECORDER.record(
                DriftEvent(
                    series=series,
                    count=float(scores["count"]),
                    ref_count=float(scores["ref_count"]),
                    psi=float(scores["psi"]),
                    ks=float(scores["ks"]),
                    z=float(scores["z"]),
                    breach=",".join(breaches),
                )
            )
        return raised

    # -------------------------------------------------------- counters

    def counters(self) -> Dict[str, Any]:
        """The ``quality`` counter-source payload: per-series gauges
        (device readback — scrape cadence) plus the last drift scores
        and per-spec breach flags."""
        out: Dict[str, Any] = {"watched_inputs": len(self._entries)}
        with self._lock:
            scores = dict(self._scores)
        specs = self._series_specs()
        for series in self.series:
            s = self._states(series)
            mom = s["mom"].astype(np.float64)
            cnt = s["cnt"]
            key = series
            count = float(mom[0])
            out[f"{key}_count"] = count
            out[f"{key}_mean"] = float(mom[1]) if count else 0.0
            out[f"{key}_std"] = (
                math.sqrt(max(float(mom[2]) / count, 0.0)) if count else 0.0
            )
            for lane, field in enumerate(CNT_FIELDS):
                if field in ("total", "nan", "posinf", "neginf", "zero",
                             "negative"):
                    out[f"{key}_{field}"] = int(cnt[lane])
            out[f"{key}_distinct"] = hll_estimate(s["reg"])
            sc = scores.get(series)
            if sc is not None:
                out[f"{key}_psi"] = sc["psi"]
                out[f"{key}_ks"] = sc["ks"]
                out[f"{key}_z"] = sc["z"]
                spec = specs.get(series)
                if spec is not None:
                    out[f"{key}_breach_psi"] = int(
                        spec.psi > 0 and abs(sc["psi"]) >= spec.psi
                        and sc["count"] >= spec.min_count
                    )
                    out[f"{key}_breach_ks"] = int(
                        spec.ks > 0 and abs(sc["ks"]) >= spec.ks
                        and sc["count"] >= spec.min_count
                    )
                    out[f"{key}_breach_z"] = int(
                        spec.z > 0 and abs(sc["z"]) >= spec.z
                        and sc["count"] >= spec.min_count
                    )
        return out

    def close(self) -> None:
        """Detach this watch from the exporters and the check hook (the
        sketch states REMAIN on the watched metrics — state removal
        would break strict snapshot loads mid-stream). The emptiness
        check and the hook unregister happen under ONE lock hold — a
        concurrent ``watch_inputs`` between them could otherwise lose
        its just-registered hooks."""
        with _WATCH_LOCK:
            _WATCHES.pop(self._id, None)
            if not _WATCHES:
                _unregister_global_hooks()


def _drift_scores(
    live: Dict[str, np.ndarray], ref: Dict[str, np.ndarray]
) -> Dict[str, float]:
    """PSI + histogram-KS + Welch z of the post-freeze window vs the
    reference. Window lanes: [below, bin_0..bin_{B-1}, above] — the
    out-of-range mass is part of the distribution (a shift past the
    edges must not be invisible)."""
    from torcheval_tpu.obs.sketch import _CNT_ABOVE, _CNT_BELOW  # lanes

    def lanes(s):
        return np.concatenate(
            (
                [float(s["cnt"][_CNT_BELOW])],
                np.asarray(s["hist"], np.float64),
                [float(s["cnt"][_CNT_ABOVE])],
            )
        )

    ref_lanes = lanes(ref)
    win_lanes = lanes(live) - ref_lanes
    mom_w = moments_window(live["mom"], ref["mom"])
    mom_r = np.asarray(ref["mom"], np.float64)
    n_w, n_r = float(mom_w[0]), float(mom_r[0])
    out = {
        "count": n_w,
        "ref_count": n_r,
        "psi": 0.0,
        "ks": 0.0,
        "z": 0.0,
    }
    rt, wt = float(ref_lanes.sum()), float(win_lanes.sum())
    if rt > 0 and wt > 0:
        eps = 1e-6
        p = np.maximum(ref_lanes / rt, eps)
        q = np.maximum(win_lanes / wt, eps)
        out["psi"] = float(np.sum((q - p) * np.log(q / p)))
        out["ks"] = float(
            np.max(np.abs(np.cumsum(win_lanes / wt - ref_lanes / rt)))
        )
    if n_w > 0 and n_r > 0:
        var_w = max(float(mom_w[2]) / n_w, 0.0)
        var_r = max(float(mom_r[2]) / n_r, 0.0)
        denom = math.sqrt(var_w / n_w + var_r / n_r)
        if denom > 0:
            out["z"] = (float(mom_w[1]) - float(mom_r[1])) / denom
        elif float(mom_w[1]) != float(mom_r[1]):
            out["z"] = math.inf if mom_w[1] > mom_r[1] else -math.inf
    return out
