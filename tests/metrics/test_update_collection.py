"""toolkit.update_collection: K metric updates in one fused dispatch.

Beyond-parity feature built on ``Metric._update_plan`` — correctness is
pinned against per-metric ``update()`` (identical states afterward), the
fallback path against non-fusable metrics, and the dispatch structure via
the compile-count trick from ``test_dispatch_counts``.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torcheval_tpu.metrics as M
from torcheval_tpu.metrics.toolkit import update_collection
from tests.metrics.test_dispatch_counts import programs_for

RNG = np.random.default_rng(23)

N, C = 128, 8
XC = jnp.asarray(RNG.uniform(size=(N, C)).astype(np.float32))
TC = jnp.asarray(RNG.integers(0, C, size=N))


def _classification_collection():
    return {
        "acc": M.MulticlassAccuracy(),
        "acc_macro": M.MulticlassAccuracy(average="macro", num_classes=C),
        "f1": M.MulticlassF1Score(),
        "precision": M.MulticlassPrecision(num_classes=C, average="macro"),
        "recall": M.MulticlassRecall(num_classes=C, average="macro"),
        "cm": M.MulticlassConfusionMatrix(C),
        "binned_auprc": M.MulticlassBinnedAUPRC(num_classes=C, threshold=16),
    }


def test_matches_individual_updates():
    grouped = _classification_collection()
    individual = _classification_collection()

    for lo, hi in ((0, 64), (64, 128)):  # two batches
        update_collection(grouped, XC[lo:hi], TC[lo:hi])
        for m in individual.values():
            m.update(XC[lo:hi], TC[lo:hi])

    for name in grouped:
        got = jax.tree_util.tree_map(np.asarray, grouped[name].state_dict())
        want = jax.tree_util.tree_map(
            np.asarray, individual[name].state_dict()
        )
        assert got.keys() == want.keys()
        for k in got:
            np.testing.assert_allclose(
                got[k], want[k], atol=1e-5, err_msg=f"{name}.{k}"
            )


def test_single_dispatch_for_fusable_group():
    metrics = _classification_collection()
    update_collection(metrics, XC, TC)  # trace/compile
    progs = programs_for(lambda: update_collection(metrics, XC, TC))
    assert len(progs) <= 1, progs


def test_fallback_for_non_fusable():
    """Buffered metrics have no plan; they update normally in the call."""
    x1 = jnp.asarray(RNG.uniform(size=N).astype(np.float32))
    t1 = jnp.asarray((RNG.random(N) < 0.5).astype(np.float32))
    metrics = {
        "auroc": M.BinaryAUROC(),  # buffered: no plan
        "acc": M.BinaryAccuracy(),  # fusable
        "ne": M.BinaryNormalizedEntropy(),  # fusable
    }
    update_collection(metrics, x1, t1)
    assert metrics["auroc"].num_samples == N
    solo = M.BinaryAccuracy().update(x1, t1)
    np.testing.assert_allclose(
        float(metrics["acc"].compute()), float(solo.compute()), atol=1e-6
    )
    import sklearn.metrics as skm

    np.testing.assert_allclose(
        float(metrics["auroc"].compute()),
        skm.roc_auc_score(np.asarray(t1), np.asarray(x1)),
        atol=1e-5,
    )


def test_list_input_and_return_identity():
    ms = [M.Sum(), M.Mean()]
    out = update_collection(ms, jnp.asarray([1.0, 2.0, 3.0]))
    assert out is ms
    assert float(ms[0].compute()) == 6.0
    np.testing.assert_allclose(float(ms[1].compute()), 2.0)


def test_kwargs_flow_through():
    metrics = {"mse": M.MeanSquaredError(), "r2": M.R2Score()}
    x = jnp.asarray(RNG.uniform(size=N).astype(np.float32))
    t = jnp.asarray(RNG.uniform(size=N).astype(np.float32))
    w = jnp.asarray(RNG.uniform(size=N).astype(np.float32))
    # mse accepts sample_weight kwarg; r2 does not — so group only the
    # metrics sharing a signature, as a user would
    update_collection({"mse": metrics["mse"]}, x, t, sample_weight=w)
    solo = M.MeanSquaredError().update(x, t, sample_weight=w)
    np.testing.assert_allclose(
        float(metrics["mse"].compute()), float(solo.compute()), rtol=1e-6
    )


def test_invalid_input_raises_before_any_state_change():
    """A bad batch must not partially update the collection: plans run
    their checks eagerly before the group program executes."""
    metrics = _classification_collection()
    with pytest.raises(ValueError):
        update_collection(metrics, XC, TC[: N // 2])  # shape mismatch
    for name, m in metrics.items():
        for k, v in m.state_dict().items():
            if isinstance(v, jax.Array):
                assert float(jnp.sum(jnp.abs(v))) == 0.0, (name, k)


def _recsys_collection():
    return {
        "ctr": M.WindowedClickThroughRate(max_num_updates=3),
        "ne": M.WindowedBinaryNormalizedEntropy(max_num_updates=3),
        "wc": M.WindowedWeightedCalibration(max_num_updates=3),
        "ctr_life": M.ClickThroughRate(),
        "ne_life": M.BinaryNormalizedEntropy(),
    }


def test_windowed_metrics_fuse_and_match_individual():
    """Windowed (ring-buffer) metrics join the group dispatch via
    transform plans; states + cursors must match per-metric updates,
    including ring wraparound (5 updates into 3-slot windows)."""
    grouped, individual = _recsys_collection(), _recsys_collection()
    for i in range(5):
        x = jnp.asarray(RNG.uniform(size=32).astype(np.float32))
        t = jnp.asarray((RNG.random(32) < 0.5).astype(np.float32))
        update_collection(grouped, x, t)
        for m in individual.values():
            m.update(x, t)
    for name in grouped:
        got = grouped[name].state_dict()
        want = individual[name].state_dict()
        assert got.keys() == want.keys(), name
        for k in got:
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want[k]), atol=1e-5,
                err_msg=f"{name}.{k}",
            )
        out_g = jax.tree_util.tree_map(np.asarray, grouped[name].compute())
        out_i = jax.tree_util.tree_map(np.asarray, individual[name].compute())
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            out_g, out_i,
        )


def test_windowed_plus_counter_single_dispatch():
    metrics = _recsys_collection()
    x = jnp.asarray(RNG.uniform(size=32).astype(np.float32))
    t = jnp.asarray((RNG.random(32) < 0.5).astype(np.float32))
    update_collection(metrics, x, t)  # compile at this cursor position
    # pin the dispatch count at a DIFFERENT cursor than the warm call, so
    # the traced-column design (no per-slot programs) is also exercised
    progs = programs_for(lambda: update_collection(metrics, x, t))
    assert len(progs) <= 1, progs


def test_windowed_auroc_fuses_in_collection():
    metrics = {
        "wauroc": M.WindowedBinaryAUROC(max_num_samples=64),
        "acc": M.BinaryAccuracy(),
    }
    solo = M.WindowedBinaryAUROC(max_num_samples=64)
    for i in range(4):  # wraps the 64-slot ring with 32-sample batches
        x = jnp.asarray(RNG.uniform(size=32).astype(np.float32))
        t = jnp.asarray((RNG.random(32) < 0.5).astype(np.float32))
        update_collection(metrics, x, t)
        solo.update(x, t)
    assert metrics["wauroc"].next_inserted == solo.next_inserted
    np.testing.assert_allclose(
        np.asarray(metrics["wauroc"].compute()),
        np.asarray(solo.compute()),
        atol=1e-6,
    )


def test_aggregation_image_streaming_plans():
    """Max/Min (transform), PSNR auto-range (5-state transform), and
    StreamingBinaryAUROC (histogram accumulate) all fuse — one dispatch
    for the whole panel, states identical to per-metric updates."""
    def mk():
        return {
            "max": M.Max(),
            "min": M.Min(),
            "psnr": M.PeakSignalNoiseRatio(),  # auto_range default
            "stream": M.StreamingBinaryAUROC(num_bins=64),
            "stream_pr": M.StreamingBinaryAUPRC(num_bins=64),
        }

    grouped, individual = mk(), mk()
    for _ in range(3):
        x = jnp.asarray(RNG.uniform(size=64).astype(np.float32))
        t = jnp.asarray((RNG.random(64) < 0.5).astype(np.float32))
        # psnr/stream take (input, target); max/min ignore the target via
        # their single-arg plan — group them by signature as a user would
        update_collection({"psnr": grouped["psnr"],
                           "stream": grouped["stream"],
                           "stream_pr": grouped["stream_pr"]}, x, t)
        update_collection({"max": grouped["max"],
                           "min": grouped["min"]}, x)
        individual["psnr"].update(x, t)
        individual["stream"].update(x, t)
        individual["stream_pr"].update(x, t)
        individual["max"].update(x)
        individual["min"].update(x)
    for name in grouped:
        got = grouped[name].state_dict()
        want = individual[name].state_dict()
        for k in got:
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want[k]), atol=1e-6,
                err_msg=f"{name}.{k}",
            )
    x = jnp.asarray(RNG.uniform(size=64).astype(np.float32))
    t = jnp.asarray((RNG.random(64) < 0.5).astype(np.float32))
    pair = {"psnr": grouped["psnr"], "stream": grouped["stream"]}
    progs = programs_for(lambda: update_collection(pair, x, t))
    assert len(progs) <= 1, progs
    extrema = {"max": grouped["max"], "min": grouped["min"]}
    progs = programs_for(lambda: update_collection(extrema, x))
    assert len(progs) <= 1, progs


def test_record_extension_point_counts_once():
    """The documented subclass path (pre-computed counters through
    ``_record``) must advance ``total_updates`` exactly once per call —
    regression for a double increment when ``_record_via`` gained a
    finalize-bearing plan."""
    from torcheval_tpu.metrics.window._base import WindowedTaskCounterMetric

    class MiniWindowed(WindowedTaskCounterMetric):
        def __init__(self):
            super().__init__()
            self._init_window_states(
                ("total",), num_tasks=1, max_num_updates=3,
                enable_lifetime=True,
            )

        def update(self, value):
            self._record((jnp.asarray([float(value)]),))
            return self

        def compute(self):
            return self._windowed_counter_sums()[0]

    m = MiniWindowed()
    for v in (1.0, 2.0, 3.0, 4.0):  # wraps the 3-slot ring once
        m.update(v)
    assert m.total_updates == 4
    assert m.next_inserted == 1
    np.testing.assert_allclose(float(m.compute()[0]), 2.0 + 3.0 + 4.0)
    np.testing.assert_allclose(np.asarray(m.total).squeeze(), 10.0)


def test_panel_converts_each_input_once(monkeypatch):
    """Per-metric preamble regression pin: a K-metric panel coerces each
    update argument ONCE, not K times (the shared conversion cache in
    update_collection — on host inputs each duplicate coercion was a full
    H2D upload; BENCH_r05 measured the 5-metric panel at ~9x one metric's
    preamble before caching)."""
    import torcheval_tpu.utils.convert as convert

    conversions = []
    real = convert._to_jax_impl

    def counting(x, **kw):
        conversions.append(id(x))
        return real(x, **kw)

    monkeypatch.setattr(convert, "_to_jax_impl", counting)
    metrics = _classification_collection()
    xc, tc = np.asarray(XC), np.asarray(TC)  # host inputs: the costly case
    conversions.clear()
    update_collection(metrics, xc, tc)
    # one conversion per distinct argument object — K metrics share them
    assert len(conversions) == len(set(conversions)) == 2, conversions


def test_plain_update_unaffected_by_cache_scope():
    """The shared cache is scoped to one update_collection call: separate
    per-metric updates still convert independently and match."""
    a = _classification_collection()["acc"]
    b = _classification_collection()["acc"]
    x, t = np.asarray(XC), np.asarray(TC)
    update_collection({"m": a}, x, t)
    b.update(x, t)
    np.testing.assert_allclose(
        float(a.compute()), float(b.compute()), atol=1e-6
    )


def test_mixed_collection_no_partial_update_on_bad_batch():
    """Plan validation runs for EVERY fusable metric before any fallback
    metric mutates: a batch that fails a fusable metric's check must leave
    non-fusable (buffered) peers untouched too."""
    x1 = jnp.asarray(RNG.uniform(size=N).astype(np.float32))
    t1 = jnp.asarray((RNG.random(N) < 0.5).astype(np.float32))
    metrics = {
        "auroc": M.BinaryAUROC(),  # fallback (buffered, no plan)
        "ne": M.BinaryNormalizedEntropy(num_tasks=2),  # plan rejects 1-D
    }
    with pytest.raises(ValueError):
        update_collection(metrics, x1, t1)
    assert metrics["auroc"].num_samples == 0  # buffer never touched
