"""Checkpoint/resume round-trips through the Orbax-backed helpers, across
every TState kind (tensor counters, list buffers, dict states, int/float,
windowed ring buffers)."""

import numpy as np
import pytest

import jax.numpy as jnp

from torcheval_tpu.metrics import (
    BinaryAUROC,
    MulticlassAccuracy,
    Throughput,
    WindowedBinaryNormalizedEntropy,
    WordErrorRate,
)
from torcheval_tpu.utils import load_metric_state, save_metric_state
from torcheval_tpu.utils.test_utils.dummy_metric import DummySumDictStateMetric
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    assert_result_close,
)

RNG = np.random.default_rng(3)


def _roundtrip(tmp_path, metric, fresh):
    save_metric_state(metric, str(tmp_path / "ck"))
    load_metric_state(fresh, str(tmp_path / "ck"))
    return fresh


def test_counter_state_roundtrip(tmp_path):
    m = MulticlassAccuracy()
    m.update(jnp.asarray(RNG.random((16, 4)), jnp.float32), jnp.asarray(RNG.integers(0, 4, 16)))
    restored = _roundtrip(tmp_path, m, MulticlassAccuracy())
    assert_result_close(restored.compute(), m.compute())
    # resumable: updates continue after restore
    restored.update(jnp.zeros((4, 4)), jnp.zeros(4, dtype=jnp.int32))


def test_list_buffer_state_roundtrip(tmp_path):
    m = BinaryAUROC()
    for _ in range(3):
        x = RNG.random(20).astype(np.float32)
        m.update(x, (RNG.random(20) < x).astype(np.float32))
    restored = _roundtrip(tmp_path, m, BinaryAUROC())
    assert_result_close(restored.compute(), m.compute())


def test_empty_buffer_state_roundtrip(tmp_path):
    m = BinaryAUROC()  # no updates: empty buffers
    restored = _roundtrip(tmp_path, m, BinaryAUROC())
    assert restored.num_samples == 0


def test_float_state_roundtrip(tmp_path):
    m = Throughput()
    m.update(100, 2.5)
    restored = _roundtrip(tmp_path, m, Throughput())
    assert_result_close(restored.compute(), m.compute())


def test_host_float_text_state_roundtrip(tmp_path):
    m = WordErrorRate()
    m.update(["a b c"], ["a b d"])
    restored = _roundtrip(tmp_path, m, WordErrorRate())
    assert_result_close(restored.compute(), m.compute())


def test_dict_state_roundtrip(tmp_path):
    m = DummySumDictStateMetric()
    m.update("a", jnp.asarray(2.0))
    m.update("b", jnp.asarray(3.0))
    restored = _roundtrip(tmp_path, m, DummySumDictStateMetric())
    assert_result_close(restored.compute(), m.compute())
    # restored dict keeps auto-zero semantics for unseen keys
    restored.update("c", jnp.asarray(1.0))


def test_window_ring_buffer_roundtrip(tmp_path):
    m = WindowedBinaryNormalizedEntropy(max_num_updates=4)
    for _ in range(6):
        x = np.clip(RNG.random(10), 0.01, 0.99).astype(np.float64)
        m.update(x, (RNG.random(10) < 0.5).astype(np.float64))
    restored = _roundtrip(
        tmp_path, m, WindowedBinaryNormalizedEntropy(max_num_updates=4)
    )
    assert_result_close(restored.compute(), m.compute())


def test_collection_roundtrip(tmp_path):
    acc = MulticlassAccuracy()
    acc.update(jnp.asarray(RNG.random((8, 3)), jnp.float32), jnp.asarray(RNG.integers(0, 3, 8)))
    auroc = BinaryAUROC()
    x = RNG.random(16).astype(np.float32)
    auroc.update(x, (RNG.random(16) < x).astype(np.float32))
    save_metric_state({"acc": acc, "auroc": auroc}, str(tmp_path / "coll"))
    fresh = {"acc": MulticlassAccuracy(), "auroc": BinaryAUROC()}
    load_metric_state(fresh, str(tmp_path / "coll"))
    assert_result_close(fresh["acc"].compute(), acc.compute())
    assert_result_close(fresh["auroc"].compute(), auroc.compute())


def test_collection_strict_mismatch_both_directions(tmp_path):
    acc = MulticlassAccuracy()
    save_metric_state({"acc": acc}, str(tmp_path / "c2"))
    # collection requests a metric the checkpoint lacks
    with pytest.raises(RuntimeError, match="missing state for \\['other'\\]"):
        load_metric_state(
            {"acc": MulticlassAccuracy(), "other": BinaryAUROC()},
            str(tmp_path / "c2"),
        )
    # checkpoint holds state the collection doesn't claim
    save_metric_state(
        {"acc": acc, "extra": MulticlassAccuracy()}, str(tmp_path / "c3")
    )
    with pytest.raises(RuntimeError, match="unclaimed saved state"):
        load_metric_state({"acc": MulticlassAccuracy()}, str(tmp_path / "c3"))
    # non-strict: loads what exists
    load_metric_state(
        {"acc": MulticlassAccuracy(), "other": BinaryAUROC()},
        str(tmp_path / "c2"),
        strict=False,
    )


def test_single_vs_collection_kind_mismatch(tmp_path):
    acc = MulticlassAccuracy()
    save_metric_state({"acc": acc}, str(tmp_path / "coll"))
    with pytest.raises(RuntimeError, match="holds a metric collection"):
        load_metric_state(MulticlassAccuracy(), str(tmp_path / "coll"))
    save_metric_state(acc, str(tmp_path / "single"))
    with pytest.raises(RuntimeError, match="holds a single metric"):
        load_metric_state(
            {"acc": MulticlassAccuracy()}, str(tmp_path / "single")
        )


def test_window_cursor_survives_resume(tmp_path):
    """Regression: a restored windowed metric must keep overwriting the
    OLDEST ring column; a parallel uninterrupted metric is the oracle."""
    rng = np.random.default_rng(8)
    batches = [
        (
            np.clip(rng.random(10), 0.01, 0.99).astype(np.float64),
            (rng.random(10) < 0.5).astype(np.float64),
        )
        for _ in range(10)
    ]
    uninterrupted = WindowedBinaryNormalizedEntropy(max_num_updates=4)
    first = WindowedBinaryNormalizedEntropy(max_num_updates=4)
    for x, t in batches[:6]:
        uninterrupted.update(x, t)
        first.update(x, t)
    save_metric_state(first, str(tmp_path / "cursor"))
    resumed = load_metric_state(
        WindowedBinaryNormalizedEntropy(max_num_updates=4),
        str(tmp_path / "cursor"),
    )
    assert resumed.next_inserted == first.next_inserted == 2
    for x, t in batches[6:]:
        uninterrupted.update(x, t)
        resumed.update(x, t)
    assert_result_close(resumed.compute(), uninterrupted.compute())
