"""HitRate class metric.

Parity: reference torcheval/metrics/ranking/hit_rate.py:19-90. Buffers
per-example scores; ``compute`` concatenates.
"""

from __future__ import annotations

from typing import Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.ranking.hit_rate import hit_rate
from torcheval_tpu.metrics._buffer import BufferedExamplesMetric

THitRate = TypeVar("THitRate", bound="HitRate")


class HitRate(BufferedExamplesMetric):
    """Concatenated per-example hit-rate scores.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import HitRate
        >>> metric = HitRate(k=2)
        >>> metric.update(jnp.array([[0.3, 0.1, 0.6], [0.5, 0.2, 0.3]]),
        ...               jnp.array([2, 1]))
        >>> metric.compute()
        Array([1., 0.], dtype=float32)
    """

    def __init__(
        self, *, k: Optional[int] = None, device: Optional[jax.Device] = None
    ) -> None:
        super().__init__(device=device)
        self.k = k
        # fixed-shape growable buffer of per-example scores (_buffer.py)
        self._add_buffer("scores", fill=0.0, axis=0)

    def update(self: THitRate, input, target) -> THitRate:
        """Score one batch of predictions against targets."""
        BufferedExamplesMetric._append(
            self,
            scores=hit_rate(self._input(input), self._input(target), k=self.k),
        )
        return self

    def compute(self) -> jax.Array:
        """All per-example scores; empty array before any update."""
        if self.num_samples == 0:
            return jnp.zeros(0)
        return self._valid()[0]
