"""Shared fixed-shape kernels for sort/threshold-curve metrics
(AUROC / AUPRC / precision-recall curves / recall@precision).

The reference compacts tie runs with data-dependent ``masked_scatter``
(reference functional/classification/auroc.py:115-152,
precision_recall_curve.py:209-232) — shapes depend on the number of distinct
thresholds, which XLA cannot compile. The TPU reformulation used here keeps
every array at the static sample count ``n``:

1. sort scores descending; cumsum weighted TP/FP;
2. mark tie-run *ends* (``threshold[i] != threshold[i+1]``, last element
   always an end);
3. propagate each run-end's cumulative values backwards over its run with a
   reverse ``cummin`` (cumsums are nondecreasing, so the nearest run-end to
   the right is the suffix minimum of run-end values);
4. integrate over the resulting curve: consecutive duplicate points have
   ``dx == 0`` and contribute nothing, so trapezoid/Riemann sums equal the
   reference's compacted-curve integrals exactly.

One fused XLA program per metric; no host syncs; vmap-able over tasks,
classes, and labels.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu._ffi import ffi as _ffi


def _sort_desc_xla(input: jax.Array) -> Tuple[jax.Array, jax.Array]:
    order = jnp.argsort(-input, axis=-1, stable=True)
    return jnp.take_along_axis(input, order, axis=-1), order


@jax.custom_jvp
def _sort_desc_native(input: jax.Array) -> Tuple[jax.Array, jax.Array]:
    from torcheval_tpu.metrics.functional.tensor_utils import _match_vma

    n = input.shape[-1]
    x2 = input.reshape(-1, n)
    call = _ffi.ffi_call(
        "torcheval_sort_desc",
        (
            jax.ShapeDtypeStruct(x2.shape, jnp.float32),
            jax.ShapeDtypeStruct(x2.shape, jnp.int32),
        ),
        vmap_method="sequential",
    )
    sorted_scores, order = call(x2)
    return (
        _match_vma(sorted_scores.reshape(input.shape), input),
        _match_vma(order.reshape(input.shape), input),
    )


@_sort_desc_native.defjvp
def _sort_desc_native_jvp(primals, tangents):
    # same JVP XLA's sort has: the tangent rides the permutation; the
    # integer order output has no tangent (float0)
    import numpy as np

    (x,), (tx,) = primals, tangents
    sorted_scores, order = _sort_desc_native(x)
    t_sorted = jnp.take_along_axis(tx, order, axis=-1)
    t_order = np.zeros(order.shape, dtype=jax.dtypes.float0)
    return (sorted_scores, order), (t_sorted, t_order)


def sort_desc(input: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Stable descending sort along axis -1: ``(sorted_scores, order)``.

    Semantics of ``jnp.argsort(-x, stable=True)`` (ties keep ascending
    original index, NaNs of either sign sort last) on every backend. The
    sort is the whole cost of the curve metrics on CPU — XLA's
    single-threaded comparison sort takes ~100 ms for 262k floats where
    the native radix argsort (``ops/native/sort_desc.cc``) takes ~6 ms —
    so the CPU lowering swaps in the FFI kernel via
    ``lax.platform_dependent``; TPU keeps the pure-XLA sort (its sort unit
    is not the bottleneck there).
    """
    if input.dtype != jnp.float32 or input.size == 0:
        return _sort_desc_xla(input)
    from torcheval_tpu.ops import native

    if not native.ensure_registered():
        return _sort_desc_xla(input)

    def _xla_i32(x):
        # platform_dependent needs identical branch output types; under
        # jax_enable_x64 argsort returns int64 while the kernel pins int32
        s, o = _sort_desc_xla(x)
        return s, o.astype(jnp.int32)

    return jax.lax.platform_dependent(
        input, cpu=_sort_desc_native, default=_xla_i32
    )


def _native_area_call(
    target_name: str, input: jax.Array, *operands: jax.Array, **attrs
) -> jax.Array:
    """Shared FFI wrapper for trailing-axis area kernels: flatten leading
    dims into tasks, call, restore shape and varying-manual-axes."""
    from torcheval_tpu.metrics.functional.tensor_utils import _match_vma

    n = input.shape[-1]
    x2 = input.reshape(-1, n)
    call = _ffi.ffi_call(
        target_name,
        jax.ShapeDtypeStruct((x2.shape[0],), jnp.float32),
        vmap_method="sequential",
    )
    out = call(
        x2, *(op.reshape(-1, op.shape[-1]) for op in operands), **attrs
    )
    return _match_vma(out.reshape(input.shape[:-1]), input)


def _native_area_ready(input: jax.Array) -> bool:
    if input.dtype != jnp.float32 or input.size == 0:
        return False
    from torcheval_tpu.ops import native

    return native.ensure_registered()


def _binary_auroc_area_xla(
    input: jax.Array, target: jax.Array, weight: Optional[jax.Array]
) -> jax.Array:
    _, cum_tp, cum_fp, _ = roc_cumulators(input, target, weight)
    return auroc_from_cumulators(cum_tp, cum_fp)


@partial(jax.custom_jvp, nondiff_argnums=(3,))
def _auroc_area_dispatch(
    input: jax.Array,
    target: jax.Array,
    weight: jax.Array,
    has_weight: bool,
) -> jax.Array:
    def native_fn(x, t, w):
        return _native_area_call(
            "torcheval_binary_auroc", x, t, w, has_weight=int(has_weight)
        )

    def xla_fn(x, t, w):
        return _binary_auroc_area_xla(x, t, w if has_weight else None)

    return jax.lax.platform_dependent(
        input, target, weight, cpu=native_fn, default=xla_fn
    )


@_auroc_area_dispatch.defjvp
def _auroc_area_jvp(has_weight, primals, tangents):
    # primal rides the fast native path; the tangent is the exact JVP of
    # the XLA implementation (the FFI call itself refuses differentiation)
    out = _auroc_area_dispatch(*primals, has_weight)
    _, t_out = jax.jvp(
        lambda x, t, w: _binary_auroc_area_xla(x, t, w if has_weight else None),
        primals,
        tangents,
    )
    return out, t_out


def binary_auroc_area(
    input: jax.Array,
    target: jax.Array,
    weight: Optional[jax.Array] = None,
) -> jax.Array:
    """Tie-compacted trapezoidal AUROC over the trailing axis.

    The full sort -> cumulate -> compact -> integrate chain; on the CPU
    lowering (native library present) it fuses into one custom call
    (radix argsort + single traversal, ``ops/native/sort_desc.cc``) —
    the XLA chain costs ~10 passes over the batch there. Differentiable:
    the custom JVP replays the XLA formulation for tangents.
    """
    if not _native_area_ready(input):
        return _binary_auroc_area_xla(input, target, weight)
    if weight is None:
        # tiny dummy operand: the kernel never reads it (has_weight=0), so
        # the common unweighted call materializes no (tasks, n) ones array
        weight_arr = jnp.zeros(input.shape[:-1] + (1,), jnp.float32)
        has_weight = False
    else:
        weight_arr = jnp.broadcast_to(weight, input.shape).astype(jnp.float32)
        has_weight = True
    return _auroc_area_dispatch(
        input, target.astype(jnp.float32), weight_arr, has_weight
    )


def _binary_auprc_area_xla(input: jax.Array, target: jax.Array) -> jax.Array:
    p, r, _, _ = prc_arrays(input, target, 1)
    return auprc_from_prc(p, r)


@jax.custom_jvp
def _auprc_area_dispatch(input: jax.Array, target01: jax.Array) -> jax.Array:
    def native_fn(x, t):
        return _native_area_call("torcheval_binary_auprc", x, t)

    return jax.lax.platform_dependent(
        input, target01, cpu=native_fn, default=_binary_auprc_area_xla
    )


@_auprc_area_dispatch.defjvp
def _auprc_area_jvp(primals, tangents):
    out = _auprc_area_dispatch(*primals)
    _, t_out = jax.jvp(_binary_auprc_area_xla, primals, tangents)
    return out, t_out


def binary_auprc_area(input: jax.Array, target: jax.Array) -> jax.Array:
    """Left-Riemann AUPRC (pos_label=1 counts) over the trailing axis —
    same native/XLA split and JVP strategy as ``binary_auroc_area``."""
    if not _native_area_ready(input):
        return _binary_auprc_area_xla(input, target)
    return _auprc_area_dispatch(input, (target == 1).astype(jnp.float32))


def _run_end_mask(sorted_scores: jax.Array) -> jax.Array:
    """True at the last element of each equal-score run (axis -1)."""
    neq = sorted_scores[..., 1:] != sorted_scores[..., :-1]
    last = jnp.ones(sorted_scores.shape[:-1] + (1,), dtype=bool)
    return jnp.concatenate([neq, last], axis=-1)


def _propagate_run_end(values: jax.Array, is_end: jax.Array) -> jax.Array:
    """Replace every element with its tie-run end's value.

    ``values`` must be nondecreasing along axis -1 (cumulative sums are).
    """
    masked = jnp.where(is_end, values, jnp.inf)
    suffix_min = jnp.flip(
        jax.lax.cummin(jnp.flip(masked, axis=-1), axis=values.ndim - 1),
        axis=-1,
    )
    return suffix_min


def roc_cumulators(
    input: jax.Array,
    target: jax.Array,
    weight: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sorted thresholds + tie-compacted cumulative TP/FP (static shapes).

    Returns (threshold_sorted, cum_tp, cum_fp, is_run_end), each shaped like
    ``input`` with axis -1 in descending-score order.
    """
    threshold, order = sort_desc(input)
    starget = jnp.take_along_axis(target, order, axis=-1).astype(jnp.float32)
    if weight is None:
        sweight = jnp.ones_like(starget)
    else:
        sweight = jnp.take_along_axis(weight, order, axis=-1).astype(jnp.float32)
    cum_tp = jnp.cumsum(sweight * starget, axis=-1)
    cum_fp = jnp.cumsum(sweight * (1.0 - starget), axis=-1)
    is_end = _run_end_mask(threshold)
    cum_tp = _propagate_run_end(cum_tp, is_end)
    cum_fp = _propagate_run_end(cum_fp, is_end)
    return threshold, cum_tp, cum_fp, is_end


def auroc_from_cumulators(cum_tp: jax.Array, cum_fp: jax.Array) -> jax.Array:
    """Trapezoidal AUROC over the (FP, TP) curve, with the (0, 0) origin
    prepended (the reference's right-aligned zero padding supplies it,
    reference auroc.py:136-150). Degenerate all-pos/all-neg -> 0.5."""
    zeros = jnp.zeros(cum_tp.shape[:-1] + (1,), cum_tp.dtype)
    y = jnp.concatenate([zeros, cum_tp], axis=-1)
    x = jnp.concatenate([zeros, cum_fp], axis=-1)
    dx = x[..., 1:] - x[..., :-1]
    area = jnp.sum(dx * (y[..., 1:] + y[..., :-1]) / 2.0, axis=-1)
    factor = cum_tp[..., -1] * cum_fp[..., -1]
    return jnp.where(factor == 0, 0.5, area / jnp.where(factor == 0, 1.0, factor))


def prc_arrays(
    input: jax.Array, target: jax.Array, pos_label: int = 1
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full-length precision/recall/threshold arrays in ascending-threshold
    order, plus the validity mask marking the reference's compacted points
    (reference `_compute_for_each_class`, precision_recall_curve.py:209-232).

    The appended (precision=1, recall=0) terminal point is NOT included;
    integrators append it themselves. Recall is NaN-corrected to 1.0 when the
    target has no positive examples.
    """
    threshold, order = sort_desc(input)
    hit = (jnp.take_along_axis(target, order, axis=-1) == pos_label).astype(
        jnp.float32
    )
    num_tp = jnp.cumsum(hit, axis=-1)
    num_fp = jnp.cumsum(1.0 - hit, axis=-1)
    is_end = _run_end_mask(threshold)
    num_tp = _propagate_run_end(num_tp, is_end)
    num_fp = _propagate_run_end(num_fp, is_end)
    precision = num_tp / (num_tp + num_fp)
    total_tp = num_tp[..., -1:]
    recall = jnp.where(total_tp == 0, 1.0, num_tp / jnp.where(total_tp == 0, 1.0, total_tp))
    # ascending-threshold order, as the reference returns (flip of the
    # descending sort)
    return (
        jnp.flip(precision, axis=-1),
        jnp.flip(recall, axis=-1),
        jnp.flip(threshold, axis=-1),
        jnp.flip(is_end, axis=-1),
    )


def auprc_from_prc(
    precision: jax.Array, recall: jax.Array
) -> jax.Array:
    """Left-Riemann AUPRC over ascending-threshold (descending-recall) curve
    points with the terminal (p=1, r=0) appended (reference auprc.py:239-251
    + tensor_utils.py:12-16). Duplicate tie-run points contribute 0."""
    ones = jnp.ones(precision.shape[:-1] + (1,), precision.dtype)
    zeros = jnp.zeros(recall.shape[:-1] + (1,), recall.dtype)
    p = jnp.concatenate([precision, ones], axis=-1)
    r = jnp.concatenate([recall, zeros], axis=-1)
    return -jnp.sum((r[..., 1:] - r[..., :-1]) * p[..., :-1], axis=-1)


def recall_at_precision_from_arrays(
    precision: jax.Array,
    recall: jax.Array,
    threshold: jax.Array,
    is_end: jax.Array,
    min_precision: float,
) -> Tuple[jax.Array, jax.Array]:
    """Max recall subject to precision >= min_precision, and the largest
    threshold attaining it (reference recall_at_fixed_precision.py:132-141).

    Operates on the padded arrays; non-run-end duplicates are masked out of
    the recall max (they duplicate a valid point so would not change it) and
    of the threshold argmax (where they could otherwise select a duplicate's
    threshold, which differs from the compacted point's).
    The appended terminal point (recall 0, threshold -1) participates,
    matching the reference's sentinel.
    """
    ok = is_end & (precision >= min_precision)
    # terminal point: precision 1 >= min_precision always; recall 0
    max_recall = jnp.max(
        jnp.where(ok, recall, 0.0), axis=-1, initial=0.0
    )
    # the reference's threshold step filters by recall only, not precision;
    # ineligible slots fill with -inf (NOT the -1 terminal sentinel, which
    # would shadow legitimate negative/logit-valued thresholds). The terminal
    # (recall=0, threshold=-1) point only competes when max_recall == 0.
    eligible = is_end & (recall == max_recall[..., None])
    candidate = jnp.max(
        jnp.where(eligible, threshold, -jnp.inf), axis=-1, initial=-jnp.inf
    )
    best = jnp.where(
        max_recall == 0, jnp.maximum(candidate, -1.0), candidate
    )
    return max_recall, jnp.abs(best)
