"""docs/custom-metrics.md must execute exactly as written.

The guide's promise is that its code blocks run top-to-bottom; this test
extracts every ```python fence and executes them in one shared namespace,
so an API change that breaks the guide breaks the suite.
"""

from __future__ import annotations

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fences():
    with open(os.path.join(REPO, "docs", "custom-metrics.md")) as f:
        text = f.read()
    return re.findall(r"```python\n(.*?)```", text, flags=re.S)


def test_guide_code_blocks_execute_in_order():
    import sys
    import types

    fences = _fences()
    assert len(fences) >= 5, "guide lost its code blocks?"
    # execute inside a registered module so the guide's classes are
    # picklable (MetricClassTester pickles the metric) — the moral
    # equivalent of the user defining them at module level
    mod = types.ModuleType("_custom_metrics_guide")
    sys.modules["_custom_metrics_guide"] = mod
    namespace = mod.__dict__
    for i, block in enumerate(fences):
        try:
            exec(compile(block, f"<custom-metrics.md block {i}>", "exec"),
                 namespace)
        except Exception as e:  # pragma: no cover - failure reporting
            raise AssertionError(
                f"custom-metrics.md block {i} failed: {e}\n---\n{block}"
            ) from e
