from torcheval_tpu.utils.checkpoint import (
    load_metric_state,
    save_metric_state,
)
from torcheval_tpu.utils.compile_counter import (
    CompileCounter,
    enable_persistent_compilation_cache,
)
from torcheval_tpu.utils.random_data import (
    get_rand_data_binary,
    get_rand_data_binned_binary,
    get_rand_data_multiclass,
    get_rand_data_multilabel,
)

# Note: the reference defines get_rand_data_multilabel but forgets to export
# it (reference utils/__init__.py:8-17); we export all four.
__all__ = [
    "CompileCounter",
    "enable_persistent_compilation_cache",
    "get_rand_data_binary",
    "get_rand_data_binned_binary",
    "get_rand_data_multiclass",
    "get_rand_data_multilabel",
    "load_metric_state",
    "save_metric_state",
]
