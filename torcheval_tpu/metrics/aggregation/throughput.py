"""Throughput class metric.

Parity: reference torcheval/metrics/aggregation/throughput.py:21-103.
Float (host-side) states by design; merge uses slowest-rank semantics:
summed item counts over the MAX of elapsed times across replicas.
"""

from __future__ import annotations

import logging
from typing import TypeVar

from torcheval_tpu.metrics.metric import MergeKind, Metric

_logger: logging.Logger = logging.getLogger(__name__)

TThroughput = TypeVar("TThroughput", bound="Throughput")


class Throughput(Metric[float]):
    """Items processed per second across the job.

    Examples::

        >>> from torcheval_tpu.metrics import Throughput
        >>> Throughput().update(64, 2.0).compute()
        32.0
    """

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("num_total", 0.0, merge=MergeKind.SUM)
        # Replicas run concurrently: wall time is the slowest replica's, not
        # the sum (reference throughput.py:94-103).
        self._add_state("elapsed_time_sec", 0.0, merge=MergeKind.MAX)

    def update(
        self: TThroughput, num_processed: int, elapsed_time_sec: float
    ) -> TThroughput:
        if num_processed < 0:
            raise ValueError(
                "Expected num_processed to be a non-negative number, but "
                f"received {num_processed}."
            )
        if elapsed_time_sec <= 0:
            raise ValueError(
                "Expected elapsed_time_sec to be a positive number, but "
                f"received {elapsed_time_sec}."
            )
        self.num_total += num_processed
        self.elapsed_time_sec += elapsed_time_sec
        return self

    def compute(self) -> float:
        if not self.elapsed_time_sec:
            _logger.warning(
                "No calls to update() have been made - returning 0.0"
            )
            return 0.0
        return self.num_total / self.elapsed_time_sec
