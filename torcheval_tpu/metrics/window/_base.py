"""Shared machinery for windowed metrics.

The reference implements five windowed metrics, four of which
(CTR / NE / MSE / WeightedCalibration, reference torcheval/metrics/window/)
share one structure: per-``update()`` sufficient statistics are written into a
fixed-shape (num_tasks, max_num_updates) ring buffer — the windowed value is
computed from the buffer's column sums, and an optional lifetime accumulator
runs alongside (e.g. reference window/normalized_entropy.py:118-144 update,
:232-296 merge). The reference duplicates the cursor/merge logic per class;
here it lives once.

TPU notes: the ring buffer is exactly the fixed-shape state XLA wants — a
column write is one ``dynamic_update_slice`` and the windowed sums reduce the
whole buffer (unfilled columns are zero, so full-buffer sums equal the
reference's valid-prefix sums, reference window/mean_squared_error.py:168-169
relies on the same invariant). Merge packs valid columns of all replicas into
an enlarged buffer, matching the reference's concatenating merge; column
*order* never matters because every consumer is a sum.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import cached_index

from torcheval_tpu.metrics.metric import MergeKind, Metric, UpdatePlan
from torcheval_tpu.metrics.shardspec import ShardSpec

TWindowed = TypeVar("TWindowed", bound="WindowedTaskCounterMetric")

# (kernel, n_counters, lifetime flag, config) -> traceable transform body
_WINDOW_TRANSFORM_CACHE: dict = {}


def _window_transform(
    kernel, n_counters: int, lifetime: bool, config, row_slice=None
):
    """A stable (cacheable) transform closure: counter kernel + lifetime
    accumulates + ring-column writes over a names-ordered flat state tuple
    ``(lifetime..., rings...)``. Used both by single-metric updates (via
    ``fused_transform``) and by ``toolkit.update_collection`` group
    programs — the SAME function object per key, so the jit caches hit.

    ``row_slice`` (the sharded-window variant): the per-update counter
    vectors span ALL tasks, but this rank's ring and lifetime states hold
    only the ``[start, stop)`` task rows — the deltas are sliced before
    the accumulate/column write, so the state stays ``tasks/world`` and
    every rank persists exactly its owned rows of the same global update
    stream."""
    key = (kernel, n_counters, lifetime, config, row_slice)
    fn = _WINDOW_TRANSFORM_CACHE.get(key)
    if fn is None:

        def transform(states, col, *dyn):
            deltas = kernel(*dyn, *config)
            if not isinstance(deltas, tuple):
                deltas = (deltas,)
            if len(deltas) != n_counters:
                raise ValueError(
                    f"kernel {kernel.__name__} returned {len(deltas)} "
                    f"counter values for {n_counters} counters"
                )
            if row_slice is not None:
                # scalar deltas broadcast to every owned row, exactly as
                # they broadcast to every task row unsharded
                deltas = tuple(
                    d if jnp.ndim(d) == 0 else d[row_slice[0]:row_slice[1]]
                    for d in deltas
                )
            if lifetime:
                lt, rings = states[:n_counters], states[n_counters:]
                new_lt = tuple(v + d for v, d in zip(lt, deltas))
            else:
                rings, new_lt = states, ()
            new_rings = tuple(
                r.at[:, col].set(d) for r, d in zip(rings, deltas)
            )
            return new_lt + new_rings

        _WINDOW_TRANSFORM_CACHE[key] = transform
        fn = transform
    return fn



def _identity_kernel(*values):
    """Pre-computed counter values pass straight through ``_record_via``."""
    return values


class RingCursorSerializationMixin:
    """Snapshot/restore of the ring-buffer write cursor.

    The cursor is a plain attribute (state-registry parity with the
    reference, window/normalized_entropy.py:100), but a resumed metric must
    not overwrite the wrong column — so ``state_dict`` carries it explicitly
    and ``load_state_dict`` restores (or re-derives) it.
    """

    _cursor_attr = "next_inserted"
    _cursor_total_state = "total_updates"
    _cursor_capacity_state = "max_num_updates"

    def state_dict(self):
        snapshot = super().state_dict()
        snapshot[self._cursor_attr] = getattr(self, self._cursor_attr)
        return snapshot

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        state_dict = dict(state_dict)
        cursor = state_dict.pop(self._cursor_attr, None)
        super().load_state_dict(state_dict, strict=strict)
        if cursor is None:
            # legacy snapshot without a cursor: re-derive (exact for any
            # never-merged history)
            cursor = getattr(self, self._cursor_total_state) % getattr(
                self, self._cursor_capacity_state
            )
        setattr(self, self._cursor_attr, int(cursor))


class WindowedTaskCounterMetric(RingCursorSerializationMixin, Metric):
    """Base for windowed metrics whose state is per-update counters.

    Subclasses call ``_init_window_states(counter_names, ...)`` in
    ``__init__``, feed each update's counter values through ``_record``, and
    build ``compute`` from ``_windowed_counter_sums`` / the lifetime states.
    """

    def _init_window_states(
        self,
        counter_names: Sequence[str],
        *,
        num_tasks: int,
        max_num_updates: int,
        enable_lifetime: bool,
        lifetime_defaults: Optional[Sequence] = None,
    ) -> None:
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        if max_num_updates < 1:
            raise ValueError(
                "`max_num_updates` value should be greater than and equal to "
                f"1, but received {max_num_updates}. "
            )
        self.num_tasks = num_tasks
        self.enable_lifetime = enable_lifetime
        self._counter_names = tuple(counter_names)
        self._add_state("max_num_updates", max_num_updates, merge=MergeKind.CUSTOM)
        self.next_inserted = 0
        self._add_state("total_updates", 0, merge=MergeKind.CUSTOM)
        # sharded windows (metrics/shardspec.py): rings and lifetime
        # vectors partition by TASK rows across the shard world — the
        # serving-scale per-key layout, where num_tasks is the big axis.
        # Owner-partitioned contract: every rank must observe the SAME
        # update stream (counter vectors are per-task, not per-example);
        # each rank persists only its owned rows, sync is a reshard of
        # disjoint rows, and the reassembled window equals the one
        # metric that saw the stream — bit-for-bit.
        ring_shard = ShardSpec(axis=0)
        if enable_lifetime:
            if lifetime_defaults is None:
                lifetime_defaults = [jnp.zeros(num_tasks) for _ in counter_names]
            for name, default in zip(counter_names, lifetime_defaults):
                self._add_state(
                    name, default, merge=MergeKind.CUSTOM, shard=ring_shard
                )
        for name in counter_names:
            self._add_state(
                f"windowed_{name}",
                jnp.zeros((num_tasks, max_num_updates)),
                merge=MergeKind.CUSTOM,
                shard=ring_shard,
            )

    # ------------------------------------------------------------- accumulate

    def _record(self, counter_values: Sequence[jax.Array]) -> None:
        """Write one update's pre-computed counters into the ring (and
        lifetime) states. Prefer :meth:`_record_via` where the producing
        kernel is jittable — it fuses the kernel into the same dispatch."""
        self._record_via(_identity_kernel, tuple(counter_values))

    def _window_plan(self, kernel, dynamic: tuple, config: tuple = ()):
        """Build the transform :class:`UpdatePlan` for one windowed update:
        ``kernel(*dynamic, *config) -> counter values``, fused with the
        lifetime accumulates and ring-column writes into ONE dispatch (the
        separate kernel + record calls each cost a device round-trip on a
        remote TPU). ``kernel`` and ``config`` entries must be hashable —
        they key the trace cache; input validation stays with the caller.

        `+` broadcasts the reference's scalar->vector state promotion
        (reference window/mean_squared_error.py:141-145). The traced column
        index is a cached device scalar: baking the Python int into an
        eager ``.at[].set`` would compile one program per ring slot and
        upload constants per call; the cursor itself stays a host int,
        advanced by the plan's ``finalize`` after the device step.
        """
        counter_names = self._counter_names
        names = (
            tuple(counter_names) if self.enable_lifetime else ()
        ) + tuple(f"windowed_{n}" for n in counter_names)
        col = self.next_inserted
        row_slice = None
        if self._sharded_states and self._own_shard_active():
            row_slice = self._shard_ctx.shard_range(self.num_tasks)

        def finalize():
            self.next_inserted = (col + 1) % self.max_num_updates
            self.total_updates += 1

        return UpdatePlan(
            _window_transform(
                kernel, len(counter_names), self.enable_lifetime, config,
                row_slice,
            ),
            names,
            (cached_index(col),) + tuple(dynamic),
            (),
            transform=True,
            finalize=finalize,
        )

    def _record_via(
        self, kernel, dynamic: tuple, config: tuple = ()
    ) -> None:
        """Run one windowed update through its fused plan (see
        :meth:`_window_plan`; the plan's ``finalize`` advances the cursor
        and update count)."""
        self._apply_update_plan(self._window_plan(kernel, dynamic, config))

    def _windowed_counter_sums(self) -> List[jax.Array]:
        """Per-task sums over the window, shape (num_tasks,) each."""
        return [
            jnp.sum(getattr(self, f"windowed_{name}"), axis=-1)
            for name in self._counter_names
        ]

    # ------------------------------------------------------------------ merge

    def merge_state(self: TWindowed, metrics: Iterable[TWindowed]) -> TWindowed:
        """Pack all replicas' valid window columns into an enlarged buffer
        (reference window/normalized_entropy.py:232-296). ``max_num_updates``
        itself is unchanged, matching the reference: the merged metric's
        *window* keeps its own size while the merged buffer holds every
        replica's live columns.

        Post-merge ``_record`` semantics (deliberate, reference parity):
        the cursor is reduced ``idx % max_num_updates`` exactly as the
        reference does (normalized_entropy.py:294-295), so a post-merge
        update overwrites a column of the *enlarged* buffer at that reduced
        index — NOT necessarily the oldest entry. The window contents after
        merge-then-update therefore drift from a strict
        oldest-first-eviction reading, but match the reference bit-for-bit;
        ``tests/metrics/window/test_window_merge_semantics.py`` pins this
        against the reference implementation. Every consumer is a
        column-sum, so no correctness invariant depends on eviction order.

        Sharded instances route to the reassembling merge
        (``Metric._merge_sharded``): carriers hold disjoint TASK rows of
        the same global window (the owner-partitioned update contract),
        so the merge places rows instead of concatenating columns.
        """
        metrics = list(metrics)
        if self._sharded_states and self._is_shard_carrier():
            return Metric.merge_state(self, metrics)
        merged_cols = self.max_num_updates + sum(m.max_num_updates for m in metrics)
        cur_size = min(self.total_updates, self.max_num_updates)
        new_bufs = {}
        for name in self._counter_names:
            buf = jnp.zeros((self.num_tasks, merged_cols))
            mine = getattr(self, f"windowed_{name}")
            new_bufs[name] = buf.at[:, :cur_size].set(mine[:, :cur_size])
        idx = cur_size
        for m in metrics:
            if self.enable_lifetime:
                for name in self._counter_names:
                    setattr(
                        self,
                        name,
                        getattr(self, name)
                        + jax.device_put(getattr(m, name), self._device),
                    )
            size = min(m.total_updates, m.max_num_updates)
            for name in self._counter_names:
                theirs = jax.device_put(
                    getattr(m, f"windowed_{name}")[:, :size], self._device
                )
                new_bufs[name] = new_bufs[name].at[:, idx : idx + size].set(theirs)
            idx += size
            self.total_updates += m.total_updates
        for name in self._counter_names:
            setattr(self, f"windowed_{name}", new_bufs[name])
        self.next_inserted = idx % self.max_num_updates
        return self

    # ---------------------------------------------------------------- compute

    def _empty_result(self):
        if self.enable_lifetime:
            return jnp.zeros(0), jnp.zeros(0)
        return jnp.zeros(0)
