"""Varying-manual-axes helpers (shard_map vma bookkeeping).

Two consumers:

- scan-carrying parallel primitives (ring attention, GPipe): a
  ``lax.scan`` carry inside ``shard_map`` must be typed varying over every
  manual axis the step outputs vary over — the union of the inputs'
  varying axes plus the primitive's own collective axis, not just the
  latter. Under a composed mesh (e.g. dp x sp) the inputs are also
  dp-varying, so a carry pcast only over the ring/pipeline axis trips a
  trace-time carry-type mismatch
  (pinned by tests/parallel/test_composed_mesh.py);
- native-kernel outputs (``metrics/functional/tensor_utils._match_vma``):
  ffi_call results come back unmarked and must re-acquire their
  reference operand's vma.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
from jax import lax

# vma typing landed after jax 0.4.x; older shard_map has no varying-axes
# bookkeeping, so on those versions both helpers reduce to no-ops (there is
# no carry-type mismatch to repair when nothing is tracked).
_HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pcast")


def _leaf_vma(leaf: Any) -> Tuple[str, ...]:
    try:
        return tuple(jax.typeof(leaf).vma)
    except Exception:
        return ()


def union_vary_axes(*values: Any, axis_name: str) -> Tuple[str, ...]:
    """The union of every leaf's varying manual axes plus ``axis_name``,
    in first-seen order."""
    axes = []
    if _HAS_VMA:
        for value in values:
            for leaf in jax.tree_util.tree_leaves(value):
                axes.extend(_leaf_vma(leaf))
    axes.append(axis_name)
    return tuple(dict.fromkeys(axes))


def pcast_varying(x: jax.Array, vary_axes: Tuple[str, ...]) -> jax.Array:
    """Mark ``x`` varying over the axes in ``vary_axes`` it does not
    already vary over (``lax.pcast`` rejects re-marking a varying axis)."""
    if not _HAS_VMA:
        return x
    missing = tuple(a for a in vary_axes if a not in _leaf_vma(x))
    return lax.pcast(x, missing, to="varying") if missing else x
