"""Overload admission control (ISSUE 17): deterministic sampling,
Horvitz-Thompson unbiasedness CI pins against the full-ingest oracle,
ladder hysteresis under the seeded spike harness, bit-identical shed
decisions across ThreadWorld ranks and elastic resume, provenance
stamping/drop regressions, and the observability surface (prometheus
gauge grammar, AdmissionEvent round trip, /healthz shedding rung,
admission counter source, federation drain-cadence tightening)."""

from __future__ import annotations

import copy
import re
import tempfile

import numpy as np
import pytest

from torcheval_tpu.elastic import ElasticSession
from torcheval_tpu.metrics import ShardContext
from torcheval_tpu.metrics.toolkit import adopt_synced
from torcheval_tpu.table import (
    AdmissionController,
    AdmissionProvenance,
    MetricTable,
    ServingBudget,
    admission_keep,
    shedding_status,
)
from torcheval_tpu.table._hash import hash_keys
from torcheval_tpu.utils.test_utils import OverloadSchedule, ThreadWorld


def _armed(rung=0, sample_p=0.1, floor_p=0.01, **table_kwargs):
    t = MetricTable(
        "ctr",
        admission=AdmissionController(
            ServingBudget(), sample_p=sample_p, floor_p=floor_p
        ),
        **table_kwargs,
    )
    t.admission_rung = rung
    return t


# ------------------------------------------------------- pure decisions


def test_admission_keep_is_pure_and_rate_calibrated():
    rng = np.random.default_rng(3)
    hashed = hash_keys(rng.integers(0, 1 << 40, 20000))
    for p in (0.5, 0.1, 0.01):
        keep = admission_keep(hashed, 7, p)
        again = admission_keep(hashed, 7, p)
        assert np.array_equal(keep, again)  # replay: pure in (key, epoch, p)
        rate = keep.mean()
        assert abs(rate - p) < 4.0 * np.sqrt(p * (1 - p) / hashed.size)
    # a new epoch re-rolls the population (different keys survive)
    k7 = admission_keep(hashed, 7, 0.5)
    k8 = admission_keep(hashed, 8, 0.5)
    assert not np.array_equal(k7, k8)
    # p=1.0 admits everything
    assert admission_keep(hashed, 7, 1.0).all()


def test_controller_validation():
    with pytest.raises(ValueError, match="sample_p"):
        AdmissionController(ServingBudget(), sample_p=0.0)
    with pytest.raises(ValueError, match="floor_p"):
        AdmissionController(ServingBudget(), sample_p=0.1, floor_p=0.5)
    with pytest.raises(ValueError, match="exit_pressure"):
        AdmissionController(
            ServingBudget(), enter_pressure=0.5, exit_pressure=0.9
        )
    with pytest.raises(ValueError, match="cooldown_drains"):
        AdmissionController(ServingBudget(), cooldown_drains=0)
    with pytest.raises(ValueError, match="max_keys"):
        AdmissionController(ServingBudget(max_keys=0))
    with pytest.raises(TypeError, match="AdmissionController"):
        MetricTable("ctr").arm_admission(object())


def test_budget_max_keys_is_shared_with_the_evictor():
    t = MetricTable(
        "ctr", admission=AdmissionController(ServingBudget(max_keys=16))
    )
    assert t.max_keys == 16
    # the tighter of table/budget bounds wins
    t2 = MetricTable(
        "ctr",
        max_keys=8,
        admission=AdmissionController(ServingBudget(max_keys=16)),
    )
    assert t2.max_keys == 8


# ------------------------------------------------ unbiasedness CI pins


@pytest.mark.parametrize("p", [0.5, 0.1, 0.01])
def test_sampled_ctr_totals_unbiased_within_ci(p):
    """HT-reweighted column totals at rung=sampled match the full-ingest
    oracle within 4-sigma Bernoulli bounds (per-key sampling: the
    estimator is sum over admitted keys of s_k / p, variance
    (1-p)/p * sum s_k^2)."""
    n = 4000 if p == 0.01 else 1000
    rng = np.random.default_rng(int(p * 1000))
    keys = np.arange(n)
    clicks = rng.integers(0, 2, n).astype(np.float32)
    weights = np.ones(n, np.float32)

    full = MetricTable("ctr")
    full.ingest(keys, clicks, weights)
    nf = int(full.n_keys)
    true_click = float(np.asarray(full.col_click)[:nf].sum())
    true_weight = float(np.asarray(full.col_weight)[:nf].sum())

    t = _armed(rung=1, sample_p=p)
    t.ingest(keys, clicks, weights)
    ns = int(t.n_keys)
    est_click = float(np.asarray(t.col_click)[:ns].sum())
    est_weight = float(np.asarray(t.col_weight)[:ns].sum())

    var_scale = (1.0 - p) / p
    bound_w = 4.0 * np.sqrt(var_scale * np.sum(weights**2)) + 1e-6
    bound_c = 4.0 * np.sqrt(var_scale * np.sum(clicks**2)) + 1e-6
    assert abs(est_weight - true_weight) <= bound_w
    assert abs(est_click - true_click) <= bound_c
    # the aggregate CTR ratio estimator lands near the oracle too
    assert abs(est_click / est_weight - true_click / true_weight) < 0.2
    # provenance reflects the sampled regime
    t.compute()
    prov = t.admission_provenance
    assert isinstance(prov, AdmissionProvenance)
    assert prov.rung == 1 and prov.sampled_fraction == p
    assert prov.shed_rows == int(t.shed_rows_total) > 0


@pytest.mark.parametrize("p", [0.5, 0.1])
def test_sampled_ne_totals_unbiased_within_ci(p):
    """Same pin through the NE family's float lane (entropy/example/
    positive columns are all HT-scaled by the shared intake)."""
    n = 1500
    rng = np.random.default_rng(5)
    keys = np.arange(n)
    preds = rng.uniform(0.05, 0.95, n).astype(np.float32)
    targets = rng.integers(0, 2, n).astype(np.float32)

    full = MetricTable("ne")
    full.ingest(keys, preds, targets)
    nf = int(full.n_keys)
    true_ex = float(np.asarray(full.col_num_examples)[:nf].sum())

    t = MetricTable(
        "ne", admission=AdmissionController(ServingBudget(), sample_p=p)
    )
    t.admission_rung = 1
    t.ingest(keys, preds, targets)
    ns = int(t.n_keys)
    est_ex = float(np.asarray(t.col_num_examples)[:ns].sum())
    bound = 4.0 * np.sqrt((1.0 - p) / p * n)
    assert abs(est_ex - true_ex) <= bound


def test_admitted_keys_read_exact_per_key_values():
    """Sampling is per (key, epoch): every row of an admitted key is
    kept, so ADMITTED keys' ratio metrics equal the full-ingest oracle —
    sampling only thins which keys report. (The HT 1/p scale rides both
    numerator and denominator, so equality is exact up to f32 rounding
    of the common factor, not bit-exact.)"""
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 200, 2000)
    clicks = rng.integers(0, 2, 2000).astype(np.float32)

    full = MetricTable("ctr")
    full.ingest(keys, clicks)
    oracle = full.compute().as_dict()

    t = _armed(rung=1, sample_p=0.3)
    t.ingest(keys, clicks)
    sampled = t.compute().as_dict()
    assert 0 < len(sampled) < len(oracle)
    for k, v in sampled.items():
        assert v == pytest.approx(oracle[k], rel=1e-5)


def test_priority_keys_are_never_shed():
    vips = [7, 13]
    t = MetricTable(
        "ctr",
        admission=AdmissionController(
            ServingBudget(), sample_p=0.05, priority_keys=vips
        ),
    )
    t.admission_rung = 2  # priority-shed: only VIPs + floor_p survive
    rng = np.random.default_rng(2)
    keys = np.concatenate([rng.integers(20, 4000, 1000), vips])
    t.ingest(keys, np.ones(keys.size, np.float32))
    surviving = set(t.compute().as_dict())
    assert set(vips) <= surviving
    assert int(t.shed_rows_total) > 0


# ------------------------------------------- cross-world determinism


def test_shed_decisions_bit_identical_across_threadworld_ranks():
    """Every rank of a ThreadWorld-4 sees the same batch and makes the
    SAME per-row admission decisions (stateless splitmix64 — no RNG
    state), so the adopted world-4 values are bit-identical to a
    world-1 armed replay."""
    rng = np.random.default_rng(23)
    batches = [
        (rng.integers(0, 120, 64), rng.integers(0, 2, 64).astype(np.float32))
        for _ in range(4)
    ]

    def run_world(world):
        def body(g):
            t = MetricTable(
                "ctr",
                shard=ShardContext(g.rank, world),
                admission=AdmissionController(ServingBudget(), sample_p=0.4),
            )
            t.admission_rung = 1
            for keys, clicks in batches:  # every rank, the full stream
                t.ingest(keys, clicks)
            counts = (int(t.admitted_rows_total), int(t.shed_rows_total))
            synced = adopt_synced(t, g)
            return counts, synced.compute().as_dict()

        return ThreadWorld(world).run(body)

    results4 = run_world(4)
    counts = {c for c, _ in results4}
    assert len(counts) == 1  # bit-identical decisions on every rank

    t1 = MetricTable(
        "ctr", admission=AdmissionController(ServingBudget(), sample_p=0.4)
    )
    t1.admission_rung = 1
    for keys, clicks in batches:
        t1.ingest(keys, clicks)
    assert (int(t1.admitted_rows_total), int(t1.shed_rows_total)) in counts


def test_elastic_resume_sheds_identically_across_world_change():
    """Ladder rung + epoch ride the snapshot: a world restored at 2 or 4
    resumes on the same rung and admits the SAME key set for the next
    batch (decisions are pure in (key, epoch, rung))."""
    rng = np.random.default_rng(6)
    warm = (rng.integers(0, 60, 48), np.ones(48, np.float32))
    probe = (rng.integers(0, 5000, 256), np.ones(256, np.float32))

    def make(rank, world):
        t = MetricTable(
            "ctr",
            shard=ShardContext(rank, world),
            admission=AdmissionController(ServingBudget(), sample_p=0.25),
        )
        return t

    with tempfile.TemporaryDirectory() as d:

        def writer(g):
            t = make(g.rank, 2)
            t.ingest(*warm)
            t.admission_rung = 1
            t.admission_epoch = 3
            ElasticSession(t, d, process_group=g, interval=10**9).snapshot()

        ThreadWorld(2).run(writer)

        def resumed_counts(world):
            def body(g):
                t = make(g.rank, world)
                sess = ElasticSession(t, d, process_group=g, interval=10**9)
                assert sess.restore() is not None
                assert int(t.admission_rung) == 1
                assert int(t.admission_epoch) == 3
                before = int(t.admitted_rows_total)
                t.ingest(*probe)
                return int(t.admitted_rows_total) - before

            return set(ThreadWorld(world).run(body))

        at2 = resumed_counts(2)
        at4 = resumed_counts(4)
        assert len(at2) == 1 and at2 == at4  # identical shed everywhere


# ------------------------------------------------------ ladder dynamics


def test_ladder_escalates_and_recovers_without_flapping():
    """Under the seeded spike harness the ladder escalates while
    overload persists, de-escalates only after the cooldown, and the
    rung trajectory is unimodal — up-sweep, plateau, down-sweep, no
    oscillation."""
    spike = OverloadSchedule.ramp(
        6, 12.0, cardinality=12.0, base_rows=48, base_keys=24, seed=9
    )
    t = MetricTable(
        "ctr",
        admission=AdmissionController(
            ServingBudget(max_keys=32),
            sample_p=0.3,
            cooldown_drains=2,
            check_every=1,
        ),
    )
    trajectory = []
    for batch in spike.batches():
        t.ingest(batch.keys, **batch.kwargs)
        adopt_synced(t)
        trajectory.append(int(t.admission_rung))
    calm = OverloadSchedule.sustained(
        8, 1.0, base_rows=8, base_keys=8, seed=10
    )
    for batch in calm.batches():
        t.ingest(batch.keys, **batch.kwargs)
        adopt_synced(t)
        trajectory.append(int(t.admission_rung))

    assert max(trajectory) >= 1  # overload was noticed
    assert trajectory[-1] == 0  # and fully recovered
    # unimodal: once the rung starts descending it never climbs again
    peak = trajectory.index(max(trajectory))
    descent = trajectory[peak:]
    assert all(a >= b for a, b in zip(descent, descent[1:]))
    # hysteresis: one up-sweep + one down-sweep worth of transitions
    assert int(t.admission_transitions) <= 2 * max(trajectory) + 1


# ---------------------------------------------------------- provenance


def test_provenance_dropped_on_reset_and_load():
    t = _armed(rung=1)
    t.ingest(np.arange(8), np.ones(8, np.float32))
    t.compute()
    assert isinstance(t.admission_provenance, AdmissionProvenance)
    sd = copy.deepcopy(t.state_dict())
    t.reset()
    assert not hasattr(t, "admission_provenance")
    t.compute()
    assert t.admission_provenance.admitted_rows == 0  # fresh, not stale
    t.load_state_dict(sd)
    t2 = _armed(rung=1)
    t2.ingest(np.arange(8), np.ones(8, np.float32))
    t2.compute()
    assert hasattr(t2, "admission_provenance")
    t2.load_state_dict(sd)
    assert not hasattr(t2, "admission_provenance")


def test_state_dict_round_trips_ladder_state():
    t = _armed(rung=2)
    t.ingest(np.arange(300), np.ones(300, np.float32))
    sd = t.state_dict()
    for k in (
        "admission_rung",
        "admission_calm",
        "admission_epoch",
        "admitted_rows_total",
        "shed_rows_total",
        "admission_transitions",
        "pressure_peak",
    ):
        assert k in sd
    fresh = _armed(rung=0)
    fresh.load_state_dict(sd)
    assert int(fresh.admission_rung) == 2
    assert int(fresh.shed_rows_total) == int(t.shed_rows_total)


def test_sync_provenance_carries_admission_fields():
    t = _armed(rung=1, sample_p=0.2)
    t.ingest(np.arange(50), np.ones(50, np.float32))
    adopt_synced(t)
    prov = t.sync_provenance
    assert prov.admission_rung == int(t.admission_rung)
    assert prov.sampled_fraction in (1.0, 0.2, 0.01)
    # plain metrics keep the appended defaults
    from torcheval_tpu.metrics import Mean
    from torcheval_tpu.metrics.toolkit import get_synced_metric

    m = Mean()
    m.update(np.asarray([1.0]))
    s = get_synced_metric(m)
    assert s.sync_provenance.sampled_fraction == 1.0
    assert s.sync_provenance.admission_rung == 0


# ------------------------------------------------------- observability


def test_prometheus_gauges_grammar_pinned():
    from torcheval_tpu.obs.counters import CounterRegistry
    from torcheval_tpu.obs.export import render_prometheus

    t = _armed(rung=1, sample_p=0.2)
    t.ingest(np.arange(400), np.ones(400, np.float32))
    reg = CounterRegistry()
    t.track_values(registry=reg)
    text = render_prometheus(reg, histograms={})
    for gauge in ("shed_fraction", "admitted_keys"):
        lines = [
            ln
            for ln in text.splitlines()
            if ln.startswith(f"torcheval_tpu_metric_table_values_{gauge} ")
        ]
        assert len(lines) == 1, gauge
        # exposition grammar: bare metric name, single space, float
        assert re.fullmatch(
            r"torcheval_tpu_metric_table_values_"
            + gauge
            + r" [0-9]+(\.[0-9]+)?([eE][+-][0-9]+)?",
            lines[0],
        ), lines[0]
    vals = reg.read()["metric_table_values"]
    assert 0.0 < vals["shed_fraction"] < 1.0
    assert vals["admitted_keys"] == float(t.n_keys)


def test_admission_counter_source_and_shedding_status():
    from torcheval_tpu.obs.counters import default_registry

    t = _armed(rung=2, sample_p=0.5, floor_p=0.05)
    status = shedding_status()
    assert status["armed"] and status["shedding"]
    assert status["rung"] == 2 and status["rung_name"] == "shed"
    assert status["sampled_fraction"] == 0.05
    counters = default_registry().read()["admission"]
    assert counters["rung"] == 2
    t.disarm_admission()
    assert not shedding_status()["armed"]
    assert default_registry().read()["admission"]["armed"] == 0


def test_healthz_gains_shedding_rung():
    from torcheval_tpu.obs.server import healthz_payload

    t = _armed(rung=1)
    payload = healthz_payload()
    assert payload["status"] == "shedding"
    assert payload["healthy"]  # graceful: the probe stays 200
    assert payload["admission"]["rung_name"] == "sampled"
    t.admission_rung = 0
    assert healthz_payload()["status"] == "ok"
    t.disarm_admission()
    assert healthz_payload()["admission"]["armed"] == 0


def test_admission_event_emitted_and_round_trips():
    from torcheval_tpu import config
    from torcheval_tpu.obs.events import AdmissionEvent, event_from_dict
    from torcheval_tpu.obs.recorder import RECORDER

    evt = AdmissionEvent(
        table="MetricTable",
        prev_rung=0,
        rung=1,
        rung_name="sampled",
        pressure=1.25,
        sampled_fraction=0.1,
        epoch=4,
    )
    back = event_from_dict(evt.as_dict())
    assert isinstance(back, AdmissionEvent)
    assert back == evt

    spike = OverloadSchedule.sustained(
        3, 14.0, cardinality=14.0, base_rows=64, base_keys=48, seed=4
    )
    with config.observability():
        t = MetricTable(
            "ctr",
            admission=AdmissionController(
                ServingBudget(max_keys=24), check_every=1
            ),
        )
        for batch in spike.batches():
            t.ingest(batch.keys, **batch.kwargs)
            adopt_synced(t)
        kinds = [e.kind for e in RECORDER.log]
        assert "admission" in kinds
        transition = next(e for e in RECORDER.log if e.kind == "admission")
        assert transition.rung > transition.prev_rung
        assert transition.pressure > 0.0


def test_federation_drain_cadence_tightens_under_shed():
    from torcheval_tpu.federation import Federation

    class _F:
        exchange_interval = Federation.exchange_interval

    t = _armed(rung=0)
    assert _F().exchange_interval(8) == 8
    t.admission_rung = 1
    assert _F().exchange_interval(8) == 4
    t.admission_rung = 2
    assert _F().exchange_interval(8) == 2
    assert _F().exchange_interval(1) == 1  # floor
    t.disarm_admission()
    assert _F().exchange_interval(8) == 8
    with pytest.raises(ValueError, match="base interval"):
        _F().exchange_interval(0)
