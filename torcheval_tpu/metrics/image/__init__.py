from torcheval_tpu.metrics.image.fid import FrechetInceptionDistance
from torcheval_tpu.metrics.image.psnr import PeakSignalNoiseRatio

__all__ = ["FrechetInceptionDistance", "PeakSignalNoiseRatio"]
