"""Multi-process (pod-style) evaluation with host-side metric sync.

Parity workload: the spawned-worker mode of the reference's
``examples/distributed_example.py`` (torchelastic launches 4 workers, each
updates replica metrics, ``sync_and_compute`` runs a gloo/NCCL collective —
reference distributed_example.py:74-151,163-174). The TPU-native analogue:
each process is one "host" of a ``jax.distributed`` job, metric states sync
through XLA collectives via ``MultiHostGroup``.

Run it single-machine (each worker is a CPU "host")::

    python -m torcheval_tpu.launcher --nproc 4 examples/multihost_example.py

or directly on a real multi-host pod (one process per host, launched by the
TPU runtime) — ``init_from_env`` is a no-op there and
``jax.distributed.initialize()`` has already happened.

For the single-controller regime (one process, all chips in one Mesh,
metrics synced inside jit) see ``examples/distributed_example.py`` — on a
TPU pod slice that path is faster; this one mirrors the reference's
process-per-rank topology.
"""



import os as _os
import sys as _sys

# file-relative fallback: `python -m examples.<name>` resolves imports from
# the CWD, not this directory, so `_backend` needs the examples dir on
# sys.path (direct `python examples/<name>.py` runs already have it)
_here = _os.path.dirname(_os.path.abspath(__file__))
_sys.path.append(_here)
_sys.path.append(_os.path.dirname(_here))  # repo root: uninstalled checkouts

from _backend import rehearsal_cpu

# local rehearsals run workers on the CPU platform (N processes cannot
# share one exclusive-claim chip, and per-rank accelerator probes would
# race it); on a real pod this is a no-op and the TPU runtime owns
# process/device assignment
rehearsal_cpu()

from torcheval_tpu.launcher import init_from_env

init_from_env()  # joins the job when run under the launcher; no-op otherwise

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.distributed import default_process_group
from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy, Throughput
from torcheval_tpu.metrics.toolkit import sync_and_compute_collection

import time

STEPS, BATCH, CLASSES = 12, 64, 10
SYNC_EVERY = 4  # reference syncs every 4 batches (distributed_example.py:123)


def main() -> None:
    rank = jax.process_index()
    group = default_process_group()
    rng = np.random.default_rng(rank)

    metrics = {
        "acc": MulticlassAccuracy(),
        "auroc": BinaryAUROC(),
        "throughput": Throughput(),
    }

    for step in range(1, STEPS + 1):
        t0 = time.perf_counter()
        # stand-in for a model forward on this host's data shard
        logits = jnp.asarray(
            rng.normal(size=(BATCH, CLASSES)).astype(np.float32)
        )
        targets = jnp.asarray(rng.integers(0, CLASSES, size=(BATCH,)))
        scores = jax.nn.softmax(logits)[:, 0]
        is_zero = (targets == 0).astype(jnp.float32)

        metrics["acc"].update(logits, targets)
        metrics["auroc"].update(scores, is_zero)
        metrics["throughput"].update(
            num_processed=BATCH, elapsed_time_sec=time.perf_counter() - t0
        )

        if step % SYNC_EVERY == 0:
            # ONE batched exchange for the whole collection
            synced = sync_and_compute_collection(metrics, group)
            if rank == 0:
                print(
                    f"step {step}: acc={float(synced['acc']):.4f} "
                    f"auroc={float(synced['auroc']):.4f} "
                    f"throughput={float(synced['throughput']):.0f}/s "
                    f"(pooled over {group.world_size} hosts)",
                    flush=True,
                )

    for m in metrics.values():
        m.reset()
    if rank == 0:
        print("done", flush=True)


if __name__ == "__main__":
    main()
