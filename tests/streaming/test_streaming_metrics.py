"""Streaming generative metrics (ISSUE 20): offline-oracle bit-identity.

The O(1)-state contract is only worth having if it costs NOTHING in
precision: feeding a stream ONE token at a time must produce the exact
result of handing the whole sequence over at once — bitwise, not
approximately — because both paths run the same sequential fold kernel.
Pinned here per family: plain and under shape bucketing, replicated
merge, ThreadWorld-4 sync, and an elastic resume mid-stream."""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from torcheval_tpu import config
from torcheval_tpu.elastic import ElasticSession
from torcheval_tpu.metrics.toolkit import clone_metric, sync_and_compute
from torcheval_tpu.streaming import (
    StreamingNgramOverlap,
    StreamingPerplexity,
    StreamingTokenAccuracy,
    StreamingTokenEditStats,
)
from torcheval_tpu.utils.compile_counter import CompileCounter
from torcheval_tpu.utils.test_utils import ThreadWorld

RNG = np.random.default_rng(7)
STEPS = 57
LOGPROBS = (-RNG.uniform(0.01, 4.0, STEPS)).astype(np.float32)
HYP = RNG.integers(0, 30, STEPS).astype(np.int32)
REF = np.where(
    RNG.uniform(size=STEPS) < 0.6, HYP, RNG.integers(0, 30, STEPS)
).astype(np.int32)
REF[-5:] = -1  # reference exhausted before the hypothesis


def _families():
    """(name, fresh(), feed_step, feed_offline) per streaming family."""

    def ppl():
        return (
            StreamingPerplexity(),
            lambda m, i: m.update(LOGPROBS[i : i + 1]),
            lambda m: m.update(LOGPROBS),
        )

    def acc():
        return (
            StreamingTokenAccuracy(),
            lambda m, i: m.update(HYP[i : i + 1], REF[i : i + 1]),
            lambda m: m.update(HYP, REF),
        )

    def edit():
        return (
            StreamingTokenEditStats(),
            lambda m, i: m.update(HYP[i : i + 1], REF[i : i + 1]),
            lambda m: m.update(HYP, REF),
        )

    def ngram():
        return (
            StreamingNgramOverlap(n_gram=4),
            lambda m, i: m.update(HYP[i : i + 1], REF[i : i + 1]),
            lambda m: m.update(HYP, REF),
        )

    return [("perplexity", ppl), ("accuracy", acc), ("edit", edit),
            ("ngram", ngram)]


FAMILIES = _families()


def _result(m):
    out = m.compute()
    if isinstance(out, tuple):  # NamedTuple families
        return tuple(np.asarray(v).tolist() for v in out)
    return np.asarray(out).tolist()


def _run(build, *, stepwise, bucketed=False, finish=True):
    m, feed_step, feed_offline = build()
    ctx = config.shape_bucketing(True) if bucketed else _null()
    with ctx:
        if stepwise:
            for i in range(STEPS):
                feed_step(m, i)
        else:
            feed_offline(m)
    if finish and hasattr(m, "finish"):
        m.finish()
    return m


class _null:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


@pytest.mark.parametrize("name,build", FAMILIES)
@pytest.mark.parametrize("bucketed", [False, True])
def test_step_by_step_equals_whole_sequence_bitwise(name, build, bucketed):
    step = _run(build, stepwise=True, bucketed=bucketed)
    offline = _run(build, stepwise=False, bucketed=bucketed)
    assert _result(step) == _result(offline), name


@pytest.mark.parametrize("name,build", FAMILIES)
def test_bucketed_equals_unbucketed_bitwise(name, build):
    assert _result(_run(build, stepwise=True, bucketed=True)) == _result(
        _run(build, stepwise=True, bucketed=False)
    ), name


@pytest.mark.parametrize("name,build", FAMILIES)
def test_replicated_merge_preserves_step_offline_identity(name, build):
    """Merging replicas that streamed step-by-step == merging replicas
    that saw whole sequences: per-carrier states are bitwise equal, so
    the rank-ordered merge fold is too."""
    m_step = _run(build, stepwise=True)
    m_step.merge_state([clone_metric(_run(build, stepwise=True))])
    m_off = _run(build, stepwise=False)
    m_off.merge_state([clone_metric(_run(build, stepwise=False))])
    assert _result(m_step) == _result(m_off), name
    # and the merge itself doubled the stream (scale sanity, not bits)
    single = _result(_run(build, stepwise=True))
    if name == "perplexity":
        assert m_step.num_total == 2 * STEPS
    elif name == "ngram":
        assert int(np.asarray(m_step.num_sequences)) == 2
    assert _result(m_step) is not None and single is not None


@pytest.mark.parametrize("name,build", FAMILIES)
def test_threadworld4_step_and_offline_sync_identically(name, build):
    """World-4, one stream per rank: the synced compute of step-fed
    replicas equals the synced compute of offline-fed replicas bitwise
    (identical per-rank states -> identical rank-ordered fold)."""

    def stream(rank):
        rng = np.random.default_rng(100 + rank)
        lp = (-rng.uniform(0.01, 4.0, 20)).astype(np.float32)
        hyp = rng.integers(0, 20, 20).astype(np.int32)
        ref = np.where(
            rng.uniform(size=20) < 0.5, hyp, rng.integers(0, 20, 20)
        ).astype(np.int32)
        return lp, hyp, ref

    def body_factory(stepwise):
        def body(g):
            m, _, _ = build()
            lp, hyp, ref = stream(g.rank)
            src = lp if name == "perplexity" else hyp
            if stepwise:
                for i in range(len(src)):
                    if name == "perplexity":
                        m.update(lp[i : i + 1])
                    else:
                        m.update(hyp[i : i + 1], ref[i : i + 1])
            else:
                if name == "perplexity":
                    m.update(lp)
                else:
                    m.update(hyp, ref)
            if hasattr(m, "finish"):
                m.finish()
            out = sync_and_compute(m, g)
            if isinstance(out, tuple):
                return tuple(np.asarray(v).tolist() for v in out)
            return np.asarray(out).tolist()

        return body

    stepped = ThreadWorld(4).run(body_factory(True))
    offline = ThreadWorld(4).run(body_factory(False))
    assert stepped == offline, name
    assert all(r == stepped[0] for r in stepped)


@pytest.mark.parametrize("name,build", FAMILIES)
def test_elastic_resume_mid_stream_bit_identical(name, build):
    """Snapshot after 23 decode steps, restore into a fresh process
    image, stream the remaining steps: compute equals the uninterrupted
    run bitwise — mid-stream state (including the ngram tail windows)
    rides the checkpoint."""
    cut = 23
    with tempfile.TemporaryDirectory() as d:
        m, feed_step, _ = build()
        sess = ElasticSession(m, d, interval=10**9)
        for i in range(cut):
            feed_step(m, i)
        sess.snapshot()
        sess.close()

        fresh, fresh_step, _ = build()
        sess2 = ElasticSession(fresh, d, interval=10**9)
        assert sess2.restore() is not None
        for i in range(cut, STEPS):
            fresh_step(fresh, i)
        if hasattr(fresh, "finish"):
            fresh.finish()
        sess2.close()

    want = _run(build, stepwise=True)
    assert _result(fresh) == _result(want), name


def test_state_is_o1_in_stream_length():
    """The whole point: state size must not grow with the stream."""
    for _, build in FAMILIES:
        short, feed, _ = build()
        long_, feed2, _ = build()
        for i in range(3):
            feed(short, i)
        for i in range(STEPS):
            feed2(long_, i % STEPS)
        for _ in range(4):  # several times around the stream
            for i in range(STEPS):
                feed2(long_, i)
        nb_short = sum(
            np.asarray(v).nbytes for v in short.state_dict().values()
        )
        nb_long = sum(
            np.asarray(v).nbytes for v in long_.state_dict().values()
        )
        assert nb_short == nb_long


def test_warmed_stepping_is_retrace_proof_under_bucketing():
    """Ragged whole-chunk updates after warming: zero fresh programs."""
    m = StreamingPerplexity()
    e = StreamingTokenEditStats()
    g = StreamingNgramOverlap(n_gram=3)
    rng = np.random.default_rng(5)
    with config.shape_bucketing(True):
        for n in (8, 3, 16, 1, 9):  # warm the pow2 buckets
            lp = (-rng.uniform(0.1, 1.0, n)).astype(np.float32)
            toks = rng.integers(0, 9, n).astype(np.int32)
            m.update(lp)
            e.update(toks, toks)
            g.update(toks, toks)
        with CompileCounter() as cc:
            for n in (5, 2, 12, 7, 1):
                lp = (-rng.uniform(0.1, 1.0, n)).astype(np.float32)
                toks = rng.integers(0, 9, n).astype(np.int32)
                m.update(lp)
                e.update(toks, toks)
                g.update(toks, toks)
        assert cc.programs == 0


def test_edit_stream_length_mismatch_raises():
    with pytest.raises(ValueError, match="sentinel"):
        StreamingTokenEditStats().update(
            np.array([1, 2], np.int32), np.array([1], np.int32)
        )


def test_ngram_validation():
    with pytest.raises(ValueError):
        StreamingNgramOverlap(n_gram=0)
    with pytest.raises(ValueError):
        StreamingNgramOverlap(buckets=100)  # not a power of two
