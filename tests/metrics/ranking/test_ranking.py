"""Ranking metric tests (CTR, HitRate, ReciprocalRank, RetrievalPrecision,
WeightedCalibration + functional-only frequency_at_k / num_collisions) vs the
reference oracle, via the shared MetricClassTester harness."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.ref_oracle import load_reference_metrics
from torcheval_tpu.metrics import (
    ClickThroughRate,
    HitRate,
    ReciprocalRank,
    RetrievalPrecision,
    WeightedCalibration,
)
from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
    assert_result_close,
)

REF_M, REF_F = load_reference_metrics()
RNG = np.random.default_rng(11)


class TestClickThroughRate(MetricClassTester):
    def test_ctr_class(self):
        inputs = [RNG.integers(0, 2, size=(16,)).astype(np.float32) for _ in range(8)]
        weights = [RNG.uniform(0.1, 1.0, size=(16,)).astype(np.float32) for _ in range(8)]
        ref = REF_M.ClickThroughRate()
        for x, w in zip(inputs, weights):
            ref.update(torch.tensor(x), torch.tensor(w))
        self.run_class_implementation_tests(
            metric=ClickThroughRate(),
            state_names={"click_total", "weight_total"},
            update_kwargs={
                "input": inputs,
                "weights": [jnp.asarray(w) for w in weights],
            },
            compute_result=np.asarray(ref.compute()),
        )

    def test_ctr_multitask(self):
        inputs = [RNG.integers(0, 2, size=(2, 8)).astype(np.float32) for _ in range(8)]
        ref = REF_M.ClickThroughRate(num_tasks=2)
        for x in inputs:
            ref.update(torch.tensor(x))
        self.run_class_implementation_tests(
            metric=ClickThroughRate(num_tasks=2),
            state_names={"click_total", "weight_total"},
            update_kwargs={"input": inputs},
            compute_result=np.asarray(ref.compute()),
        )

    def test_ctr_functional(self):
        x = RNG.integers(0, 2, size=(20,)).astype(np.float32)
        w = RNG.uniform(0.5, 2.0, size=(20,)).astype(np.float32)
        assert_result_close(
            F.click_through_rate(jnp.asarray(x), jnp.asarray(w)),
            np.asarray(REF_F.click_through_rate(torch.tensor(x), torch.tensor(w))),
        )

    def test_ctr_invalid(self):
        with pytest.raises(ValueError, match="one or two dimensional"):
            F.click_through_rate(jnp.ones((2, 2, 2)))
        with pytest.raises(ValueError, match="same shape"):
            F.click_through_rate(jnp.ones(4), jnp.ones(5))
        with pytest.raises(ValueError, match="num_tasks = 2"):
            F.click_through_rate(jnp.ones(4), num_tasks=2)


class TestHitRate(MetricClassTester):
    def test_hit_rate_class(self):
        inputs = [RNG.uniform(size=(6, 5)).astype(np.float32) for _ in range(8)]
        targets = [RNG.integers(0, 5, size=(6,)) for _ in range(8)]
        ref = REF_M.HitRate(k=3)
        for x, t in zip(inputs, targets):
            ref.update(torch.tensor(x), torch.tensor(t))
        self.run_class_implementation_tests(
            metric=HitRate(k=3),
            state_names={"scores", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=np.asarray(ref.compute()),
        )

    def test_hit_rate_functional(self):
        x = RNG.uniform(size=(10, 4)).astype(np.float32)
        t = RNG.integers(0, 4, size=(10,))
        for k in (None, 1, 2, 10):
            assert_result_close(
                F.hit_rate(jnp.asarray(x), jnp.asarray(t), k=k),
                np.asarray(REF_F.hit_rate(torch.tensor(x), torch.tensor(t), k=k)),
            )

    def test_hit_rate_invalid(self):
        with pytest.raises(ValueError, match="one-dimensional"):
            F.hit_rate(jnp.ones((2, 2)), jnp.ones((2, 2)))
        with pytest.raises(ValueError, match="positive"):
            F.hit_rate(jnp.ones((2, 2)), jnp.zeros(2, dtype=jnp.int32), k=0)


class TestReciprocalRank(MetricClassTester):
    def test_reciprocal_rank_class(self):
        inputs = [RNG.uniform(size=(6, 5)).astype(np.float32) for _ in range(8)]
        targets = [RNG.integers(0, 5, size=(6,)) for _ in range(8)]
        ref = REF_M.ReciprocalRank()
        for x, t in zip(inputs, targets):
            ref.update(torch.tensor(x), torch.tensor(t))
        self.run_class_implementation_tests(
            metric=ReciprocalRank(),
            state_names={"scores", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=np.asarray(ref.compute()),
        )

    def test_reciprocal_rank_functional_topk(self):
        x = RNG.uniform(size=(10, 4)).astype(np.float32)
        t = RNG.integers(0, 4, size=(10,))
        for k in (None, 2):
            assert_result_close(
                F.reciprocal_rank(jnp.asarray(x), jnp.asarray(t), k=k),
                np.asarray(
                    REF_F.reciprocal_rank(torch.tensor(x), torch.tensor(t), k=k)
                ),
            )


class TestWeightedCalibration(MetricClassTester):
    def test_weighted_calibration_class(self):
        inputs = [RNG.uniform(size=(12,)).astype(np.float32) for _ in range(8)]
        targets = [RNG.integers(0, 2, size=(12,)).astype(np.float32) for _ in range(8)]
        weights = [RNG.uniform(0.1, 2.0, size=(12,)).astype(np.float32) for _ in range(8)]
        ref = REF_M.WeightedCalibration()
        for x, t, w in zip(inputs, targets, weights):
            ref.update(torch.tensor(x), torch.tensor(t), torch.tensor(w))
        self.run_class_implementation_tests(
            metric=WeightedCalibration(),
            state_names={"weighted_input_sum", "weighted_target_sum"},
            update_kwargs={
                "input": inputs,
                "target": targets,
                "weight": [jnp.asarray(w) for w in weights],
            },
            compute_result=np.asarray(ref.compute()),
        )

    def test_weighted_calibration_multitask_functional(self):
        x = RNG.uniform(size=(2, 10)).astype(np.float32)
        t = RNG.integers(0, 2, size=(2, 10)).astype(np.float32)
        assert_result_close(
            F.weighted_calibration(jnp.asarray(x), jnp.asarray(t), num_tasks=2),
            np.asarray(
                REF_F.weighted_calibration(torch.tensor(x), torch.tensor(t), num_tasks=2)
            ),
        )

    def test_weighted_calibration_zero_target_returns_empty(self):
        m = WeightedCalibration()
        m.update(jnp.array([0.5, 0.5]), jnp.array([0.0, 0.0]))
        assert m.compute().shape == (0,)


class TestRetrievalPrecision(MetricClassTester):
    def test_retrieval_precision_single_query(self):
        inputs = [RNG.uniform(size=(8,)).astype(np.float32) for _ in range(8)]
        targets = [RNG.integers(0, 2, size=(8,)).astype(np.float32) for _ in range(8)]
        ref = REF_M.RetrievalPrecision(k=3)
        for x, t in zip(inputs, targets):
            ref.update(torch.tensor(x), torch.tensor(t))
        self.run_class_implementation_tests(
            metric=RetrievalPrecision(k=3),
            state_names={"topk", "target"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=np.asarray(ref.compute()),
        )

    @pytest.mark.slow
    def test_retrieval_precision_multi_query(self):
        inputs = [RNG.uniform(size=(10,)).astype(np.float32) for _ in range(4)]
        targets = [RNG.integers(0, 2, size=(10,)).astype(np.float32) for _ in range(4)]
        indexes = [RNG.integers(0, 3, size=(10,)) for _ in range(4)]
        ref = REF_M.RetrievalPrecision(k=2, num_queries=3, avg="macro")
        ours = RetrievalPrecision(k=2, num_queries=3, avg="macro")
        for x, t, i in zip(inputs, targets, indexes):
            ref.update(torch.tensor(x), torch.tensor(t), torch.tensor(i))
            ours.update(jnp.asarray(x), jnp.asarray(t), jnp.asarray(i))
        assert_result_close(ours.compute(), np.asarray(ref.compute()))

    def test_retrieval_precision_merge(self):
        xs = [RNG.uniform(size=(6,)).astype(np.float32) for _ in range(2)]
        ts = [RNG.integers(0, 2, size=(6,)).astype(np.float32) for _ in range(2)]
        ref_a = REF_M.RetrievalPrecision(k=2)
        ref_b = REF_M.RetrievalPrecision(k=2)
        ref_a.update(torch.tensor(xs[0]), torch.tensor(ts[0]))
        ref_b.update(torch.tensor(xs[1]), torch.tensor(ts[1]))
        ref_a.merge_state([ref_b])
        a = RetrievalPrecision(k=2).update(jnp.asarray(xs[0]), jnp.asarray(ts[0]))
        b = RetrievalPrecision(k=2).update(jnp.asarray(xs[1]), jnp.asarray(ts[1]))
        a.merge_state([b])
        assert_result_close(a.compute(), np.asarray(ref_a.compute()))

    def test_retrieval_precision_functional(self):
        x = np.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2], dtype=np.float32)
        t = np.array([0, 0, 1, 1, 1, 0, 1], dtype=np.float32)
        for kwargs in (
            {},
            {"k": 2},
            {"k": 4},
            {"k": 10},
            {"k": 10, "limit_k_to_size": True},
        ):
            assert_result_close(
                F.retrieval_precision(jnp.asarray(x), jnp.asarray(t), **kwargs),
                np.asarray(
                    REF_F.retrieval_precision(torch.tensor(x), torch.tensor(t), **kwargs)
                ),
            )

    def test_retrieval_precision_empty_target_actions(self):
        x = jnp.array([0.5, 0.3])
        t = jnp.array([0.0, 0.0])
        assert float(RetrievalPrecision(k=1).update(x, t).compute()[0]) == 0.0
        assert (
            float(
                RetrievalPrecision(empty_target_action="pos", k=1)
                .update(x, t)
                .compute()[0]
            )
            == 1.0
        )
        assert np.isnan(
            float(
                RetrievalPrecision(empty_target_action="skip", k=1)
                .update(x, t)
                .compute()[0]
            )
        )
        with pytest.raises(ValueError, match="no positive value"):
            RetrievalPrecision(empty_target_action="err", k=1).update(x, t).compute()

    def test_retrieval_precision_invalid_params(self):
        with pytest.raises(ValueError, match="positive integer"):
            RetrievalPrecision(k=0)
        with pytest.raises(ValueError, match="limit_k_to_size"):
            RetrievalPrecision(limit_k_to_size=True)
        with pytest.raises(ValueError, match="empty_target_action"):
            RetrievalPrecision(empty_target_action="bogus")
        with pytest.raises(ValueError, match="indexes"):
            RetrievalPrecision(num_queries=2).update(jnp.ones(2), jnp.ones(2))


class TestFunctionalOnly:
    def test_frequency_at_k(self):
        x = RNG.uniform(size=(12,)).astype(np.float32)
        assert_result_close(
            F.frequency_at_k(jnp.asarray(x), 0.5),
            np.asarray(REF_F.frequency_at_k(torch.tensor(x), 0.5)),
        )
        with pytest.raises(ValueError, match="negative"):
            F.frequency_at_k(jnp.ones(3), -1.0)

    def test_num_collisions(self):
        x = np.array([3, 4, 1, 3, 1, 1, 5])
        assert_result_close(
            F.num_collisions(jnp.asarray(x)),
            np.asarray(REF_F.num_collisions(torch.tensor(x))),
        )
        with pytest.raises(ValueError, match="integer"):
            F.num_collisions(jnp.ones(3, dtype=jnp.float32))


class TestReviewRegressions:
    def test_out_of_range_indexes_ignored(self):
        ours = RetrievalPrecision(k=2, num_queries=2)
        ours.update(
            jnp.array([0.5, 0.3, 0.9, 0.1]),
            jnp.array([1.0, 0.0, 1.0, 1.0]),
            jnp.array([0, 1, -1, 1]),
        )
        ref = REF_M.RetrievalPrecision(k=2, num_queries=2)
        ref.update(
            torch.tensor([0.5, 0.3, 0.9, 0.1]),
            torch.tensor([1.0, 0.0, 1.0, 1.0]),
            torch.tensor([0, 1, -1, 1]),
        )
        assert_result_close(ours.compute(), np.asarray(ref.compute()))

    def test_num_tasks_validation(self):
        with pytest.raises(ValueError, match="num_tasks"):
            ClickThroughRate(num_tasks=0)
        with pytest.raises(ValueError, match="num_tasks"):
            WeightedCalibration(num_tasks=0)

    def test_debug_validation_target_range(self):
        from torcheval_tpu.config import set_debug_validation

        set_debug_validation(True)
        try:
            with pytest.raises(ValueError, match="target values"):
                F.hit_rate(jnp.array([[0.3, 0.1, 0.6]]), jnp.array([5]), k=2)
            with pytest.raises(ValueError, match="target values"):
                F.reciprocal_rank(jnp.array([[0.3, 0.1, 0.6]]), jnp.array([5]))
        finally:
            set_debug_validation(False)
