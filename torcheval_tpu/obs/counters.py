"""Unified counter registry federating the stack's scattered counters.

Before this module, each subsystem grew its own observability record in
isolation: ``utils.CompileCounter`` (PR 1, XLA program demands),
``resilience.SyncHealth`` / ``default_sync_health()`` (PR 2, sync
attempts/retries/timeouts/degradations), and ``elastic.ElasticSession``
timings (PR 4, snapshots). They all keep working exactly as before — the
registry ABSORBS them behind one read API rather than replacing them:

    >>> from torcheval_tpu import obs
    >>> reg = obs.default_registry()
    >>> reg.read()["sync"]["attempts"]     # == default_sync_health().attempts
    >>> reg.flat()["compile.programs"]     # one flat namespace for exporters

Sources are pull-based suppliers (zero cost until read), so registering a
source adds nothing to any hot path. The default registry federates:

- ``compile``: a process-wide always-active ``CompileCounter`` (installed
  on first registry access; jax.monitoring listeners are O(1) per compile
  and compiles are rare/expensive);
- ``sync``: ``resilience.default_sync_health().as_dict()`` — the record
  every config-driven resilient sync already accumulates into;
- ``events``: the global recorder's per-kind event counts + ring stats;
- ``snapshots``: elastic snapshot/restore tallies (updated by
  ``elastic.ElasticSession`` whether or not the recorder is enabled —
  the snapshot path is not a hot path, and a restart diagnosis wants
  these even when event recording was off).

``register``/``unregister`` let applications add their own sources; the
exporters (``render_prometheus``, ``format_report``,
``gather_observability``) read whatever the registry holds.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

__all__ = ["CounterRegistry", "default_registry"]

# elastic snapshot/restore tallies (see module docstring for why these
# accumulate independently of the recorder's enabled flag)
_SNAPSHOT_STATS: Dict[str, Any] = {  # tev: guarded-by=_SNAPSHOT_LOCK
    "snapshots_written": 0,
    "snapshot_secs_total": 0.0,
    "last_snapshot_secs": 0.0,
    "last_generation": -1,
    "restores": 0,
    "restore_secs_total": 0.0,
}
_SNAPSHOT_LOCK = threading.Lock()


def note_snapshot(generation: int, seconds: float) -> None:
    """Called by ``elastic.ElasticSession`` after each written bundle."""
    with _SNAPSHOT_LOCK:
        _SNAPSHOT_STATS["snapshots_written"] += 1
        _SNAPSHOT_STATS["snapshot_secs_total"] += float(seconds)
        _SNAPSHOT_STATS["last_snapshot_secs"] = float(seconds)
        _SNAPSHOT_STATS["last_generation"] = int(generation)


def note_restore(seconds: float) -> None:
    """Called by ``elastic.ElasticSession`` after a successful restore."""
    with _SNAPSHOT_LOCK:
        _SNAPSHOT_STATS["restores"] += 1
        _SNAPSHOT_STATS["restore_secs_total"] += float(seconds)


def _snapshot_source() -> Dict[str, Any]:
    with _SNAPSHOT_LOCK:
        return dict(_SNAPSHOT_STATS)


def _sync_source() -> Dict[str, Any]:
    from torcheval_tpu.resilience import default_sync_health

    return default_sync_health().as_dict()


def _flight_source() -> Dict[str, Any]:
    from torcheval_tpu.obs.flight import FLIGHT

    return FLIGHT.counters()


def _admission_source() -> Dict[str, Any]:
    from torcheval_tpu.table._admission import armed_counter_source

    return armed_counter_source()


def _wire_source() -> Dict[str, Any]:
    from torcheval_tpu.wire import LADDER

    return LADDER.counters()


def _events_source() -> Dict[str, Any]:
    from torcheval_tpu.obs.recorder import RECORDER

    log = RECORDER.log
    out: Dict[str, Any] = {
        "enabled": int(RECORDER.enabled),
        "recorded_total": log.total,
        "retained": len(log),
        "dropped": log.dropped,
        "capacity": log.capacity,
    }
    for kind, count in sorted(log.counts.items()):
        out[f"kind_{kind}"] = count
    return out


class CounterRegistry:
    """Named pull-based counter sources behind one read API.

    A source is ``name -> supplier`` where ``supplier()`` returns a flat
    ``{counter: value}`` dict. Suppliers run only at read time
    (:meth:`read` / :meth:`flat`), so registration is free on every hot
    path. A supplier that raises is reported as
    ``{"error": "<message>"}`` instead of failing the whole read — one
    broken source must not take down an exporter scrape.
    """

    def __init__(self) -> None:
        self._sources: Dict[str, Callable[[], Dict[str, Any]]] = {}  # tev: guarded-by=_lock
        self._lock = threading.Lock()

    def register(
        self, name: str, supplier: Callable[[], Dict[str, Any]]
    ) -> None:
        with self._lock:
            self._sources[name] = supplier

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    @property
    def sources(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._sources))

    def read(self) -> Dict[str, Dict[str, Any]]:
        """``{source: {counter: value}}``, sources in sorted order."""
        with self._lock:
            items = sorted(self._sources.items())
        out: Dict[str, Dict[str, Any]] = {}
        for name, supplier in items:
            try:
                out[name] = dict(supplier())
            except Exception as e:  # noqa: BLE001 — one source, not the scrape
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def flat(self) -> Dict[str, Any]:
        """One flat ``{"source.counter": value}`` namespace (exporters)."""
        return {
            f"{source}.{counter}": value
            for source, counters in self.read().items()
            for counter, value in counters.items()
        }


_DEFAULT: Optional[CounterRegistry] = None  # tev: guarded-by=_DEFAULT_LOCK
_GLOBAL_COMPILE = None  # tev: guarded-by=_DEFAULT_LOCK
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> CounterRegistry:
    """The process-wide registry with the built-in sources (module
    docstring). Created lazily; the same instance is returned forever
    after, so application sources registered on it persist."""
    global _DEFAULT, _GLOBAL_COMPILE
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            from torcheval_tpu.utils.compile_counter import CompileCounter

            _GLOBAL_COMPILE = CompileCounter()
            _GLOBAL_COMPILE.__enter__()  # active for the process lifetime
            compile_counter = _GLOBAL_COMPILE

            def _compile_source() -> Dict[str, Any]:
                return {
                    "programs": compile_counter.programs,
                    "compiles": compile_counter.compiles,
                    "cache_hits": compile_counter.cache_hits,
                    "compile_secs": compile_counter.compile_secs,
                }

            registry = CounterRegistry()
            registry.register("compile", _compile_source)
            registry.register("sync", _sync_source)
            registry.register("events", _events_source)
            registry.register("snapshots", _snapshot_source)
            # flight-recorder ring stats (ISSUE 11); the watchdog and
            # SLO monitor register "watchdog"/"slo" sources when armed
            registry.register("flight", _flight_source)
            # overload admission ladder across armed metric tables
            # (worst rung wins; zeros while nothing is armed)
            registry.register("admission", _admission_source)
            # quantized wire ladder: configured rung + drift-breach caps
            registry.register("wire", _wire_source)
            _DEFAULT = registry
        return _DEFAULT
