"""Concurrency verifier (ISSUE 15): every rule family FIRES on a seeded
violation and passes CLEAN over the shipped library, plus the CLI gate,
the suppression audit, the obs bridge, and regressions for the genuine
races the first library-wide sweep surfaced (Monitor._alert torn
return, SyncHealth.as_dict torn snapshot, LatencyHistogram.__eq__).

Stdlib-only on the library side: none of these tests import jax.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import torcheval_tpu
from torcheval_tpu.analysis.annotations import CONCURRENCY_RULE_IDS
from torcheval_tpu.analysis.concurrency import (
    DEFAULT_TARGETS,
    check_concurrency,
    thread_contexts,
)
from torcheval_tpu.analysis.locks import build_universe

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
PACKAGE_DIR = os.path.dirname(os.path.abspath(torcheval_tpu.__file__))


def _check(tmp_path, sources):
    if isinstance(sources, str):
        sources = {"fixture.py": sources}
    for name, source in sources.items():
        (tmp_path / name).write_text(source)
    return check_concurrency([str(tmp_path)], record=False)


def _active(report):
    return sorted({f.rule for f in report.findings if not f.suppressed})


# ------------------------------------------------- seeded-violation fixtures

SEEDED = {
    # PR 10 class: a bound field touched outside its lock
    "guarded-field": (
        "import threading\n"
        "class Ring:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []  # tev: guarded-by=_lock\n"
        "    def bad(self):\n"
        "        self.items.append(1)\n"
    ),
    # a lock-owning class mutating undeclared shared state
    "unguarded-state": (
        "import threading\n"
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.hits = []  # tev: guarded-by=_lock\n"
        "        self.count = 0\n"
        "    def bump(self):\n"
        "        self.count += 1\n"
    ),
    # PR 3 class: opposite nested acquisition orders
    "lock-order-cycle": (
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def fence_then_ring():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def ring_then_fence():\n"
        "    with B:\n"
        "        with A:\n"
        "            pass\n"
    ),
    "blocking-under-lock": (
        "import threading\n"
        "import time\n"
        "L = threading.Lock()\n"
        "def hold_and_sleep():\n"
        "    with L:\n"
        "        time.sleep(0.1)\n"
    ),
    # PR 4 class: one collective site reachable from main AND a writer
    "cross-thread-collective": (
        "import threading\n"
        "class Session:\n"
        "    def __init__(self, group):\n"
        "        self.group = group\n"
        "        self._thread = threading.Thread(target=self._loop)\n"
        "    def _loop(self):  # tev: scope=writer\n"
        "        self._flush()\n"
        "    def _flush(self):\n"
        "        return self.group.allgather_object(1)\n"
        "    def snapshot(self):\n"
        "        return self._flush()\n"
    ),
    "unannotated-thread-target": (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._thread = threading.Thread(target=self._loop)\n"
        "    def _loop(self):\n"
        "        pass\n"
    ),
    "bad-annotation": (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0  # tev: guarded-by=_no_such_lock\n"
    ),
}

CLEAN_TWINS = {
    "guarded-field": (
        "import threading\n"
        "class Ring:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []  # tev: guarded-by=_lock\n"
        "    def good(self):\n"
        "        with self._lock:\n"
        "            self.items.append(1)\n"
    ),
    "unguarded-state": (
        "import threading\n"
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0  # tev: guarded-by=_lock\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.count += 1\n"
    ),
    # same two locks, one consistent order everywhere
    "lock-order-cycle": (
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def f():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def g():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
    ),
    "blocking-under-lock": (
        "import threading\n"
        "import time\n"
        "L = threading.Lock()\n"
        "def sleep_outside():\n"
        "    with L:\n"
        "        pass\n"
        "    time.sleep(0.1)\n"
    ),
    # the writer-owned collective is single-context: no main-path caller
    "cross-thread-collective": (
        "import threading\n"
        "class Session:\n"
        "    def __init__(self, group):\n"
        "        self.group = group\n"
        "        self._thread = threading.Thread(target=self._loop)\n"
        "    def _loop(self):  # tev: scope=writer\n"
        "        self._flush()\n"
        "    def _flush(self):\n"
        "        return self.group.allgather_object(1)\n"
    ),
    "unannotated-thread-target": (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._thread = threading.Thread(target=self._loop)\n"
        "    def _loop(self):  # tev: scope=worker\n"
        "        pass\n"
    ),
    "bad-annotation": (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0  # tev: guarded-by=_lock\n"
    ),
}


@pytest.mark.parametrize("rule", sorted(SEEDED))
def test_rule_fires_on_seeded_violation(rule, tmp_path):
    report = _check(tmp_path, SEEDED[rule])
    assert rule in _active(report), (
        f"rule {rule} did not fire on its seeded violation:\n"
        + report.format_text()
    )
    assert not report.ok


@pytest.mark.parametrize("rule", sorted(CLEAN_TWINS))
def test_clean_twin_passes(rule, tmp_path):
    report = _check(tmp_path, CLEAN_TWINS[rule])
    assert rule not in _active(report), (
        f"rule {rule} fired on its clean twin:\n" + report.format_text()
    )


def test_every_concurrency_rule_has_a_seeded_fixture():
    """New concurrency rules must land with a firing fixture — the
    acceptance bullet is per rule family."""
    assert set(SEEDED) == set(CONCURRENCY_RULE_IDS)


# ----------------------------------------------------------- rule semantics


def test_lock_order_cycle_carries_both_acquisition_stacks(tmp_path):
    report = _check(tmp_path, SEEDED["lock-order-cycle"])
    (finding,) = [f for f in report.findings if f.rule == "lock-order-cycle"]
    # both edges of the A/B cycle, each with its acquisition site chain
    assert "A -> B" in finding.message and "B -> A" in finding.message
    assert "fixture:5" in finding.message and "fixture:9" in finding.message


def test_lock_order_cycle_detects_multi_item_with(tmp_path):
    """``with A, B:`` acquires A then B exactly like nested withs — the
    one-line idiom must feed the same acquisition edges."""
    report = _check(
        tmp_path,
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def f():\n"
        "    with A, B:\n"
        "        pass\n"
        "def g():\n"
        "    with B:\n"
        "        with A:\n"
        "            pass\n",
    )
    assert "lock-order-cycle" in _active(report)


def test_unknown_rule_suppression_fails_closed(tmp_path):
    """A suppression naming ANY unknown rule id suppresses nothing: the
    underlying finding stays active (and the lint flags the typo as
    bad-suppression) — a typo can never turn the gate green."""
    source = SEEDED["blocking-under-lock"].replace(
        "        time.sleep(0.1)\n",
        "        time.sleep(0.1)  # tev: disable=blocking-under-lok,blocking-under-lock -- typo'd twin\n",
    )
    report = _check(tmp_path, source)
    assert "blocking-under-lock" in _active(report)
    assert not report.ok


def test_lock_order_cycle_through_a_call_chain(tmp_path):
    """The PR 3 shape: a process-global fence lock and an object lock
    acquired in opposite orders THROUGH function calls, not just lexical
    nesting."""
    report = _check(
        tmp_path,
        "import threading\n"
        "FENCE = threading.Lock()\n"
        "class Group:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def sync(self):\n"
        "        with self._lock:\n"
        "            wait_fence()\n"
        "    def note(self):\n"
        "        with self._lock:\n"
        "            pass\n"
        "def wait_fence():\n"
        "    with FENCE:\n"
        "        pass\n"
        "def fence_all(group: Group):\n"
        "    with FENCE:\n"
        "        group.note()\n",
    )
    assert "lock-order-cycle" in _active(report)


def test_closure_under_lock_inherits_the_lexical_lock_scope(tmp_path):
    """A nested def inside a ``with <lock>`` body runs lock-held — it
    must not re-check lock-free as its own function (and its accesses
    outside any lock still flag via the enclosing walk)."""
    report = _check(
        tmp_path,
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []  # tev: guarded-by=_lock\n"
        "    def use(self):\n"
        "        with self._lock:\n"
        "            def probe():\n"
        "                return len(self.items)\n"
        "            return probe()\n",
    )
    assert "guarded-field" not in _active(report), report.format_text()


def test_blocking_under_lock_condition_wait_is_exempt(tmp_path):
    """``Condition.wait_for`` on the HELD lock releases it — the one
    legal blocking-while-holding shape (ThreadWorld.exchange)."""
    report = _check(
        tmp_path,
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Condition()\n"
        "        self.ready = False  # tev: guarded-by=_lock\n"
        "    def get(self):\n"
        "        with self._lock:\n"
        "            self._lock.wait_for(lambda: self.ready)\n",
    )
    assert "blocking-under-lock" not in _active(report)


def test_collective_issue_under_lock_is_blocking(tmp_path):
    report = _check(
        tmp_path,
        "import threading\n"
        "L = threading.Lock()\n"
        "def sync(group, x):\n"
        "    with L:\n"
        "        return group.allgather_object(x)\n",
    )
    assert "blocking-under-lock" in _active(report)


def test_fence_routed_collective_is_exempt(tmp_path):
    """A multi-context collective that routes through the resilience
    in-flight fence names is safe by construction."""
    source = SEEDED["cross-thread-collective"].replace(
        "    def _flush(self):\n",
        "    def _flush(self):\n"
        "        _still_in_flight(0.0)\n",
    )
    source = "def _still_in_flight(budget):\n    return False\n" + source
    report = _check(tmp_path, source)
    assert "cross-thread-collective" not in _active(report)


def test_thread_contexts_propagate_through_calls(tmp_path):
    (tmp_path / "mod.py").write_text(SEEDED["cross-thread-collective"])
    universe = build_universe([str(tmp_path)])
    contexts = thread_contexts(universe)
    flush = [v for k, v in contexts.items() if k[1] == "Session._flush"]
    assert flush and flush[0] == {"main", "writer"}


def test_suppression_with_reason_is_honored_and_audited(tmp_path):
    source = SEEDED["blocking-under-lock"].replace(
        "        time.sleep(0.1)\n",
        "        time.sleep(0.1)  # tev: disable=blocking-under-lock -- fixture: deliberate hold\n",
    )
    report = _check(tmp_path, source)
    assert report.ok
    (finding,) = [
        f for f in report.findings if f.rule == "blocking-under-lock"
    ]
    assert finding.suppressed
    assert finding.suppress_reason == "fixture: deliberate hold"


def test_reasonless_suppression_does_not_suppress(tmp_path):
    source = SEEDED["blocking-under-lock"].replace(
        "        time.sleep(0.1)\n",
        "        time.sleep(0.1)  # tev: disable=blocking-under-lock\n",
    )
    report = _check(tmp_path, source)
    assert "blocking-under-lock" in _active(report)
    assert not report.ok


# ------------------------------------------------------- library-wide sweep


def test_library_sweep_is_clean():
    """The ISSUE 15 acceptance gate: zero unsuppressed findings over the
    shipped library."""
    report = check_concurrency([PACKAGE_DIR], record=False)
    assert report.checked > 0
    active = [f for f in report.findings if not f.suppressed]
    assert report.ok and not active, report.format_text(
        include_suppressed=False
    )


def test_library_sweep_covers_the_issue_targets():
    """The named sweep floor (obs/, resilience, elastic, federation,
    utils/checkpoint) exists and is inside the default package sweep."""
    for target in DEFAULT_TARGETS:
        assert os.path.exists(os.path.join(PACKAGE_DIR, target)), target
    universe = build_universe([PACKAGE_DIR])
    names = set(universe.modules)
    for needed in (
        "torcheval_tpu.obs.flight",
        "torcheval_tpu.resilience",
        "torcheval_tpu.elastic",
        "torcheval_tpu.federation",
        "torcheval_tpu.utils.checkpoint",
    ):
        assert needed in names


def test_library_suppressions_all_carry_reasons():
    report = check_concurrency([PACKAGE_DIR], record=False)
    for finding in report.findings:
        if finding.suppressed:
            assert finding.suppress_reason, finding.format()


def test_library_thread_entries_are_annotated():
    """The thread fleet the ISSUE names is modeled: the elastic writer,
    the JSONL writer, the watchdog, and the resilience deadline worker
    all carry thread-scope annotations."""
    universe = build_universe([PACKAGE_DIR])
    scopes = {
        (fn.module, fn.qual): fn.thread_scope
        for module in universe.modules.values()
        for fn in module.all_functions()
        if fn.thread_scope is not None
    }
    assert scopes[("torcheval_tpu.elastic", "_SnapshotWriter._loop")] == "writer"
    assert scopes[("torcheval_tpu.obs.export", "JsonlWriter._loop")] == "writer"
    assert (
        scopes[("torcheval_tpu.obs.watchdog", "StallWatchdog._loop")]
        == "watchdog"
    )
    assert scopes[("torcheval_tpu.resilience", "_SyncWorker._loop")] == "worker"


def test_elastic_writer_collective_is_the_pr4_class():
    """The PR 4 incident is VISIBLE to the model (the writer/main
    multi-context collective is detected) and resolved by a reasoned
    suppression documenting the dedicated communicator."""
    report = check_concurrency([PACKAGE_DIR], record=False)
    hits = [
        f
        for f in report.findings
        if f.rule == "cross-thread-collective"
        and f.path.endswith("elastic.py")
    ]
    assert hits, "the elastic writer gather is no longer modeled"
    assert all(f.suppressed and "dedicated" in f.suppress_reason.lower()
               for f in hits)


# ----------------------------------------------------------------- CLI gate


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "torcheval_tpu.analysis", *args],
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def test_cli_concurrency_gate_passes_on_library(tmp_path):
    out = tmp_path / "report.json"
    proc = _run_cli(
        "--no-lint",
        "--concurrency",
        PACKAGE_DIR,
        "--report",
        "json",
        "--output",
        str(out),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(out.read_text())
    assert payload["ok"] is True
    assert payload["counts"]["errors"] == 0
    assert any(
        f["tool"] == "concurrency" for f in payload["findings"]
    ), "concurrency findings (suppressed) should appear in the artifact"


@pytest.mark.parametrize(
    "rule",
    [
        "guarded-field",
        "lock-order-cycle",
        "blocking-under-lock",
        "cross-thread-collective",
    ],
)
def test_cli_gate_fails_on_each_seeded_rule_family(rule, tmp_path):
    """The acceptance bullet verbatim: each rule family has a committed
    seeded-violation fixture the CI gate demonstrably fails on."""
    (tmp_path / "fixture.py").write_text(SEEDED[rule])
    proc = _run_cli("--no-lint", "--concurrency", str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert rule in proc.stdout


def test_cli_concurrency_composes_with_lint(tmp_path):
    (tmp_path / "fixture.py").write_text(
        "import threading\n_L = threading.Lock()\n"
    )
    proc = _run_cli("--concurrency", str(tmp_path))
    # bare-lock (lint) fires even though the concurrency passes are clean
    assert proc.returncode == 1
    assert "bare-lock" in proc.stdout


# ------------------------------------------------------------- obs bridge


def test_active_findings_mirror_as_analysis_events(tmp_path):
    from torcheval_tpu.obs.recorder import RECORDER

    (tmp_path / "fixture.py").write_text(SEEDED["guarded-field"])
    RECORDER.enable()
    try:
        check_concurrency([str(tmp_path)])
        events = [
            e for e in RECORDER.log.tail() if e.kind == "analysis"
        ]
        assert any(
            e.rule == "guarded-field" and e.tool == "concurrency"
            for e in events
        )
    finally:
        RECORDER.disable()
        RECORDER.reset()


def test_last_report_is_recorded(tmp_path):
    from torcheval_tpu.analysis import last_report

    (tmp_path / "fixture.py").write_text(CLEAN_TWINS["guarded-field"])
    report = check_concurrency([str(tmp_path)])
    assert last_report() is report


# ---------------------------------------- regressions for the genuine fixes


def test_monitor_alert_returns_its_own_alert_dict():
    """Monitor._alert used to re-read self._active[key] AFTER releasing
    the lock — a concurrent checker's replacement could be returned as
    this call's alert (caught by the guarded-field sweep). The alert is
    now captured under the lock."""
    from torcheval_tpu.obs.monitor import Monitor

    m = Monitor(cooldown=0.0)
    a1 = m._alert("slo", "threshold", 1.0, 0.5, "first")
    a2 = m._alert("slo", "threshold", 2.0, 0.5, "second")
    assert a1["value"] == 1.0 and a1["message"] == "first"
    assert a2["value"] == 2.0 and a2["message"] == "second"


def test_monitor_alert_concurrent_returns_are_not_torn():
    """Two concurrent _alert calls on one key each get the dict THEY
    recorded, under every explored interleaving (the schedule harness
    drives the race the static finding described)."""
    from torcheval_tpu.obs import monitor as monitor_mod
    from torcheval_tpu.utils.test_utils import DeterministicScheduler

    for seed in range(6):
        m = monitor_mod.Monitor(cooldown=0.0)
        sched = DeterministicScheduler(seed=seed, trace=[monitor_mod])
        sched.spawn(m._alert, "k", "drift", 1.0, 0.0, "one")
        sched.spawn(m._alert, "k", "drift", 2.0, 0.0, "two")
        result = sched.run()
        values = sorted(a["value"] for a in result.values)
        assert values == [1.0, 2.0], (seed, result.values)


def test_latency_histogram_eq_semantics_preserved():
    from torcheval_tpu.obs.hist import LatencyHistogram

    h1, h2 = LatencyHistogram(), LatencyHistogram()
    assert h1 == h2
    h1.observe(0.001)
    assert h1 != h2
    h2.observe(0.001)
    assert h1 == h2
    assert h1.__eq__(object()) is NotImplemented
