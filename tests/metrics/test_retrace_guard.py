"""Retrace-proofing regression guard (tier-1, fast).

The shape-bucketing layer (torcheval_tpu/metrics/_bucket.py) exists to make
a variable-shape eval stream compile O(log max_batch) fused programs
instead of one per distinct batch shape. A regression here is silent —
results stay correct while every ragged batch pays a fresh trace+compile —
so this guard runs a 20-step loop over 7 distinct batch sizes under the
compile counter and fails loudly if the program count exceeds the bucket
bound. A control without bucketing proves the counter would have seen the
retraces, and a shard_map arm pins that the mask-aware kernels add ZERO
collectives to an in-jit-synced step.
"""

from __future__ import annotations

import math
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 jax keeps it under experimental
    from jax.experimental.shard_map import shard_map

from torcheval_tpu import config
from torcheval_tpu.metrics import (
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
)
from torcheval_tpu.metrics._bucket import bucket_bound, bucket_length
from torcheval_tpu.metrics.functional.classification.accuracy import (
    _multiclass_accuracy_update,
    _multiclass_accuracy_update_masked,
)
from torcheval_tpu.metrics.sharded import sync_states_in_jit
from torcheval_tpu.metrics.toolkit import update_collection
from torcheval_tpu.utils import CompileCounter
from torcheval_tpu.utils.hlo import (
    collective_count,
    compile_fully_optimized,
)

RNG = np.random.default_rng(3)

MAX_BATCH, CLASSES = 64, 8
# 20 steps cycling 7 distinct batch sizes — ragged tails and odd mid-stream
# shapes; numpy inputs (the data-loader reality), so padding is pure host
# work and the counter sees only fused update programs.
SIZES = [64, 64, 37, 64, 19, 64, 50, 7, 64, 23, 64, 64, 37, 3, 64, 19,
         64, 50, 64, 37]
X = RNG.uniform(size=(MAX_BATCH, CLASSES)).astype(np.float32)
T = np.asarray(RNG.integers(0, CLASSES, size=(MAX_BATCH,)))

assert len(SIZES) == 20 and len(set(SIZES)) == 7


def _expected_buckets():
    return {bucket_length(n) for n in SIZES}


def test_compile_counter_sees_fresh_compiles():
    """Counter self-check: a never-before-compiled program must count 1 —
    guards against a JAX monitoring-event rename making the bound below
    vacuously true."""
    salt = jnp.float32(RNG.uniform())  # unique constant -> unique program
    with CompileCounter() as cc:
        jax.block_until_ready(
            jax.jit(lambda a: jnp.cumsum(a) * salt)(jnp.arange(17.0))
        )
    assert cc.programs >= 1


def test_ragged_stream_compiles_within_bucket_bound():
    metric = MulticlassAccuracy()
    with config.shape_bucketing():
        with CompileCounter() as cc:
            for n in SIZES:
                metric.update(X[:n], T[:n])
            jax.block_until_ready(metric.num_total)

    issue_bound = math.ceil(math.log2(MAX_BATCH)) + 1
    assert cc.programs <= len(_expected_buckets()), (
        f"{cc.programs} programs for buckets {_expected_buckets()}"
    )
    assert cc.programs <= issue_bound
    assert cc.programs <= bucket_bound(MAX_BATCH)

    # the stream really was ragged: without bucketing the same sizes
    # compile one program each
    control = MulticlassAccuracy()
    with CompileCounter() as cc_ctrl:
        for n in sorted(set(SIZES)):
            control.update(X[:n], T[:n])
        jax.block_until_ready(control.num_total)
    assert cc_ctrl.programs >= len(set(SIZES))

    # and the bucketed stream computed the same value
    np.testing.assert_allclose(
        np.asarray(metric.compute()),
        np.asarray(
            MulticlassAccuracy()
            .update(
                np.concatenate([X[:n] for n in SIZES]),
                np.concatenate([T[:n] for n in SIZES]),
            )
            .compute()
        ),
        rtol=1e-6,
    )


def test_update_collection_compiles_one_group_program_per_bucket():
    """The fused GROUP dispatch must bucket too: K metrics on a ragged
    stream compile one group program per bucket, not K programs per
    distinct shape."""
    panel = {
        "acc": MulticlassAccuracy(),
        "f1": MulticlassF1Score(),
        "precision": MulticlassPrecision(num_classes=CLASSES, average="macro"),
        "recall": MulticlassRecall(num_classes=CLASSES, average="macro"),
        "cm": MulticlassConfusionMatrix(CLASSES),
    }
    with config.shape_bucketing():
        with CompileCounter() as cc:
            for n in SIZES:
                update_collection(panel, X[:n], T[:n])
            jax.block_until_ready(panel["acc"].num_total)
    # one GROUP program per bucket (not per metric, not per shape)
    assert cc.programs <= len(_expected_buckets()), (
        f"{cc.programs} group programs for buckets {_expected_buckets()}"
    )


def test_mixed_panel_keeps_bucketed_group_bound():
    """A metric WITHOUT a mask-aware kernel in the panel (here: a
    windowed ring-buffer metric, transform plan) must not drag the
    bucketed metrics' group program into per-shape retraces — unbucketed
    plans group separately, so their inherent per-shape compiles add to
    the total but the bucketed group stays at one program per bucket."""
    from torcheval_tpu.metrics import BinaryAccuracy, WindowedMeanSquaredError

    panel = {
        "acc": BinaryAccuracy(),
        "wmse": WindowedMeanSquaredError(max_num_updates=4),
    }
    scores = RNG.uniform(size=(MAX_BATCH,)).astype(np.float32)
    labels = (RNG.random(MAX_BATCH) < 0.5).astype(np.float32)
    with config.shape_bucketing():
        with CompileCounter() as cc:
            for n in SIZES:
                update_collection(panel, scores[:n], labels[:n])
            jax.block_until_ready(panel["acc"].num_total)
    # the windowed metric retraces once per distinct shape (no masked
    # kernel — inherent); the bucketed group must still cost at most one
    # program per bucket on top of that
    budget = len(_expected_buckets()) + len(set(SIZES))
    assert cc.programs <= budget, (
        f"{cc.programs} programs; bucketed group must stay at "
        f"{len(_expected_buckets())} on top of {len(set(SIZES))} "
        "windowed-metric retraces"
    )
    # value parity for the bucketed member of the mixed panel
    np.testing.assert_array_equal(
        np.asarray(panel["acc"].compute()),
        np.asarray(
            BinaryAccuracy()
            .update(
                np.concatenate([scores[:n] for n in SIZES]),
                np.concatenate([labels[:n] for n in SIZES]),
            )
            .compute()
        ),
    )


@pytest.fixture(scope="module")
def mesh():
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    return Mesh(np.array(cpus[:8]), ("dp",))


def test_masked_kernel_adds_no_collectives(mesh):
    """Masking is a local concern: an in-jit-synced eval step using the
    mask-aware accuracy kernel must lower to EXACTLY the collectives of
    the unmasked step (sharded.py's unchanged-collective-count contract)."""
    n = 8
    batch, d = 8 * n, 16
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(d, CLASSES)).astype(np.float32))
    x = jax.device_put(
        jnp.asarray(rng.normal(size=(batch, d)).astype(np.float32)),
        NamedSharding(mesh, P("dp", None)),
    )
    y = jax.device_put(
        jnp.asarray(rng.integers(0, CLASSES, size=(batch,))),
        NamedSharding(mesh, P("dp")),
    )
    state = {"nc": jnp.zeros(()), "nt": jnp.zeros(())}
    valid_sizes = jnp.asarray([n - 3], dtype=jnp.int32)

    @jax.jit
    @partial(
        shard_map, mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P(), P()),
        out_specs=(P(), P()),
    )
    def step_unmasked(x, y, w, state):
        logits = jnp.tanh(x @ w)
        nc, nt = _multiclass_accuracy_update(logits, y, "micro", None, 1)
        local = {"nc": state["nc"] + nc, "nt": state["nt"] + nt}
        return jax.lax.psum(jnp.sum(logits), "dp"), sync_states_in_jit(
            local, "dp"
        )

    @jax.jit
    @partial(
        shard_map, mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P(), P(), P()),
        out_specs=(P(), P()),
    )
    def step_masked(x, y, w, valid_sizes, state):
        logits = jnp.tanh(x @ w)
        nc, nt = _multiclass_accuracy_update_masked(
            logits, y, valid_sizes, "micro", None, 1
        )
        local = {"nc": state["nc"] + nc, "nt": state["nt"] + nt}
        return jax.lax.psum(jnp.sum(logits), "dp"), sync_states_in_jit(
            local, "dp"
        )

    plain = collective_count(
        compile_fully_optimized(step_unmasked.lower(x, y, w, state))
    )
    masked = collective_count(
        compile_fully_optimized(
            step_masked.lower(x, y, w, valid_sizes, state)
        )
    )
    assert masked == plain, (
        f"masked step lowered to {masked} collectives vs {plain} unmasked"
    )

    # and the masked step's counters really exclude the padded rows
    _, synced = step_masked(x, y, w, valid_sizes, state)
    assert float(synced["nt"]) == 8 * (n - 3)
