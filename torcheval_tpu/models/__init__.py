from torcheval_tpu.models.transformer import (
    TransformerLM,
    init_params,
    param_specs,
)

__all__ = ["TransformerLM", "init_params", "param_specs"]
