"""Single-device training loop with in-loop metrics.

Parity workload: reference examples/simple_example.py — train a tiny model,
call ``metric.update`` per batch (async, no host sync), ``compute`` per epoch,
``reset`` between epochs.
"""


import os as _os
import sys as _sys

# file-relative fallback: `python -m examples.<name>` resolves imports from
# the CWD, not this directory, so `_backend` needs the examples dir on
# sys.path (direct `python examples/<name>.py` runs already have it)
_here = _os.path.dirname(_os.path.abspath(__file__))
_sys.path.append(_here)
_sys.path.append(_os.path.dirname(_here))  # repo root: uninstalled checkouts

from _backend import ensure_backend

ensure_backend()  # fall back to CPU if the accelerator relay is unreachable

import jax
import jax.numpy as jnp
import optax

from torcheval_tpu.metrics import (
    Mean,
    MulticlassAccuracy,
    MulticlassF1Score,
    Throughput,
)
from torcheval_tpu.metrics.toolkit import update_collection
from torcheval_tpu.models import TransformerLM, init_params

import time

VOCAB, BATCH, SEQ, STEPS, EPOCHS = 64, 8, 16, 12, 2


def main() -> None:
    model = TransformerLM(vocab_size=VOCAB, d_model=32, n_heads=2, n_layers=1)
    params = init_params(model, batch=BATCH, seq=SEQ)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens, targets):
        def loss_fn(p):
            logits = model.apply(p, tokens)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, targets[..., None], -1).squeeze(-1)
            return jnp.mean(nll), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, logits

    # accuracy + F1 track the same (logits, labels) batch: update them with
    # ONE fused dispatch per step via update_collection
    cls_metrics = {
        "acc": MulticlassAccuracy(),
        "f1": MulticlassF1Score(num_classes=VOCAB, average="macro"),
    }
    loss_mean = Mean()
    tput = Throughput()

    key = jax.random.PRNGKey(0)
    for epoch in range(EPOCHS):
        t0 = time.perf_counter()
        for step in range(STEPS):
            key, k1 = jax.random.split(key)
            tokens = jax.random.randint(k1, (BATCH, SEQ), 0, VOCAB)
            targets = jnp.roll(tokens, -1, axis=-1)
            params, opt_state, loss, logits = train_step(
                params, opt_state, tokens, targets
            )
            update_collection(
                cls_metrics, logits.reshape(-1, VOCAB), targets.reshape(-1)
            )
            loss_mean.update(loss)
        tput.update(STEPS * BATCH * SEQ, time.perf_counter() - t0)
        print(
            f"epoch {epoch}: loss={float(loss_mean.compute()):.4f} "
            f"acc={float(cls_metrics['acc'].compute()):.4f} "
            f"f1={float(cls_metrics['f1'].compute()):.4f} "
            f"throughput={tput.compute():.0f} tok/s"
        )
        for metric in (*cls_metrics.values(), loss_mean):
            metric.reset()


if __name__ == "__main__":
    main()
