"""Packaging contract: the repo must stay pip-installable.

Parity target: the reference ships setup.py/pyproject.toml/LICENSE
(/root/reference/setup.py:1-80); here the contract is pinned by tests so a
refactor can't silently orphan the metadata. The actual install is
exercised by CI (`pip install -e .[dev]`) and the wheel workflow.
"""

from __future__ import annotations

import os
import re

try:
    import tomllib  # 3.11+
except ImportError:  # pragma: no cover - 3.10
    import tomli as tomllib

import torcheval_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pyproject():
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)


def test_version_single_sourced():
    """pyproject declares version dynamic, sourced from version.py, and the
    package exposes the same string."""
    py = _pyproject()
    assert "version" in py["project"]["dynamic"]
    assert (
        py["tool"]["setuptools"]["dynamic"]["version"]["attr"]
        == "torcheval_tpu.version.__version__"
    )
    from torcheval_tpu.version import __version__

    assert torcheval_tpu.__version__ == __version__
    assert re.fullmatch(r"\d+\.\d+\.\d+", __version__)


def test_native_kernel_sources_ship_in_wheel():
    """The C++ kernels build on first use, so the wheel must carry .cc
    sources (and must NOT carry a prebuilt .so, which would be stale on any
    other toolchain)."""
    py = _pyproject()
    data = py["tool"]["setuptools"]["package-data"]["torcheval_tpu.ops.native"]
    assert "*.cc" in data
    native = os.path.join(REPO, "torcheval_tpu", "ops", "native")
    cc = [f for f in os.listdir(native) if f.endswith(".cc")]
    assert len(cc) >= 4, cc


def test_license_present():
    with open(os.path.join(REPO, "LICENSE")) as f:
        assert "BSD 3-Clause" in f.read()
    assert _pyproject()["project"]["license"] == {"file": "LICENSE"}


def test_core_deps_are_jax_native():
    """torch must never be a hard dependency — it is the optional front
    door, not the compute path."""
    py = _pyproject()
    deps = " ".join(py["project"]["dependencies"])
    assert "torch" not in deps
    for want in ("jax", "flax", "numpy", "orbax-checkpoint"):
        assert want in deps, want
    extras = py["project"]["optional-dependencies"]
    assert any("torch" in d for d in extras["torch"])


def test_examples_import_the_installed_package():
    """No example may re-add the repo root to sys.path — they must work in
    any cwd against the pip-installed package."""
    exdir = os.path.join(REPO, "examples")
    for name in sorted(os.listdir(exdir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(exdir, name)) as f:
            src = f.read()
        assert "sys.path.insert" not in src, name


def test_ci_installs_via_pyproject():
    with open(os.path.join(REPO, ".github", "workflows", "unit_test.yaml")) as f:
        ci = f.read()
    assert "pip install -e .[dev]" in ci
