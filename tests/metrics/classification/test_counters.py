"""Counter-family class metric tests (ConfusionMatrix / F1 / Precision /
Recall / NormalizedEntropy) vs the reference oracle."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
from sklearn.metrics import confusion_matrix as sk_confusion_matrix
from sklearn.metrics import f1_score as sk_f1

from tests.ref_oracle import load_reference_metrics
from torcheval_tpu.metrics import (
    BinaryConfusionMatrix,
    BinaryF1Score,
    BinaryNormalizedEntropy,
    BinaryPrecision,
    BinaryRecall,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
)
from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
    assert_result_close,
)

REF_M, REF_F = load_reference_metrics()
RNG = np.random.default_rng(33)
N_UP, BATCH, C = 8, 12, 4


def _ref_result(metric, update_args):
    for args in update_args:
        metric.update(*[torch.tensor(np.asarray(a)) for a in args])
    return np.asarray(metric.compute())


class TestConfusionMatrix(MetricClassTester):
    @pytest.mark.parametrize("normalize", [None, "pred", "true", "all"])
    def test_multiclass_cm(self, normalize):
        inputs = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        targets = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        expected = _ref_result(
            REF_M.MulticlassConfusionMatrix(C, normalize=normalize),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=MulticlassConfusionMatrix(C, normalize=normalize),
            state_names={"confusion_matrix"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_binary_cm(self):
        inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [RNG.integers(0, 2, BATCH) for _ in range(N_UP)]
        expected = _ref_result(
            REF_M.BinaryConfusionMatrix(), list(zip(inputs, targets))
        )
        self.run_class_implementation_tests(
            metric=BinaryConfusionMatrix(),
            state_names={"confusion_matrix"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_vs_sklearn(self):
        pred = RNG.integers(0, C, 100)
        true = RNG.integers(0, C, 100)
        ours = F.multiclass_confusion_matrix(
            jnp.asarray(pred), jnp.asarray(true), num_classes=C
        )
        assert_result_close(ours, sk_confusion_matrix(true, pred))

    def test_score_input_argmax(self):
        scores = RNG.uniform(size=(50, C)).astype(np.float32)
        true = RNG.integers(0, C, 50)
        ours = F.multiclass_confusion_matrix(
            jnp.asarray(scores), jnp.asarray(true), num_classes=C
        )
        assert_result_close(ours, sk_confusion_matrix(true, scores.argmax(1)))

    def test_param_checks(self):
        with pytest.raises(ValueError, match="at least two"):
            MulticlassConfusionMatrix(1)
        with pytest.raises(ValueError, match="normalize must be"):
            MulticlassConfusionMatrix(3, normalize="rows")

    def test_normalized_view(self):
        m = MulticlassConfusionMatrix(2)
        m.update(jnp.array([0, 1, 1]), jnp.array([0, 1, 0]))
        norm = m.normalized("all")
        assert float(jnp.sum(norm)) == pytest.approx(1.0)


class TestF1Score(MetricClassTester):
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
    def test_multiclass_f1(self, average):
        inputs = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        targets = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        kwargs = {"average": average}
        if average != "micro":
            kwargs["num_classes"] = C
        expected = _ref_result(
            REF_M.MulticlassF1Score(**kwargs), list(zip(inputs, targets))
        )
        self.run_class_implementation_tests(
            metric=MulticlassF1Score(**kwargs),
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_binary_f1(self):
        inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [RNG.integers(0, 2, BATCH) for _ in range(N_UP)]
        expected = _ref_result(REF_M.BinaryF1Score(), list(zip(inputs, targets)))
        self.run_class_implementation_tests(
            metric=BinaryF1Score(),
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_vs_sklearn(self):
        pred = RNG.integers(0, C, 100)
        true = RNG.integers(0, C, 100)
        for avg in ["micro", "macro", "weighted"]:
            assert_result_close(
                F.multiclass_f1_score(
                    jnp.asarray(pred), jnp.asarray(true), num_classes=C, average=avg
                ),
                sk_f1(true, pred, average=avg),
            )


class TestPrecisionRecall(MetricClassTester):
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
    def test_multiclass_precision(self, average):
        inputs = [
            RNG.uniform(size=(BATCH, C)).astype(np.float32) for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        kwargs = {"average": average}
        if average != "micro":
            kwargs["num_classes"] = C
        expected = _ref_result(
            REF_M.MulticlassPrecision(**kwargs), list(zip(inputs, targets))
        )
        self.run_class_implementation_tests(
            metric=MulticlassPrecision(**kwargs),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
    def test_multiclass_recall(self, average):
        inputs = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        targets = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        kwargs = {"average": average}
        if average != "micro":
            kwargs["num_classes"] = C
        expected = _ref_result(
            REF_M.MulticlassRecall(**kwargs), list(zip(inputs, targets))
        )
        self.run_class_implementation_tests(
            metric=MulticlassRecall(**kwargs),
            state_names={"num_tp", "num_labels", "num_predictions"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_binary_precision_recall(self):
        inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [RNG.integers(0, 2, BATCH) for _ in range(N_UP)]
        expected_p = _ref_result(REF_M.BinaryPrecision(), list(zip(inputs, targets)))
        self.run_class_implementation_tests(
            metric=BinaryPrecision(),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected_p,
        )
        expected_r = _ref_result(REF_M.BinaryRecall(), list(zip(inputs, targets)))
        self.run_class_implementation_tests(
            metric=BinaryRecall(),
            state_names={"num_tp", "num_true_labels"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected_r,
        )


class TestNormalizedEntropy(MetricClassTester):
    @pytest.mark.parametrize("from_logits", [False, True])
    def test_ne(self, from_logits):
        if from_logits:
            inputs = [
                ((RNG.uniform(size=BATCH) - 0.5) * 4).astype(np.float32)
                for _ in range(N_UP)
            ]
        else:
            inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [
            RNG.integers(0, 2, BATCH).astype(np.float32) for _ in range(N_UP)
        ]
        expected = _ref_result(
            REF_M.BinaryNormalizedEntropy(from_logits=from_logits),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=BinaryNormalizedEntropy(from_logits=from_logits),
            state_names={"total_entropy", "num_examples", "num_positive"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
            atol=1e-4,
        )

    def test_ne_weighted_multitask(self):
        x = RNG.uniform(size=(2, 20)).astype(np.float32)
        t = RNG.integers(0, 2, (2, 20)).astype(np.float32)
        w = RNG.uniform(0.5, 2.0, (2, 20)).astype(np.float32)
        ours = F.binary_normalized_entropy(
            jnp.asarray(x), jnp.asarray(t), weight=jnp.asarray(w), num_tasks=2
        )
        ref = REF_F.binary_normalized_entropy(
            torch.tensor(x), torch.tensor(t), weight=torch.tensor(w), num_tasks=2
        )
        assert_result_close(ours, np.asarray(ref), atol=1e-4)

    @pytest.mark.parametrize(
        "case",
        [
            # degenerate positive-rate tails: the reference's float64-eps
            # clamp (reference binary_normalized_entropy.py:107-117) makes
            # the baseline tiny and NE huge; our float32 kernel must land
            # within float32 precision of the same huge value
            ([0.2], [1.0]),
            ([0.7, 0.3], [0.0, 0.0]),
            # input exactly 0/1: torch BCE clamps each log term at -100
            ([0.0, 0.5], [1.0, 0.0]),
            ([1.0, 0.5], [0.0, 1.0]),
        ],
        ids=["all-pos", "all-neg", "input-zero", "input-one"],
    )
    def test_ne_degenerate_tails(self, case):
        x, t = (np.asarray(v, np.float32) for v in case)
        ours = float(
            F.binary_normalized_entropy(jnp.asarray(x), jnp.asarray(t))
        )
        ref = float(
            REF_F.binary_normalized_entropy(torch.tensor(x), torch.tensor(t))
        )
        assert ours == pytest.approx(ref, rel=1e-4)

    def test_prob_range_check_gated_by_debug_validation(self):
        from torcheval_tpu.config import debug_validation

        # value check forces a host sync, so it only runs in debug mode
        with debug_validation():
            with pytest.raises(ValueError, match="probability"):
                F.binary_normalized_entropy(
                    jnp.array([1.5, 0.2]), jnp.array([1.0, 0.0])
                )
        # off by default: no sync, no raise
        F.binary_normalized_entropy(jnp.array([1.5, 0.2]), jnp.array([1.0, 0.0]))
