"""CPU lowerings must actually contain the native custom-calls.

The dispatchers fall back to pure XLA silently when registration fails —
correct but 10-20x slower on CPU. These pins turn a silent perf
regression (loader bug, registration rename, dispatch-guard typo) into a
test failure by asserting the FFI target names appear in the compiled
HLO of each hot entry point.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _require_native():
    from torcheval_tpu.ops import native

    if not native.ensure_registered():
        pytest.skip("native toolchain unavailable")


def _compiled_text(fn, *args):
    return jax.jit(fn).lower(*args).compile().as_text()


def test_auroc_lowering_uses_fused_kernel():
    from torcheval_tpu.metrics.functional.classification._curve_kernels import (
        binary_auroc_area,
    )

    x = jnp.zeros(64, jnp.float32)
    t = jnp.zeros(64, jnp.float32)
    assert "torcheval_binary_auroc" in _compiled_text(
        lambda x, t: binary_auroc_area(x, t), x, t
    )


def test_auprc_lowering_uses_fused_kernel():
    from torcheval_tpu.metrics.functional.classification._curve_kernels import (
        binary_auprc_area,
    )

    x = jnp.zeros(64, jnp.float32)
    t = jnp.zeros(64, jnp.float32)
    assert "torcheval_binary_auprc" in _compiled_text(binary_auprc_area, x, t)


def test_sort_lowering_uses_radix_kernel():
    from torcheval_tpu.metrics.functional.classification._curve_kernels import (
        sort_desc,
    )

    x = jnp.zeros(64, jnp.float32)
    assert "torcheval_sort_desc" in _compiled_text(sort_desc, x)


def test_accuracy_lowering_uses_correct_mask():
    from torcheval_tpu.metrics.functional.tensor_utils import correct_mask

    x = jnp.zeros((8, 5), jnp.float32)
    t = jnp.zeros(8, jnp.int32)
    assert "torcheval_correct_mask" in _compiled_text(correct_mask, x, t)


def test_argmax_lowering_uses_native_kernel():
    from torcheval_tpu.metrics.functional.tensor_utils import argmax_last

    x = jnp.zeros((8, 5), jnp.float32)
    assert "torcheval_argmax_last" in _compiled_text(argmax_last, x)


def test_perplexity_update_uses_native_ce():
    # eager dispatch (device-based, not platform_dependent): run once and
    # verify the jitted native wrapper is what executes
    from torcheval_tpu.metrics.functional.text.perplexity import (
        _perplexity_update_native_jit,
        _use_native_ce,
    )

    L = jnp.zeros((1, 4, 16), jnp.float32)
    assert _use_native_ce(L)
    assert "torcheval_ce_nll" in (
        jax.jit(lambda L, T: _perplexity_update_native_jit(L, T, None))
        .lower(L, jnp.zeros((1, 4), jnp.int32))
        .compile()
        .as_text()
    )


def test_fallbacks_keep_working_without_native():
    """With the native registry forced off, every dispatcher must still
    produce correct results through pure XLA."""
    import torcheval_tpu.ops.native as native

    from torcheval_tpu.metrics.functional.classification._curve_kernels import (
        binary_auprc_area,
        binary_auroc_area,
        sort_desc,
    )
    from torcheval_tpu.metrics.functional.tensor_utils import (
        argmax_last,
        correct_mask,
    )

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(size=128).astype(np.float32))
    t = jnp.asarray((rng.random(128) < 0.5).astype(np.float32))
    x2 = jnp.asarray(rng.normal(size=(16, 7)).astype(np.float32))
    t2 = jnp.asarray(rng.integers(0, 7, size=16))

    with_native = (
        float(binary_auroc_area(x, t)),
        float(binary_auprc_area(x, t)),
        np.asarray(sort_desc(x)[1]),
        np.asarray(argmax_last(x2)),
        np.asarray(correct_mask(x2, t2)),
    )
    saved = native._registered
    native._registered = False
    try:
        without = (
            float(binary_auroc_area(x, t)),
            float(binary_auprc_area(x, t)),
            np.asarray(sort_desc(x)[1]),
            np.asarray(argmax_last(x2)),
            np.asarray(correct_mask(x2, t2)),
        )
    finally:
        native._registered = saved
    np.testing.assert_allclose(with_native[0], without[0], rtol=1e-5)
    np.testing.assert_allclose(with_native[1], without[1], rtol=1e-5)
    for a, b in zip(with_native[2:], without[2:]):
        np.testing.assert_array_equal(a, b)
