// Stable descending argsort — C++ XLA custom-call (CPU host kernel).
//
// The curve metrics (AUROC / AUPRC / PR-curve) are sort-bound on CPU: XLA
// lowers jnp.argsort to a single-threaded comparison sort (~100 ms for
// 262k floats) while this LSD radix sort over the IEEE-754 total-order key
// runs in ~5-10 ms. Registered for the CPU backend only; TPU lowers the
// pure-XLA sort onto its own sort unit. Parity role: torch.sort's radix
// path that the reference's TorchScript curve kernels lean on (reference
// functional/classification/auroc.py:115-152).
//
// Inputs:  scores (T, N) f32.
// Outputs: sorted (T, N) f32 descending, order (T, N) s32 — stable: ties
//          keep ascending original index, exactly like
//          jnp.argsort(-x, stable=True); NaNs (either sign) sort last,
//          also matching it.

#include <cstdint>
#include <cstring>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// Ascending order of the returned key == stable descending score order.
// F(b) is the standard IEEE total-order map (ascending F == ascending x);
// the complement flips it to descending. Two remaps pin bit-exact parity
// with XLA CPU's comparator: positive NaNs would otherwise sort first, so
// they move past -Inf's key (negative NaNs already land there, matching
// NaN-last argsort(-x)); and XLA CPU compares with flush-to-zero, so ±0
// and every subnormal collapse into one stable tie class keyed as +0.
inline uint32_t DescKey(float x) {
  uint32_t b;
  std::memcpy(&b, &x, sizeof(b));
  const uint32_t mag = b & 0x7FFFFFFFu;
  const uint32_t f = (b & 0x80000000u) ? ~b : (b | 0x80000000u);
  uint32_t k = ~f;
  if (mag > 0x7F800000u) k = 0xFFFFFFFFu;  // NaN (either sign): last
  if (mag < 0x00800000u) k = 0x7FFFFFFFu;  // zero/subnormal: +0's key
  return k;
}

// LSD radix argsort, parameterized on the digit plan. All histograms are
// built in the SAME pass that builds the keys (one read of the data
// instead of one per radix pass); digits whose histogram is a single
// bucket skip their scatter entirely (common for real data: the sign /
// top-exponent digit is near-constant). Stability is the LSD invariant
// and is digit-width independent.
template <int kPasses, int kBits>
void RadixImpl(const float* x, int64_t n, float* sorted_out,
               int32_t* order_out, uint32_t* k0, int32_t* i0, uint32_t* k1,
               int32_t* i1) {
  constexpr int kBuckets = 1 << kBits;
  constexpr uint32_t kMask = kBuckets - 1;
  int64_t hist[kPasses][kBuckets] = {};
  for (int64_t i = 0; i < n; ++i) {
    const uint32_t k = DescKey(x[i]);
    k0[i] = k;
    i0[i] = static_cast<int32_t>(i);
    for (int p = 0; p < kPasses; ++p) {
      // the final digit has fewer than kBits significant bits; the shift
      // alone zeroes the excess, so one mask serves every pass
      ++hist[p][(k >> (p * kBits)) & kMask];
    }
  }
  uint32_t* ks = k0;
  int32_t* is = i0;
  uint32_t* kd = k1;
  int32_t* id = i1;
  for (int p = 0; p < kPasses; ++p) {
    const int shift = p * kBits;
    const int64_t* h = hist[p];
    if (h[(ks[0] >> shift) & kMask] == n) continue;  // constant digit
    int64_t pos[kBuckets];
    int64_t acc = 0;
    for (int b = 0; b < kBuckets; ++b) {
      pos[b] = acc;
      acc += h[b];
    }
    for (int64_t i = 0; i < n; ++i) {
      const int64_t dest = pos[(ks[i] >> shift) & kMask]++;
      kd[dest] = ks[i];
      id[dest] = is[i];
    }
    std::swap(ks, kd);
    std::swap(is, id);
  }
  for (int64_t i = 0; i < n; ++i) {
    order_out[i] = is[i];
    sorted_out[i] = x[is[i]];
  }
}

void RadixArgsortDesc(const float* x, int64_t n, float* sorted_out,
                      int32_t* order_out, uint32_t* k0, int32_t* i0,
                      uint32_t* k1, int32_t* i1) {
  if (n == 0) {
    return;  // ks[0] (a size-0 vector's data()) must never be read; the
             // Python dispatchers route empty inputs to XLA, this guards
             // direct FFI callers
  }
  if (n >= 4096) {
    // 11+11+10 bits: three data sweeps instead of four; the 2^11-entry
    // tables (~64 KiB of stack across hist+pos) stay cache-resident
    RadixImpl<3, 11>(x, n, sorted_out, order_out, k0, i0, k1, i1);
  } else {
    // small rows (vmapped per-class curves): 8-bit tables cost less to
    // zero and prefix-sum than the row costs to sort
    RadixImpl<4, 8>(x, n, sorted_out, order_out, k0, i0, k1, i1);
  }
}

}  // namespace

namespace {

// One-pass trapezoidal AUROC over the descending-sorted (FP, TP) curve
// with tie-run compaction — the fused equivalent of roc_cumulators +
// auroc_from_cumulators (_curve_kernels.py): area accrues only at run
// ends, origin (0,0) implied, degenerate single-class input -> 0.5.
// ``w == nullptr`` means unweighted (all-ones).
double AurocFromSorted(const float* s, const float* l, const float* w,
                       const int32_t* order, int64_t n) {
  double tp = 0.0, fp = 0.0, prev_tp = 0.0, prev_fp = 0.0, area = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    const int32_t o = order[i];
    const double wi = w ? w[o] : 1.0;
    const double li = l[o];
    tp += wi * li;
    fp += wi * (1.0 - li);
    if (i == n - 1 || s[i] != s[i + 1]) {  // tie-run end
      area += (fp - prev_fp) * (tp + prev_tp) * 0.5;
      prev_tp = tp;
      prev_fp = fp;
    }
  }
  const double denom = tp * fp;
  // == 0 (not > 0): NaN or negative weights must flow through the division
  // exactly like the XLA branch's where(factor == 0, 0.5, area / factor)
  return denom == 0.0 ? 0.5 : area / denom;
}

// One-pass left-Riemann AUPRC (unweighted counts, reference convention):
// sum over tie-runs of (delta tp) * precision(run end) / total positives,
// terminal (p=1, r=0) point implied; no positives -> 0.
double AuprcFromSorted(const float* s, const float* l, const int32_t* order,
                       int64_t n) {
  double tp = 0.0, count = 0.0, prev_tp = 0.0, area = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    tp += l[order[i]];
    count += 1.0;
    if (i == n - 1 || s[i] != s[i + 1]) {
      area += (tp - prev_tp) * (tp / count);
      prev_tp = tp;
    }
  }
  return tp == 0.0 ? 0.0 : area / tp;  // NaN labels propagate, as in XLA
}

}  // namespace

namespace {

// Shared driver: validate (tasks, n) layout, argsort each task row, apply
// ``fn(sorted, order, task)`` for the per-task area.
template <typename Fn>
ffi::Error ForEachTaskSorted(const ffi::Buffer<ffi::F32>& scores,
                             float* out, Fn&& fn) {
  const auto dims = scores.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("scores must be rank 2 (tasks, n)");
  }
  const int64_t tasks = dims[0];
  const int64_t n = dims[1];
  const float* x = scores.typed_data();
  std::vector<uint32_t> k0(n), k1(n);
  std::vector<int32_t> i0(n), i1(n);
  std::vector<float> sorted(n);
  std::vector<int32_t> order(n);
  for (int64_t t = 0; t < tasks; ++t) {
    RadixArgsortDesc(x + t * n, n, sorted.data(), order.data(), k0.data(),
                     i0.data(), k1.data(), i1.data());
    out[t] = static_cast<float>(fn(sorted.data(), order.data(), t, n));
  }
  return ffi::Error::Success();
}

}  // namespace

static ffi::Error BinaryAurocImpl(ffi::Buffer<ffi::F32> scores,
                                  ffi::Buffer<ffi::F32> labels,
                                  ffi::Buffer<ffi::F32> weights,
                                  int64_t has_weight,
                                  ffi::ResultBuffer<ffi::F32> auroc) {
  const float* l = labels.typed_data();
  const float* w = has_weight ? weights.typed_data() : nullptr;
  return ForEachTaskSorted(
      scores, auroc->typed_data(),
      [&](const float* sorted, const int32_t* order, int64_t t, int64_t n) {
        return AurocFromSorted(sorted, l + t * n,
                               w ? w + t * n : nullptr, order, n);
      });
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(BinaryAuroc, BinaryAurocImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Attr<int64_t>("has_weight")
                                  .Ret<ffi::Buffer<ffi::F32>>());

static ffi::Error BinaryAuprcImpl(ffi::Buffer<ffi::F32> scores,
                                  ffi::Buffer<ffi::F32> labels,
                                  ffi::ResultBuffer<ffi::F32> auprc) {
  const float* l = labels.typed_data();
  return ForEachTaskSorted(
      scores, auprc->typed_data(),
      [&](const float* sorted, const int32_t* order, int64_t t, int64_t n) {
        return AuprcFromSorted(sorted, l + t * n, order, n);
      });
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(BinaryAuprc, BinaryAuprcImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

static ffi::Error SortDescImpl(ffi::Buffer<ffi::F32> scores,
                               ffi::ResultBuffer<ffi::F32> sorted,
                               ffi::ResultBuffer<ffi::S32> order) {
  const auto dims = scores.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("scores must be rank 2 (tasks, n)");
  }
  const int64_t tasks = dims[0];
  const int64_t n = dims[1];
  const float* x = scores.typed_data();
  float* s = sorted->typed_data();
  int32_t* o = order->typed_data();

  std::vector<uint32_t> k0(n), k1(n);
  std::vector<int32_t> i0(n), i1(n);
  for (int64_t t = 0; t < tasks; ++t) {
    RadixArgsortDesc(x + t * n, n, s + t * n, o + t * n, k0.data(), i0.data(),
                     k1.data(), i1.data());
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(SortDesc, SortDescImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>());
