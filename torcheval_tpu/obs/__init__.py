"""Unified observability for the eval stack (docs/observability.md).

The eval-loop contract this library is built on — cheap ``update()`` per
step, expensive ``sync_and_compute()`` occasionally — only holds in
production when operators can SEE where time, bytes, and retries go.
Before this subsystem that signal was scattered: ``utils.CompileCounter``
(retraces), ``resilience.SyncHealth`` (sync attempts/degradations),
``SyncProvenance`` (who contributed), payload byte counts (bench-only),
elastic snapshot timings (session-local). ``torcheval_tpu.obs`` gives it
one home, in the shape real collective stacks ship telemetry (Prime
Collective Communications Library technical report, arxiv 2505.14065;
EQuARX's byte/overhead accounting, arxiv 2506.17615):

- **Events** (:mod:`~torcheval_tpu.obs.events`): typed lifecycle records
  — ``UpdateEvent``/``ComputeEvent`` (metric core), ``SyncEvent``
  (provenance + wire bytes), ``RetryEvent`` (resilience retries,
  degradations, re-formations), ``SnapshotEvent``/``RestoreEvent``
  (elastic), ``CompileEvent`` (XLA program demands), ``SpanEvent`` (user
  phases) — stamped with monotonic + wall time and the step cursor.
- **Recorder** (:mod:`~torcheval_tpu.obs.recorder`): the process-global
  sink. OFF by default and near-zero-cost when off — every instrumented
  site guards on one attribute read; recording adds no host syncs and no
  collectives to any step path (pinned by tier-1 tests). ``span()``
  phases also land in XLA traces via ``jax.profiler.TraceAnnotation``.
- **Counters** (:mod:`~torcheval_tpu.obs.counters`):
  ``CounterRegistry`` federates the existing counters (CompileCounter,
  ``default_sync_health()``, elastic timings) behind one read API
  without touching their call sites.
- **Exporters** (:mod:`~torcheval_tpu.obs.export`): async JSONL writer,
  ``render_prometheus()`` text exposition, ``format_report()`` human
  table, and ``gather_observability(group)`` — one collective merging
  every rank's summary for the leader.

Enable with ``config.observability(...)``, ``obs.enable()``, or env
``TORCHEVAL_TPU_OBSERVABILITY=1`` (a ``*.jsonl`` value also attaches the
line writer)::

    >>> from torcheval_tpu import obs
    >>> with config.observability(jsonl="/tmp/eval-events.jsonl"):
    ...     for step, batch in enumerate(loader):
    ...         obs.recorder().set_step(step)
    ...         update_collection(metrics, *batch)
    >>> print(obs.format_report())
"""

from torcheval_tpu.obs.counters import CounterRegistry, default_registry
from torcheval_tpu.obs.events import (
    SCHEMA_VERSION,
    AlertEvent,
    AnalysisEvent,
    CompileEvent,
    ComputeEvent,
    DriftEvent,
    Event,
    FailoverEvent,
    MemoryEvent,
    PlaneSyncEvent,
    RegionSyncEvent,
    RestoreEvent,
    RetryEvent,
    SnapshotEvent,
    SpanEvent,
    StallEvent,
    SyncEvent,
    UpdateEvent,
    WireTierEvent,
    event_from_dict,
)
from torcheval_tpu.obs.flight import (
    FLIGHT,
    FlightDiff,
    FlightRecord,
    FlightRecorder,
    diff_flight_rings,
    format_flight,
    gather_flight,
)
from torcheval_tpu.obs.monitor import (
    EwmaStat,
    Monitor,
    SloSpec,
    arm_monitor,
    current_monitor,
    disarm_monitor,
    register_check_hook,
    unregister_check_hook,
)
# The data-quality layer (obs/sketch.py, obs/quality.py) subclasses
# Metric, and metric.py imports obs.recorder — importing it eagerly here
# would close an import cycle whenever `torcheval_tpu.metrics` loads
# first. PEP 562 lazy attributes break the cycle: the modules load on
# first attribute access, by which point the metric core is initialized.
_LAZY_QUALITY = {
    "QUALITY": "quality",
    "DriftSpec": "quality",
    "QualityWatch": "quality",
    "active_watches": "quality",
    "watch_inputs": "quality",
    "InputSketch": "sketch",
    "SketchConfig": "sketch",
    "SketchSummary": "sketch",
    "chan_merge": "sketch",
    "hll_estimate": "sketch",
}


def __getattr__(name):
    module = _LAZY_QUALITY.get(name)
    if module is None:
        raise AttributeError(
            f"module 'torcheval_tpu.obs' has no attribute {name!r}"
        )
    import importlib

    mod = importlib.import_module(f"torcheval_tpu.obs.{module}")
    value = getattr(mod, name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value
from torcheval_tpu.obs.server import (
    ObsServer,
    current_server,
    healthz_payload,
    start_server,
    stop_server,
)
from torcheval_tpu.obs.watchdog import (
    StallWatchdog,
    arm_watchdog,
    current_watchdog,
    disarm_watchdog,
)
from torcheval_tpu.obs.export import (
    JsonlWriter,
    export_chrome_trace,
    format_report,
    gather_observability,
    gather_traces,
    read_jsonl,
    render_prometheus,
)
from torcheval_tpu.obs.hist import LatencyHistogram
from torcheval_tpu.obs.hist import snapshot as latency_snapshot
from torcheval_tpu.obs.memory import (
    logical_state_bytes,
    memory_report,
    metric_update_costs,
    per_rank_state_bytes,
    program_costs,
    state_bytes,
    track_metrics,
)
from torcheval_tpu.obs.recorder import (
    RECORDER,
    EventLog,
    Recorder,
    disable,
    enable,
    enabled,
    recorder,
    span,
)
from torcheval_tpu.obs.trace import trace_path

__all__ = [
    "FLIGHT",
    "QUALITY",
    "SCHEMA_VERSION",
    "AlertEvent",
    "AnalysisEvent",
    "CompileEvent",
    "ComputeEvent",
    "CounterRegistry",
    "DriftEvent",
    "DriftSpec",
    "Event",
    "EventLog",
    "EwmaStat",
    "FailoverEvent",
    "FlightDiff",
    "FlightRecord",
    "FlightRecorder",
    "InputSketch",
    "JsonlWriter",
    "LatencyHistogram",
    "MemoryEvent",
    "Monitor",
    "ObsServer",
    "PlaneSyncEvent",
    "QualityWatch",
    "Recorder",
    "RegionSyncEvent",
    "RestoreEvent",
    "RetryEvent",
    "SketchConfig",
    "SketchSummary",
    "SloSpec",
    "SnapshotEvent",
    "SpanEvent",
    "StallEvent",
    "StallWatchdog",
    "SyncEvent",
    "UpdateEvent",
    "WireTierEvent",
    "active_watches",
    "arm_monitor",
    "arm_watchdog",
    "chan_merge",
    "current_monitor",
    "current_server",
    "current_watchdog",
    "default_registry",
    "diff_flight_rings",
    "disable",
    "disarm_monitor",
    "disarm_watchdog",
    "enable",
    "enabled",
    "event_from_dict",
    "hll_estimate",
    "export_chrome_trace",
    "format_flight",
    "format_report",
    "gather_flight",
    "gather_observability",
    "gather_traces",
    "healthz_payload",
    "latency_snapshot",
    "logical_state_bytes",
    "memory_report",
    "metric_update_costs",
    "program_costs",
    "read_jsonl",
    "recorder",
    "register_check_hook",
    "render_prometheus",
    "span",
    "per_rank_state_bytes",
    "start_server",
    "state_bytes",
    "stop_server",
    "trace_path",
    "track_metrics",
    "unregister_check_hook",
    "watch_inputs",
]
