"""Collective lockstep checker: catch would-deadlock divergence statically.

A multi-rank program deadlocks when any two ranks disagree about the next
collective — a hazard Prime CCL (arXiv:2505.14065) detects *dynamically*
with timeouts and lockstep heartbeats. Here we catch the same hazard
classes *before* the job runs, by extracting each rank's ordered
collective plan and diffing:

- **rank-divergent programs** (:func:`verify_rank_lockstep`): trace the
  per-rank program each member of a mesh/subgroup would run (builders are
  parameterized by rank — the only way per-rank programs differ in this
  library) and diff the ordered (primitive, axes) sequences. Any
  divergence is a would-deadlock finding with the first diverging op's
  jaxpr provenance.
- **branch-dependent collectives** (:func:`check_program_lockstep`): a
  collective under a ``lax.cond`` whose branches carry *different*
  collective sequences deadlocks the moment the predicate differs across
  ranks. Statically, a predicate cannot be proven rank-uniform, so
  asymmetric branches are errors; a collective inside a ``while`` body is
  a warning (the trip count must be rank-uniform — true for this
  library's fixed-size loops, unprovable in general).
- **eager call plans** (:func:`eager_sync_plan` +
  :func:`check_eager_lockstep`): the host-side ``synclib``/toolkit sync
  issues ``ProcessGroup`` collectives whose *sequence depends on the
  metric states* (the payload gather is skipped when every rank's packed
  payload is empty). Recording the plan against a
  :class:`PlanRecordingGroup` — a loop-back group that never
  communicates — and diffing across ranks turns the thread-local
  in-flight-fence discipline (PR 2-3) into a statically checkable
  contract: same metrics, same op sequence, every rank.

All checks share :class:`~torcheval_tpu.analysis.report.Finding` records
with the verifier and the lint, so one JSON report (and the conftest
forensics hook) covers all three layers.
"""

from __future__ import annotations

import copy
import re
from dataclasses import dataclass
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

import jax
import numpy as np

from torcheval_tpu.analysis.program import (
    COLLECTIVE_PRIMITIVES,
    _abstractize,
    _eqn_provenance,
    _sub_jaxprs,
)
from torcheval_tpu.analysis.report import Finding, Report, set_last_report

__all__ = [
    "CollectiveOp",
    "PlanRecordingGroup",
    "check_eager_lockstep",
    "check_program_lockstep",
    "collective_plan",
    "eager_sync_plan",
    "verify_rank_lockstep",
]


@dataclass(frozen=True)
class CollectiveOp:
    """One collective in a program's ordered plan.

    ``axes`` is the named mesh axis (or axis tuple) the op spans;
    ``context`` is the control-flow path from the top level (e.g.
    ``("cond[branch1]",)`` for an op inside a conditional arm);
    ``provenance`` is the user source line the jaxpr records. Two ops
    must agree on ``(name, axes)`` to rendezvous — ``context`` and
    ``provenance`` are diagnostics, excluded from equality checks.
    """

    name: str
    axes: Tuple[str, ...] = ()
    context: Tuple[str, ...] = ()
    provenance: str = ""

    @property
    def key(self) -> Tuple[str, Tuple[str, ...]]:
        return (self.name, self.axes)

    def format(self) -> str:
        where = f" under {'/'.join(self.context)}" if self.context else ""
        axes = f"[{','.join(self.axes)}]" if self.axes else ""
        return f"{self.name}{axes}{where} ({self.provenance})"


def _axes_of(eqn) -> Tuple[str, ...]:
    """Named mesh axes an eqn's collective spans (param spelling varies
    by primitive: psum/pmax/pmin use ``axes``, gather/permute forms use
    ``axis_name``)."""
    params = eqn.params
    raw = params.get("axes", params.get("axis_name", ()))
    if raw is None:
        raw = ()
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return tuple(str(a) for a in raw)


def _iter_with_context(jaxpr, context: Tuple[str, ...] = ()):
    """Program-order (eqn, control-flow-context) pairs, descending into
    every sub-jaxpr. ``cond``/``while`` arms get labeled context entries
    so hazards report *which* arm carries the divergent collective."""
    for eqn in jaxpr.eqns:
        yield eqn, context
        pname = eqn.primitive.name
        if pname == "cond":
            for i, branch in enumerate(eqn.params["branches"]):
                yield from _iter_with_context(
                    branch.jaxpr, context + (f"cond[branch{i}]",)
                )
        elif pname == "while":
            yield from _iter_with_context(
                eqn.params["cond_jaxpr"].jaxpr, context + ("while[cond]",)
            )
            yield from _iter_with_context(
                eqn.params["body_jaxpr"].jaxpr, context + ("while[body]",)
            )
        else:
            label = {"scan": "scan[body]"}.get(pname)
            for sub in _sub_jaxprs(eqn.params):
                yield from _iter_with_context(
                    sub, context + (label,) if label else context
                )


def _plan_of_jaxpr(jaxpr, context=()) -> Tuple[CollectiveOp, ...]:
    ops = []
    for eqn, ctx in _iter_with_context(jaxpr, context):
        if eqn.primitive.name in COLLECTIVE_PRIMITIVES:
            ops.append(
                CollectiveOp(
                    name=eqn.primitive.name,
                    axes=_axes_of(eqn),
                    context=ctx,
                    provenance=_eqn_provenance(eqn),
                )
            )
    return tuple(ops)


def collective_plan(fn, *args: Any) -> Tuple[CollectiveOp, ...]:
    """The ordered collective plan of one traceable program (jaxpr level,
    nothing executes — concrete args are abstracted first)."""
    closed = jax.make_jaxpr(fn)(*(_abstractize(a) for a in args))
    return _plan_of_jaxpr(closed.jaxpr)


# ------------------------------------------------- single-program hazards


def _structural_hazards(jaxpr, label: str) -> List[Finding]:
    """Structural lockstep hazards of one already-traced jaxpr (the
    shared engine of :func:`check_program_lockstep` and
    :func:`verify_rank_lockstep` — each program is traced exactly once)."""
    findings: List[Finding] = []
    for eqn, ctx in _iter_with_context(jaxpr):
        pname = eqn.primitive.name
        if pname == "cond":
            branch_plans = [
                tuple(op.key for op in _plan_of_jaxpr(b.jaxpr))
                for b in eqn.params["branches"]
            ]
            if len(set(branch_plans)) > 1:
                detail = "; ".join(
                    f"branch{i}={list(p)}" for i, p in enumerate(branch_plans)
                )
                findings.append(
                    Finding(
                        tool="lockstep",
                        rule="branch-dependent-collective",
                        path=label,
                        message=(
                            f"cond at {_eqn_provenance(eqn)} has branches "
                            f"with different collective sequences ({detail})"
                            ": if the predicate ever differs across ranks, "
                            "the ranks issue mismatched collectives and "
                            "the job deadlocks"
                        ),
                    )
                )
        elif pname == "while":
            body_ops = _plan_of_jaxpr(eqn.params["body_jaxpr"].jaxpr)
            cond_ops = _plan_of_jaxpr(eqn.params["cond_jaxpr"].jaxpr)
            for op in cond_ops + body_ops:
                # Each collective is attributed to its INNERMOST enclosing
                # while (reported when the walk reaches that eqn); skipping
                # deeper-nested ops here keeps one hazard = one finding.
                if any(c.startswith("while[") for c in op.context):
                    continue
                findings.append(
                    Finding(
                        tool="lockstep",
                        rule="collective-in-loop",
                        path=label,
                        severity="warning",
                        message=(
                            f"collective {op.format()} inside a while at "
                            f"{_eqn_provenance(eqn)}: the trip count must "
                            "be identical on every rank or the collective "
                            "counts diverge (would-deadlock)"
                        ),
                    )
                )
    return findings


def check_program_lockstep(
    fn, *args: Any, name: Optional[str] = None
) -> Report:
    """Structural lockstep hazards of ONE program: asymmetric-branch
    collectives (error — the predicate cannot be proven rank-uniform)
    and collectives under a ``while`` (warning — the trip count must be
    rank-uniform)."""
    label = name or getattr(fn, "__name__", None) or "<program>"
    report = Report(tool="lockstep")
    report.checked = 1
    closed = jax.make_jaxpr(fn)(*(_abstractize(a) for a in args))
    report.findings.extend(_structural_hazards(closed.jaxpr, label))
    return set_last_report(report)


# --------------------------------------------------- per-rank program diff


def _diff_plans(
    report: Report,
    label: str,
    rule: str,
    plans: Mapping[Any, Sequence[Any]],
    fmt: Callable[[Any], str],
) -> None:
    """Diff every member's ordered plan against the first member's; emit
    one finding per diverging member at the first point of divergence."""
    members = sorted(plans)
    base_member = members[0]
    base = list(plans[base_member])
    for member in members[1:]:
        plan = list(plans[member])
        if [getattr(p, "key", p) for p in plan] == [
            getattr(p, "key", p) for p in base
        ]:
            continue
        # first index where the two plans disagree (or one runs out)
        i = 0
        while (
            i < len(base)
            and i < len(plan)
            and getattr(base[i], "key", base[i])
            == getattr(plan[i], "key", plan[i])
        ):
            i += 1
        mine = fmt(plan[i]) if i < len(plan) else "<no further collectives>"
        theirs = fmt(base[i]) if i < len(base) else "<no further collectives>"
        report.findings.append(
            Finding(
                tool="lockstep",
                rule=rule,
                path=label,
                message=(
                    f"rank {member} diverges from rank {base_member} at "
                    f"collective #{i}: {mine} vs {theirs} — mismatched "
                    "collectives never rendezvous; the job deadlocks at "
                    f"this op (full plans: rank {base_member}="
                    f"{[fmt(p) for p in base]}, rank {member}="
                    f"{[fmt(p) for p in plan]})"
                ),
            )
        )


def verify_rank_lockstep(
    build_fn: Callable[[int], Callable],
    ranks: Iterable[int],
    *args: Any,
    name: Optional[str] = None,
    check_structure: bool = True,
) -> Report:
    """Trace ``build_fn(rank)`` for every member and diff the ordered
    collective plans — the static form of "every rank must issue the
    identical collective sequence".

    ``build_fn`` returns the traceable program rank ``r`` would run
    (SPMD programs are rank-independent by construction and trivially
    pass; the hazard is rank-parameterized construction — leader-only
    reductions, rank-gated branches). ``args`` may be concrete or
    abstract; nothing executes. With ``check_structure`` each per-rank
    program is also checked for the structural hazards of
    :func:`check_program_lockstep`, from the same single trace per rank.
    """
    label = name or getattr(build_fn, "__name__", None) or "<program>"
    report = Report(tool="lockstep")
    plans: Dict[int, Tuple[CollectiveOp, ...]] = {}
    abstract_args = tuple(_abstractize(a) for a in args)
    for rank in ranks:
        closed = jax.make_jaxpr(build_fn(rank))(*abstract_args)
        plans[rank] = _plan_of_jaxpr(closed.jaxpr)
        report.checked += 1
        if check_structure:
            report.findings.extend(
                _structural_hazards(closed.jaxpr, f"{label}[rank {rank}]")
            )
    if plans:
        _diff_plans(
            report,
            label,
            "rank-divergent-collective",
            plans,
            lambda op: op.format(),
        )
    return set_last_report(report)


# ------------------------------------------------------- eager call plans


class PlanRecordingGroup:
    """A loop-back :class:`~torcheval_tpu.distributed.ProcessGroup` that
    RECORDS the collective call plan instead of communicating.

    Every gather returns ``world_size`` copies of the local payload, so
    the sync protocol runs to completion in-process — a dry run of the
    eager plan, no wire, no peers. ``calls`` is the ordered op-name
    sequence (with LOCAL payload byte sizes for array gathers —
    forensics only; :func:`check_eager_lockstep` strips them before
    diffing, since the padded protocol makes fill level rank-local) the dry run
    issued — what a real group would be asked to perform *given this
    rank's local view* (globally-coordinated decisions, e.g. the
    all-ranks-empty payload skip, can differ; see
    :func:`eager_sync_plan`).
    """

    def __init__(self, world_size: int = 2, rank: int = 0):
        self._world = int(world_size)
        self._rank = int(rank)
        self.calls: List[str] = []

    # --- ProcessGroup surface (duck-typed; synclib dispatches on unwrap)

    @property
    def world_size(self) -> int:
        return self._world

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def is_member(self) -> bool:
        return True

    @property
    def ranks(self) -> Tuple[int, ...]:
        return tuple(range(self._world))

    def unwrap(self):
        return self

    def allgather_object(self, obj: Any) -> List[Any]:
        self.calls.append("allgather_object")
        return [copy.deepcopy(obj) for _ in range(self._world)]

    def allgather_array(self, x: Any) -> List[np.ndarray]:
        arr = np.asarray(x)
        self.calls.append(f"allgather_array[{arr.nbytes}B]")
        return [arr.copy() for _ in range(self._world)]

    def allgather_object_with_ranks(self, obj: Any):
        return self.allgather_object(obj), list(range(self._world))

    def allgather_array_with_ranks(self, x: Any):
        return self.allgather_array(x), list(range(self._world))


def _array_leaves(value: Any):
    if isinstance(value, dict):
        for v in value.values():
            yield from _array_leaves(v)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _array_leaves(v)
    elif hasattr(value, "shape") and hasattr(value, "dtype"):
        yield value


def eager_sync_plan(
    metrics: Mapping[str, Any],
    *,
    world_size: int = 2,
    rank: int = 0,
) -> Tuple[str, ...]:
    """The ordered ``ProcessGroup`` op sequence one rank's eager
    collection sync would issue for ``metrics`` (``{name: Metric}``):
    one metadata ``allgather_object`` — annotated with the state
    traversal order, the framing every rank must agree on — plus one
    payload ``allgather_array`` when the collection carries any
    array-valued state.

    The protocol is dry-run to completion against a
    :class:`PlanRecordingGroup` (no wire, no peers; metrics are
    deep-copied so buffered states are not consumed), but the returned
    plan is computed from the collection's STRUCTURE, not this rank's
    fill level: the real protocol pads payloads to the global max and
    skips the payload gather only by *global* agreement, so local byte
    counts must not (and here cannot) fake a divergence.

    One deliberate over-approximation follows: when EVERY rank's packed
    payload is empty (e.g. a collection of buffered metrics synced
    before any update), the real protocol skips the payload gather by
    that same global agreement, while this plan still lists it. The
    skip is rank-uniform by construction — the decision rides the
    metadata every rank just exchanged — so it can never deadlock and
    never produces a divergence finding; the plan simply errs on the
    side of listing every op the structure can require."""
    from torcheval_tpu.metrics import synclib

    group = PlanRecordingGroup(world_size=world_size, rank=rank)
    states = {
        name: copy.deepcopy(m)._sync_state_dict()
        for name, m in metrics.items()
    }
    order = synclib.metrics_traversal_order(states)
    synclib.sync_states(states, group)  # dry run: the protocol must work
    plan = [
        "allgather_object["
        + ",".join(f"{m}.{s}" for m, s in order)
        + "]"
    ]
    if any(
        True
        for m, s in order
        for _ in _array_leaves(states[m][s])
    ):
        plan.append("allgather_array")
    return tuple(plan)


# PlanRecordingGroup annotates array gathers with the LOCAL payload byte
# count (useful forensics); the real protocol pads payloads to the global
# max, so local sizes must be ignored when diffing or two ranks that
# differ only in fill level would read as divergent.
_LOCAL_SIZE = re.compile(r"\[\d+B\]")


def check_eager_lockstep(
    plans: Mapping[int, Sequence[str]], *, name: str = "<eager sync>"
) -> Report:
    """Diff per-rank eager call plans (from :func:`eager_sync_plan`, or
    hand-recorded via :class:`PlanRecordingGroup`). Any divergence —
    op kind or payload framing — is a would-deadlock finding: the ranks
    would issue mismatched (or differently-counted) group collectives.

    Local payload byte-size annotations (``allgather_array[40B]``) are
    stripped before comparison: the padded protocol makes fill level a
    per-rank free variable, never a lockstep hazard (the same
    normalization :func:`eager_sync_plan` gets by construction)."""
    report = Report(tool="lockstep")
    report.checked = len(plans)
    if plans:
        _diff_plans(
            report,
            name,
            "eager-plan-divergence",
            {
                r: [_LOCAL_SIZE.sub("", str(op)) for op in p]
                for r, p in plans.items()
            },
            str,
        )
    return set_last_report(report)
