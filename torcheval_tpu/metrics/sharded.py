"""In-jit metric state sync: collectives fused into the step program.

The reference's fastest path still leaves jit to sync (pickle + gloo/NCCL,
reference toolkit.py:388). On TPU we can do strictly better: when the
training/eval step runs under ``pjit``/``shard_map`` over a Mesh, metric
states live in the step's carry and cross-replica sync is a single
``lax.psum``/``pmax``/``all_gather`` *inside* the compiled program — zero
host round-trips, overlapped with the step's other collectives by XLA. This
module provides that path, driven by the same declarative ``MergeKind``
metadata the eager merge uses.

Typical use (data-parallel eval with in-step metrics)::

    acc = MulticlassAccuracy()          # template: holds specs, not data
    specs = state_merge_specs(acc)

    @partial(shard_map, mesh=mesh, in_specs=(P("dp"), P("dp"), P()), out_specs=P())
    def eval_step(x, y, state):
        logits = model(x)
        num_correct, num_total = _multiclass_accuracy_update(
            logits, y, "micro", None, 1)
        local = {"num_correct": num_correct, "num_total": num_total}
        return sync_states_in_jit(tree_add(state, local), "dp", specs)

The synced state can be loaded back into the class metric with
``metric.load_state_dict`` for reporting/checkpointing.

Variable-shape eval (shape bucketing): the mask-aware kernel twins
(``*_update_masked``, see torcheval_tpu/metrics/_bucket.py) drop into this
path unchanged — pad the per-replica batch to its bucket outside the step,
pass the valid-extent vector as one extra (replicated or per-replica)
argument, and accumulate the masked kernel's deltas into the same carry::

    nc, nt = _multiclass_accuracy_update_masked(
        logits_padded, y_padded, valid_sizes, "micro", None, 1)

Masking is a LOCAL concern: state shapes and merge kinds are identical to
the unmasked path, so ``sync_states_in_jit`` lowers to the exact same
collectives — zero added to the step program
(tests/metrics/test_retrace_guard.py pins this structurally).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from torcheval_tpu.metrics.metric import MergeKind, Metric


def state_merge_specs(metric: Metric) -> Dict[str, MergeKind]:
    """The declarative merge semantics registered by ``_add_state``."""
    return dict(metric._state_name_to_merge_kind)


def sync_states_in_jit(
    states: Dict[str, Any],
    axis_name: str,
    specs: Optional[Dict[str, MergeKind]] = None,
) -> Dict[str, Any]:
    """Merge per-replica metric states across a named mesh axis, inside jit.

    - ``SUM`` counters -> ``lax.psum`` (one fused all-reduce over ICI),
    - ``MAX``/``MIN`` -> ``lax.pmax``/``pmin``,
    - ``EXTEND`` buffers -> ``lax.all_gather`` + flatten along the example
      axis. Static-shape precondition: per-replica buffers must be
      equal-sized. The fixed-shape buffer layer
      (``torcheval_tpu.metrics._buffer``) guarantees this under SPMD — every
      replica performs the same update sequence, so capacities match — and
      its pad-neutral fills mean the padding interleaved in the flattened
      gather is harmless to the padded-buffer compute kernels.

    ``specs`` defaults to SUM for every state. Unknown/CUSTOM kinds raise:
    bespoke merges cannot be lowered generically — sync those eagerly via
    the toolkit.

    All same-kind, same-dtype states are fused into ONE collective
    (flatten-concat -> psum/pmax/pmin -> split): a whole metric collection
    syncs in a handful of collectives regardless of state count — the in-jit
    analogue of the reference's single batched ``all_gather_object`` for
    collections (reference toolkit.py:263-334).
    """
    synced: Dict[str, Any] = {}
    reduce_groups: Dict[Any, list] = {}  # (kind, dtype) -> [(name, value)]
    reducers = {
        MergeKind.SUM: lax.psum,
        MergeKind.MAX: lax.pmax,
        MergeKind.MIN: lax.pmin,
    }
    for name, value in states.items():
        kind = (specs or {}).get(name, MergeKind.SUM)
        if kind in reducers:
            value = jnp.asarray(value)
            reduce_groups.setdefault((kind, value.dtype), []).append(
                (name, value)
            )
        elif kind is MergeKind.EXTEND:
            # Gather-as-psum: scatter the local shard into a zero [world, ...]
            # buffer at this replica's index, then all-reduce. Semantically an
            # all_gather, but psum's output is statically known to be
            # replicated, which shard_map's replication checker requires for
            # un-partitioned out_specs (lax.all_gather is not so marked).
            world = lax.psum(1, axis_name)
            idx = lax.axis_index(axis_name)
            buf = jnp.zeros((world,) + value.shape, value.dtype).at[idx].set(value)
            gathered = lax.psum(buf, axis_name)
            synced[name] = jnp.reshape(
                gathered, (-1,) + tuple(value.shape[1:])
            )
        else:
            raise NotImplementedError(
                f"State {name!r} has merge kind {kind}; custom merges must "
                "use the eager toolkit sync."
            )

    for (kind, _dtype), group in reduce_groups.items():
        reducer = reducers[kind]
        if len(group) == 1:
            name, value = group[0]
            synced[name] = reducer(value, axis_name)
            continue
        flat = jnp.concatenate([v.ravel() for _, v in group])
        merged = reducer(flat, axis_name)
        offset = 0
        for name, value in group:
            synced[name] = merged[offset:offset + value.size].reshape(
                value.shape
            )
            offset += value.size
    return synced


def tree_add(state: Dict[str, Any], delta: Dict[str, Any]) -> Dict[str, Any]:
    """Accumulate an update's counter deltas into the carried state."""
    return jax.tree_util.tree_map(lambda a, b: a + b, state, delta)
