#!/usr/bin/env python
"""One-time EXTERNAL capture: FID golden under real torchvision weights.

This image has no egress and no torchvision, so the published-checkpoint
attestation (VERDICT r4 missing #2) cannot be produced in-repo. Run this
script once on any machine with ``torchvision`` installed and the
pretrained ``inception_v3`` weights downloadable:

    python scripts/capture_fid_realweights_golden.py

It writes ``tests/metrics/image/golden_fid_realweights.npz`` containing:

- ``real_images`` / ``fake_images``: committed uint8 NCHW inputs (the
  image bytes ship in the artifact, so there is no generation-drift risk
  between capturer and verifier);
- ``real_features`` / ``fake_features``: 2048-d pooled activations from
  the REFERENCE pipeline — torchvision ``inception_v3(weights="DEFAULT")``
  with ``fc`` removed, 299x299 bilinear interpolation,
  ``align_corners=False`` (reference torcheval/metrics/image/fid.py:28-50);
- ``fid``: the Frechet distance between the two feature sets (float64
  numpy, eigendecomposition sqrtm);
- ``weight_sha256``: digest over the sorted state-dict tensors, so a
  verifier proves it loaded the same checkpoint before comparing.

Commit the npz; ``tests/metrics/image/test_fid_realweights_golden.py``
then asserts the Flax port + weight mapping reproduce these numbers
wherever the weights are available (e.g. the fid_golden CI workflow).

With ``--check``, re-captures and compares against the committed npz
instead of overwriting it.
"""

from __future__ import annotations

import argparse
import hashlib
import os

import numpy as np

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "metrics", "image", "golden_fid_realweights.npz",
)
N, C, H, W = 16, 3, 64, 64
SEED = 20260731


def golden_images() -> tuple[np.ndarray, np.ndarray]:
    """Deterministic uint8 NCHW image batches (smooth structure + noise —
    enough signal that the two sets have distinct feature statistics)."""
    rng = np.random.default_rng(SEED)
    yy, xx = np.mgrid[0:H, 0:W].astype(np.float32)
    base = np.stack(
        [np.sin(yy / 7.0 + c) * np.cos(xx / 9.0 - c) for c in range(C)]
    )  # (C, H, W) in [-1, 1]
    real = 0.5 + 0.35 * base[None] + 0.15 * rng.standard_normal((N, C, H, W))
    fake = 0.5 - 0.25 * base[None] + 0.25 * rng.standard_normal((N, C, H, W))
    to_u8 = lambda a: (np.clip(a, 0.0, 1.0) * 255.0).round().astype(np.uint8)
    return to_u8(real), to_u8(fake)


def state_dict_sha256(state_dict) -> str:
    h = hashlib.sha256()
    for name in sorted(state_dict):
        h.update(name.encode())
        h.update(np.ascontiguousarray(state_dict[name]).tobytes())
    return h.hexdigest()


def fid_from_features(fr: np.ndarray, ff: np.ndarray) -> float:
    """Frechet distance in float64 (PSD sqrtm via eigendecomposition)."""
    fr, ff = fr.astype(np.float64), ff.astype(np.float64)
    mu_r, mu_f = fr.mean(0), ff.mean(0)
    cov_r, cov_f = np.cov(fr, rowvar=False), np.cov(ff, rowvar=False)
    w, v = np.linalg.eigh(cov_r)
    sqrt_r = (v * np.sqrt(np.clip(w, 0, None))) @ v.T
    m = sqrt_r @ cov_f @ sqrt_r
    w2 = np.linalg.eigvalsh(m)
    tr_sqrt = np.sqrt(np.clip(w2, 0, None)).sum()
    d = mu_r - mu_f
    return float(d @ d + np.trace(cov_r) + np.trace(cov_f) - 2.0 * tr_sqrt)


def capture():
    import torch
    import torch.nn.functional as F
    from torchvision import models

    model = models.inception_v3(weights="DEFAULT")
    sha = state_dict_sha256(
        {k: v.detach().numpy() for k, v in model.state_dict().items()}
    )
    model.fc = torch.nn.Identity()
    model.eval()

    real_u8, fake_u8 = golden_images()

    def features(u8: np.ndarray) -> np.ndarray:
        x = torch.tensor(u8.astype(np.float32) / 255.0)
        with torch.no_grad():
            x = F.interpolate(
                x, size=(299, 299), mode="bilinear", align_corners=False
            )
            return model(x).numpy()

    fr, ff = features(real_u8), features(fake_u8)
    return {
        "real_images": real_u8,
        "fake_images": fake_u8,
        "real_features": fr,
        "fake_features": ff,
        "fid": np.float64(fid_from_features(fr, ff)),
        "weight_sha256": np.bytes_(sha.encode()),
        "seed": np.int64(SEED),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="compare a fresh capture against the committed npz")
    args = ap.parse_args()

    data = capture()
    if args.check:
        with np.load(OUT) as committed:
            np.testing.assert_array_equal(
                committed["real_images"], data["real_images"]
            )
            assert (
                bytes(committed["weight_sha256"]) == bytes(data["weight_sha256"])
            ), "different checkpoint than the committed capture"
            np.testing.assert_allclose(
                committed["real_features"], data["real_features"],
                rtol=1e-4, atol=1e-4,
            )
            np.testing.assert_allclose(
                float(committed["fid"]), float(data["fid"]), rtol=1e-4
            )
        print(f"check ok: {OUT} matches a fresh capture "
              f"(fid={float(data['fid']):.6f})")
    else:
        np.savez_compressed(OUT, **data)
        print(f"wrote {OUT} (fid={float(data['fid']):.6f}, "
              f"weights sha256={bytes(data['weight_sha256']).decode()[:16]}…)")


if __name__ == "__main__":
    main()
