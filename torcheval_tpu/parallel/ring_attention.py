"""Ring attention: exact attention over sequence-sharded inputs.

Long-context evaluation shards the sequence axis across devices; computing
exact attention then requires every query block to see every key/value
block. Ring attention does this with O(seq/P) memory per device and P-1
``lax.ppermute`` hops over the ICI ring: each step combines the resident
query block with the currently-held K/V block using the online-softmax
(flash) accumulation, then rotates K/V to the next device — communication
fully overlappable with compute by XLA.

The reference has no sequence parallelism (it is a metrics library;
SURVEY.md section 5.7) — this primitive exists so the *evaluation* stack
(flagship model forward + metric updates, see ``__graft_entry__``) scales to
long sequences the way the surrounding TPU training stack does. The
blockwise formulation follows the public ring-attention recipe (Liu et al.,
2023, arXiv:2310.01889).

Use inside ``shard_map`` over a mesh with a sequence axis::

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, "sp", None, None),) * 3,
             out_specs=P(None, "sp", None, None))
    def attn(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=True)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from torcheval_tpu.utils.vma import pcast_varying, union_vary_axes

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/max() NaN-free


def _block_attend(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_offset: jax.Array,
    kv_offset: jax.Array,
    causal: bool,
    scale: float,
):
    """Scores of one (q-block, kv-block) pair with global-position causal
    masking. Shapes: q (B, nq, H, D), k/v (B, nk, H, D)."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    return scores


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact multi-head attention over a sequence-sharded (B, S/P, H, D)
    layout; must be called inside ``shard_map``/``pjit`` with ``axis_name``
    naming the sequence mesh axis.

    Returns the local (B, S/P, H, D) output block. Numerically equivalent to
    dense softmax attention over the gathered sequence (online-softmax
    accumulation is exact, not approximate).
    """
    num_shards = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    batch, nq, heads, dim = q.shape
    scale = scale if scale is not None else dim ** -0.5
    block = nq  # equal-size sequence blocks per device

    q_offset = my_index * block

    # running online-softmax state; the scan carry must be varying over
    # the union of the inputs' manual axes (k/v can vary over axes q does
    # not, e.g. per-replica KV caches) — see utils/vma.py
    vary_axes = union_vary_axes(q, k, v, axis_name=axis_name)

    def _varying(x):
        return pcast_varying(x, vary_axes)

    acc = _varying(jnp.zeros((batch, heads, nq, dim), jnp.float32))
    denom = _varying(jnp.zeros((batch, heads, nq), jnp.float32))
    running_max = _varying(jnp.full((batch, heads, nq), NEG_INF, jnp.float32))

    def step(carry, _):
        acc, denom, running_max, k_blk, v_blk, kv_index = carry
        kv_offset = kv_index * block
        scores = _block_attend(q, k_blk, v_blk, q_offset, kv_offset, causal, scale)
        blk_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(running_max, blk_max)
        correction = jnp.exp(running_max - new_max)
        p = jnp.exp(scores - new_max[..., None])
        denom = denom * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        # rotate K/V one hop around the ring (device i -> i+1)
        perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        kv_index = lax.ppermute(kv_index, axis_name, perm)
        return (acc, denom, new_max, k_blk, v_blk, kv_index), None

    carry = (acc, denom, running_max, k, v, my_index)
    carry, _ = lax.scan(step, carry, None, length=num_shards)
    acc, denom, _, _, _, _ = carry

    # fully-masked rows cannot occur under causal=True (each q sees itself);
    # guard anyway so non-causal edge shards stay finite
    out = acc / jnp.maximum(denom, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def dense_reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Unsharded oracle with identical semantics (tests / single device)."""
    dim = q.shape[-1]
    scale = scale if scale is not None else dim ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        nq, nk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(nq)[:, None] >= jnp.arange(nk)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
