"""Minimal one-per-state-type metrics for base/toolkit/sync tests.

Parity with reference torcheval/utils/test_utils/dummy_metric.py: a tensor
state, a list state, and a dict state variant of a trivial sum metric.
"""

from __future__ import annotations

from typing import Iterable, Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import MergeKind, Metric

TDummySumMetric = TypeVar("TDummySumMetric")


class DummySumMetric(Metric[jax.Array]):
    """Sums scalar updates into a tensor state."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("sum", jnp.zeros(()), merge=MergeKind.SUM)

    def update(self, x) -> "DummySumMetric":
        self.sum = self.sum + self._input_float(x)
        return self

    def compute(self) -> jax.Array:
        return self.sum


class DummySumListStateMetric(Metric[jax.Array]):
    """Buffers every update in a list state."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("x", [], merge=MergeKind.EXTEND)

    def update(self, x) -> "DummySumListStateMetric":
        self.x.append(self._input_float(x))
        return self

    def compute(self) -> jax.Array:
        return jnp.asarray(sum(t.sum() for t in self.x))


class DummySumDictStateMetric(Metric[jax.Array]):
    """Keyed sums in a dict state."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("x", {}, merge=MergeKind.SUM)

    def update(self, k: str, v) -> "DummySumDictStateMetric":
        self.x[k] = self.x[k] + self._input_float(v)
        return self

    def compute(self):
        return self.x
