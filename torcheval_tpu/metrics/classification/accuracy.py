"""Accuracy class metrics.

Parity: reference torcheval/metrics/classification/accuracy.py
(MulticlassAccuracy :32, BinaryAccuracy :151, MultilabelAccuracy :215,
TopKMultilabelAccuracy :317). The classes only own counter accumulation;
all math lives in the jitted functional kernels.
"""

from __future__ import annotations

from typing import Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.accuracy import (
    _accuracy_compute,
    _accuracy_param_check,
    _accuracy_update_input_check,
    _binary_accuracy_update,
    _binary_accuracy_update_input_check,
    _binary_accuracy_update_masked,
    _multiclass_accuracy_update,
    _multiclass_accuracy_update_masked,
    _multilabel_accuracy_param_check,
    _multilabel_accuracy_update,
    _multilabel_accuracy_update_input_check,
    _multilabel_accuracy_update_masked,
    _topk_multilabel_accuracy_param_check,
    _topk_multilabel_accuracy_update,
    _topk_multilabel_accuracy_update_input_check,
    _topk_multilabel_accuracy_update_masked,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric, UpdatePlan

TAccuracy = TypeVar("TAccuracy", bound="MulticlassAccuracy")


class MulticlassAccuracy(Metric[jax.Array]):
    """Accuracy for multiclass classification; O(1) counter states.

    Args:
        average: ``"micro"`` | ``"macro"`` | ``"none"``/``None``.
        num_classes: required for non-micro averaging.
        k: top-k correctness (needs 2-D score inputs).

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassAccuracy
        >>> metric = MulticlassAccuracy()
        >>> metric.update(jnp.array([0, 2, 1, 3]), jnp.array([0, 1, 2, 3]))
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __init__(
        self,
        *,
        average: Optional[str] = "micro",
        num_classes: Optional[int] = None,
        k: int = 1,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _accuracy_param_check(average, num_classes, k)
        self.average = average
        self.num_classes = num_classes
        self.k = k
        if average == "micro":
            self._add_state("num_correct", jnp.zeros(()), merge=MergeKind.SUM)
            self._add_state("num_total", jnp.zeros(()), merge=MergeKind.SUM)
        else:
            assert num_classes is not None
            self._add_state(
                "num_correct", jnp.zeros(num_classes), merge=MergeKind.SUM
            )
            self._add_state(
                "num_total", jnp.zeros(num_classes), merge=MergeKind.SUM
            )

    # plans carry mask-aware kernel twins: under config.shape_bucketing()
    # ragged batches pad to power-of-two buckets (metrics/_bucket.py)
    _bucketed_update = True

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _accuracy_update_input_check(input, target, self.num_classes, self.k)
        return UpdatePlan(
            _multiclass_accuracy_update,
            ("num_correct", "num_total"),
            (input, target),
            (self.average, self.num_classes, self.k),
            masked_kernel=_multiclass_accuracy_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self: TAccuracy, input, target) -> TAccuracy:
        # one fused dispatch: kernel + counter accumulation in one program
        return self._apply_update_plan(self._update_plan(input, target))

    def compute(self) -> jax.Array:
        return _accuracy_compute(self.num_correct, self.num_total, self.average)


class BinaryAccuracy(MulticlassAccuracy):
    """Binary accuracy with score binarization at ``threshold``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryAccuracy
        >>> metric = BinaryAccuracy()
        >>> metric.update(jnp.array([0.9, 0.2, 0.6, 0.1]), jnp.array([1, 0, 0, 1]))
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __init__(self, *, threshold: float = 0.5, device=None) -> None:
        super().__init__(device=device)
        self.threshold = threshold

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _binary_accuracy_update_input_check(input, target)
        return UpdatePlan(
            _binary_accuracy_update,
            ("num_correct", "num_total"),
            (input, target),
            (float(self.threshold),),
            masked_kernel=_binary_accuracy_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self, input, target) -> "BinaryAccuracy":
        return self._apply_update_plan(self._update_plan(input, target))


class MultilabelAccuracy(MulticlassAccuracy):
    """Multilabel accuracy under one of five matching criteria.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MultilabelAccuracy
        >>> metric = MultilabelAccuracy()
        >>> metric.update(jnp.array([[0.1, 0.9], [0.8, 0.9]]),
        ...               jnp.array([[0, 1], [1, 1]]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        criteria: str = "exact_match",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multilabel_accuracy_param_check(criteria)
        self.threshold = threshold
        self.criteria = criteria

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _multilabel_accuracy_update_input_check(input, target)
        return UpdatePlan(
            _multilabel_accuracy_update,
            ("num_correct", "num_total"),
            (input, target),
            (float(self.threshold), self.criteria),
            masked_kernel=_multilabel_accuracy_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self, input, target) -> "MultilabelAccuracy":
        return self._apply_update_plan(self._update_plan(input, target))


class TopKMultilabelAccuracy(MulticlassAccuracy):
    """Multilabel accuracy with top-k binarization of scores.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import TopKMultilabelAccuracy
        >>> metric = TopKMultilabelAccuracy(criteria="hamming", k=2)
        >>> metric.update(jnp.array([[0.9, 0.2, 0.8], [0.1, 0.7, 0.3], [0.6, 0.5, 0.4]]), jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 1]]))
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    def __init__(
        self,
        *,
        criteria: str = "exact_match",
        k: int = 2,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _topk_multilabel_accuracy_param_check(criteria, k)
        self.criteria = criteria
        self.k = k

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _topk_multilabel_accuracy_update_input_check(input, target, self.k)
        return UpdatePlan(
            _topk_multilabel_accuracy_update,
            ("num_correct", "num_total"),
            (input, target),
            (self.criteria, self.k),
            masked_kernel=_topk_multilabel_accuracy_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self, input, target) -> "TopKMultilabelAccuracy":
        return self._apply_update_plan(self._update_plan(input, target))
