"""Shared `# tev:` source-annotation grammar for the analyzer layers.

One comment grammar, parsed in one place, so the lint (``lint.py``) and
the concurrency verifier (``locks.py`` / ``concurrency.py``) cannot
drift apart on what a suppression or a binding looks like:

- ``# tev: disable=<rule>[,<rule>...] -- <reason>`` — per-line
  suppression. The reason is mandatory; a reasonless suppression is a
  ``bad-suppression`` finding and does NOT suppress (the underlying
  finding stays active, so a lazy suppression can never turn the gate
  green).
- ``# tev: scope=jit|host`` — file-level module classification (first
  lines; the lint's jit-reachability model).
- ``# tev: scope=worker|writer|watchdog|syncplane`` — on a ``def``
  line: the function is a background-THREAD entry point and everything
  reachable from it runs in that thread context (the concurrency hazard
  model).
- ``# tev: guarded-by=<lock>`` — on an attribute assignment (in
  ``__init__``, a dataclass field line, or a module-global assignment):
  the attribute is shared mutable state protected by ``<lock>`` (an
  attribute name of the same class, or a module-global lock name).
  Every later read/write of the attribute must sit inside a
  ``with <lock>`` scope.

Stdlib-only by design (the CI concurrency gate runs jax-free, like the
lint).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "CONCURRENCY_RULE_IDS",
    "GUARDED_RE",
    "LOCK_TYPE_NAMES",
    "SUPPRESS_RE",
    "THREAD_SCOPES",
    "THREAD_SCOPE_RE",
    "lock_ctor_kind",
    "parse_guarded_lines",
    "parse_suppressions",
    "parse_thread_scopes",
]

# The one lock-constructor vocabulary shared by the lint's ``bare-lock``
# rule and the verifier's lock inventory (``analysis/locks.py``) — a
# type added here is seen by BOTH, so the two passes cannot drift.
LOCK_TYPE_NAMES = frozenset(
    {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
)


def lock_ctor_kind(value: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``field(default_factory=threading.Lock)``
    -> the lock type name, else ``None``."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    if name in LOCK_TYPE_NAMES:
        return name
    if name == "field":
        for kw in value.keywords:
            if kw.arg == "default_factory":
                factory = kw.value
                fname = factory.attr if isinstance(
                    factory, ast.Attribute
                ) else (factory.id if isinstance(factory, ast.Name) else "")
                if fname in LOCK_TYPE_NAMES:
                    return fname
    return None

SUPPRESS_RE = re.compile(
    r"#\s*tev:\s*disable=([\w\-,]+)(?:\s*--\s*(.*\S))?\s*$"
)
GUARDED_RE = re.compile(r"#\s*tev:\s*guarded-by=([\w]+)\b")
THREAD_SCOPE_RE = re.compile(
    r"#\s*tev:\s*scope=(worker|writer|watchdog|syncplane)\b"
)

THREAD_SCOPES = ("worker", "writer", "watchdog", "syncplane")

# Rule ids of the concurrency verifier (docs/static-analysis.md,
# "Concurrency rules"). Listed statically so the lint's suppression
# audit accepts them without importing the concurrency passes (a plain
# lint run must stay cheap), and so the verifier can assert it registers
# exactly these.
CONCURRENCY_RULE_IDS = frozenset(
    {
        "unguarded-state",
        "guarded-field",
        "lock-order-cycle",
        "blocking-under-lock",
        "cross-thread-collective",
        "unannotated-thread-target",
        "bad-annotation",
    }
)


def parse_suppressions(
    lines: List[str], known_ids: Iterable[str]
) -> Tuple[Dict[int, Tuple[Set[str], str]], List[Tuple[int, int, str]]]:
    """Per-line suppression map plus bad-suppression findings.

    Returns ``({line: ({rule_id, ...}, reason)}, [(line, col, message)])``
    — reasonless or unknown-rule suppressions land in the second list
    and do NOT enter the map (they suppress nothing)."""
    known = set(known_ids)
    suppressions: Dict[int, Tuple[Set[str], str]] = {}
    bad: List[Tuple[int, int, str]] = []
    for i, line in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        ids = {part.strip() for part in m.group(1).split(",") if part.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            bad.append(
                (
                    i,
                    m.start(),
                    "suppression without a reason: write "
                    "`# tev: disable=<rule> -- <why this is intentional>`",
                )
            )
            continue
        unknown = ids - known
        if unknown:
            # fail closed: a suppression naming ANY unknown rule
            # suppresses nothing — a typo'd id must surface both as a
            # bad-suppression (lint) and as the still-active underlying
            # finding, never as a silently green gate
            bad.append(
                (
                    i,
                    m.start(),
                    f"suppression names unknown rule(s) {sorted(unknown)}; "
                    f"known: {sorted(known)}",
                )
            )
            continue
        suppressions[i] = (ids, reason)
    return suppressions, bad


def parse_guarded_lines(lines: List[str]) -> Dict[int, str]:
    """``{line: lock_name}`` for every ``# tev: guarded-by=`` comment."""
    out: Dict[int, str] = {}
    for i, line in enumerate(lines, start=1):
        m = GUARDED_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out


def parse_thread_scopes(lines: List[str]) -> Dict[int, str]:
    """``{line: scope}`` for every thread-context ``# tev: scope=``
    comment (worker/writer/watchdog — the jit/host spellings belong to
    the lint's file-level model and are deliberately not matched)."""
    out: Dict[int, str] = {}
    for i, line in enumerate(lines, start=1):
        m = THREAD_SCOPE_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out
