"""AUROC (area under the ROC curve).

Parity: reference torcheval/metrics/functional/classification/auroc.py
(binary :25-73 with multi-task + weights; multiclass :75-111 one-vs-rest;
compute kernels :115-235). Tie handling via the static-shape run-end
propagation in ``_curve_kernels`` (exact parity with the reference's
masked_scatter compaction).

``use_fused=True`` selects the sort-free fused kernel
(``torcheval_tpu.ops.fused_auc``: Pallas on TPU, C++ XLA custom-call on CPU)
— the analogue of the reference's opt-in fbgemm_gpu CUDA AUC (reference
auroc.py:161-173); ``use_fbgemm`` is accepted as an alias. The fused kernel
min/max-normalizes scores per task (AUC is rank-invariant) and is exact up
to its bin resolution.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification._curve_kernels import (
    binary_auroc_area,
)
from torcheval_tpu.utils.convert import to_jax


@jax.jit
def _binary_auroc_compute_jit(
    input: jax.Array, target: jax.Array, weight: Optional[jax.Array]
) -> jax.Array:
    return binary_auroc_area(input, target, weight)


def _binary_auroc_compute(
    input: jax.Array,
    target: jax.Array,
    weight: Optional[jax.Array] = None,
    use_fused: bool = False,
) -> jax.Array:
    if use_fused:
        from torcheval_tpu.ops import fused_auc

        return fused_auc(input, target, weight)
    return _binary_auroc_compute_jit(input, target, weight)


def _binary_auroc_update_input_check(
    input: jax.Array,
    target: jax.Array,
    num_tasks: int,
    weight: Optional[jax.Array] = None,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if weight is not None and weight.shape != target.shape:
        raise ValueError(
            "The `weight` and `target` should have the same shape, "
            f"got shapes {weight.shape} and {target.shape}."
        )
    if num_tasks == 1:
        if input.ndim > 1:
            raise ValueError(
                "`num_tasks = 1`, `input` is expected to be one-dimensional "
                f"tensor, but got shape ({input.shape})."
            )
    elif input.ndim == 1 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`num_tasks = {num_tasks}`, `input`'s shape is expected to be "
            f"({num_tasks}, num_samples), but got shape ({input.shape})."
        )


def binary_auroc(
    input,
    target,
    *,
    num_tasks: int = 1,
    weight=None,
    use_fused: bool = False,
    use_fbgemm: Optional[bool] = None,
) -> jax.Array:
    """Compute AUROC for binary classification.

    Class version: ``torcheval_tpu.metrics.BinaryAUROC``.

    Args:
        input: predicted scores, (n,) or (num_tasks, n).
        target: 0/1 labels, same shape.
        num_tasks: number of independent tasks (rows).
        weight: optional per-example weights.
        use_fused: opt-in sort-free approximate kernel (no tie masking) —
            the TPU analogue of the reference's fbgemm CUDA kernel.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import binary_auroc
        >>> binary_auroc(jnp.array([0.1, 0.5, 0.7, 0.8]), jnp.array([0, 0, 1, 1]))
        Array(1., dtype=float32)
    """
    if use_fbgemm is not None:
        use_fused = use_fbgemm
    input, target = to_jax(input), to_jax(target)
    weight = to_jax(weight) if weight is not None else None
    _binary_auroc_update_input_check(input, target, num_tasks, weight)
    return _binary_auroc_compute(input, target, weight, use_fused)


@jax.jit
def _multiclass_auroc_compute_jit(
    input: jax.Array,
    target: jax.Array,
    valid: Optional[jax.Array] = None,
) -> jax.Array:
    # one-vs-rest: per-class descending sort of the transposed scores
    # (reference auroc.py:206-235), vmapped over classes. ``valid`` is an
    # optional (N,) mask used by the fixed-shape buffered class metric:
    # padded rows get weight 0 so they contribute to no class's curve.
    num_classes = input.shape[1]
    scores = input.T  # (C, N)
    targets = (target[None, :] == jnp.arange(num_classes)[:, None]).astype(
        jnp.float32
    )
    weight = (
        None
        if valid is None
        else jnp.broadcast_to(
            valid.astype(jnp.float32)[None, :], scores.shape
        )
    )
    return binary_auroc_area(scores, targets, weight)


def _multiclass_auroc_param_check(num_classes: int, average: Optional[str]) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if num_classes is None or num_classes <= 1:
        raise ValueError(
            f"`num_classes` has to be at least 2, got {num_classes}."
        )


def _multiclass_auroc_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: int
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.ndim != 2 or input.shape[1] != num_classes:
        raise ValueError(
            f"input should have shape of (num_sample, num_classes), "
            f"got {input.shape} and num_classes={num_classes}."
        )


def multiclass_auroc(
    input,
    target,
    *,
    num_classes: int,
    average: Optional[str] = "macro",
) -> jax.Array:
    """Compute one-vs-rest AUROC for multiclass classification.

    Class version: ``torcheval_tpu.metrics.MulticlassAUROC``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import multiclass_auroc
        >>> multiclass_auroc(
        ...     jnp.array([[0.1, 0.1], [0.5, 0.5]]), jnp.array([0, 1]),
        ...     num_classes=2)
        Array(0.5, dtype=float32)
    """
    input, target = to_jax(input), to_jax(target)
    _multiclass_auroc_param_check(num_classes, average)
    _multiclass_auroc_update_input_check(input, target, num_classes)
    aurocs = _multiclass_auroc_compute_jit(input, target)
    if average == "macro":
        return jnp.mean(aurocs)
    return aurocs
