"""Cross-region eval federation (ISSUE 14): staleness-tolerant WAN sync,
partition tolerance & anti-entropy recovery.

The acceptance criteria pinned here:

- two regions partitioned for K exchange rounds then healed converge to
  a global state BIT-IDENTICAL to the uninterrupted oracle, with
  degradation provenance and a staleness alert emitted while partitioned
  (``test_partition_heal_bit_identical_to_oracle``);
- re-delivered and out-of-order inter-region epochs are idempotent /
  commutative per the epoch ledger, pinned against the toolkit merge
  oracle for SUM / MAX / EXTEND plus one sharded and one ``MetricTable``
  family (``test_exactly_once_*``);
- a ThreadWorld-8 two-region soak under a seeded randomized fault
  schedule (drops, partitions, duplicates, delay jitter) converges
  bit-identically after healing (``test_soak_*``);
- the epoch ledger rides elastic snapshot bundles so a crash
  mid-exchange neither double-counts nor drops a delta
  (``test_federation_ledger_rides_elastic_bundle``).

Float bit-identity note: the federation merges region-cumulative states
in region order, so a two-level float fold ``(r0+r1)+(r2+r3)`` replaces
the flat toolkit fold ``((r0+r1)+r2)+r3``. Tests comparing against the
FLAT toolkit oracle therefore use integer-valued float data (every
addition exact, fold-order-invariant — the PR 13 dyadic discipline);
tests comparing a faulted federation run against a fault-free
FEDERATION run need no such restriction.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

import jax.numpy as jnp

from torcheval_tpu import metrics as M
from torcheval_tpu import obs
from torcheval_tpu.federation import (
    Federation,
    FederationProvenance,
    InProcessLinkBus,
    RegionPartitionError,
    RegionSpec,
    apply_delta,
    encode_delta,
)
from torcheval_tpu.metrics.toolkit import sync_and_compute_collection
from torcheval_tpu.utils.test_utils import (
    ChaosLinkTransport,
    LinkFaultSpec,
    ThreadWorld,
)

REGIONS_2X2 = [("us", (0, 1)), ("eu", (2, 3))]
REGIONS_1X2 = [("us", (0,)), ("eu", (1,))]
REGIONS_4X2 = [("us", (0, 1, 2, 3)), ("eu", (4, 5, 6, 7))]


@pytest.fixture(autouse=True)
def _federation_cleanup():
    yield
    import torcheval_tpu.federation as fed_mod
    from torcheval_tpu.obs.counters import default_registry
    from torcheval_tpu.obs.flight import FLIGHT

    with fed_mod._CURRENT_LOCK:
        fed_mod._CURRENT = None
    default_registry().unregister("federation")
    FLIGHT.reset()


def _make_metrics():
    """SUM (float + int counters), MAX, EXTEND — the merge-kind zoo."""
    return {
        "acc": M.MulticlassAccuracy(),
        "sum": M.Sum(),
        "max": M.Max(),
        "cat": M.Cat(),
    }


def _update(coll, rank, rnd):
    """Integer-valued float data (exact addition at any fold order)."""
    rng = np.random.default_rng(1000 * rank + rnd)
    x = jnp.asarray(rng.random((8, 4)).astype(np.float32))
    t = jnp.asarray(rng.integers(0, 4, 8))
    s = jnp.asarray(rng.integers(0, 16, 8).astype(np.float32))
    coll["acc"].update(x, t)
    coll["sum"].update(s)
    coll["max"].update(s)
    coll["cat"].update(s)


def _values(coll):
    return {k: np.asarray(m.compute()) for k, m in coll.items()}


def _flat_oracle(world_size, rounds, make=_make_metrics, update=_update):
    """Flat toolkit sync over every rank's full stream — the
    uninterrupted-oracle merge."""
    world = ThreadWorld(world_size)

    def run(g):
        coll = make()
        for rnd in range(rounds):
            update(coll, g.rank, rnd)
        return {
            k: np.asarray(v)
            for k, v in sync_and_compute_collection(coll, g).items()
        }

    return world.run(run)[0]


def _run_federation(
    world_size,
    regions,
    rounds,
    *,
    transport=None,
    settle=2,
    partition_after=2,
    policy="quorum",
    make=_make_metrics,
    update=_update,
    round_hook=None,
    collect=None,
):
    """Drive one federation world: per round every rank updates, a
    barrier lines the world up, ``round_hook(rnd)`` (rank 0 only)
    mutates the chaos transport, then every rank runs one
    ``federate``; ``settle`` extra no-data rounds propagate the final
    epochs. Returns ``(results, feds)`` where results[rank] is the final
    merged values + provenance (plus whatever ``collect`` grabbed)."""
    world = ThreadWorld(world_size)
    transport = transport if transport is not None else InProcessLinkBus()
    barrier = threading.Barrier(world_size)
    feds = {}

    def run(g):
        fed = Federation(
            g,
            regions,
            transport=transport,
            partition_after=partition_after,
            policy=policy,
        )
        feds[g.rank] = fed
        coll = make()
        merged = None
        extra = {}
        for rnd in range(rounds + settle):
            if rnd < rounds:
                update(coll, g.rank, rnd)
            barrier.wait()
            if g.rank == 0 and round_hook is not None:
                round_hook(rnd)
            barrier.wait()
            merged = fed.federate(coll)
            barrier.wait()
            if collect is not None:
                collect(g.rank, rnd, fed, merged, extra)
        out = _values(merged)
        prov = merged[next(iter(merged))].federation_provenance
        return out, prov, extra

    return world.run(run), feds


# ---------------------------------------------------------------------------
# Construction contracts
# ---------------------------------------------------------------------------


def test_regions_must_partition_group_ranks():
    world = ThreadWorld(4)
    with pytest.raises(ValueError, match="partition"):
        Federation(world.views[0], [("us", (0, 1)), ("eu", (2,))])
    with pytest.raises(ValueError, match="unique"):
        Federation(world.views[0], [("us", (0, 1)), ("us", (2, 3))])
    with pytest.raises(ValueError, match="out of range"):
        Federation(world.views[0], [("us", (0, 1)), ("eu", (2, 9))])
    with pytest.raises(ValueError, match="policy"):
        Federation(world.views[0], REGIONS_2X2, policy="shrug")


def test_local_replica_group_rejected():
    from torcheval_tpu.distributed import LocalReplicaGroup

    with pytest.raises(TypeError, match="rank-per-process"):
        Federation(LocalReplicaGroup(), [("solo", (0,))])


def test_region_order_canonicalized_by_leader_rank():
    world = ThreadWorld(4)
    fed = Federation(
        world.views[0],
        [("eu", (2, 3)), ("us", (0, 1))],  # deliberately unsorted
        transport=InProcessLinkBus(),
    )
    assert fed.region_names == ("us", "eu")
    assert fed.regions[0] == RegionSpec("us", (0, 1))
    fed.close()


def test_word_delta_codec_roundtrip():
    rng = np.random.default_rng(5)
    base = rng.integers(0, 256, 4097, dtype=np.uint8)
    cur = base.copy()
    cur[13] ^= 0xFF
    cur[4096] ^= 0x1
    delta = encode_delta(base, cur)
    assert delta is not None
    assert np.array_equal(apply_delta(base, delta), cur)
    # dense change: the diff does not pay — full wins
    assert encode_delta(base, rng.integers(0, 256, 4097, dtype=np.uint8)) is None
    # length change: never a delta
    assert encode_delta(base, cur[:100]) is None


# ---------------------------------------------------------------------------
# Healthy-path convergence + staleness declarations
# ---------------------------------------------------------------------------


def test_two_region_convergence_bit_identical_to_flat_oracle():
    """4 ranks, 2 regions, healthy links: after settle rounds every rank's
    federated read is BIT-identical to the flat toolkit oracle (integer
    data; EXTEND concatenation order is region order == rank order)."""
    rounds = 3
    results, _feds = _run_federation(4, REGIONS_2X2, rounds)
    oracle = _flat_oracle(4, rounds)
    for vals, prov, _ in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k
        assert isinstance(prov, FederationProvenance)
        assert not prov.degraded
        assert prov.merged_regions == ("us", "eu")


def test_three_region_full_mesh_convergence():
    """Three regions (full leader mesh): region-order merge still equals
    the flat oracle bit-for-bit, and every link keeps its own ledger."""
    regions = [("us", (0,)), ("eu", (1,)), ("ap", (2,))]
    rounds = 3
    (results, feds) = _run_federation(3, regions, rounds, settle=2)
    oracle = _flat_oracle(3, rounds)
    for vals, prov, _ in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k
        assert prov.merged_regions == ("us", "eu", "ap")
    assert feds[0].link_health("eu").merges > 0
    assert feds[0].link_health("ap").merges > 0


def test_single_metric_and_value_forms():
    world = ThreadWorld(2)
    bus = InProcessLinkBus()
    barrier = threading.Barrier(2)

    def run(g):
        fed = Federation(g, REGIONS_1X2, transport=bus)
        m = M.Sum()
        value = None
        for rnd in range(3):
            m.update(jnp.asarray(float(g.rank + rnd)))
            barrier.wait()
            value = fed.sync_and_compute(m)
            barrier.wait()
        return float(value), fed.last_provenance

    results = world.run(run)
    # both regions have merged everything through round 2's exchange
    # except possibly the last round's peer batch; settle one more round
    # is not needed here — just check the provenance shape and agreement
    # on the read each rank declares
    for value, prov in results:
        assert isinstance(prov, FederationProvenance)
        assert prov.epoch == 3


def test_bounded_staleness_declared_per_region():
    """The federated read declares, per region, the last merged epoch
    and its age — and a healthy link's staleness stays <= 1 round."""

    def collect(rank, rnd, fed, merged, extra):
        extra.setdefault("staleness", []).append(
            tuple(
                (s.name, s.epoch, s.staleness_epochs)
                for s in fed.last_provenance.regions
            )
        )

    (results, _) = _run_federation(4, REGIONS_2X2, 3, collect=collect)
    for vals, prov, extra in results[0:1]:
        for statuses in extra["staleness"][1:]:
            for name, epoch, stale in statuses:
                assert stale <= 1, statuses
        self_status = [s for s in prov.regions if s.is_self][0]
        assert self_status.staleness_epochs == 0
        assert self_status.age_seconds == 0.0


# ---------------------------------------------------------------------------
# Exactly-once: the epoch ledger under duplicates and reordering
# ---------------------------------------------------------------------------


def _dup_reorder_faults():
    """Duplicate + reorder every early message on both directed links."""
    out = []
    for src, dst in (("us", "eu"), ("eu", "us")):
        out.append(LinkFaultSpec(src, dst, 0, "duplicate", times=8))
        out.append(LinkFaultSpec(src, dst, 1, "reorder", times=1))
        out.append(LinkFaultSpec(src, dst, 4, "reorder", times=1))
    return out


def test_exactly_once_sum_max_extend_under_duplicates_and_reorder():
    """ISSUE 14 satellite: re-delivered and out-of-order inter-region
    epochs are idempotent/commutative per the epoch ledger — the chaotic
    run's converged state is BIT-identical to the flat toolkit merge
    oracle for SUM (float+int), MAX and EXTEND states, and the ledger
    actually saw duplicates (non-vacuous)."""
    rounds = 5
    chaos = ChaosLinkTransport(InProcessLinkBus(), _dup_reorder_faults())
    (results, feds) = _run_federation(
        4, REGIONS_2X2, rounds, transport=chaos, settle=3
    )
    oracle = _flat_oracle(4, rounds)
    for vals, prov, _ in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k
    h_us = feds[0].link_health("eu")
    h_eu = feds[2].link_health("us")
    assert h_us.duplicates + h_eu.duplicates > 0
    assert h_us.merges > 0 and h_eu.merges > 0


def test_exactly_once_sharded_and_table_families():
    """The ledger discipline holds for an intra-region SHARDED family
    (MulticlassConfusionMatrix with a per-region ShardContext) and a
    hash-partitioned ``MetricTable`` family: converged per-key /
    per-cell state equals the replicated world-1 replay oracle exactly,
    under duplicated + reordered delivery."""
    from torcheval_tpu.metrics import ShardContext
    from torcheval_tpu.table import MetricTable

    rounds, world_size = 3, 4

    def make_for(rank):
        region_rank = rank % 2
        return {
            "cm": M.MulticlassConfusionMatrix(
                16, shard=ShardContext(region_rank, 2)
            ),
            "tb": MetricTable("ctr", shard=ShardContext(region_rank, 2)),
        }

    def update(coll, rank, rnd):
        rng = np.random.default_rng(31 * rank + rnd)
        t = jnp.asarray(rng.integers(0, 16, 16))
        p = jnp.asarray(rng.integers(0, 16, 16))
        coll["cm"].update(jnp.eye(16)[p], t)
        keys = rng.integers(0, 32, 16)
        clicks = rng.integers(0, 2, 16).astype(np.float32)
        coll["tb"].ingest(keys, clicks)

    world = ThreadWorld(world_size)
    chaos = ChaosLinkTransport(InProcessLinkBus(), _dup_reorder_faults())
    barrier = threading.Barrier(world_size)
    feds = {}

    def run(g):
        fed = Federation(g, REGIONS_2X2, transport=chaos)
        feds[g.rank] = fed
        coll = make_for(g.rank)
        merged = None
        for rnd in range(rounds + 3):
            if rnd < rounds:
                update(coll, g.rank, rnd)
            barrier.wait()
            merged = fed.federate(coll)
            barrier.wait()
        return (
            np.asarray(merged["cm"].compute()),
            merged["tb"].compute().as_dict(),
        )

    results = world.run(run)

    cm_o = M.MulticlassConfusionMatrix(16)
    tb_o = MetricTable("ctr")
    for rank in range(world_size):
        for rnd in range(rounds):
            update({"cm": cm_o, "tb": tb_o}, rank, rnd)
    want_cm = np.asarray(cm_o.compute())
    want_tb = tb_o.compute().as_dict()
    for cm, tb in results:
        assert np.array_equal(cm, want_cm)
        assert tb == want_tb
    assert (
        feds[0].link_health("eu").duplicates
        + feds[2].link_health("us").duplicates
        > 0
    )


def test_stale_redelivery_discarded_and_reacked():
    """A message older than the ledger's epoch is discarded (idempotent)
    and RE-ACKed so the sender's view converges — pinned by capturing a
    real early message and re-posting it after later epochs merged."""
    import pickle

    captured = {}

    class TapBus(InProcessLinkBus):
        def post(self, src, dst, blob):
            if (
                src == "us"
                and dst == "eu"
                and "blob" not in captured
                and pickle.loads(blob).get("kind") in ("full", "delta")
            ):
                captured["blob"] = blob
            super().post(src, dst, blob)

    bus = TapBus()

    def round_hook(rnd):
        if rnd == 3 and "blob" in captured:
            # re-deliver round-1's us->eu snapshot long after eu merged
            # newer epochs
            bus.post("us", "eu", captured["blob"])

    (results, feds) = _run_federation(
        2, REGIONS_1X2, 4, transport=bus, round_hook=round_hook, settle=2
    )
    oracle = _flat_oracle(2, 4)
    for vals, prov, _ in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k
    assert feds[1].link_health("us").duplicates >= 1


# ---------------------------------------------------------------------------
# Partition tolerance + anti-entropy (the ISSUE acceptance pin)
# ---------------------------------------------------------------------------


def test_partition_heal_bit_identical_to_oracle():
    """THE acceptance criterion: two regions partitioned for K rounds
    then healed converge to a global state bit-identical to the
    uninterrupted oracle; while partitioned, reads carry degradation
    provenance (dark region, growing staleness) and a staleness
    ``AlertEvent`` is emitted."""
    rounds, part_start, part_end = 8, 2, 6
    chaos = ChaosLinkTransport(InProcessLinkBus())

    def round_hook(rnd):
        if rnd == part_start:
            chaos.partition_both("us", "eu")
        if rnd == part_end:
            chaos.heal_both("us", "eu")

    mid = {}

    def collect(rank, rnd, fed, merged, extra):
        if rnd == part_end - 1:
            mid[rank] = fed.last_provenance

    rec = obs.recorder()
    prev = rec.enabled
    rec.enable()
    try:
        (results, feds) = _run_federation(
            4,
            REGIONS_2X2,
            rounds,
            transport=chaos,
            settle=3,
            round_hook=round_hook,
            collect=collect,
        )
        alerts = [
            e
            for e in rec.log.tail()
            if e.kind == "alert" and e.alert == "region-staleness"
        ]
    finally:
        if not prev:
            rec.disable()

    oracle = _flat_oracle(4, rounds)
    for vals, prov, _ in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k
        assert not prov.degraded  # healed

    # degradation provenance while partitioned
    prov = mid[0]
    assert prov.degraded
    assert prov.merged_regions == ("us",)
    eu = [s for s in prov.regions if s.name == "eu"][0]
    assert eu.dark and eu.staleness_epochs > 2
    # the staleness alert fired while partitioned, naming the region
    assert alerts and any(a.name == "federation/eu" for a in alerts)
    # link health observed the partition and the heal
    h = feds[0].link_health("eu")
    assert h.partitions >= 1 and h.heals >= 1


def test_partition_raise_policy():
    """policy='raise' refuses a dark-region read with a typed error —
    and accepts a healthy one (the error is partition-specific)."""
    rounds, part_start = 6, 2
    chaos = ChaosLinkTransport(InProcessLinkBus())
    world = ThreadWorld(2)
    barrier = threading.Barrier(2)

    def run(g):
        fed = Federation(
            g, REGIONS_1X2, transport=chaos, partition_after=2,
            policy="raise",
        )
        coll = _make_metrics()
        healthy_read = None
        for rnd in range(rounds):
            _update(coll, g.rank, rnd)
            barrier.wait()
            if g.rank == 0 and rnd == part_start:
                chaos.partition_both("us", "eu")
            barrier.wait()
            fed.exchange(coll)
            barrier.wait()
            if rnd == part_start - 1:
                # healthy links, both regions contributed: raise-policy
                # reads succeed
                healthy_read = fed.federate(coll)
            barrier.wait()
        raised = None
        try:
            fed.federate(coll)
        except RegionPartitionError as e:
            raised = e
        return healthy_read is not None, raised

    results = world.run(run)
    for healthy_ok, raised in results:
        assert healthy_ok
        assert isinstance(raised, RegionPartitionError)
        assert "dark" in str(raised)


def test_anti_entropy_one_cumulative_message_heals():
    """While partitioned the sender BACKS OFF (posts fewer probes than
    rounds); on heal, ONE cumulative snapshot re-converges the peer —
    no replay of the dark window's epochs."""
    rounds, part_start, part_end = 10, 1, 8
    chaos = ChaosLinkTransport(InProcessLinkBus())

    def round_hook(rnd):
        if rnd == part_start:
            chaos.partition_both("us", "eu")
        if rnd == part_end:
            chaos.heal_both("us", "eu")

    settle = 4
    (results, feds) = _run_federation(
        2,
        REGIONS_1X2,
        rounds,
        transport=chaos,
        settle=settle,
        round_hook=round_hook,
        partition_after=2,
    )
    oracle = _flat_oracle(2, rounds)
    for vals, prov, _ in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k
    # backoff: at least one dark round SKIPPED posting (the exponential
    # probe schedule), and everything posted into the window was dropped
    dark_rounds = part_end - part_start
    h = feds[0].link_health("eu")
    assert h.posts < rounds + 4  # strictly fewer than one per round
    dropped = chaos.dropped.get(("us", "eu"), 0)
    assert 0 < dropped <= dark_rounds
    # anti-entropy, not replay: the dark window's epochs were never
    # individually merged — the total merge count stays bounded by the
    # NON-dark rounds (plus slack for the healing cumulative message),
    # far below one-merge-per-epoch replay
    merges_total = feds[1].link_health("us").merges
    assert 1 <= merges_total <= (rounds + settle) - dark_rounds + 2


def test_asymmetric_partition_one_direction_dark():
    """Asymmetric chaos: eu->us dropped, us->eu delivering. us sees eu
    dark (no merges arrive); eu keeps merging us's snapshots — the two
    sides' provenance disagree exactly as the link does."""
    rounds = 7
    chaos = ChaosLinkTransport(InProcessLinkBus())

    def round_hook(rnd):
        if rnd == 1:
            chaos.partition("eu", "us")

    (results, feds) = _run_federation(
        2,
        REGIONS_1X2,
        rounds,
        transport=chaos,
        settle=0,
        round_hook=round_hook,
        partition_after=2,
    )
    us_prov = results[0][1]
    eu_prov = results[1][1]
    assert [s.dark for s in us_prov.regions if s.name == "eu"] == [True]
    assert us_prov.degraded
    # eu still merges us's data: us not dark from eu's side
    assert [s.dark for s in eu_prov.regions if s.name == "us"] == [False]
    assert not eu_prov.degraded
    assert feds[1].link_health("us").merges > 0


def test_chaos_schedule_replays_deterministically():
    """Same seed + same call sequence => identical delivery outcomes
    (the deterministic-replay contract of the link chaos harness)."""

    def run_once():
        chaos = ChaosLinkTransport(
            InProcessLinkBus(),
            [LinkFaultSpec("us", "eu", 2, "drop", times=2)],
            jitter_polls=(0, 2),
            seed=1234,
        )
        for i in range(10):
            chaos.post("us", "eu", b"m%d" % i)
            chaos.poll("eu")
        # drain what is still held
        tail = []
        for _ in range(5):
            tail.extend(chaos.poll("eu"))
        return (dict(chaos.dropped), dict(chaos.delivered))

    assert run_once() == run_once()


# ---------------------------------------------------------------------------
# ThreadWorld-8 soak under a seeded randomized fault schedule (tier-1)
# ---------------------------------------------------------------------------


def _soak(rounds, seed):
    rng = np.random.default_rng(seed)
    chaos = ChaosLinkTransport(
        InProcessLinkBus(),
        # seeded scripted duplicates on early message indices
        [
            LinkFaultSpec(src, dst, int(m), "duplicate")
            for src, dst in (("us", "eu"), ("eu", "us"))
            for m in rng.choice(rounds, size=3, replace=False)
        ],
        jitter_polls=(0, 2),
        seed=seed,
    )
    # one seeded partition window per direction (possibly overlapping)
    windows = {}
    for src, dst in (("us", "eu"), ("eu", "us")):
        a = int(rng.integers(1, rounds - 3))
        b = int(rng.integers(a + 1, rounds - 1))
        windows[(src, dst)] = (a, b)

    def round_hook(rnd):
        for (src, dst), (a, b) in windows.items():
            if rnd == a:
                chaos.partition(src, dst)
            if rnd == b:
                chaos.heal(src, dst)

    (results, feds) = _run_federation(
        8,
        REGIONS_4X2,
        rounds,
        transport=chaos,
        settle=4,
        round_hook=round_hook,
        partition_after=2,
    )
    oracle = _flat_oracle(8, rounds)
    for rank, (vals, prov, _) in enumerate(results):
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), (rank, k, seed)
        assert not prov.degraded
    # the schedule was not vacuous: something was actually dropped
    assert sum(chaos.dropped.values()) > 0


def test_soak_threadworld8_two_regions_seeded_faults():
    """ISSUE 14 satellite: 8 ranks in 2 regions under a seeded
    randomized fault schedule (asymmetric partition windows, delivery
    jitter, duplicates); after healing, every rank's global compute is
    bit-identical to the fault-free flat oracle."""
    _soak(rounds=8, seed=140)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [141, 142, 143])
def test_soak_threadworld8_long(seed):
    """Longer soak variant (slow tier): more rounds, more seeds."""
    _soak(rounds=16, seed=seed)


# ---------------------------------------------------------------------------
# Delta wire: cumulative deltas beat full snapshots on large static states
# ---------------------------------------------------------------------------


def test_delta_bytes_beat_full_bytes_on_sparse_touch():
    """A large mostly-STATIC dense state (64-class confusion matrix
    densely warmed, then touched on few cells per round) ships
    word-sparse DELTAS between epochs — strictly smaller than the full
    snapshot — and still converges bit-identically. (A mostly-ZERO
    state would already ship tiny via synclib's sparse wire encoding;
    the delta codec is the win for dense-but-stable payloads, where the
    per-epoch change is sparse even though the values are not.)"""
    warm_p, warm_t = np.meshgrid(np.arange(64), np.arange(64))
    warm_p, warm_t = warm_p.reshape(-1), warm_t.reshape(-1)

    def make():
        return {"cm": M.MulticlassConfusionMatrix(64)}

    def update(coll, rank, rnd):
        if rnd == 0:
            # every (pred, target) cell counted once: the packed state is
            # DENSE (sparse wire encoding does not engage) and 16 KiB
            coll["cm"].update(jnp.eye(64)[warm_p], jnp.asarray(warm_t))
            return
        rng = np.random.default_rng(17 * rank + rnd)
        t = jnp.asarray(rng.integers(0, 8, 16))
        p = jnp.asarray(rng.integers(0, 8, 16))
        coll["cm"].update(jnp.eye(64)[p], t)

    (results, feds) = _run_federation(
        2, REGIONS_1X2, 5, make=make, update=update, settle=2
    )
    oracle = _flat_oracle(2, 5, make=make, update=update)
    for vals, prov, _ in results:
        assert np.array_equal(vals["cm"], oracle["cm"])
    h = feds[0].link_health("eu")
    assert h.deltas_sent >= 2
    full_per_msg = h.full_bytes / max(h.fulls_sent, 1)
    delta_per_msg = h.delta_bytes / h.deltas_sent
    assert delta_per_msg < full_per_msg / 4, h.as_dict()


# ---------------------------------------------------------------------------
# Elastic integration: the ledger rides snapshot bundles
# ---------------------------------------------------------------------------


def test_federation_ledger_rides_elastic_bundle(tmp_path):
    """Crash mid-exchange: the epoch ledger (merged snapshots + acked
    epochs + history) rides the elastic bundle; the restored federation
    discards a re-delivered old epoch (no double count) and re-derives
    un-acked state from the cumulative snapshot (no dropped delta) —
    global compute equals the oracle."""
    from torcheval_tpu.elastic import ElasticSession

    import pickle

    rounds = 3
    world = ThreadWorld(2)
    barrier = threading.Barrier(2)
    captured = {}

    class TapBus(InProcessLinkBus):
        def post(self, src, dst, blob):
            if pickle.loads(blob).get("kind") in ("full", "delta"):
                captured.setdefault((src, dst), []).append(blob)
            super().post(src, dst, blob)

    bus = TapBus()

    def phase1(g):
        fed = Federation(g, REGIONS_1X2, transport=bus, partition_after=3)
        coll = _make_metrics()
        session = ElasticSession(
            coll, str(tmp_path), process_group=g, interval=1000,
            federation=fed,
        )
        for rnd in range(rounds):
            _update(coll, g.rank, rnd)
            barrier.wait()
            fed.federate(coll)
            barrier.wait()
        session.snapshot()
        session.close()
        fed.close()
        return {
            name: {k: np.asarray(v) for k, v in m.state_dict().items()}
            for name, m in coll.items()
        }

    world.run(phase1)

    # "crash": fresh processes — new federations with a fresh transport,
    # restore from the bundle
    world2 = ThreadWorld(2)
    bus2 = InProcessLinkBus()
    barrier2 = threading.Barrier(2)
    feds2 = {}

    def phase2(g):
        fed = Federation(g, REGIONS_1X2, transport=bus2, partition_after=3)
        feds2[g.rank] = fed
        coll = _make_metrics()
        session = ElasticSession(
            coll, str(tmp_path), process_group=g, interval=1000,
            federation=fed,
        )
        restored = session.restore()
        assert restored is not None
        # the restored ledger remembers the peer's merged epochs
        peer = "eu" if g.rank == 0 else "us"
        assert fed._links[peer].merged_epoch > 0
        barrier2.wait()
        if g.rank == 1:
            # re-deliver the OLDEST pre-crash us->eu message: the ledger
            # must discard it (double-count guard)
            bus2.post("us", "eu", captured[("us", "eu")][0])
        barrier2.wait()
        merged = None
        for _ in range(3):  # settle: anti-entropy fulls + the redelivery
            barrier2.wait()
            merged = fed.federate(coll)
            barrier2.wait()
        session.close()
        return _values(merged)

    results = world2.run(phase2)
    oracle = _flat_oracle(2, rounds)
    for vals in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k
    assert feds2[1].link_health("us").duplicates >= 1


def test_load_ledger_layout_mismatch_starts_fresh():
    world = ThreadWorld(2)
    fed = Federation(
        world.views[0], REGIONS_1X2, transport=InProcessLinkBus()
    )
    with pytest.warns(RuntimeWarning, match="layout mismatch"):
        fed.load_ledger({"schema": 1, "regions": [("x", (0,))], "epoch": 9})
    assert fed.epoch == 0
    fed.close()


# ---------------------------------------------------------------------------
# Observability: events, gauges, healthz, flight records
# ---------------------------------------------------------------------------


def test_region_sync_event_schema_roundtrip():
    from torcheval_tpu.obs.events import RegionSyncEvent, event_from_dict

    e = RegionSyncEvent(
        rank=0, region="us", peer="eu", action="merge", epoch=4,
        local_epoch=5, peer_epoch=4, nbytes=123, staleness_epochs=0,
    )
    d = e.as_dict()
    assert d["kind"] == "region_sync" and d["schema"] == 1
    assert event_from_dict(d) == e
    d["future_field"] = "ignored"
    assert event_from_dict(d) == e


def test_exchange_emits_region_sync_events():
    rec = obs.recorder()
    prev = rec.enabled
    rec.enable()
    try:
        (results, feds) = _run_federation(2, REGIONS_1X2, 2, settle=1)
        events = [e for e in rec.log.tail() if e.kind == "region_sync"]
    finally:
        if not prev:
            rec.disable()
    actions = {e.action for e in events}
    assert "merge" in actions and {"send-full", "send-delta"} & actions
    merge = [e for e in events if e.action == "merge"][-1]
    assert merge.region in ("us", "eu") and merge.peer in ("us", "eu")
    assert merge.epoch >= 1 and merge.nbytes > 0


def test_staleness_gauges_in_counter_registry_and_prometheus():
    from torcheval_tpu.obs.counters import default_registry
    from torcheval_tpu.obs.export import render_prometheus

    (results, feds) = _run_federation(2, REGIONS_1X2, 2, settle=1)
    import torcheval_tpu.federation as fed_mod

    # ThreadWorld constructs one fed per rank; make rank 0's the armed one
    with fed_mod._CURRENT_LOCK:
        fed_mod._CURRENT = feds[0]
    default_registry().register("federation", feds[0]._counter_source)
    reading = default_registry().read()["federation"]
    assert "region_staleness_epochs/eu" in reading
    assert "region_last_merge_age/eu" in reading
    assert reading["epoch"] == feds[0].epoch
    text = render_prometheus()
    assert "region_staleness_epochs" in text
    for fed in feds.values():
        fed.close()


def test_healthz_degrades_to_503_past_staleness_bound():
    """ISSUE 14 satellite: /healthz fails (healthy=False, status
    'stale-region') once a region's staleness exceeds the configurable
    bound, and recovers after heal."""
    from torcheval_tpu.obs.server import healthz_payload

    rounds, part_start = 7, 1
    chaos = ChaosLinkTransport(InProcessLinkBus())

    def round_hook(rnd):
        if rnd == part_start:
            chaos.partition_both("us", "eu")

    (results, feds) = _run_federation(
        2,
        REGIONS_1X2,
        rounds,
        transport=chaos,
        settle=0,
        round_hook=round_hook,
        partition_after=2,
    )
    import torcheval_tpu.federation as fed_mod

    with fed_mod._CURRENT_LOCK:
        fed_mod._CURRENT = feds[0]
    payload = healthz_payload()
    assert payload["status"] == "stale-region"
    assert payload["healthy"] is False
    eu = [r for r in payload["federation"]["regions"] if r["name"] == "eu"][0]
    assert eu["staleness_epochs"] > feds[0].staleness_503
    assert eu["dark"]

    # heal: drive a few more healthy rounds through BOTH feds
    chaos.heal_both("us", "eu")
    world = ThreadWorld(2)  # fresh threads driving the same fed objects
    barrier = threading.Barrier(2)
    colls = {0: _make_metrics(), 1: _make_metrics()}

    def resume(g):
        for _ in range(3):
            barrier.wait()
            feds[g.rank].exchange(colls[g.rank])
            barrier.wait()

    # the feds hold subgroups of the ORIGINAL world's views; re-driving
    # them needs the original rank threads — emulate by calling exchange
    # from fresh threads bound to the same per-rank federation objects
    threads = [
        threading.Thread(target=resume, args=(type("G", (), {"rank": r})(),))
        for r in (0, 1)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60.0)
    payload = healthz_payload()
    assert payload["status"] == "ok"
    assert payload["healthy"] is True
    for fed in feds.values():
        fed.close()


def test_flight_records_name_stalled_region():
    """ISSUE 14 satellite: inter-region exchanges land in the flight
    ring as long-lived records whose op NAMES the region
    (``region_delta:us->eu``); during a partition the un-acked probe
    record is RE-issued with no ack (attempts >= 2), which is what lets
    ``diff_flight_rings`` name the stalled region without false-flagging
    a healthy link's single un-acked interval."""
    from torcheval_tpu.obs.flight import FLIGHT, diff_flight_rings

    rounds, part_start = 9, 1
    chaos = ChaosLinkTransport(InProcessLinkBus())

    def round_hook(rnd):
        if rnd == part_start:
            chaos.partition_both("us", "eu")

    FLIGHT.reset()
    FLIGHT.enable("test-federation")
    try:
        (results, feds) = _run_federation(
            2,
            REGIONS_1X2,
            rounds,
            transport=chaos,
            settle=0,
            round_hook=round_hook,
            partition_after=2,
        )
        per_rank = FLIGHT.per_rank()
        # rank 0 (us leader) holds region_delta records; the un-acked
        # probe is in flight
        ops = {r["op"] for r in per_rank.get(0, ())}
        assert "region_delta:us->eu" in ops
        in_flight = [
            r
            for r in per_rank[0]
            if r["op"] == "region_delta:us->eu"
            and r["state"] in ("enqueued", "issued")
        ]
        assert in_flight
        # the partitioned probe record was re-issued without an ack —
        # the stall-arm qualification (a healthy link stays attempts 1)
        assert max(r["attempts"] for r in in_flight) >= 2
        diff = diff_flight_rings({0: per_rank[0]}, stall_after=0.0)
        assert not diff.ok
        assert "region_delta:us->eu" in diff.stalled_op
        # the failed (partition-detected) record is also on the ring
        failed = [
            r
            for r in per_rank[0]
            if r["op"] == "region_delta:us->eu" and r["state"] == "failed"
        ]
        assert failed and "partitioned" in failed[0]["detail"]
    finally:
        FLIGHT.disable("test-federation")
        FLIGHT.reset()
    for fed in feds.values():
        fed.close()


def test_malformed_and_foreign_messages_never_poison_the_drain():
    """Review-round regression: a blob that unpickles to a NON-DICT
    (foreign traffic on a shared transport namespace) and a dict missing
    its fields are both dropped without crashing exchange()."""
    import pickle

    bus = InProcessLinkBus()

    def round_hook(rnd):
        if rnd == 1:
            bus.post("eu", "us", pickle.dumps([1, 2, 3]))  # non-dict
            bus.post("eu", "us", b"\x00not pickle")  # torn
            bus.post("eu", "us", pickle.dumps({"kind": "delta"}))  # fields
    (results, feds) = _run_federation(
        2, REGIONS_1X2, 3, transport=bus, round_hook=round_hook, settle=2
    )
    oracle = _flat_oracle(2, 3)
    for vals, prov, _ in results:
        for k, want in oracle.items():
            assert np.array_equal(vals[k], want), k


def test_healthy_links_no_flight_divergence_and_no_watchdog_aging():
    """Review-round regression: tracked link records must not fabricate
    a lockstep divergence across leaders (each direction has its own op
    name) and must not be aged by the stall watchdog (they stay in
    flight across the whole inter-exchange interval by design)."""
    from torcheval_tpu.obs.flight import FLIGHT, diff_flight_rings

    FLIGHT.reset()
    FLIGHT.enable("test-federation-healthy")
    try:
        (results, feds) = _run_federation(2, REGIONS_1X2, 3, settle=1)
        per_rank = FLIGHT.per_rank()
        assert set(per_rank) >= {0, 1}
        diff = diff_flight_rings(per_rank, stall_after=3600.0)
        assert diff.diverged_rank is None, diff.format()
        # the un-acked newest epoch IS in flight — and exempt from
        # watchdog aging via the tracked flag
        tracked = [
            r
            for r in FLIGHT.in_flight()
            if r.op.startswith("region_delta:")
        ]
        assert tracked and all(r.tracked for r in tracked)
        # the watchdog loop's selection: no un-tracked in-flight record
        # exists to age, even at a zero deadline
        stuck = [
            r
            for r in FLIGHT.in_flight()
            if not getattr(r, "tracked", False) and r.age() >= 0.0
        ]
        assert stuck == []
    finally:
        FLIGHT.disable("test-federation-healthy")
        FLIGHT.reset()
    for fed in feds.values():
        fed.close()


def test_ledger_broadcast_ships_full_buffers_only_on_epoch_change():
    """Review-round regression: the intra-region ledger broadcast ships
    a link's full snapshot buffer only when its merged epoch advanced;
    quiet rounds broadcast light stamps (the WAN side's delta economy
    must not be undone by re-shipping full snapshots intra-region)."""
    world = ThreadWorld(2)
    fed = Federation(
        world.views[0], REGIONS_1X2, transport=InProcessLinkBus()
    )
    link = fed._links["eu"]
    link.merged_epoch = 3
    link.merged_meta = ("order", "meta")
    link.merged_buf = np.arange(16, dtype=np.uint8)
    first = fed._ledger_view()["eu"]
    assert "merged_buf" in first and "merged_meta" in first
    second = fed._ledger_view()["eu"]
    assert "merged_buf" not in second and "merged_meta" not in second
    assert second["merged_epoch"] == 3
    link.merged_epoch = 4
    third = fed._ledger_view()["eu"]
    assert "merged_buf" in third
    # a member adopting a light entry keeps its buffer, updates stamps
    link.merged_at_round = 9
    fed._adopt_ledger_view({"eu": {
        "merged_epoch": 4, "merged_at_round": 9, "merged_wall": 1.0,
        "dark": False,
    }})
    assert link.merged_buf is not None and link.merged_at_round == 9
    fed.close()


def test_non_member_single_metric_keeps_caller_shape():
    """Review-round regression: a non-member rank passing a single bare
    Metric gets the SAME bare metric back (never the internal wrapping
    dict)."""
    world = ThreadWorld(2)
    sub = world.views[0].new_subgroup([1])
    fed = Federation(sub, [("solo", (0,))], transport=InProcessLinkBus())
    m = M.Sum()
    assert fed.exchange(m) is m
    assert fed.federate(m) is m


def test_out_of_order_close_keeps_current_federations_gauges():
    """Review-round regression: closing an EARLIER federation must not
    strip the counter source (or the current_federation slot) of a
    later-armed one."""
    from torcheval_tpu.federation import current_federation
    from torcheval_tpu.obs.counters import default_registry

    world_a, world_b = ThreadWorld(2), ThreadWorld(2)
    fed_a = Federation(
        world_a.views[0], REGIONS_1X2, transport=InProcessLinkBus()
    )
    fed_b = Federation(
        world_b.views[0], REGIONS_1X2, transport=InProcessLinkBus()
    )
    assert current_federation() is fed_b
    fed_a.close()
    assert current_federation() is fed_b
    assert "federation" in default_registry().sources
    fed_b.close()
    assert current_federation() is None
    assert "federation" not in default_registry().sources


def test_non_member_handle_is_inert():
    """A process outside the group gets an inert federation handle —
    the subgroup non-member contract."""
    world = ThreadWorld(2)
    view = world.views[0]

    fed = Federation(view, REGIONS_1X2, transport=InProcessLinkBus())
    assert fed.is_member
    fed.close()

    # non-membership via a subgroup handle this rank is not in
    sub = view.new_subgroup([1])
    assert not sub.is_member
    fed2 = Federation(sub, [("solo", (0,))], transport=InProcessLinkBus())
    assert not fed2.is_member
    coll = _make_metrics()
    assert fed2.exchange(coll) is coll
    assert not fed2.stale_for_healthz()


# ---------------------------------------------------------------------------
# The quantized WAN wire (ISSUE 18): int8 rung stays epoch-idempotent
# ---------------------------------------------------------------------------


def _make_dense_float():
    """One big dense float family (rides int8) + one tiny counter
    (stays exact under any rung — below the lossy byte floor)."""
    return {"cat": M.Cat(), "sum": M.Sum()}


def _update_dense_float(coll, rank, rnd):
    rng = np.random.default_rng(7000 + 100 * rank + rnd)
    x = jnp.asarray(rng.normal(size=512).astype(np.float32))
    coll["cat"].update(x)
    coll["sum"].update(x)


def test_partition_heal_at_int8_rung_is_epoch_idempotent():
    """ISSUE 18: the WAN wire at the int8 rung keeps the federation's
    exactly-once discipline — a partitioned-then-healed chaos run with
    duplicate delivery converges per-rank BIT-identical to the
    fault-free federation run at the same rung. Replacement-by-max-epoch
    of cumulative snapshots makes the lossy wire deterministic (a healed
    replay re-ships the same quantized bytes; the crc pins the canonical
    post-dequantize payload), so chaos cannot compound quantization
    error."""
    from torcheval_tpu import config as te_config

    rounds = 6
    faults = [
        LinkFaultSpec("us", "eu", 0, "duplicate", times=4),
        LinkFaultSpec("eu", "us", 1, "duplicate", times=4),
    ]
    chaos = ChaosLinkTransport(InProcessLinkBus(), faults)

    def round_hook(rnd):
        if rnd == 2:
            chaos.partition_both("us", "eu")
        if rnd == 4:
            chaos.heal_both("us", "eu")

    with te_config.wire_ladder_mode("int8"):
        (chaotic, feds) = _run_federation(
            4,
            REGIONS_2X2,
            rounds,
            transport=chaos,
            settle=3,
            round_hook=round_hook,
            make=_make_dense_float,
            update=_update_dense_float,
        )
        h = (
            feds[0].link_health("eu").duplicates
            + feds[2].link_health("us").duplicates
        )
        (clean, _) = _run_federation(
            4,
            REGIONS_2X2,
            rounds,
            settle=3,
            make=_make_dense_float,
            update=_update_dense_float,
        )
    (exact, _) = _run_federation(
        4,
        REGIONS_2X2,
        rounds,
        settle=3,
        make=_make_dense_float,
        update=_update_dense_float,
    )
    assert h > 0  # the ledger actually absorbed duplicates
    for (cv, cp, _), (fv, _, _) in zip(chaotic, clean):
        assert not cp.degraded  # healed
        for k, want in fv.items():
            assert np.array_equal(cv[k], want), k
    # non-vacuous: the rung was actually lossy for the dense family
    assert not np.array_equal(chaotic[0][0]["cat"], exact[0][0]["cat"])
    # ... while the tiny counter below the byte floor stayed exact
    np.testing.assert_array_equal(chaotic[0][0]["sum"], exact[0][0]["sum"])
