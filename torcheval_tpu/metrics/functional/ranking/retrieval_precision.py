"""Retrieval precision (precision @ k).

Parity: reference torcheval/metrics/functional/ranking/retrieval_precision.py
(`retrieval_precision` :7-83, `_retrieval_precision_param_check` :86-96,
`_retrieval_precision_update_input_check` :99-119,
`_retrieval_precision_compute`/`get_topk`/count helpers :122-162).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.ops.topk import topk
from torcheval_tpu.utils.convert import to_jax


def _retrieval_precision_param_check(
    k: Optional[int] = None, limit_k_to_size: bool = False
) -> None:
    if k is not None and k <= 0:
        raise ValueError(f"k must be a positive integer, got k={k}.")
    if limit_k_to_size and k is None:
        raise ValueError(
            "when limit_k_to_size is True, k must be a positive (>0) integer."
        )


def _retrieval_precision_update_input_check(
    input: jax.Array,
    target: jax.Array,
    num_tasks: int = 1,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "input and target must be of the same shape, got "
            f"input.shape={input.shape} and target.shape={target.shape}."
        )
    if num_tasks == 1:
        if input.ndim != 1:
            raise ValueError(
                "input and target should be one dimensional tensors, "
                f"got input and target dimensions={input.ndim}."
            )
    elif input.ndim != 2 or input.shape[0] != num_tasks:
        raise ValueError(
            "input and target should be two dimensional tensors with "
            f"{num_tasks} rows, got input and target shape={input.shape}."
        )


@partial(jax.jit, static_argnames=("k",))
def get_topk(t: jax.Array, k: Optional[int]) -> Tuple[jax.Array, jax.Array]:
    """Top-k values and indices along the last axis (ties unordered, as in the
    reference's ``torch.topk``)."""
    nb_samples = t.shape[-1]
    if k is None:
        k = nb_samples
    # O(n) native selection on the CPU lowering (ops/native/topk.cc);
    # lax.top_k everywhere else — identical semantics
    return topk(t, min(k, nb_samples))


def _compute_nb_relevant_items_retrieved(
    input: jax.Array, k: Optional[int], target: jax.Array
) -> jax.Array:
    _, topk_idx = get_topk(input, k)
    return jnp.sum(jnp.take_along_axis(target, topk_idx, axis=-1), axis=-1)


def _compute_total_number_items_retrieved(
    input: jax.Array, k: Optional[int] = None, limit_k_to_size: bool = False
) -> int:
    nb_samples = input.shape[-1]
    if k is None:
        return nb_samples
    if limit_k_to_size:
        return min(k, nb_samples)
    return k


@partial(jax.jit, static_argnames=("k", "limit_k_to_size"))
def _retrieval_precision_compute(
    input: jax.Array,
    target: jax.Array,
    k: Optional[int] = None,
    limit_k_to_size: bool = False,
) -> jax.Array:
    # fully fused: the eager form dispatched 3 ops and uploaded the
    # divisor constant per call
    nb_relevant = _compute_nb_relevant_items_retrieved(input, k, target)
    nb_retrieved = _compute_total_number_items_retrieved(input, k, limit_k_to_size)
    return nb_relevant / nb_retrieved


def retrieval_precision(
    input,
    target,
    k: Optional[int] = None,
    limit_k_to_size: bool = False,
    num_tasks: int = 1,
) -> jax.Array:
    """Proportion of relevant items among the top-k retrieved items.

    Class version: ``torcheval_tpu.metrics.RetrievalPrecision``.

    Args:
        input: predicted relevance scores, shape (num_samples,) or
            (num_tasks, num_samples).
        target: 0/1 relevance labels, same shape.
        k: number of retrieved elements considered (None = all).
        limit_k_to_size: clamp k to the number of samples.
        num_tasks: number of independent tasks (rows).

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import retrieval_precision
        >>> retrieval_precision(jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2]),
        ...                     jnp.array([0, 0, 1, 1, 1, 0, 1]), k=2)
        Array(0.5, dtype=float32)
    """
    input, target = to_jax(input), to_jax(target)
    _retrieval_precision_param_check(k, limit_k_to_size)
    _retrieval_precision_update_input_check(input, target, num_tasks)
    return _retrieval_precision_compute(input, target, k, limit_k_to_size)
