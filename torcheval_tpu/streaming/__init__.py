"""Streaming generative eval: O(1) decode-step metrics.

Token-streaming quality metrics that accept ONE decode step at a time
and carry constant-size state — the metric-side analogue of an O(1)
autoregressive decode cache (docs/streaming-eval.md):

- :class:`StreamingPerplexity` — running NLL sum + token count.
- :class:`StreamingTokenAccuracy` / :class:`StreamingTokenEditStats` —
  positional WER/CER-core substitution/insertion/deletion counters
  against a reference stream.
- :class:`StreamingNgramOverlap` — bounded n-gram tail + hashed clipped-
  match count planes, the BLEU precision core without sequence storage.

Each is a standard :class:`~torcheval_tpu.metrics.metric.Metric`, so
sync, subgroups, elastic checkpointing, ShardSpec and the wire ladder
apply unchanged. For MANY concurrent streams keyed by request id, use
:class:`StreamTable` (``torcheval_tpu.table.streaming``): one fused
device ingest per decode batch, per-request slots, TTL/eviction
lifecycle and drain-time distribution sketches.
"""

from torcheval_tpu.streaming.edit import (
    StreamingTokenAccuracy,
    StreamingTokenEditStats,
    TokenEditStats,
)
from torcheval_tpu.streaming.ngram import NgramOverlap, StreamingNgramOverlap
from torcheval_tpu.streaming.perplexity import StreamingPerplexity

__all__ = [
    "NgramOverlap",
    "StreamTable",
    "StreamingNgramOverlap",
    "StreamingPerplexity",
    "StreamingTokenAccuracy",
    "StreamingTokenEditStats",
    "TokenEditStats",
]


def __getattr__(name):
    # lazy: table.streaming imports streaming._mix, so an eager import
    # here would be circular whenever table.streaming loads first
    if name == "StreamTable":
        from torcheval_tpu.table.streaming import StreamTable

        return StreamTable
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
