"""Quantized wire ladder (ISSUE 18): codec, per-family policy, fallback.

Four layers under test:

- the blockwise int8 codec itself (``torcheval_tpu.wire``): roundtrip
  inside the published hard bound (``amax(block)/254`` per element), the
  traceable jnp twins bit-matching the numpy wire, and the wire-bytes
  arithmetic behind the >= 3x acceptance claim;
- the eager packed wire (``metrics.synclib``) at all three rungs,
  pinned per family against the merge oracle: bytes shrink, error stays
  inside the codec bound, integer-counter states are BIT-exact at every
  rung, and sparse trimming composes with quantization (trim first,
  then quantize the trimmed payload);
- the process-wide :class:`~torcheval_tpu.wire.WireLadder` fallback
  registry: a measured ``DriftSpec`` budget breach steps the family one
  rung toward ``exact``, emits a typed ``WireTierEvent``, and the NEXT
  sync observably rides the mercy rung (``SyncProvenance.wire_tier``);
- schema discipline: ``SyncProvenance.wire_tier`` is appended-defaulted
  (legacy positional construction keeps working) and the new/extended
  events round-trip through schema-1 JSONL dicts.

In-jit int8 (EXTEND gather + owner-partitioned reduce-scatter) is
pinned in tests/metrics/test_sharded.py; the federation WAN wire at
int8 in tests/metrics/test_federation.py.
"""

from __future__ import annotations

import copy

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torcheval_tpu import config as te_config
from torcheval_tpu import obs, wire
from torcheval_tpu.distributed import LocalReplicaGroup
from torcheval_tpu.metrics import (
    BinaryAUROC,
    Cat,
    MulticlassAccuracy,
    StreamingBinaryAUROC,
    WindowedBinaryAUROC,
)
from torcheval_tpu.metrics import synclib
from torcheval_tpu.metrics.synclib import (
    _pack_rank_states,
    metrics_traversal_order,
)
from torcheval_tpu.metrics.toolkit import (
    get_synced_metric_collection,
    sync_and_compute,
)
from torcheval_tpu.obs import quality

RNG = np.random.default_rng(18)


@pytest.fixture(autouse=True)
def _clean_ladder():
    """No ladder policy, breach cap, or quality watch leaks across
    tests."""
    yield
    wire.LADDER.reset()
    te_config.set_wire_ladder("exact")
    for watch in quality.active_watches():
        watch.close()


# --------------------------------------------------------------- the codec


@pytest.mark.parametrize("size", [1, 5, 31, 32, 33, 1000, 4096])
def test_quantize_roundtrip_within_hard_bound(size):
    a = RNG.normal(size=size).astype(np.float32) * 3.0
    q, scales = wire.quantize_blockwise(a, 32)
    out = wire.dequantize_blockwise(q, scales, size)
    bound = wire.int8_error_bound(a, 32)
    assert out.shape == (size,)
    assert float(np.max(np.abs(out - a))) <= bound
    # the bound itself is tight-ish: amax/254 of the worst block
    assert bound <= float(np.abs(a).max()) / 254.0 + 1e-12


def test_quantize_zero_blocks_exact():
    a = np.zeros(128, np.float32)
    a[70] = 5.0  # one nonzero block, three all-zero blocks
    q, scales = wire.quantize_blockwise(a, 32)
    assert scales[0] == 0.0 and scales[3] == 0.0
    out = wire.dequantize_blockwise(q, scales, a.size)
    np.testing.assert_array_equal(out[:64], 0.0)
    assert abs(out[70] - 5.0) <= wire.int8_error_bound(a, 32)


def test_jit_twins_match_numpy_codec():
    """The traceable quantize/pack/unpack must be the SAME wire as the
    numpy codec — every tier dequantizes to identical values."""
    a = RNG.normal(size=333).astype(np.float32)
    q_np, s_np = wire.quantize_blockwise(a, 32)
    q_j, s_j = jax.jit(lambda x: wire.quantize_blockwise_jit(x, 32))(
        jnp.asarray(a)
    )
    np.testing.assert_array_equal(np.asarray(q_j), q_np)
    np.testing.assert_array_equal(np.asarray(s_j), s_np)
    packed = jax.jit(wire.pack_wire)(q_j, s_j)
    assert packed.dtype == jnp.uint8
    assert packed.size == wire.int8_wire_bytes(a.size, 32)
    unpacked = jax.jit(
        lambda w: wire.unpack_wire(w, s_np.size, 32)
    )(packed)
    np.testing.assert_array_equal(
        np.asarray(unpacked)[: a.size],
        wire.dequantize_blockwise(q_np, s_np, a.size),
    )


def test_wire_bytes_ratio_and_rungs():
    # the arithmetic behind the >= 3x acceptance claim: 1 + 4/32 bytes
    # per element vs 4 exact bytes -> 3.55x at the default block
    assert wire.int8_wire_bytes(4096, 32) == 4096 + 4 * 128
    assert 4 * 4096 / wire.int8_wire_bytes(4096, 32) > 3.5
    assert wire.RUNGS == ("exact", "bf16", "int8")
    assert wire.rung_index("off") == 0  # legacy spelling
    assert wire.normalize_rung("off") == "exact"
    with pytest.raises(ValueError, match="unknown wire rung"):
        wire.rung_index("fp4")


# ------------------------------------------------- config: the ladder policy


def test_wire_ladder_config_and_legacy_views():
    te_config.set_wire_ladder("*=bf16,MulticlassAUROC=int8")
    assert te_config.wire_rung_for("MulticlassAUROC") == "int8"
    assert te_config.wire_rung_for("Mean") == "bf16"  # the default family
    # the legacy single-policy API is a view over the "*" entry
    assert te_config.sync_compression() == "bf16"
    te_config.set_sync_compression("off")
    assert te_config.wire_rung_for("Mean") == "exact"
    assert te_config.wire_rung_for("MulticlassAUROC") == "int8"
    with te_config.wire_ladder_mode("int8"):
        assert te_config.wire_rung_for("anything") == "int8"
    assert te_config.wire_rung_for("Mean") == "exact"  # restored
    with pytest.raises(ValueError):
        te_config.set_wire_ladder("fp4")


# ------------------------- eager wire: per-family bytes x error vs oracle


def _wire_bytes_at(metric, rung) -> int:
    payload = {"_m": metric._sync_state_dict()}
    order = metrics_traversal_order(payload)
    _, flat = _pack_rank_states(payload, order, rung)
    return int(flat.size)


def _state_bound(metric, block) -> float:
    """The codec's hard bound over every float state the metric ships."""
    bound = 0.0
    for v in jax.tree_util.tree_leaves(metric._sync_state_dict()):
        a = np.asarray(v)
        if a.dtype.kind == "f" and a.nbytes > 1024:
            bound = max(bound, wire.int8_error_bound(a, block))
    return bound


def _auroc_replicas(factory, world=4, n=2000):
    out = []
    for r in range(world):
        rng = np.random.default_rng(200 + r)
        m = factory()
        m.update(
            jnp.asarray(rng.random(n).astype(np.float32)),
            jnp.asarray((rng.random(n) < 0.5).astype(np.float32)),
        )
        out.append(m)
    return out


FLOAT_FAMILIES = [
    ("BinaryAUROC", lambda: BinaryAUROC()),
    ("WindowedBinaryAUROC", lambda: WindowedBinaryAUROC(max_num_samples=4096)),
    ("Cat", lambda: Cat()),
]


@pytest.mark.parametrize("name,factory", FLOAT_FAMILIES)
def test_float_family_bytes_and_error_ladder(name, factory):
    """THE acceptance table, one float family per row: at each rung the
    synced result stays within the codec's hard bound of the merge
    oracle, and the int8 rung ships >= 3x fewer payload bytes than
    exact."""
    if name == "Cat":
        ms = []
        for r in range(4):
            rng = np.random.default_rng(300 + r)
            m = Cat()
            m.update(jnp.asarray(rng.normal(size=2000).astype(np.float32)))
            ms.append(m)
    else:
        ms = _auroc_replicas(factory)
    group = LocalReplicaGroup(jax.devices("cpu")[:1] * 4)
    block = te_config.wire_block_size()

    bytes_at = {}
    vals = {}
    for rung in wire.RUNGS:
        bytes_at[rung] = sum(_wire_bytes_at(m, rung) for m in ms)
        with te_config.wire_ladder_mode(rung):
            vals[rung] = np.asarray(
                sync_and_compute([copy.deepcopy(m) for m in ms], group)
            )
    oracle = copy.deepcopy(ms[0])
    oracle.merge_state([copy.deepcopy(m) for m in ms[1:]])
    want = np.asarray(oracle.compute())

    np.testing.assert_array_equal(vals["exact"], want)  # rung 0: bit-exact
    assert bytes_at["bf16"] < bytes_at["exact"]
    # acceptance: >= 3x fewer payload bytes at the int8 rung
    assert bytes_at["int8"] * 3 <= bytes_at["exact"], (
        name,
        bytes_at,
    )
    # error pinned to the CODEC bound, not a vibes tolerance: each
    # shipped element is quantized exactly once, so the synced states
    # sit within max-over-ranks amax(block)/254 of the oracle's
    bound = max(_state_bound(m, block) for m in ms)
    assert bound > 0.0
    if name == "Cat":  # identity compute: value error == state error
        assert float(np.max(np.abs(vals["int8"] - want))) <= bound
    else:
        # AUROC is a rank statistic of the states; perturbations
        # bounded by the grid step move it by o(1)
        assert abs(float(vals["int8"]) - float(want)) < 0.02
    assert np.all(np.isfinite(vals["int8"]))


def test_integer_counter_states_bit_exact_at_every_rung():
    """Acceptance: pure-integer-counter states are BIT-exact at every
    rung — the quantizer never touches them — and their wire bytes do
    not change."""
    ms = []
    for r in range(4):
        rng = np.random.default_rng(400 + r)
        m = MulticlassAccuracy(num_classes=4, average=None)
        m.update(
            jnp.asarray(rng.uniform(size=(512, 4)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 4, size=512)),
        )
        ms.append(m)
    group = LocalReplicaGroup(jax.devices("cpu")[:1] * 4)
    oracle = copy.deepcopy(ms[0])
    oracle.merge_state([copy.deepcopy(m) for m in ms[1:]])
    want = np.asarray(oracle.compute())
    base = _wire_bytes_at(ms[0], "exact")
    for rung in wire.RUNGS:
        with te_config.wire_ladder_mode(rung):
            got = np.asarray(
                sync_and_compute([copy.deepcopy(m) for m in ms], group)
            )
        np.testing.assert_array_equal(got, want)
        assert _wire_bytes_at(ms[0], rung) == base


def test_sparse_trim_composes_with_int8():
    """Trim-then-quantize: a mostly-zero histogram rides the sparse
    encoding FIRST, then only the surviving values quantize (the
    ``sparse8`` composition) — fewer bytes than sparse alone, and the
    nonzero sites reconstruct within the codec bound."""
    a = np.zeros(16384, np.float32)
    idx = RNG.choice(16384, size=900, replace=False)
    a[idx] = RNG.normal(size=900).astype(np.float32)
    entry_exact, chunks_exact = synclib._encode_array(a, "exact")
    entry_int8, chunks_int8 = synclib._encode_array(a, "int8")
    assert entry_exact[2][0] == "sparse"
    assert entry_int8[2][0] == "sparse8"
    exact_bytes = sum(c.size for c in chunks_exact)
    int8_bytes = sum(c.size for c in chunks_int8)
    assert int8_bytes < exact_bytes
    buf = np.concatenate([c.reshape(-1) for c in chunks_int8])
    out, off = synclib._decode_array(buf, 0, entry_int8)
    assert off == buf.size
    vals = a[np.sort(idx)]
    bound = wire.int8_error_bound(vals, 32)
    assert float(np.max(np.abs(out - a))) <= bound
    assert not np.any(out[a == 0.0])  # trimmed (original-zero) sites stay zero


def test_provenance_reports_actual_wire_tier_per_metric():
    """``SyncProvenance.wire_tier`` reports what the wire DID, not what
    was configured: under an int8 policy a big float family stamps
    "int8" while a tiny integer-counter metric in the SAME collection
    stays "exact"."""
    def _replica(r):
        rng = np.random.default_rng(500 + r)
        big = BinaryAUROC()
        big.update(
            jnp.asarray(rng.random(2000).astype(np.float32)),
            jnp.asarray((rng.random(2000) < 0.5).astype(np.float32)),
        )
        small = MulticlassAccuracy()
        small.update(
            jnp.asarray(rng.uniform(size=(32, 4)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 4, size=32)),
        )
        return {"big": big, "small": small}

    group = LocalReplicaGroup(jax.devices("cpu")[:1] * 2)
    with te_config.wire_ladder_mode("int8"):
        synced = get_synced_metric_collection(
            [_replica(0), _replica(1)], group
        )
    assert synced["big"].sync_provenance.wire_tier == "int8"
    assert synced["small"].sync_provenance.wire_tier == "exact"

    # the per-rank meta fold behind it: lossiest tier across ranks wins
    order = [("m", "x")]
    int8_meta = [("tensor", [((2016,), "float32", ("int8block", 32, 63, 0))], None)]
    raw_meta = [("tensor", [((2016,), "float32", None)], None)]
    sparse_meta = [("tensor", [((2016,), "float32", ("sparse", 3, "<f4"))], None)]
    assert synclib._meta_wire_tiers(order, [int8_meta, raw_meta]) == {
        "m": "int8"
    }
    assert synclib._meta_wire_tiers(order, [raw_meta, sparse_meta]) == {
        "m": "exact"
    }


# ----------------------------- ladder registry: breach -> fallback -> event


def test_breach_steps_cap_and_emits_event(obs_recorder):
    te_config.set_wire_ladder("int8")
    assert wire.effective_rung("BinaryAUROC") == "int8"
    step1 = wire.note_budget_breach(
        "BinaryAUROC", series="score/0", breach="psi"
    )
    assert step1 == ("int8", "bf16")
    assert wire.LADDER.cap("BinaryAUROC") == "bf16"
    assert wire.effective_rung("BinaryAUROC") == "bf16"
    step2 = wire.note_budget_breach("BinaryAUROC", breach="ks")
    assert step2 == ("bf16", "exact")
    assert wire.effective_rung("BinaryAUROC") == "exact"
    # already exact: nothing left to fall back to, no event
    assert wire.note_budget_breach("BinaryAUROC") is None
    events = [e for e in obs_recorder.log.tail() if e.kind == "wire_tier"]
    assert [(e.prev_tier, e.tier) for e in events] == [
        ("int8", "bf16"),
        ("bf16", "exact"),
    ]
    assert events[0].family == "BinaryAUROC"
    assert events[0].series == "score/0"
    assert events[0].breach == "psi"
    # other families are untouched
    assert wire.effective_rung("Cat") == "int8"
    # counters surface the fallback
    counters = obs.default_registry().read()["wire"]
    assert counters["fallback_families"] == 1
    assert counters["cap_BinaryAUROC"] == "exact"
    wire.LADDER.reset("BinaryAUROC")
    assert wire.effective_rung("BinaryAUROC") == "int8"


def test_drift_budget_breach_falls_back_next_sync_rides_mercy_rung(
    obs_recorder,
):
    """The end-to-end fallback contract (seeded, deterministic): a
    DriftSpec budget breach on a watched metric steps its family from
    int8 to bf16, emits the WireTierEvent, and the NEXT sync observably
    rides bf16 (``SyncProvenance.wire_tier``)."""
    te_config.set_wire_ladder("int8")
    rng = np.random.default_rng(11)
    metric = WindowedBinaryAUROC(max_num_samples=4096)
    # plan arg 0 is the ring-buffer column index; the scores are arg 1
    watch = quality.watch_inputs(
        metric, bounds=(-4.0, 4.0), num_bins=16, label="score", args=(1,)
    )
    for _ in range(4):
        metric.update(
            jnp.asarray(rng.normal(size=512).astype(np.float32)),
            jnp.asarray((rng.random(512) < 0.5).astype(np.float32)),
        )
    watch.add_drift(
        quality.DriftSpec(psi=0.2, ks=0.15, z=6.0, min_count=128)
    )
    monitor = obs.Monitor(cooldown=0.0)
    assert monitor.check() == []  # in-bounds replay, no breach
    assert wire.effective_rung("WindowedBinaryAUROC") == "int8"
    for _ in range(4):
        metric.update(
            jnp.asarray((rng.normal(size=512) + 1.5).astype(np.float32)),
            jnp.asarray((rng.random(512) < 0.5).astype(np.float32)),
        )
    raised = monitor.check()
    assert raised  # drift alerts fired
    assert wire.effective_rung("WindowedBinaryAUROC") == "bf16"
    events = [e for e in obs_recorder.log.tail() if e.kind == "wire_tier"]
    assert events and events[-1].tier == "bf16"
    assert events[-1].family == "WindowedBinaryAUROC"
    assert events[-1].series == "score/1"
    assert set(events[-1].breach.split(",")) <= {"psi", "ks", "z"}

    # the NEXT sync rides the mercy rung, visible in provenance
    group = LocalReplicaGroup(jax.devices("cpu")[:1] * 2)
    synced = get_synced_metric_collection(
        [{"m": copy.deepcopy(metric)}, {"m": copy.deepcopy(metric)}],
        group,
    )
    prov = synced["m"].sync_provenance
    assert prov.wire_tier == "bf16"
    sync_events = [e for e in obs_recorder.log.tail() if e.kind == "sync"]
    assert sync_events and sync_events[-1].wire_tier == "bf16"


# ------------------------------------- schema discipline: provenance/events


def test_sync_provenance_legacy_positional_construction():
    from torcheval_tpu.resilience import SyncProvenance

    legacy = SyncProvenance((0, 1), 2, False, "all")  # PR 2 arity
    assert legacy.wire_tier == "exact"
    assert legacy.admission_rung == 0 and legacy.version == 0
    staleness = SyncProvenance((0,), 2, True, "quorum", True, 3, 1, 0.5)
    assert staleness.wire_tier == "exact"
    full = SyncProvenance(
        (0, 1), 2, False, "all", False, 0, 0, 0.0, 1.0, 0, 0, "int8"
    )
    assert full.wire_tier == "int8"
    assert legacy._replace(wire_tier="bf16").wire_tier == "bf16"


def test_wire_tier_event_schema1_jsonl_roundtrip():
    from torcheval_tpu.obs.events import (
        SyncEvent,
        WireTierEvent,
        event_from_dict,
    )

    ev = WireTierEvent(
        family="BinaryAUROC",
        series="score/0",
        prev_tier="int8",
        tier="bf16",
        breach="psi,ks",
    )
    d = ev.as_dict()
    assert d["schema"] == 1  # new event type, SAME schema version
    assert d["kind"] == "wire_tier"
    assert event_from_dict(d) == ev
    d["future_field"] = "x"  # newer-writer tolerance
    assert event_from_dict(d).tier == "bf16"

    # SyncEvent.wire_tier is a new OPTIONAL field: legacy dicts without
    # it (schema-1 JSONL written before this PR) still reconstruct
    s = SyncEvent(metrics=2, world_size=4, wire_tier="int8")
    sd = s.as_dict()
    assert sd["schema"] == 1
    assert event_from_dict(sd) == s
    del sd["wire_tier"]
    assert event_from_dict(sd).wire_tier == "exact"


def test_canonical_crc_symmetric_across_rungs():
    """Federation's crc moves to POST-DEQUANTIZE canonical bytes: the
    crc of a wire packed at any rung equals the crc of its decoded
    canonical re-pack — so sender (packs lossy) and receiver (holds
    decoded arrays) agree without shipping a second checksum."""
    states = {
        "m": {
            "buf": jnp.asarray(RNG.normal(size=2000).astype(np.float32)),
            "n": jnp.asarray(7, jnp.int32),
        }
    }
    order = metrics_traversal_order(states)
    for rung in wire.RUNGS:
        meta, flat = _pack_rank_states(
            {"m": dict(states["m"])}, order, rung
        )
        crc1 = synclib.canonical_crc(order, meta, flat)
        # decode, re-pack exact, crc again: must be the same number
        decoded = synclib._unpack_rank_states(
            {"m": dict(states["m"])}, order, meta, flat
        )
        meta2, flat2 = _pack_rank_states(decoded, order, "exact")
        assert crc1 == synclib.canonical_crc(order, meta2, flat2)
