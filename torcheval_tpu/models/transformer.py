"""A small Flax transformer LM used by examples, benchmarks and the
multi-chip dry-run.

The reference is a metrics library whose examples drive small ``nn.Module``s
(reference examples/simple_example.py, distributed_example.py); this is our
equivalent workload generator, written mesh-aware so metrics can be exercised
under real dp/tp shardings:

- parameters carry ``PartitionSpec``s (``param_specs``) sharding attention
  heads and MLP hidden over the ``tp`` axis,
- the batch axis shards over ``dp``; under ``pjit`` XLA inserts the
  tp-reduction and dp-metric collectives automatically.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class Block(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm()(x)
        h = nn.SelfAttention(
            num_heads=self.n_heads,
            qkv_features=self.d_model,
            use_bias=False,
            deterministic=True,
        )(h, mask=nn.make_causal_mask(jnp.ones(h.shape[:2], dtype=bool)))
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.d_ff, use_bias=False)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, use_bias=False)(h)
        return x + h


class TransformerLM(nn.Module):
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    max_len: int = 128

    @nn.compact
    def __call__(self, tokens):
        pos = jnp.arange(tokens.shape[-1])
        x = nn.Embed(self.vocab_size, self.d_model)(tokens)
        x = x + nn.Embed(self.max_len, self.d_model)(pos)
        for _ in range(self.n_layers):
            x = Block(self.d_model, self.n_heads, self.d_ff)(x)
        x = nn.LayerNorm()(x)
        return nn.Dense(self.vocab_size, use_bias=False)(x)


def init_params(model: TransformerLM, batch: int = 2, seq: int = 16, seed: int = 0):
    tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
    return model.init(jax.random.PRNGKey(seed), tokens)


def param_specs(params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpecs for tensor parallelism over a ``tp`` mesh axis.

    2-D kernels shard their output features over tp (input-features for the
    down-projections, detected by name); embeddings shard features over tp;
    everything else (LayerNorm scales, 1-D params) replicates.
    """

    def spec_for(path, leaf):
        names = [getattr(k, "key", str(k)) for k in path]
        if leaf.ndim < 2:
            return P()
        joined = "/".join(names)
        if "Embed" in joined:
            return P(None, "tp")
        if "out" in joined or "Dense_1" in joined:
            # attention out-proj and MLP down-proj: contract over sharded dim
            return P("tp", None)
        if leaf.ndim >= 2:
            return P(*([None] * (leaf.ndim - 1) + ["tp"]))
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)
