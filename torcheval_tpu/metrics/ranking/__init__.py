from torcheval_tpu.metrics.ranking.click_through_rate import ClickThroughRate
from torcheval_tpu.metrics.ranking.hit_rate import HitRate
from torcheval_tpu.metrics.ranking.reciprocal_rank import ReciprocalRank
from torcheval_tpu.metrics.ranking.retrieval_precision import RetrievalPrecision
from torcheval_tpu.metrics.ranking.weighted_calibration import WeightedCalibration

__all__ = [
    "ClickThroughRate",
    "HitRate",
    "ReciprocalRank",
    "RetrievalPrecision",
    "WeightedCalibration",
]
