// Top-k selection — C++ XLA custom-call (CPU host kernel).
//
// XLA:CPU's top_k lowering sorts/selects at a few ns per element; to
// actually beat it the scan must do LESS than one branch per element.
// This kernel keeps a k-entry min-heap of packed (value_key, index)
// words and screens the stream through a chunked, auto-vectorized
// prefilter: each 32-element chunk computes its order keys and OR-folds
// a "beats the current k-th best" flag — for random data almost every
// chunk folds to zero and is skipped without touching the heap. Only
// chunks containing a candidate fall back to the scalar insert path.
// Worst case (ascending input, every element inserts) degrades to the
// classic O(n log k) heap scan.
//
// Semantics are IDENTICAL to jax.lax.top_k on CPU (pinned by
// tests/ops/test_segment_hist_topk.py): descending IEEE-754 totalOrder —
// +NaN > +Inf > ... > +0 > -0 > ... > -Inf > -NaN, i.e. bit-pattern
// order, NOT the NaN-last / ±0-collapsed key sort_desc.cc uses to match
// argsort(-x) — with ties ranked by ascending original index (stable).
//
// Inputs:  x (T, N) f32.
// Outputs: values (T, K) f32, indices (T, K) s32; K <= N taken from the
//          result shape (the dispatcher clamps k).
//
// Build: g++ -O3 -march=native -fPIC -shared (see native/__init__.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <functional>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// Ascending IEEE totalOrder as an unsigned 32-bit key (sign-magnitude ->
// lexicographic): ascending key == ascending totalOrder. Branchless so
// the prefilter loop vectorizes: negative b XORs all bits (~b),
// non-negative XORs just the sign (b | 0x80000000).
inline uint32_t OrderKey(uint32_t b) {
  const uint32_t m =
      static_cast<uint32_t>(static_cast<int32_t>(b) >> 31);
  return b ^ (m | 0x80000000u);
}

// Packing ~index into the low bits makes one uint64 comparison implement
// "value descending, index ascending" exactly.
inline uint64_t PackKey(uint32_t order_key, int64_t i) {
  return (static_cast<uint64_t>(order_key) << 32) |
         static_cast<uint32_t>(~static_cast<uint32_t>(i));
}

inline int32_t UnpackIndex(uint64_t key) {
  return static_cast<int32_t>(~static_cast<uint32_t>(key));
}

constexpr int kChunk = 32;

// Replace the min-heap's root (the current k-th best) and restore the
// heap property with ONE sift-down. std::pop_heap + push_heap walks the
// tree twice per displacement; a displacing candidate always evicts the
// root, so the single sift halves the per-insert tree work — the
// per-row fixed cost that dominated small rows (64x1000: the seed
// threshold starts low, so the first chunks nearly all fall through the
// prefilter into this path). Heap is min-at-root under
// std::greater<uint64_t>.
inline void ReplaceMin(uint64_t* heap, int64_t k, uint64_t v) {
  int64_t i = 0;
  for (;;) {
    const int64_t l = 2 * i + 1;
    const int64_t r = l + 1;
    int64_t s = i;
    uint64_t sv = v;
    if (l < k && heap[l] < sv) {
      s = l;
      sv = heap[l];
    }
    if (r < k && heap[r] < sv) {
      s = r;
    }
    if (s == i) {
      break;
    }
    heap[i] = heap[s];
    i = s;
  }
  heap[i] = v;
}

// Heap-scan one row: keys[0..k) ends holding the k largest packed keys,
// sorted descending.
//
// Seed window: the heap is seeded from the first min(n, 4k+64) elements
// via one nth_element + make_heap instead of just the first k. The
// running threshold then starts near its final value, so the expected
// number of chunks that fall through the vectorized prefilter into the
// scalar insert path drops from ~k·ln(n/k) spread over the early chunks
// to ~k·ln(n/window) — the early-phase scalar scans were the other half
// of the small-row fixed cost.
void TopKRow(const float* row, int64_t n, int64_t k, uint64_t* heap) {
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(row);
  const int64_t seed = std::min<int64_t>(n, 4 * k + 64);
  for (int64_t j = 0; j < seed; ++j) {
    heap[j] = PackKey(OrderKey(bits[j]), j);
  }
  if (seed > k) {
    std::nth_element(heap, heap + (k - 1), heap + seed,
                     std::greater<uint64_t>());
  }
  std::make_heap(heap, heap + k, std::greater<uint64_t>());
  // Exactness of the key32-only prefilter: candidates with key32 EQUAL
  // to the heap minimum's key32 can never displace it — the scan moves
  // forward, so their packed index bits are strictly smaller.
  uint32_t min_key = static_cast<uint32_t>(heap[0] >> 32);
  int64_t i = seed;
  for (; i + kChunk <= n; i += kChunk) {
    // max-fold prefilter: a pure vertical max over the chunk's keys
    // (vectorizes to packed unsigned max), one compare per chunk
    uint32_t mx = 0;
    for (int c = 0; c < kChunk; ++c) {
      const uint32_t ok = OrderKey(bits[i + c]);
      mx = ok > mx ? ok : mx;
    }
    if (mx <= min_key) {
      continue;
    }
    for (int c = 0; c < kChunk; ++c) {
      const uint32_t ok = OrderKey(bits[i + c]);
      if (ok > min_key) {
        ReplaceMin(heap, k, PackKey(ok, i + c));
        min_key = static_cast<uint32_t>(heap[0] >> 32);
      }
    }
  }
  for (; i < n; ++i) {  // tail
    const uint32_t ok = OrderKey(bits[i]);
    if (ok > min_key) {
      ReplaceMin(heap, k, PackKey(ok, i));
      min_key = static_cast<uint32_t>(heap[0] >> 32);
    }
  }
  std::sort(heap, heap + k, std::greater<uint64_t>());
}

}  // namespace

static ffi::Error TopKImpl(ffi::Buffer<ffi::F32> x,
                           ffi::ResultBuffer<ffi::F32> values,
                           ffi::ResultBuffer<ffi::S32> indices) {
  const auto dims = x.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("x must be rank 2 (tasks, n)");
  }
  const int64_t tasks = dims[0];
  const int64_t n = dims[1];
  const auto vdims = values->dimensions();
  const auto idims = indices->dimensions();
  if (vdims.size() != 2 || idims.size() != 2 || vdims[0] != tasks ||
      idims[0] != tasks || vdims[1] != idims[1]) {
    return ffi::Error::InvalidArgument(
        "values/indices must be (tasks, k) with matching k");
  }
  const int64_t k = vdims[1];
  if (k > n) {
    return ffi::Error::InvalidArgument("k must be <= n");
  }
  const float* in = x.typed_data();
  float* v = values->typed_data();
  int32_t* idx = indices->typed_data();
  if (k == 0) {
    return ffi::Error::Success();
  }

  std::vector<uint64_t> keys(n);
  for (int64_t t = 0; t < tasks; ++t) {
    const float* row = in + t * n;
    if (k * 4 >= n) {
      // large-k: the heap churns on most elements; a straight sort of
      // all packed keys is cheaper and shares the stability contract
      const uint32_t* bits = reinterpret_cast<const uint32_t*>(row);
      for (int64_t i = 0; i < n; ++i) {
        keys[i] = PackKey(OrderKey(bits[i]), i);
      }
      std::sort(keys.begin(), keys.end(), std::greater<uint64_t>());
    } else {
      TopKRow(row, n, k, keys.data());
    }
    for (int64_t j = 0; j < k; ++j) {
      const int32_t i = UnpackIndex(keys[j]);
      idx[t * k + j] = i;
      v[t * k + j] = row[i];
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(TopK, TopKImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>());
