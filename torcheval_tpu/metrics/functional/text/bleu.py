"""BLEU score.

Parity: reference torcheval/metrics/functional/text/bleu.py (`bleu_score`
:13-62, `_bleu_score_update` :65-111, `_bleu_score_compute` :114-137,
brevity penalty :140-146, `_get_ngrams` :149-162). N-gram counting is
host-side string processing (as in the reference); the per-update result is
a small fixed-size vector of counters that accumulates on device.
"""

from __future__ import annotations

from collections import Counter
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


def _get_ngrams(sentence: Sequence[str], n_gram: int) -> Counter:
    if n_gram not in (1, 2, 3, 4):
        raise ValueError(f"n_gram should be 1, 2, 3, or 4, got {n_gram}.")
    ngram_counts: Counter = Counter()
    for n_val in range(1, n_gram + 1):
        for i in range(0, len(sentence) - n_val + 1):
            ngram_counts[tuple(sentence[i : i + n_val])] += 1
    return ngram_counts


def _bleu_score_update(
    input: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int,
) -> Tuple[float, float, np.ndarray, np.ndarray]:
    """Clipped n-gram matches and possible matches per order for one batch.

    Returns host-side counters (floats / numpy vectors); the caller
    accumulates them into device state.
    """
    input_ = [input] if isinstance(input, str) else input
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]

    if len(input_) != len(target_):
        raise ValueError(
            "Input and target corpus should have same sizes, but input "
            f"corpus size = {len(input_)}, target corpus size = {len(target_)} "
        )

    input_len = 0.0
    target_len = 0.0
    matches_by_order = np.zeros(n_gram, dtype=np.float64)
    possible_matches_by_order = np.zeros(n_gram, dtype=np.float64)

    for candidate, references in zip(input_, target_):
        candidate_tokenized = candidate.split()
        references_tokenized = [ref.split() for ref in references]

        len_candidate = len(candidate_tokenized)
        len_reference = min(len(ref) for ref in references_tokenized)
        input_len += len_candidate
        target_len += len_reference

        candidate_ngram_counter = _get_ngrams(candidate_tokenized, n_gram)
        reference_ngram_counter: Counter = Counter()
        for ref in references_tokenized:
            reference_ngram_counter |= _get_ngrams(ref, n_gram)
        overlap = candidate_ngram_counter & reference_ngram_counter

        for ngram in overlap:
            matches_by_order[len(ngram) - 1] += overlap[ngram]

        for i in range(n_gram):
            if len_candidate - i > 0:
                possible_matches_by_order[i] += len_candidate - i

    if np.min(possible_matches_by_order) == 0:
        raise ValueError(
            "the input is too short to find all n-gram matches with "
            f"n_gram={n_gram}"
        )

    return input_len, target_len, matches_by_order, possible_matches_by_order


def _bleu_score_compute(
    input_len: jax.Array,
    target_len: jax.Array,
    matches_by_order: jax.Array,
    possible_matches_by_order: jax.Array,
    n_gram: int,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    if weights is not None:
        weights = jnp.asarray(weights)
        if n_gram != weights.shape[0]:
            raise ValueError(
                "the length of weights should equal n_gram, got "
                f"len(weights)={weights.shape[0]}, n_gram={n_gram}"
            )
    if weights is None:
        weights = jnp.full((n_gram,), 1 / n_gram, dtype=jnp.float32)

    input_len = jnp.asarray(input_len, dtype=jnp.float32)
    target_len = jnp.asarray(target_len, dtype=jnp.float32)
    matches = jnp.asarray(matches_by_order, dtype=jnp.float32)
    possible = jnp.asarray(possible_matches_by_order, dtype=jnp.float32)

    precisions = matches / possible
    geometric_mean = jnp.exp(jnp.sum(weights * jnp.log(precisions)))
    brevity_penalty = jnp.where(
        input_len > target_len, 1.0, jnp.exp(1 - target_len / input_len)
    )
    return brevity_penalty * geometric_mean


def bleu_score(
    input: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """BLEU score of translations against (multi-)references.

    Class version: ``torcheval_tpu.metrics.BLEUScore``.

    Args:
        input: translations to score — a string or sequence of strings.
        target: list of references for each translation; requires
            ``len(input) == len(target)``.
        n_gram: maximum n-gram order, in {1, 2, 3, 4}.
        weights: optional per-order weight distribution of length ``n_gram``
            (uniform if unspecified).

    Examples::

        >>> from torcheval_tpu.metrics.functional import bleu_score
        >>> candidates = ["the squirrel is eating the nut"]
        >>> references = [["a squirrel is eating a nut",
        ...                "the squirrel is eating a tasty nut"]]
        >>> bleu_score(candidates, references, n_gram=4)
        Array(0.53728497, dtype=float32)
    """
    if n_gram not in (1, 2, 3, 4):
        raise ValueError(f"n_gram should be 1, 2, 3, or 4, got {n_gram}.")
    (
        input_len,
        target_len,
        matches_by_order,
        possible_matches_by_order,
    ) = _bleu_score_update(input, target, n_gram)
    return _bleu_score_compute(
        jnp.asarray(input_len),
        jnp.asarray(target_len),
        jnp.asarray(matches_by_order),
        jnp.asarray(possible_matches_by_order),
        n_gram,
        weights,
    )
