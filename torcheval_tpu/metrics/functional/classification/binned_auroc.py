"""Binned AUROC: trapezoidal AUROC over a fixed threshold grid.

Parity: reference torcheval/metrics/functional/classification/binned_auroc.py
(binary :17-137; multiclass :140-220). Returns ``(auroc, threshold)``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.tensor_utils import (
    create_threshold_tensor,
    trapezoid,
)
from torcheval_tpu.utils.convert import to_jax

DEFAULT_NUM_THRESHOLD = 200


def _binary_binned_auroc_param_check(num_tasks: int, threshold: jax.Array) -> None:
    if num_tasks < 1:
        raise ValueError(
            "`num_tasks` value should be greater than and equal to 1, but "
            f"received {num_tasks}. "
        )


@jax.jit
def _binned_auroc_from_counts(
    tp: jax.Array, fp: jax.Array
) -> jax.Array:
    """tp/fp per (ascending) threshold, shape (..., T): flip to ascending
    cumulative order, prepend 0, trapezoid, degenerate -> 0.5."""
    cum_tp = jnp.flip(tp, axis=-1)
    cum_fp = jnp.flip(fp, axis=-1)
    zeros = jnp.zeros(cum_tp.shape[:-1] + (1,), cum_tp.dtype)
    cum_tp = jnp.concatenate([zeros, cum_tp], axis=-1)
    cum_fp = jnp.concatenate([zeros, cum_fp], axis=-1)
    factor = cum_tp[..., -1] * cum_fp[..., -1]
    area = trapezoid(cum_tp, cum_fp, axis=-1)
    return jnp.where(factor == 0, 0.5, area / jnp.where(factor == 0, 1.0, factor))


@jax.jit
def _binary_binned_auroc_compute_jit(
    input: jax.Array, target: jax.Array, threshold: jax.Array
) -> jax.Array:
    # (T, tasks, n) prediction mask per threshold
    squeeze = input.ndim == 1
    if squeeze:
        input = input[None, :]
        target = target[None, :]
    pred = input[None, :, :] >= threshold[:, None, None]
    tgt = target[None, :, :].astype(jnp.float32)
    tp = jnp.sum(pred * tgt, axis=-1)  # (T, tasks)
    fp = jnp.sum(pred, axis=-1) - tp
    auroc = _binned_auroc_from_counts(tp.T, fp.T)  # (tasks,)
    return auroc[0] if squeeze else auroc


def _hist_binned_flat_index(
    input: jax.Array, target: jax.Array, threshold: jax.Array
) -> jax.Array:
    """Flat histogram cell per sample for the O(1)-state binned AUROC:
    ``target * T + bin`` where ``bin`` is the rightmost threshold <= the
    score (so ``score >= threshold[j]  <=>  bin >= j``, making suffix
    sums of the histogram reproduce the dense ``input >= threshold[j]``
    counters exactly). Scores below ``threshold[0]`` map to ``-1``
    (dropped — the dense kernel counts them at no threshold either).
    Consumed by the sharded routing layer and the dense update alike.
    """
    num_t = threshold.shape[0]
    b = jnp.searchsorted(threshold, input, side="right") - 1
    return jnp.where(
        b < 0,
        -1,
        target.astype(jnp.int32) * num_t + b.astype(jnp.int32),
    )


def _hist_binned_update(
    input: jax.Array, target: jax.Array, threshold: jax.Array
) -> jax.Array:
    """Dense histogram delta ``(2T,)`` int32 for one batch — the
    replicated-instance update kernel of ``HistogramBinnedAUROC``
    (negatives in ``[0, T)``, positives in ``[T, 2T)``). One
    ``segment_count`` (PR 6 native one-pass on CPU); O(n log T) per
    batch instead of the dense compare's O(n*T)."""
    from torcheval_tpu.ops import segment

    num_t = threshold.shape[0]
    idx = _hist_binned_flat_index(input, target, threshold)
    return segment.segment_count(
        segment.safe_ids(idx, 2 * num_t), 2 * num_t
    )


def _hist_binned_auroc_compute(
    hist: jax.Array, num_t: int
) -> jax.Array:
    """AUROC from the ``(2T,)`` histogram: suffix sums rebuild the
    per-threshold tp/fp counters (integer-exact), then the shared
    trapezoid (``_binned_auroc_from_counts``) — bit-identical outputs
    for bit-identical histograms, any world size."""
    neg, pos = hist[:num_t], hist[num_t:]
    tp = jnp.cumsum(pos[::-1])[::-1].astype(jnp.float32)
    fp = jnp.cumsum(neg[::-1])[::-1].astype(jnp.float32)
    return _binned_auroc_from_counts(tp, fp)


def _binary_binned_auroc_compute(
    input: jax.Array, target: jax.Array, threshold: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    return _binary_binned_auroc_compute_jit(input, target, threshold), threshold


def binary_binned_auroc(
    input,
    target,
    *,
    num_tasks: int = 1,
    threshold: Union[int, List[float], jax.Array] = DEFAULT_NUM_THRESHOLD,
) -> Tuple[jax.Array, jax.Array]:
    """Binned AUROC for binary classification; returns (auroc, threshold).

    Class version: ``torcheval_tpu.metrics.BinaryBinnedAUROC``.

    For ``num_tasks=1`` the auroc is a scalar, as the reference's docstring
    promises (``tensor(0.5)``, reference binned_auroc.py:46-48); the
    reference *implementation* actually returns shape ``(1,)`` there (an
    internal-broadcast quirk of its compute, reference binned_auroc.py:116)
    — we deliberately match its documented shape, and its own tests compare
    via broadcast so both agree.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import binary_binned_auroc
        >>> binary_binned_auroc(jnp.array([0.1, 0.5, 0.7, 0.8]),
        ...                     jnp.array([0, 0, 1, 1]), threshold=5)
        (Array(0.875, dtype=float32), Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32))
    """
    input, target = to_jax(input), to_jax(target)
    threshold = create_threshold_tensor(threshold)
    _binary_binned_auroc_param_check(num_tasks, threshold)
    _binary_auroc_update_input_check(input, target, num_tasks)
    return _binary_binned_auroc_compute(input, target, threshold)


def _multiclass_binned_auroc_param_check(
    num_classes: int, threshold: jax.Array, average: Optional[str]
) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if num_classes < 2:
        raise ValueError(f"`num_classes` has to be at least 2, got {num_classes}.")


@jax.jit
def _multiclass_binned_auroc_compute_jit(
    input: jax.Array, target: jax.Array, threshold: jax.Array
) -> jax.Array:
    num_classes = input.shape[1]
    pred = input[None, :, :] >= threshold[:, None, None]  # (T, N, C)
    onehot = jax.nn.one_hot(target, num_classes)
    tp = jnp.sum(pred * onehot[None, :, :], axis=1)  # (T, C)
    fp = jnp.sum(pred, axis=1) - tp
    return _binned_auroc_from_counts(tp.T, fp.T)  # (C,)


def multiclass_binned_auroc(
    input,
    target,
    *,
    num_classes: int,
    threshold: Union[int, List[float], jax.Array] = DEFAULT_NUM_THRESHOLD,
    average: Optional[str] = "macro",
) -> Tuple[jax.Array, jax.Array]:
    """Binned one-vs-rest AUROC for multiclass classification.

    Class version: ``torcheval_tpu.metrics.MulticlassBinnedAUROC``.

    Divergence from the reference: the reference's kernel sums the
    prediction mask over the *class* axis instead of the sample axis
    (reference binned_auroc.py:186-200), yielding one value per sample
    rather than per class (visible in its own docstring: 5 values for
    num_classes=3). This implementation computes the intended per-class
    one-vs-rest AUROC; with a dense threshold grid it converges to
    ``multiclass_auroc`` exactly.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import multiclass_binned_auroc
        >>> multiclass_binned_auroc(jnp.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1],
        ...                  [0.1, 0.2, 0.7], [0.3, 0.5, 0.2]]), jnp.array([0, 1, 2, 1]), num_classes=3, threshold=5)
        (Array(1., dtype=float32), Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32))
    """
    input, target = to_jax(input), to_jax(target)
    threshold = create_threshold_tensor(threshold)
    _multiclass_binned_auroc_param_check(num_classes, threshold, average)
    _multiclass_auroc_update_input_check(input, target, num_classes)
    auroc = _multiclass_binned_auroc_compute_jit(input, target, threshold)
    if average == "macro":
        return jnp.mean(auroc), threshold
    return auroc, threshold
