"""WordErrorRate class metric.

Parity: reference torcheval/metrics/text/word_error_rate.py:22-114. Host
float counters (exact double precision; the text DP runs on host anyway),
SUM-merged through the sync layer's int/float path.
"""

from __future__ import annotations

from typing import List, Optional, TypeVar, Union

import jax

from torcheval_tpu.metrics.functional.text.word_error_rate import (
    _word_error_rate_compute,
    _word_error_rate_update,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric

TWordErrorRate = TypeVar("TWordErrorRate", bound="WordErrorRate")


class WordErrorRate(Metric[jax.Array]):
    """Word error rate over all updates.

    Functional version: ``torcheval_tpu.metrics.functional.word_error_rate``.

    Examples::

        >>> from torcheval_tpu.metrics import WordErrorRate
        >>> metric = WordErrorRate()
        >>> metric.update(["this is the prediction", "there is an other sample"],
        ...               ["this is the reference", "there is another one"])
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __init__(self, *, device: Optional[jax.Device] = None) -> None:
        super().__init__(device=device)
        self._add_state("errors", 0.0, merge=MergeKind.SUM)
        self._add_state("total", 0.0, merge=MergeKind.SUM)

    def update(
        self: TWordErrorRate,
        input: Union[str, List[str]],
        target: Union[str, List[str]],
    ) -> TWordErrorRate:
        """Accumulate edit distances for one batch of sentence pairs."""
        errors, total = _word_error_rate_update(input, target)
        self.errors += errors
        self.total += total
        return self

    def compute(self) -> jax.Array:
        """Running word error rate."""
        return _word_error_rate_compute(self.errors, self.total)
