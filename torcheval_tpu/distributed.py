"""Process/replica group abstractions for metric state sync.

The reference syncs metric replicas across ``torch.distributed`` process
groups (NCCL/Gloo; reference toolkit.py:206-260, synclib.py). JAX has two
distinct distributed regimes, both covered here behind one small interface:

- **Multi-host** (one controller process per host of a TPU pod,
  ``jax.distributed.initialize``): ``MultiHostGroup`` — collectives ride
  ICI/DCN via ``jax.experimental.multihost_utils``. This is the true
  analogue of the reference's process groups.
- **Single-controller multi-device** (one process drives N chips — the
  normal JAX regime the reference has no equivalent of): metric replicas
  live on different devices of the local process. ``LocalReplicaGroup``
  models the reference's "ranks" for tests and eager loops; the really
  fast path is not here at all but in ``torcheval_tpu.metrics.sharded``,
  which syncs states *inside* a jitted step with ``lax.psum``.

Object gathers use the pickle->uint8->pad->allgather trick: XLA collectives
need static shapes, so lengths are exchanged first — the same protocol the
reference implements with dummy-tensor padding (reference synclib.py:159-178).

Groups are codec-agnostic: the bytes they ship are whatever the eager
packer produced, so the quantized wire ladder (``torcheval_tpu/wire.py``,
``exact | bf16 | int8-blockwise`` per metric family — docs/distributed.md,
"Quantized wire ladder") compresses payloads *before* they reach any
group's gather, and the length exchange above automatically sizes the
collective to the post-codec byte count.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.obs import flight as _flight
from torcheval_tpu.obs.flight import FLIGHT as _FLIGHT

# The length exchange preceding a padded object gather travels as an EXPLICIT
# fixed-width wire dtype: int64 would be silently downcast to int32 by XLA
# under the default x64-disabled jax config, so payload sizes >= 2**31 bytes
# would corrupt undetected. Instead a 64-bit length is split into two int32
# halves (base 2**31, both non-negative), which survives any x64 setting.
# Pinned by tests/test_wire_dtype.py.
LENGTH_WIRE_DTYPE = np.int32
_LENGTH_BASE = 1 << 31


def encode_length(n: int) -> np.ndarray:
    """Byte length -> shape-(2,) int32 wire array (hi, lo base ``2**31``).

    Covers lengths up to ``2**62 - 1`` (4 EiB) — both halves stay valid
    non-negative int32 values under any jax x64 setting.
    """
    if not 0 <= n < _LENGTH_BASE * _LENGTH_BASE:
        raise ValueError(
            f"length must be in [0, 2**62), got {n} (non-negative "
            "int32-pair wire encoding)"
        )
    return np.asarray(
        [n // _LENGTH_BASE, n % _LENGTH_BASE], dtype=LENGTH_WIRE_DTYPE
    )


def decode_length(arr: Any) -> int:
    """Inverse of :func:`encode_length` for one rank's (hi, lo) pair."""
    hi, lo = (int(v) for v in np.asarray(arr).reshape(-1))
    return hi * _LENGTH_BASE + lo


def _check_subgroup_ranks(ranks: Sequence[int], world: int) -> Tuple[int, ...]:
    """Validate a subgroup rank list: non-empty, unique, sorted into
    canonical order, within ``[0, world)``."""
    out = tuple(sorted(int(r) for r in ranks))
    if not out:
        raise ValueError("a subgroup needs at least one rank")
    if len(set(out)) != len(out):
        raise ValueError(f"duplicate ranks in subgroup: {list(ranks)}")
    if out[0] < 0 or out[-1] >= world:
        raise ValueError(
            f"subgroup ranks {list(ranks)} out of range for world size {world}"
        )
    return out


def coordination_client():
    """The ``jax.distributed`` coordination-service client the job
    rendezvoused through — the shared KV transport behind
    :class:`MultiHostSubgroup` gathers and the federation's inter-region
    mailboxes (``federation.KVLinkTransport``). Raises when the
    coordination service was never initialized."""
    from jax._src import distributed as jdist

    client = getattr(jdist.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "the jax.distributed coordination service is not initialized "
            "(jax.distributed.initialize / "
            "torcheval_tpu.launcher.init_from_env) — required for "
            "MultiHostSubgroup collectives and KV link transports"
        )
    return client


class ProcessGroup:
    """Minimal interface the sync layer needs from a replica group."""

    @property
    def world_size(self) -> int:
        raise NotImplementedError

    @property
    def rank(self) -> int:
        raise NotImplementedError

    # ------------------------------------------------------ subgroup scoping

    @property
    def is_member(self) -> bool:
        """Whether THIS process participates in the group's collectives.

        Always True for whole-world groups; a subgroup handle held by a
        non-member process reports False, and the toolkit entry points
        then return the local metric untouched (the reference's
        ``process_group=`` subset semantics, reference toolkit.py:34-67).
        """
        return True

    @property
    def ranks(self) -> Tuple[int, ...]:
        """Global ranks of the members, ascending. Whole-world groups are
        ``range(world_size)``; subgroups report their member subset (the
        group-relative ranks used on the wire map through this tuple)."""
        return tuple(range(self.world_size))

    def new_subgroup(self, ranks: Sequence[int]) -> "ProcessGroup":
        """A group scoped to ``ranks`` (global, of THIS group) — the
        analogue of ``torch.distributed.new_group`` (SURVEY §2.8): every
        toolkit entry point then syncs over exactly that subset. Like the
        reference, call it on EVERY process of the parent group, in the
        same order; non-members receive a handle with
        ``is_member == False``. Composable: ``resilience.ResilientGroup``
        forwards with its policy intact, and chaos wrappers decorate the
        returned subgroup like any other group."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support subgroup scoping"
        )

    def allgather_array(self, x: jax.Array) -> List[np.ndarray]:
        """Gather one same-shaped array from every rank, in rank order."""
        raise NotImplementedError

    def allgather_object(self, obj: Any) -> List[Any]:
        """Gather one picklable object from every rank, in rank order."""
        raise NotImplementedError

    # ------------------------------------------------- resilience extensions

    def unwrap(self) -> "ProcessGroup":
        """The innermost group behind any decorators (``ResilientGroup``,
        ``FaultInjectionGroup``). Plain groups return themselves; the sync
        layer dispatches on ``unwrap()`` so wrapping never changes which
        protocol (local-replica vs multi-host) is spoken."""
        return self

    def allgather_object_with_ranks(
        self, obj: Any
    ) -> Tuple[List[Any], List[int]]:
        """Gather plus the participating-rank list. Plain groups always
        return every rank; ``torcheval_tpu.resilience.ResilientGroup``
        overrides this to report partial participation after degradation."""
        return self.allgather_object(obj), list(range(self.world_size))

    def allgather_array_with_ranks(
        self, x: Any
    ) -> Tuple[List[np.ndarray], List[int]]:
        """Array-gather twin of :meth:`allgather_object_with_ranks`."""
        return self.allgather_array(x), list(range(self.world_size))


class SingleProcessGroup(ProcessGroup):
    """World of one — the reference's world_size==1 fast path
    (reference toolkit.py:337-350)."""

    @property
    def world_size(self) -> int:
        return 1

    @property
    def rank(self) -> int:
        return 0

    def allgather_array(self, x) -> List[np.ndarray]:
        return [np.asarray(x)]

    def allgather_object(self, obj) -> List[Any]:
        return [obj]

    def new_subgroup(self, ranks: Sequence[int]) -> "SingleProcessGroup":
        _check_subgroup_ranks(ranks, 1)
        return self


class LocalReplicaGroup(ProcessGroup):
    """N metric replicas driven by one controller process (typically one per
    local device). 'Gather' is in-process; used by tests to model ranks the
    way the reference's spawned gloo workers do, and by eager eval loops
    that keep one metric replica per device.

    The sync entry points accept a *list* of per-replica payloads when
    running under this group (single-controller owns all replicas at once).

    ``new_subgroup(ranks)`` scopes the group to a replica subset: the
    toolkit then accepts EITHER the member-only replica list or the full
    parent-world list (member replicas are selected by rank, the rest stay
    untouched — the reference's subset semantics in single-controller
    form).
    """

    def __init__(self, devices: Optional[Sequence[jax.Device]] = None) -> None:
        self.devices = list(devices) if devices is not None else jax.local_devices()
        # set by new_subgroup on the child it returns
        self._member_ranks: Optional[Tuple[int, ...]] = None
        self.parent_world: Optional[int] = None

    @property
    def world_size(self) -> int:
        return len(self.devices)

    @property
    def rank(self) -> int:
        return 0

    @property
    def ranks(self) -> Tuple[int, ...]:
        if self._member_ranks is not None:
            return self._member_ranks
        return tuple(range(self.world_size))

    def new_subgroup(self, ranks: Sequence[int]) -> "LocalReplicaGroup":
        ranks = _check_subgroup_ranks(ranks, self.world_size)
        sub = LocalReplicaGroup([self.devices[r] for r in ranks])
        sub._member_ranks = ranks
        sub.parent_world = self.world_size
        return sub

    def allgather_array(self, xs) -> List[np.ndarray]:
        # xs is the per-replica list already resident in this process
        return [np.asarray(x) for x in xs]

    def allgather_object(self, objs) -> List[Any]:
        return list(objs)


class MultiHostGroup(ProcessGroup):
    """All JAX processes of a multi-host job (``jax.distributed.initialize``).

    Arrays are gathered with ``multihost_utils.process_allgather`` (lowers to
    an XLA all_gather over ICI/DCN); objects via pickled-bytes padding.
    """

    def __init__(self) -> None:
        self._world = jax.process_count()
        self._rank = jax.process_index()

    @property
    def world_size(self) -> int:
        return self._world

    @property
    def rank(self) -> int:
        return self._rank

    def allgather_array(self, x) -> List[np.ndarray]:
        arr = np.asarray(x)
        if _FLIGHT.enabled:
            # flight-recorded (ISSUE 11): the per-thread ring sees this
            # collective enter and leave — one attribute read when off
            return _flight.guarded_collective(
                "allgather_array", arr.nbytes, self._rank, self._world,
                lambda: self._allgather_array_impl(arr),
            )
        return self._allgather_array_impl(arr)

    def _allgather_array_impl(self, arr: np.ndarray) -> List[np.ndarray]:
        from jax.experimental import multihost_utils

        # normalize the gather layout the same way allgather_object does:
        # some jax versions return (world*n,) concatenated instead of
        # (world, n) stacked (and world=1 gathers come back unstacked)
        stacked = np.asarray(
            multihost_utils.process_allgather(arr, tiled=False)
        ).reshape((self._world,) + arr.shape)
        return [np.asarray(s) for s in stacked]

    def allgather_object(self, obj) -> List[Any]:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        if _FLIGHT.enabled:
            return _flight.guarded_collective(
                "allgather_object", payload.nbytes, self._rank, self._world,
                lambda: self._allgather_object_impl(payload),
            )
        return self._allgather_object_impl(payload)

    def _allgather_object_impl(self, payload: np.ndarray) -> List[Any]:
        from jax.experimental import multihost_utils

        # explicit int32-pair wire encoding: see encode_length (an int64
        # here would be silently downcast to int32 under x64-disabled jax)
        lengths = np.asarray(
            multihost_utils.process_allgather(
                encode_length(payload.size), tiled=False
            )
        ).reshape(self._world, 2)
        sizes = [decode_length(lengths[r]) for r in range(self._world)]
        max_len = max(sizes)
        padded = np.zeros(max_len, dtype=np.uint8)
        padded[: payload.size] = payload
        # some jax versions return the gather concatenated (world*max_len,)
        # instead of stacked (world, max_len); normalize the layout
        gathered = np.asarray(
            multihost_utils.process_allgather(padded, tiled=False)
        ).reshape(self._world, max_len)
        return [
            pickle.loads(gathered[r, : sizes[r]].tobytes())
            for r in range(self._world)
        ]

    def new_subgroup(self, ranks: Sequence[int]) -> "MultiHostSubgroup":
        return MultiHostSubgroup(_check_subgroup_ranks(ranks, self._world))


# per-(member tuple) construction counter, namespacing concurrent subgroup
# instances over the same ranks. Deterministic as long as every process
# constructs its subgroups in the same order (the documented contract,
# identical to torch.distributed.new_group).
_SUBGROUP_SEQ: dict = {}


class MultiHostSubgroup(ProcessGroup):
    """A subset of the multi-host job's processes, synced over the
    ``jax.distributed`` coordination service's key-value store.

    XLA collectives (``multihost_utils.process_allgather``) are
    whole-job-only: every process must participate or the pod hangs —
    which is exactly what subgroup scoping must avoid (non-members stay
    untouched AND uninvolved). The coordination KV store the job already
    rendezvoused through has no such constraint, so subgroup gathers ride
    it: each member publishes its payload under a sequence-numbered key
    and reads its peers'. Latency is coordinator-RPC, not ICI — right for
    the eager metrics-sync cadence (occasional, KB-to-MB payloads,
    already host-side), wrong for anything in a step's hot loop.

    Construction contract (same as ``torch.distributed.new_group``): every
    process of the parent group constructs the subgroup, in the same
    order; non-members receive a handle with ``is_member == False`` whose
    collectives refuse to run (the toolkit short-circuits before calling
    them).

    Cleanup is lockstep-safe: a member starting collective ``n`` deletes
    its own key of collective ``n - 2`` — any peer still reading is at
    ``n - 1`` or later, so no live key is ever deleted. The LAST one or
    two collectives' keys therefore outlive the exchange; call
    :meth:`close` once every member is past its final collective (end of
    the eval job) to sweep them, and REUSE one subgroup across syncs
    rather than constructing a fresh one per sync — each construction
    namespaces new keys, so per-sync construction grows the coordinator's
    KV store by the trailing keys of every instance.
    """

    def __init__(
        self, ranks: Sequence[int], *, timeout: float = 600.0
    ) -> None:
        self._ranks = tuple(ranks)
        me = jax.process_index()
        self._member_index = (
            self._ranks.index(me) if me in self._ranks else None
        )
        self.timeout = float(timeout)
        key = ("mh-subgroup",) + self._ranks
        _SUBGROUP_SEQ[key] = _SUBGROUP_SEQ.get(key, 0) + 1
        self._tag = "-".join(map(str, self._ranks)) + f"/{_SUBGROUP_SEQ[key]}"
        self._seq = 0

    @property
    def world_size(self) -> int:
        return len(self._ranks)

    @property
    def rank(self) -> int:
        return -1 if self._member_index is None else self._member_index

    @property
    def is_member(self) -> bool:
        return self._member_index is not None

    @property
    def ranks(self) -> Tuple[int, ...]:
        return self._ranks

    def new_subgroup(self, ranks: Sequence[int]) -> "MultiHostSubgroup":
        # ranks are THIS group's (relative); map through to global
        rel = _check_subgroup_ranks(ranks, len(self._ranks))
        return MultiHostSubgroup(
            tuple(self._ranks[r] for r in rel), timeout=self.timeout
        )

    def _client(self):
        return coordination_client()

    def _kv_allgather(self, payload: bytes) -> List[bytes]:
        if self._member_index is None:
            raise RuntimeError(
                f"process {jax.process_index()} is not a member of subgroup "
                f"{self._ranks}; non-members must not issue its collectives "
                "(the toolkit returns their local metrics untouched)"
            )
        if _FLIGHT.enabled:
            return _flight.guarded_collective(
                "kv_allgather", len(payload),
                self._ranks[self._member_index], len(self._ranks),
                lambda: self._kv_allgather_impl(payload),
            )
        return self._kv_allgather_impl(payload)

    def _kv_allgather_impl(self, payload: bytes) -> List[bytes]:
        client = self._client()
        seq = self._seq
        self._seq += 1
        prefix = f"torcheval_sync/{self._tag}/{seq}"
        me = self._ranks[self._member_index]
        client.key_value_set_bytes(f"{prefix}/{me}", bytes(payload))
        timeout_ms = max(1, int(self.timeout * 1000))
        out = [
            bytes(
                client.blocking_key_value_get_bytes(
                    f"torcheval_sync/{self._tag}/{seq}/{r}", timeout_ms
                )
            )
            for r in self._ranks
        ]
        if seq >= 2:  # lockstep-safe cleanup (class docstring)
            try:
                client.key_value_delete(
                    f"torcheval_sync/{self._tag}/{seq - 2}/{me}"
                )
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass
        return out

    def close(self) -> None:
        """Best-effort sweep of this member's trailing KV keys. Call only
        after every member has finished its last collective on this
        subgroup — a peer still mid-read would lose the payload."""
        if self._member_index is None or self._seq == 0:
            return
        client = self._client()
        me = self._ranks[self._member_index]
        for seq in range(max(0, self._seq - 2), self._seq):
            try:
                client.key_value_delete(
                    f"torcheval_sync/{self._tag}/{seq}/{me}"
                )
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass

    def allgather_object(self, obj: Any) -> List[Any]:
        gathered = self._kv_allgather(pickle.dumps(obj))
        return [pickle.loads(b) for b in gathered]

    def allgather_array(self, x: Any) -> List[np.ndarray]:
        arr = np.ascontiguousarray(np.asarray(x))
        gathered = self._kv_allgather(pickle.dumps(arr))
        return [np.asarray(pickle.loads(b)) for b in gathered]


class HierarchicalGroup(ProcessGroup):
    """Two-level eager sync: intra-node gather -> one inter-node exchange
    among node leaders -> intra-node broadcast.

    The pod-scale collective pattern of "Automatic Cross-Replica Sharding
    of Weight Update" (arxiv 2004.13336): when intra-node links (ICI,
    NVLink, shared memory) are much faster than the inter-node fabric
    (DCN), a flat world-size-N gather puts N payloads on the slow fabric;
    the two-level shape exchanges one aggregate per NODE among the node
    leaders instead. Opt-in decorator — results are identical to the flat
    gather (same payloads, same rank order), only the wire pattern
    changes. ``leader_collectives`` / ``node_collectives`` count the
    split for observability (``bench.py sync_payload`` reports them).

    Built on :meth:`ProcessGroup.new_subgroup`, so it works over any
    rank-per-process group that supports subgroup scoping
    (``MultiHostGroup``, test worlds); construct it on every process.

    Transport honesty: what this class guarantees today is the exchange
    SHAPE (only leaders exchange across nodes — the quantity the bench
    counts), not a measured speedup. Over ``MultiHostGroup`` the
    subgroup collectives currently ride the coordination KV store
    (``MultiHostSubgroup``), whose per-exchange latency is a coordinator
    RPC — typically SLOWER than the flat ``process_allgather`` XLA
    collective for small worlds, so on such jobs treat this as the
    pattern + observability vehicle, not an optimization. The bandwidth
    win materializes when the node subgroups map onto a transport where
    intra-node exchange is genuinely cheap (future subgroup-scoped XLA
    collectives, or test worlds emulating one).
    """

    def __init__(
        self,
        inner: ProcessGroup,
        *,
        group_size: Optional[int] = None,
        groups: Optional[Sequence[Sequence[int]]] = None,
    ) -> None:
        if isinstance(inner.unwrap(), LocalReplicaGroup):
            raise ValueError(
                "HierarchicalGroup needs a rank-per-process group "
                "(MultiHostGroup); a LocalReplicaGroup is one process — "
                "there is no slow fabric to optimize"
            )
        world = inner.world_size
        if groups is None:
            if group_size is None or group_size < 1:
                raise ValueError("pass group_size >= 1 or explicit groups")
            groups = [
                list(range(lo, min(lo + group_size, world)))
                for lo in range(0, world, group_size)
            ]
        nodes = [_check_subgroup_ranks(g, world) for g in groups]
        covered = sorted(r for node in nodes for r in node)
        if covered != list(range(world)):
            raise ValueError(
                f"groups {groups} must partition ranks 0..{world - 1}"
            )
        # canonical node order = ascending leader rank: the leaders
        # subgroup gathers in THAT order, and allgather_object zips the
        # gathered per-node lists against self._nodes — an unsorted
        # explicit `groups` would otherwise reassemble payloads under the
        # wrong ranks
        nodes.sort(key=lambda n: n[0])
        self._inner = inner
        self._nodes = nodes
        me = inner.rank
        mine = next((n for n in nodes if me in n), None)
        if not inner.is_member or mine is None:
            # the documented contract constructs the hierarchy on every
            # process of the parent; a non-member gets the same graceful
            # handle every other group kind returns
            self._node = None
            self._leaders = None
        else:
            self._node = inner.new_subgroup(mine)
            self._leaders = inner.new_subgroup([n[0] for n in nodes])
        self.node_collectives = 0
        self.leader_collectives = 0

    @property
    def world_size(self) -> int:
        return self._inner.world_size

    @property
    def rank(self) -> int:
        return self._inner.rank

    @property
    def is_member(self) -> bool:
        return self._node is not None

    @property
    def ranks(self) -> Tuple[int, ...]:
        return self._inner.ranks

    def unwrap(self) -> ProcessGroup:
        return self._inner.unwrap()

    def allgather_object(self, obj: Any) -> List[Any]:
        if self._node is None:
            raise RuntimeError(
                "this process is not a member of the hierarchical group's "
                "parent; non-members must not issue its collectives (the "
                "toolkit returns their local metrics untouched)"
            )
        # level 1: gather within this node
        self.node_collectives += 1
        node_vals = self._node.allgather_object(obj)
        # level 2: ONE exchange among node leaders, each carrying its
        # whole node's payloads
        flat: Optional[List[Any]] = None
        if self._leaders.is_member:
            self.leader_collectives += 1
            per_node = self._leaders.allgather_object(node_vals)
            flat = [None] * self.world_size
            for node, vals in zip(self._nodes, per_node):
                for r, v in zip(node, vals):
                    flat[r] = v
        # level 3: leaders broadcast the assembled world within their node
        # (an allgather where only the leader's slot carries data)
        self.node_collectives += 1
        shared = self._node.allgather_object(flat)
        return shared[0]  # the node leader is its subgroup's rank 0

    def allgather_array(self, x: Any) -> List[np.ndarray]:
        return [
            np.asarray(a)
            for a in self.allgather_object(np.ascontiguousarray(np.asarray(x)))
        ]


def default_process_group() -> ProcessGroup:
    """World group: multi-host when the job has >1 processes, else a world
    of one (mirrors the reference's ``process_group=None`` default)."""
    if jax.process_count() > 1:
        return MultiHostGroup()
    return SingleProcessGroup()
