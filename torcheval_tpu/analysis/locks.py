"""Lock-discipline static analysis: guarded-by bindings and lock order.

The host-threading sibling of the program verifier: PR 2's worker-thread
leak, PR 3's process-global-fence deadlock, PR 4's writer/main-thread
collective-sequence race and PR 10's flight-ring generation race were
all caught by review, not by a gate. This module turns that review
checklist into rules over the AST (stdlib-only — the CI concurrency
gate runs jax-free, like the lint):

- **Lock inventory**: every ``threading.Lock/RLock/Condition``
  construction — ``self._lock = threading.Lock()`` in ``__init__``, a
  dataclass ``field(default_factory=threading.Lock)``, or a module
  global — becomes a named lock (``Class._lock`` / ``_GLOBAL_LOCK``).
- **unguarded-state**: a lock-owning class (or module) must bind each
  shared mutable attribute — one mutated outside ``__init__`` — to its
  lock with ``# tev: guarded-by=<lock>`` on the attribute's definition
  line. State rooted in ``threading.local`` or frozen via
  ``MappingProxyType`` (and synchronization primitives themselves) is
  auto-exempt; a deliberately lock-free field carries a reasoned
  ``# tev: disable=unguarded-state -- <why>`` instead.
- **guarded-field**: a bound attribute read or written outside a
  ``with <lock>`` scope (``__init__`` excepted) is a race finding — the
  PR 10 flight-ring class, caught at the line.
- **blocking-under-lock**: ``time.sleep``, ``queue.get``, ``.wait()``,
  ``.join()``, a collective issue, or a call into a function that
  lexically blocks, made while a lock is held — the convoy/deadlock
  feeder. ``Condition.wait/wait_for`` on the held lock itself is the
  one legal shape (it releases the lock) and is exempt.
- **lock-order-cycle**: nested ``with``-acquisitions (lexical, plus
  calls resolved through the module universe) build a directed
  acquisition graph; a cycle is a would-deadlock finding carrying every
  edge's acquisition stack — the PR 3 fence-deadlock class, caught
  statically.

Resolution is deliberately name-based and conservative: ``self.x`` in
the defining class, module globals, ``from``-imports, module aliases,
``GLOBAL = ClassName()`` instances, and ``g: Optional[ClassName]``
annotations. What cannot be resolved produces no finding (cross-object
attribute chains like ``other.health._lock`` are out of scope; the
deterministic-schedule harness covers them dynamically).

Suppression uses the lint grammar (``# tev: disable=<rule> -- reason``)
and suppressed findings stay in the report, flagged, for audit.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from torcheval_tpu.analysis.annotations import (
    CONCURRENCY_RULE_IDS,
    LOCK_TYPE_NAMES as _LOCK_TYPES,
    lock_ctor_kind as _lock_ctor_kind,
    parse_guarded_lines,
    parse_suppressions,
    parse_thread_scopes,
)
from torcheval_tpu.analysis.report import Finding, Report

__all__ = [
    "LockKey",
    "Universe",
    "build_universe",
    "check_locks",
    "iter_py_files",
]

LockKey = Tuple[str, str]  # (module dotted name, "Class.attr" | "GLOBAL")

# constructor types whose instances are safe to mutate without the
# owner's lock (self-synchronized, or thread-local by construction)
_EXEMPT_TYPES = _LOCK_TYPES | frozenset(
    {
        "local",
        "Event",
        "Thread",
        "Queue",
        "SimpleQueue",
        "LifoQueue",
        "PriorityQueue",
        "MappingProxyType",
    }
)
_MUTATORS = frozenset(
    {
        "add",
        "append",
        "appendleft",
        "clear",
        "discard",
        "extend",
        "insert",
        "pop",
        "popitem",
        "remove",
        "setdefault",
        "sort",
        "update",
    }
)
# attribute-call names that block the calling thread
_BLOCKING_METHODS = frozenset({"wait", "wait_for", "join", "acquire"})
_COLLECTIVE_METHODS = frozenset(
    {
        "allgather_object",
        "allgather_array",
        "allgather_object_with_ranks",
        "allgather_array_with_ranks",
    }
)
# module-level callables that block (time.sleep / from time import sleep;
# bounded_call parks on the deadline worker's done event)
_BLOCKING_FUNCTIONS = frozenset({"sleep", "bounded_call"})
# referencing any of these names routes the function through the
# per-caller-thread in-flight collective fence (resilience.py) — its
# collective sites are fence-protected by construction
FENCE_NAMES = frozenset(
    {"_tls_state", "_still_in_flight", "_get_worker", "_reclaim_finished"}
)
_INIT_METHODS = ("__init__", "__post_init__")


def _known_rule_ids() -> set:
    """Concurrency + lint rule ids — a mixed suppression line like
    ``disable=host-sync,guarded-field`` must not read as a typo to the
    fail-closed parser just because half of it targets the other tool.
    Lazy import: lint never imports this module, so no cycle."""
    from torcheval_tpu.analysis.lint import RULES

    return set(RULES) | set(CONCURRENCY_RULE_IDS)


def _module_name(path: str) -> str:
    norm = os.path.abspath(path).replace(os.sep, "/")
    idx = norm.rfind("torcheval_tpu/")
    if idx >= 0:
        rel = norm[idx:]
    else:
        rel = os.path.basename(norm)
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


def _call_name(node: ast.AST) -> str:
    """Terminal name of a Call's func (``threading.Lock`` -> ``Lock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _ctor_exempt(value: ast.AST) -> bool:
    """Constructed state that never needs a guarded-by binding."""
    if isinstance(value, ast.Call):
        return _call_name(value.func) in _EXEMPT_TYPES
    return False


def _expr_terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _lockish(node: ast.AST) -> bool:
    """Heuristic: does this with-context expression LOOK like a lock?
    (Unresolvable lock-shaped acquisitions still count as "a lock is
    held" for blocking-under-lock, but match no guarded binding.)"""
    term = _expr_terminal(node).lower()
    return "lock" in term or term in ("mutex", "cond", "condition")


class _ClassModel:
    __slots__ = (
        "name",
        "node",
        "locks",
        "bindings",
        "exempt",
        "defined",
        "mutated",
        "methods",
    )

    def __init__(self, name: str, node: ast.ClassDef) -> None:
        self.name = name
        self.node = node
        self.locks: Dict[str, int] = {}  # attr -> line of construction
        self.bindings: Dict[str, Tuple[str, int]] = {}  # attr -> (lock, line)
        self.exempt: Set[str] = set()  # ctor-exempt attrs
        self.defined: Dict[str, int] = {}  # attr -> definition line
        self.mutated: Dict[str, int] = {}  # attr -> first out-of-init mutation
        self.methods: Dict[str, ast.AST] = {}


class _FunctionInfo:
    __slots__ = (
        "module",
        "qual",
        "cls",
        "node",
        "line",
        "thread_scope",
        "calls",
        "with_sites",
        "direct_edges",
        "blocking",
        "collectives",
        "fenced",
        "nested",
    )

    def __init__(self, module: str, qual: str, cls: Optional[str], node) -> None:
        self.module = module
        self.qual = qual
        self.cls = cls
        self.node = node
        self.line = node.lineno
        self.thread_scope: Optional[str] = None
        # filled by Universe._analyze_function:
        self.calls: List[Tuple[Any, int, Tuple]] = []  # (ref, line, held)
        self.with_sites: List[Tuple[LockKey, int]] = []
        self.direct_edges: List[Tuple[LockKey, int, LockKey, int]] = []
        self.blocking: List[Tuple[int, str]] = []  # lexical blocking calls
        self.collectives: List[Tuple[int, str]] = []
        self.fenced = False
        self.nested: Dict[str, "_FunctionInfo"] = {}


class _ModuleModel:
    """One parsed file: classes, locks, bindings, imports, functions."""

    def __init__(self, path: str, tree: ast.Module, lines: List[str]) -> None:
        self.path = path
        self.name = _module_name(path)
        self.tree = tree
        self.lines = lines
        self.suppressions, _ = parse_suppressions(lines, _known_rule_ids())
        self.guarded = parse_guarded_lines(lines)
        self.thread_scopes = parse_thread_scopes(lines)
        self.classes: Dict[str, _ClassModel] = {}
        self.mod_locks: Dict[str, int] = {}
        self.mod_bindings: Dict[str, Tuple[str, int]] = {}
        self.mod_globals: Dict[str, int] = {}  # top-level assigned names
        self.mod_exempt: Set[str] = set()
        self.functions: Dict[str, _FunctionInfo] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.mod_aliases: Dict[str, str] = {}
        self.instances: Dict[str, str] = {}  # global -> class name (local ref)
        self.thread_targets: List[Tuple[ast.AST, int]] = []
        self._parse()

    # ----------------------------------------------------------- parsing

    def _parse(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._parse_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = _FunctionInfo(
                    self.name, node.name, None, node
                )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.mod_aliases[alias.asname or alias.name] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        node.module,
                        alias.name,
                    )
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self._parse_global_assign(node)
        for fn in list(self.functions.values()):
            self._collect_nested(fn)
        for fn in self.all_functions():
            scope = self.thread_scopes.get(fn.node.lineno)
            if scope is not None:
                fn.thread_scope = scope
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and _call_name(node.func) == "Thread"
            ):
                for kw in node.keywords:
                    if kw.arg == "target":
                        self.thread_targets.append((kw.value, node.lineno))

    def _collect_nested(self, fn: _FunctionInfo) -> None:
        for node in ast.walk(fn.node):
            if node is fn.node:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sub = _FunctionInfo(
                    self.name, f"{fn.qual}.{node.name}", fn.cls, node
                )
                fn.nested[node.name] = sub

    def _parse_global_assign(self, node) -> None:
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
        )
        value = getattr(node, "value", None)
        for target in targets:
            if not isinstance(target, ast.Name):
                continue
            name = target.id
            self.mod_globals.setdefault(name, node.lineno)
            if value is not None and _lock_ctor_kind(value) is not None:
                self.mod_locks[name] = node.lineno
            if value is not None and _ctor_exempt(value):
                self.mod_exempt.add(name)
            lock = self.guarded.get(node.lineno)
            if lock is not None:
                self.mod_bindings[name] = (lock, node.lineno)
            # `_G: Optional[ClassName] = None` — instance-type annotation
            if isinstance(node, ast.AnnAssign):
                cls = self._annotation_class(node.annotation)
                if cls is not None:
                    self.instances[name] = cls
            elif (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
            ):
                self.instances.setdefault(name, value.func.id)

    def _annotation_class(self, node) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value.strip().split("[")[-1].rstrip("]") or None
        if isinstance(node, ast.Subscript) and _expr_terminal(
            node.value
        ) in ("Optional", "Final", "ClassVar"):
            return self._annotation_class(node.slice)
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def _parse_class(self, node: ast.ClassDef) -> None:
        cm = _ClassModel(node.name, node)
        self.classes[node.name] = cm
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cm.methods[stmt.name] = stmt
                qual = f"{node.name}.{stmt.name}"
                self.functions[qual] = _FunctionInfo(
                    self.name, qual, node.name, stmt
                )
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                value = getattr(stmt, "value", None)
                for target in targets:
                    if not isinstance(target, ast.Name):
                        continue
                    attr = target.id
                    cm.defined.setdefault(attr, stmt.lineno)
                    if value is not None and _lock_ctor_kind(value):
                        cm.locks[attr] = stmt.lineno
                    if value is not None and _ctor_exempt(value):
                        cm.exempt.add(attr)
                    lock = self.guarded.get(stmt.lineno)
                    if lock is not None:
                        cm.bindings[attr] = (lock, stmt.lineno)
        # __init__ / __post_init__ self-attribute definitions
        for init_name in _INIT_METHODS:
            init = cm.methods.get(init_name)
            if init is None:
                continue
            for sub in ast.walk(init):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        sub.targets
                        if isinstance(sub, ast.Assign)
                        else [sub.target]
                    )
                    value = getattr(sub, "value", None)
                    for target in targets:
                        attr = _self_attr(target)
                        if attr is None:
                            continue
                        cm.defined.setdefault(attr, sub.lineno)
                        if value is not None and _lock_ctor_kind(value):
                            cm.locks.setdefault(attr, sub.lineno)
                        if value is not None and _ctor_exempt(value):
                            cm.exempt.add(attr)
                        lock = self.guarded.get(sub.lineno)
                        if lock is not None:
                            cm.bindings.setdefault(attr, (lock, sub.lineno))
        # out-of-init mutation census
        for mname, mnode in cm.methods.items():
            if mname in _INIT_METHODS:
                continue
            for attr, line in _self_mutations(mnode):
                if attr in cm.locks or attr in cm.exempt:
                    continue
                prev = cm.mutated.get(attr)
                if prev is None or line < prev:
                    cm.mutated[attr] = line
    def all_functions(self) -> Iterable[_FunctionInfo]:
        for fn in self.functions.values():
            yield fn
            yield from fn.nested.values()


def _self_attr(node) -> Optional[str]:
    """``self.x`` (or the ``self.x`` inside ``self.x[...]``) -> ``x``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _self_mutations(fn_node) -> Iterable[Tuple[str, int]]:
    """(attr, line) for every ``self.x`` write / in-place mutation."""
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for target in targets:
                if isinstance(target, ast.Tuple):
                    elts = target.elts
                else:
                    elts = [target]
                for elt in elts:
                    attr = _self_attr(elt)
                    if attr is not None:
                        yield attr, node.lineno
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr is not None:
                    yield attr, node.lineno


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    """Every ``.py`` under files/directories, sorted (the lint walk)."""
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d
                    for d in dirs
                    if d not in ("__pycache__", ".git", "node_modules")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        elif path.endswith(".py"):
            yield path


class Universe:
    """All swept modules plus name-based call/lock resolution — shared
    by the lock-discipline passes here and the thread/collective hazard
    model in ``analysis/concurrency.py``."""

    def __init__(self) -> None:
        self.modules: Dict[str, _ModuleModel] = {}
        self.parse_failures: List[Tuple[str, int, str]] = []

    # ---------------------------------------------------------- loading

    def add_file(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, UnicodeDecodeError) as e:
            self.parse_failures.append((path, 0, f"unreadable: {e}"))
            return
        except SyntaxError as e:
            self.parse_failures.append(
                (path, e.lineno or 0, f"syntax error: {e.msg}")
            )
            return
        model = _ModuleModel(path, tree, source.splitlines())
        self.modules[model.name] = model

    def analyze(self) -> None:
        for module in self.modules.values():
            for fn in module.all_functions():
                self._analyze_function(module, fn)

    # -------------------------------------------------------- resolution

    def _module_of(self, dotted: str) -> Optional[_ModuleModel]:
        if dotted in self.modules:
            return self.modules[dotted]
        # a from-import of a symbol re-exported by a package __init__
        # (e.g. `from torcheval_tpu.obs import flight`) resolves the
        # submodule by suffix
        for name, model in self.modules.items():
            if name.endswith("." + dotted.rsplit(".", 1)[-1]):
                if dotted in name or name.endswith(dotted):
                    return model
        return None

    def _resolve_class(
        self, module: _ModuleModel, cls_name: str
    ) -> Optional[Tuple[_ModuleModel, _ClassModel]]:
        cm = module.classes.get(cls_name)
        if cm is not None:
            return module, cm
        imported = module.from_imports.get(cls_name)
        if imported is not None:
            target = self._module_of(imported[0])
            if target is not None:
                cm = target.classes.get(imported[1])
                if cm is not None:
                    return target, cm
        return None

    def _instance_class(
        self, module: _ModuleModel, name: str
    ) -> Optional[Tuple[_ModuleModel, _ClassModel]]:
        cls_name = module.instances.get(name)
        if cls_name is None:
            return None
        return self._resolve_class(module, cls_name)

    def _resolve_imported_module(
        self, module: _ModuleModel, name: str
    ) -> Optional[_ModuleModel]:
        if name in module.mod_aliases:
            return self._module_of(module.mod_aliases[name])
        imported = module.from_imports.get(name)
        if imported is not None:
            # `from torcheval_tpu.obs import flight as _flight`
            return self._module_of(f"{imported[0]}.{imported[1]}")
        return None

    def resolve_lock_expr(
        self,
        expr: ast.AST,
        module: _ModuleModel,
        cls: Optional[str],
        local_types: Dict[str, str],
    ) -> Optional[LockKey]:
        if isinstance(expr, ast.Name):
            if expr.id in module.mod_locks:
                return (module.name, expr.id)
            imported = module.from_imports.get(expr.id)
            if imported is not None:
                target = self._module_of(imported[0])
                if target is not None and imported[1] in target.mod_locks:
                    return (target.name, imported[1])
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                cm = module.classes.get(cls)
                if cm is not None and expr.attr in cm.locks:
                    return (module.name, f"{cls}.{expr.attr}")
                return None
            target = self._resolve_imported_module(module, base.id)
            if target is not None and expr.attr in target.mod_locks:
                return (target.name, expr.attr)
            inst = self._instance_class(
                module, local_types.get(base.id, "")
            ) or self._instance_class(module, base.id)
            if inst is None and base.id in local_types:
                inst = self._resolve_class(module, local_types[base.id])
            if inst is not None and expr.attr in inst[1].locks:
                return (inst[0].name, f"{inst[1].name}.{expr.attr}")
        elif isinstance(base, ast.Attribute) and isinstance(
            base.value, ast.Name
        ):
            # `_mod.GLOBAL.lock` — module alias, global instance, attr
            target = self._resolve_imported_module(module, base.value.id)
            if target is not None:
                inst = self._instance_class(target, base.attr)
                if inst is not None and expr.attr in inst[1].locks:
                    return (inst[0].name, f"{inst[1].name}.{expr.attr}")
        return None

    def resolve_call(
        self,
        func: ast.AST,
        module: _ModuleModel,
        fn: _FunctionInfo,
        local_types: Dict[str, str],
    ) -> Optional[_FunctionInfo]:
        """A call expression -> the _FunctionInfo it targets, when the
        name-based rules can tell; None for dynamic/foreign calls."""
        if isinstance(func, ast.Name):
            name = func.id
            if name in fn.nested:
                return fn.nested[name]
            if name in module.functions:
                return module.functions[name]
            imported = module.from_imports.get(name)
            if imported is not None:
                target = self._module_of(imported[0])
                if target is not None and imported[1] in target.functions:
                    return target.functions[imported[1]]
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        attr = func.attr
        if isinstance(base, ast.Name):
            if base.id == "self" and fn.cls is not None:
                qual = f"{fn.cls}.{attr}"
                if qual in module.functions:
                    return module.functions[qual]
                # bound-callback fallback: exactly one class in this
                # module defines the method (`self._write_bundle` handed
                # to a writer class as a callback)
                hits = [
                    f
                    for q, f in module.functions.items()
                    if q.endswith("." + attr)
                ]
                if len(hits) == 1:
                    return hits[0]
                return None
            target = self._resolve_imported_module(module, base.id)
            if target is not None and attr in target.functions:
                return target.functions[attr]
            cls_name = local_types.get(base.id) or module.instances.get(
                base.id
            )
            if cls_name is not None:
                resolved = self._resolve_class(module, cls_name)
                if resolved is not None:
                    target_mod, cm = resolved
                    qual = f"{cm.name}.{attr}"
                    return target_mod.functions.get(qual)
        elif isinstance(base, ast.Attribute) and isinstance(
            base.value, ast.Name
        ):
            target = self._resolve_imported_module(module, base.value.id)
            if target is not None:
                cls_name = target.instances.get(base.attr)
                if cls_name is not None:
                    resolved = self._resolve_class(target, cls_name)
                    if resolved is not None:
                        target_mod, cm = resolved
                        return target_mod.functions.get(f"{cm.name}.{attr}")
        return None

    # ----------------------------------------------- per-function analysis

    def _analyze_function(
        self, module: _ModuleModel, fn: _FunctionInfo
    ) -> None:
        local_types: Dict[str, str] = {}
        args_node = fn.node.args
        for arg in (
            list(args_node.posonlyargs)
            + list(args_node.args)
            + list(args_node.kwonlyargs)
        ):
            if arg.annotation is not None:
                cls = module._annotation_class(arg.annotation)
                if cls is not None:
                    local_types.setdefault(arg.arg, cls)
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name) and isinstance(
                    node.value, ast.Call
                ):
                    ctor = node.value.func
                    if isinstance(ctor, ast.Name):
                        local_types.setdefault(target.id, ctor.id)
            if isinstance(node, ast.Name) and node.id in FENCE_NAMES:
                fn.fenced = True
            if isinstance(node, ast.Call):
                cattr = _call_name(node.func)
                if cattr in FENCE_NAMES:
                    fn.fenced = True

        nested_nodes = {sub.node for sub in fn.nested.values()}

        def visit(node, held: Tuple[Tuple[Optional[LockKey], ast.AST, int], ...]):
            if node in nested_nodes:
                return  # analyzed as its own function
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    expr = item.context_expr
                    key = self.resolve_lock_expr(
                        expr, module, fn.cls, local_types
                    )
                    if key is not None or _lockish(expr):
                        if key is not None:
                            fn.with_sites.append((key, node.lineno))
                            # order edges against everything already
                            # held — including EARLIER ITEMS of this
                            # same statement (`with A, B:` acquires A
                            # then B, exactly like nested withs)
                            for outer_key, _, outer_line in new_held:
                                if outer_key is not None:
                                    fn.direct_edges.append(
                                        (
                                            outer_key,
                                            outer_line,
                                            key,
                                            node.lineno,
                                        )
                                    )
                        new_held = new_held + ((key, expr, node.lineno),)
                for child in node.body:
                    visit(child, new_held)
                return
            if isinstance(node, ast.Call):
                self._note_call(module, fn, node, held, local_types)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.node.body:
            visit(stmt, ())

    def _note_call(
        self,
        module: _ModuleModel,
        fn: _FunctionInfo,
        node: ast.Call,
        held,
        local_types,
    ) -> None:
        name = _call_name(node.func)
        blocking: Optional[str] = None
        if name in _COLLECTIVE_METHODS:
            fn.collectives.append((node.lineno, name))
            blocking = f"collective `{name}`"
        elif isinstance(node.func, ast.Attribute):
            if name in _BLOCKING_METHODS:
                receiver = ast.dump(node.func.value)
                held_exprs = {ast.dump(e) for _, e, _ in held}
                term = _expr_terminal(node.func.value).lower()
                if name in ("wait", "wait_for") and receiver in held_exprs:
                    blocking = None  # Condition.wait on the held lock
                elif name == "join" and not (
                    term == "_q"
                    or any(
                        hint in term
                        for hint in ("thread", "proc", "worker", "queue", "jobs")
                    )
                ):
                    blocking = None  # str.join / os.path.join, not a thread
                else:
                    blocking = f"`.{name}()`"
            elif name == "get" and not node.args and not node.keywords:
                blocking = "`.get()` (queue hand-off)"
            elif name == "sleep" and _expr_terminal(node.func.value) == "time":
                blocking = "`time.sleep`"
        elif isinstance(node.func, ast.Name) and name in _BLOCKING_FUNCTIONS:
            blocking = f"`{name}()`"
        if blocking is not None:
            fn.blocking.append((node.lineno, blocking))
            if held:
                lock_desc = _expr_terminal(held[-1][1]) or "a lock"
                fn.blocking[-1] = (
                    node.lineno,
                    f"{blocking} while holding `{lock_desc}` "
                    f"(acquired line {held[-1][2]})",
                )
        callee = self.resolve_call(node.func, module, fn, local_types)
        fn.calls.append((callee, node.lineno, held))

    # ------------------------------------------------------- pass: discipline

    def discipline_findings(self) -> List[Finding]:
        findings: List[Finding] = []
        for module in self.modules.values():
            findings.extend(self._module_discipline(module))
        return findings

    def _emit(
        self,
        module: _ModuleModel,
        rule: str,
        line: int,
        message: str,
        severity: str = "error",
    ) -> Finding:
        suppressed = False
        reason = ""
        entry = module.suppressions.get(line)
        if entry is not None and rule in entry[0]:
            suppressed = True
            reason = entry[1]
        return Finding(
            tool="concurrency",
            rule=rule,
            path=module.path,
            line=line,
            message=message,
            severity=severity,
            suppressed=suppressed,
            suppress_reason=reason,
        )

    def _binding_key(
        self, module: _ModuleModel, cls: Optional[_ClassModel], lock: str
    ) -> Optional[LockKey]:
        if cls is not None and lock in cls.locks:
            return (module.name, f"{cls.name}.{lock}")
        if lock in module.mod_locks:
            return (module.name, lock)
        return None

    def _module_discipline(self, module: _ModuleModel) -> List[Finding]:
        findings: List[Finding] = []
        # --- classes -------------------------------------------------
        for cm in module.classes.values():
            for attr, (lock, line) in sorted(cm.bindings.items()):
                if self._binding_key(module, cm, lock) is None:
                    findings.append(
                        self._emit(
                            module,
                            "bad-annotation",
                            line,
                            f"guarded-by names unknown lock `{lock}` "
                            f"(class {cm.name} locks: "
                            f"{sorted(cm.locks) or 'none'}; module locks: "
                            f"{sorted(module.mod_locks) or 'none'})",
                        )
                    )
            if cm.locks:
                for attr, mline in sorted(cm.mutated.items()):
                    if attr in cm.bindings:
                        continue
                    line = cm.defined.get(attr, mline)
                    findings.append(
                        self._emit(
                            module,
                            "unguarded-state",
                            line,
                            f"`{cm.name}.{attr}` is mutated outside "
                            f"__init__ (line {mline}) in a lock-owning "
                            f"class with no `# tev: guarded-by=` binding "
                            f"(locks here: {sorted(cm.locks)}); bind it, "
                            "or exempt with `# tev: "
                            "disable=unguarded-state -- <reason>`",
                        )
                    )
        # --- module globals ------------------------------------------
        for name, (lock, line) in sorted(module.mod_bindings.items()):
            if lock not in module.mod_locks:
                findings.append(
                    self._emit(
                        module,
                        "bad-annotation",
                        line,
                        f"guarded-by names unknown module lock `{lock}` "
                        f"(module locks: {sorted(module.mod_locks) or 'none'})",
                    )
                )
        if module.mod_locks:
            mutated = self._global_mutations(module)
            for name, mline in sorted(mutated.items()):
                if (
                    name in module.mod_bindings
                    or name in module.mod_locks
                    or name in module.mod_exempt
                ):
                    continue
                line = module.mod_globals.get(name, mline)
                findings.append(
                    self._emit(
                        module,
                        "unguarded-state",
                        line,
                        f"module global `{name}` is mutated by functions "
                        "in a lock-owning module with no `# tev: "
                        "guarded-by=` binding (locks here: "
                        f"{sorted(module.mod_locks)}); bind it, or exempt "
                        "with `# tev: disable=unguarded-state -- <reason>`",
                    )
                )
        # --- guarded-field + blocking-under-lock ----------------------
        # blocking sites are per-function (each _FunctionInfo records its
        # own lexical holds); the guarded-field walk runs on TOP-LEVEL
        # functions/methods only and descends into nested defs carrying
        # the enclosing lexical lock context — a closure running under
        # its parent's `with` must not re-check lock-free
        for fn in module.all_functions():
            findings.extend(self._function_discipline(module, fn, fields=False))
        for fn in module.functions.values():
            findings.extend(self._function_discipline(module, fn, fields=True))
        return findings

    def _global_mutations(self, module: _ModuleModel) -> Dict[str, int]:
        mutated: Dict[str, int] = {}
        for fn in module.all_functions():
            declared_global: Set[str] = set()
            local_names: Set[str] = set()
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Global):
                    declared_global.update(node.names)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Store
                ):
                    if node.id not in declared_global:
                        local_names.add(node.id)
            for node in ast.walk(fn.node):
                hits: List[Tuple[str, int]] = []
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for target in targets:
                        if (
                            isinstance(target, ast.Name)
                            and target.id in declared_global
                        ):
                            hits.append((target.id, node.lineno))
                        elif isinstance(target, ast.Subscript) and isinstance(
                            target.value, ast.Name
                        ):
                            hits.append((target.value.id, node.lineno))
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    if node.func.attr in _MUTATORS and isinstance(
                        node.func.value, ast.Name
                    ):
                        hits.append((node.func.value.id, node.lineno))
                for name, line in hits:
                    if (
                        name in module.mod_globals
                        and name not in local_names
                    ):
                        prev = mutated.get(name)
                        if prev is None or line < prev:
                            mutated[name] = line
        return mutated

    def _function_discipline(
        self, module: _ModuleModel, fn: _FunctionInfo, *, fields: bool
    ) -> List[Finding]:
        findings: List[Finding] = []
        method_name = fn.qual.rsplit(".", 1)[-1]
        if method_name in _INIT_METHODS:
            return findings
        cm = module.classes.get(fn.cls) if fn.cls else None
        if fields:
            return self._guarded_field_walk(module, fn, cm)
        # blocking-under-lock: lexical sites already carry their message
        for line, message in fn.blocking:
            if "while holding" in message:
                findings.append(
                    self._emit(
                        module,
                        "blocking-under-lock",
                        line,
                        f"{message} — a blocked holder convoys every "
                        "contender (and deadlocks if the unblocker needs "
                        "this lock)",
                    )
                )
        # one-level interprocedural: a call made under a lock into a
        # function that lexically blocks
        for callee, line, held in fn.calls:
            if callee is None or not held:
                continue
            if callee.blocking:
                bline, bwhat = callee.blocking[0]
                lock_desc = _expr_terminal(held[-1][1]) or "a lock"
                findings.append(
                    self._emit(
                        module,
                        "blocking-under-lock",
                        line,
                        f"call to `{callee.qual}` while holding "
                        f"`{lock_desc}` (acquired line {held[-1][2]}) — "
                        f"the callee blocks ({bwhat.split(' while ')[0]} "
                        f"at {os.path.basename(callee.module)}:{bline})",
                    )
                )
        return findings

    def _guarded_field_walk(
        self,
        module: _ModuleModel,
        fn: _FunctionInfo,
        cm: Optional[_ClassModel],
    ) -> List[Finding]:
        """Enforce guarded-by bindings over one top-level function or
        method, descending into nested defs WITH the enclosing lexical
        lock context (a closure under its parent's ``with`` is held)."""
        findings: List[Finding] = []
        local_types: Dict[str, str] = {}

        def required_key(lock: str) -> Optional[LockKey]:
            return self._binding_key(module, cm, lock)

        nested_nodes = {sub.node for sub in fn.nested.values()}
        declared_global: Set[str] = set()
        local_names: Set[str] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                if node.id not in declared_global:
                    local_names.add(node.id)

        seen: Set[Tuple[str, int]] = set()

        def visit(node, held_keys: frozenset):
            if node in nested_nodes:
                pass  # nested defs inherit the lexical lock scope
            if isinstance(node, ast.With):
                new_keys = held_keys
                for item in node.items:
                    key = self.resolve_lock_expr(
                        item.context_expr, module, fn.cls, local_types
                    )
                    if key is not None:
                        new_keys = new_keys | {key}
                for child in node.body:
                    visit(child, new_keys)
                return
            attr = None
            scope = ""
            binding = None
            bind_line = 0
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ):
                if node.value.id == "self" and cm is not None:
                    attr = node.attr
                    entry = cm.bindings.get(attr)
                    if entry is not None:
                        binding, bind_line = entry
                        scope = f"{cm.name}.{attr}"
            elif isinstance(node, ast.Name):
                entry = module.mod_bindings.get(node.id)
                if (
                    entry is not None
                    and node.id not in local_names
                ):
                    attr = node.id
                    binding, bind_line = entry
                    scope = attr
            if binding is not None and node.lineno != bind_line:
                key = required_key(binding)
                if key is not None and key not in held_keys:
                    mark = (scope, node.lineno)
                    if mark not in seen:
                        seen.add(mark)
                        findings.append(
                            self._emit(
                                module,
                                "guarded-field",
                                node.lineno,
                                f"`{scope}` is bound to `{binding}` "
                                f"(guarded-by, line {bind_line}) but is "
                                "read/written here outside any "
                                f"`with {binding}` scope",
                            )
                        )
            for child in ast.iter_child_nodes(node):
                visit(child, held_keys)

        for stmt in fn.node.body:
            visit(stmt, frozenset())
        return findings

    # ------------------------------------------------------- pass: lock order

    def lock_order_findings(self) -> List[Finding]:
        # transitively-acquired locks per function, with one witness
        # chain per (function, lock)
        acquired: Dict[Tuple[str, str], Dict[LockKey, List[str]]] = {}

        def site(fn: _FunctionInfo, line: int) -> str:
            return f"{os.path.basename(fn.module)}:{line} ({fn.qual})"

        def compute(fn: _FunctionInfo, stack: Set[Tuple[str, str]]):
            key = (fn.module, fn.qual)
            if key in acquired:
                return acquired[key]
            if key in stack:
                return {}
            stack = stack | {key}
            out: Dict[LockKey, List[str]] = {}
            for lock, line in fn.with_sites:
                out.setdefault(lock, [site(fn, line)])
            for callee, line, _held in fn.calls:
                if callee is None:
                    continue
                for lock, chain in compute(callee, stack).items():
                    out.setdefault(lock, [site(fn, line)] + chain)
            acquired[key] = out
            return out

        edges: Dict[LockKey, Dict[LockKey, List[str]]] = {}

        def add_edge(a: LockKey, b: LockKey, chain: List[str]) -> None:
            if a == b:
                return
            edges.setdefault(a, {}).setdefault(b, chain)

        for module in self.modules.values():
            for fn in module.all_functions():
                compute(fn, set())
                for outer, oline, inner, iline in fn.direct_edges:
                    add_edge(
                        outer,
                        inner,
                        [site(fn, oline), site(fn, iline)],
                    )
                for callee, line, held in fn.calls:
                    if callee is None:
                        continue
                    inner_locks = compute(callee, set())
                    for _hkey, _hexpr, hline in held:
                        if _hkey is None:
                            continue
                        for lock, chain in inner_locks.items():
                            add_edge(
                                _hkey,
                                lock,
                                [site(fn, hline), site(fn, line)] + chain,
                            )

        findings: List[Finding] = []
        reported: Set[Tuple[LockKey, ...]] = set()
        for start in sorted(edges):
            cycle = self._find_cycle(edges, start)
            if cycle is None:
                continue
            canon_idx = cycle.index(min(cycle))
            canon = tuple(cycle[canon_idx:] + cycle[:canon_idx])
            if canon in reported:
                continue
            reported.add(canon)
            parts = []
            for i, a in enumerate(cycle):
                b = cycle[(i + 1) % len(cycle)]
                chain = edges[a][b]
                parts.append(
                    f"{a[1]} -> {b[1]} (acquired via: "
                    + " -> ".join(chain)
                    + ")"
                )
            first_a, first_b = cycle[0], cycle[1 % len(cycle)]
            first_chain = edges[first_a][first_b]
            module, line = self._site_location(first_chain[0])
            finding = Finding(
                tool="concurrency",
                rule="lock-order-cycle",
                path=module.path if module else first_chain[0],
                line=line,
                message=(
                    "lock-order cycle (would-deadlock: two threads "
                    "entering from different edges wait on each other "
                    "forever): " + "; ".join(parts)
                ),
            )
            if module is not None:
                entry = module.suppressions.get(line)
                if entry is not None and "lock-order-cycle" in entry[0]:
                    finding.suppressed = True
                    finding.suppress_reason = entry[1]
            findings.append(finding)
        return findings

    def _site_location(
        self, site: str
    ) -> Tuple[Optional[_ModuleModel], int]:
        # "module.py:123 (qual)" -> (_ModuleModel, 123)
        try:
            loc = site.split(" ")[0]
            fname, line_s = loc.rsplit(":", 1)
            line = int(line_s)
        except ValueError:
            return None, 0
        for module in self.modules.values():
            if os.path.basename(module.name) == fname or module.name.endswith(
                fname
            ):
                return module, line
        return None, line

    @staticmethod
    def _find_cycle(
        edges: Dict[LockKey, Dict[LockKey, List[str]]], start: LockKey
    ) -> Optional[List[LockKey]]:
        path: List[LockKey] = []
        on_path: Set[LockKey] = set()
        visited: Set[LockKey] = set()

        def dfs(node: LockKey) -> Optional[List[LockKey]]:
            if node in on_path:
                return path[path.index(node):]
            if node in visited:
                return None
            visited.add(node)
            path.append(node)
            on_path.add(node)
            for nxt in sorted(edges.get(node, {})):
                found = dfs(nxt)
                if found is not None:
                    return found
            path.pop()
            on_path.discard(node)
            return None

        return dfs(start)


def build_universe(paths: Iterable[str]) -> Universe:
    """Parse and analyze every ``.py`` under ``paths`` into a
    :class:`Universe` (the shared front half of ``check_locks`` and
    ``concurrency.check_concurrency``)."""
    universe = Universe()
    for path in iter_py_files(paths):
        universe.add_file(path)
    universe.analyze()
    return universe


def check_locks(
    paths: Iterable[str], *, universe: Optional[Universe] = None
) -> Report:
    """The lock-discipline + lock-order report over ``paths`` (or an
    already-built :class:`Universe`)."""
    if universe is None:
        universe = build_universe(paths)
    report = Report(tool="concurrency")
    report.checked = len(universe.modules)
    for path, line, message in universe.parse_failures:
        report.findings.append(
            Finding(
                tool="concurrency",
                rule="parse-error",
                path=path,
                line=line,
                message=message,
                severity="warning",
            )
        )
    report.findings.extend(universe.discipline_findings())
    report.findings.extend(universe.lock_order_findings())
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report
