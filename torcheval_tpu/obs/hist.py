"""Fixed-bucket log₂ latency histograms: O(1) insert, mergeable.

The PR 5 event stream carries every individual latency, but a ring
buffer is the wrong structure for "what is the p99 update latency over
the last hour" — old events are evicted, and answering a quantile from
events means a sort at read time. :class:`LatencyHistogram` is the
digest the question wants:

- **Fixed log₂ buckets**: bucket *i* counts samples in
  ``[2^(i-1), 2^i)`` microseconds (bucket 0 is the sub-µs bucket, the
  last bucket is unbounded). 40 buckets span sub-µs to ~7.6 days —
  latencies live on a log scale, so ~2× resolution everywhere is the
  right trade for a fixed-size, allocation-free structure.
- **O(1) insert** (:meth:`observe`): one ``int.bit_length`` and two adds
  under a plain lock — cheap enough to sit behind the recorder-gated
  update/compute/sync timers without moving the <2% overhead budget.
- **Mergeable, bit-identically** (:meth:`merge`): counts are integers
  and the running ``sum`` is accumulated in a fixed order, so every rank
  merging the same per-rank snapshots in the same (ascending-rank) order
  produces the same bits — the merge-oracle property the cross-rank
  scrape relies on (pinned by tests/metrics/test_tracing.py).
- **Approximate quantiles** (:meth:`quantile`): the upper bound of the
  bucket holding the target sample — conservative (never under-reports),
  within one bucket (≤2×) of the true value by construction.

The process-global registry (:func:`observe` / :func:`snapshot`) is what
the instrumented sites feed; ``export.render_prometheus`` emits each key
as a proper ``# TYPE ... histogram`` with cumulative ``_bucket`` series,
``_sum`` and ``_count``; ``export.format_report`` prints p50/p99.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

__all__ = [
    "LatencyHistogram",
    "NUM_BUCKETS",
    "bucket_index",
    "bucket_upper_bounds_us",
    "observe",
    "reset",
    "snapshot",
]

NUM_BUCKETS = 40


def bucket_index(seconds: float) -> int:
    """The log₂ bucket for a latency: ``int(µs).bit_length()`` clamped.

    0 µs → bucket 0; 1 µs → 1; 2-3 µs → 2; ...; everything at or above
    ``2^(NUM_BUCKETS-2)`` µs lands in the last, unbounded bucket.
    """
    us = int(seconds * 1e6)
    if us <= 0:
        return 0
    return min(us.bit_length(), NUM_BUCKETS - 1)


def bucket_upper_bounds_us() -> List[float]:
    """Exclusive upper bound of each bucket in µs (last is +Inf)."""
    return [2.0 ** i for i in range(NUM_BUCKETS - 1)] + [float("inf")]


class LatencyHistogram:
    """One fixed-shape latency digest (see module docstring)."""

    __slots__ = ("counts", "sum", "count", "_lock")

    def __init__(self) -> None:
        self.counts = [0] * NUM_BUCKETS  # tev: guarded-by=_lock
        self.sum = 0.0  # tev: guarded-by=_lock
        self.count = 0  # tev: guarded-by=_lock
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        """O(1): one bucket increment + running sum/count."""
        idx = bucket_index(seconds)
        with self._lock:
            self.counts[idx] += 1
            self.sum += seconds
            self.count += 1

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold ``other`` into self (elementwise counts, ``sum += other``;
        merging snapshots in a fixed order is bit-identical everywhere)."""
        with self._lock:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.sum += other.sum
            self.count += other.count
        return self

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile in SECONDS: the upper bound of the
        bucket containing the ⌈q·count⌉-th sample (None when empty; the
        unbounded last bucket reports its lower bound)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return None
        target = max(1, int(q * total + 0.999999))
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                exp = i if i < NUM_BUCKETS - 1 else NUM_BUCKETS - 2
                return (2.0 ** exp) / 1e6
        return (2.0 ** (NUM_BUCKETS - 2)) / 1e6  # unreachable

    # ------------------------------------------------------- serialization

    def as_dict(self) -> Dict[str, object]:
        """JSON-safe snapshot (the cross-rank gather payload)."""
        with self._lock:
            return {
                "counts": list(self.counts),
                "sum": self.sum,
                "count": self.count,
            }

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "LatencyHistogram":
        h = cls()
        counts = list(data.get("counts", ()))  # type: ignore[arg-type]
        if len(counts) != NUM_BUCKETS:
            raise ValueError(
                f"histogram snapshot has {len(counts)} buckets, "
                f"expected {NUM_BUCKETS}"
            )
        h.counts = [int(c) for c in counts]
        h.sum = float(data.get("sum", 0.0))  # type: ignore[arg-type]
        h.count = int(data.get("count", 0))  # type: ignore[arg-type]
        return h

    def __eq__(self, other: object) -> bool:
        # snapshot each side under its own lock: a racing insert must
        # not tear the comparison (ISSUE 15 guarded-field sweep)
        if not isinstance(other, LatencyHistogram):
            return NotImplemented
        return self.as_dict() == other.as_dict()


# --------------------------------------------------------- global registry

_REGISTRY: Dict[str, LatencyHistogram] = {}  # tev: guarded-by=_REGISTRY_LOCK
_REGISTRY_LOCK = threading.Lock()


def observe(key: str, seconds: float) -> None:
    """Record one latency under ``key`` in the process-global registry
    (keys like ``update/MulticlassAccuracy``, ``compute/Mean``,
    ``sync`` — what the instrumented sites feed while the recorder is
    on). Creates the histogram on first use. The insert is inlined
    (rather than delegating to :meth:`LatencyHistogram.observe`) — this
    sits on the recorder-ON update path, where call depth is budget."""
    h = _REGISTRY.get(key)  # tev: disable=guarded-field -- lock-free dict probe on the recorder-ON update path; two racers both fall through to the locked setdefault, which picks one winner
    if h is None:
        with _REGISTRY_LOCK:
            h = _REGISTRY.setdefault(key, LatencyHistogram())
    us = int(seconds * 1e6)
    idx = min(us.bit_length(), NUM_BUCKETS - 1) if us > 0 else 0
    with h._lock:
        h.counts[idx] += 1
        h.sum += seconds
        h.count += 1


def snapshot() -> Dict[str, LatencyHistogram]:
    """A point-in-time copy of the registry: ``{key: histogram-copy}``
    (safe to merge/serialize without racing live inserts)."""
    with _REGISTRY_LOCK:
        keys = list(_REGISTRY.items())
    return {k: LatencyHistogram.from_dict(h.as_dict()) for k, h in keys}


def reset() -> None:
    """Drop every registered histogram (tests and bench arms)."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
