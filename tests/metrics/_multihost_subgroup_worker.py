"""Worker: subgroup-scoped sync over the REAL jax.distributed wire.

Spawned by ``test_multihost.py::test_subgroup_sync_over_the_wire`` with 4
cooperating processes. Exercises ``MultiHostGroup.new_subgroup`` — the
KV-store collective side channel — end to end:

- a 2-of-4 subgroup syncs sync-matrix metrics among its members while
  NON-MEMBERS run their own code path and stay untouched (the ISSUE
  acceptance: reference subgroup semantics over spawned ranks);
- the complement subgroup syncs independently and concurrently;
- fault injection composes: the members wrap the subgroup in a
  ``FaultInjectionGroup`` with a scripted transient + a
  ``ResilientGroup`` retry budget, and still converge;
- a two-level ``HierarchicalGroup`` over all 4 ranks must equal the flat
  subgroup-of-everyone sync.

Only KV-store collectives are used (no ``process_allgather``), so this
worker runs even on jaxlibs whose CPU backend lacks multiprocess XLA
collectives.
"""

from __future__ import annotations

import json


def main() -> None:
    import jax

    from torcheval_tpu.launcher import init_from_env

    init_from_env()
    rank = jax.process_index()

    import numpy as np

    from tests.metrics._sync_matrix import build_cases, run_case, to_jsonable
    from torcheval_tpu.distributed import HierarchicalGroup, MultiHostGroup
    from torcheval_tpu.metrics.toolkit import sync_and_compute
    from torcheval_tpu.resilience import ResilientGroup
    from torcheval_tpu.utils.test_utils import FaultInjectionGroup, FaultSpec

    group = MultiHostGroup()
    results = {}

    cases = build_cases()
    names = ["MulticlassAccuracy", "BinaryAUROC", "Throughput"]

    # ---- 2-of-4 subgroup: members (1, 2); non-members untouched ----------
    sub = group.new_subgroup([1, 2])
    for name in names:
        factory, gen = cases[name]
        metric = run_case(factory(), gen, rank)
        value = to_jsonable(sync_and_compute(metric, sub))
        results[f"sub12/{name}"] = value
    results["sub12/is_member"] = sub.is_member

    # ---- the complement subgroup syncs independently ---------------------
    comp = group.new_subgroup([0, 3])
    factory, gen = cases["MulticlassAccuracy"]
    metric = run_case(factory(), gen, rank)
    results["sub03/MulticlassAccuracy"] = to_jsonable(
        sync_and_compute(metric, comp)
    )

    # ---- fault injection over the subgroup -------------------------------
    sub2 = group.new_subgroup([1, 2])
    factory, gen = cases["MulticlassAccuracy"]
    metric = run_case(factory(), gen, rank)
    if sub2.is_member:
        chaos = FaultInjectionGroup(
            sub2, faults=[FaultSpec(call=0, kind="transient")]
        )
        resilient = ResilientGroup(
            chaos, timeout=120.0, retries=2, policy="raise"
        )
        results["faulted/MulticlassAccuracy"] = to_jsonable(
            sync_and_compute(metric, resilient)
        )
        results["faulted/retries"] = resilient.health.transient_errors
    else:
        results["faulted/MulticlassAccuracy"] = to_jsonable(
            sync_and_compute(metric, sub2)
        )

    # ---- hierarchical (2 nodes of 2) == flat -----------------------------
    hier = HierarchicalGroup(group, group_size=2)
    factory, gen = cases["MulticlassAccuracy"]
    metric = run_case(factory(), gen, rank)
    results["hier/MulticlassAccuracy"] = to_jsonable(
        sync_and_compute(metric, hier)
    )
    results["hier/leader_collectives"] = hier.leader_collectives
    results["hier/node_collectives"] = hier.node_collectives

    print("RESULT " + json.dumps({"rank": rank, **results}), flush=True)


if __name__ == "__main__":
    main()
