"""One-dispatch fused counter accumulation.

Counter metrics' hot loop is ``state += kernel(batch)``. Dispatching the
kernel and each eager add separately costs 3-4 device round-trips per
``update()`` — pure overhead for O(1)-state metrics whose kernels run in
microseconds (the reference hides this inside one torch op stream; on
TPU/JAX, per-dispatch latency dominates instead). This helper jits
``kernel(*dynamic, *config)`` together with the state adds into ONE
compiled program, cached per (kernel, config, arity) so repeated updates
hit the same executable.

Shape bucketing composes upstream of this layer: under
``config.shape_bucketing()`` plans arrive already rewritten
(metrics/_bucket.py) — dynamic args padded to their power-of-two bucket
plus a trailing valid-extent vector, kernel swapped for its mask-aware
twin — so the per-(kernel, config, arity) caches here see one stable
signature per bucket instead of one per distinct batch shape. That holds
for the group path too: an ``update_collection`` over K bucketed metrics
compiles one group program per bucket, not per ragged shape.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax

_CACHE: Dict[Any, Callable] = {}


def _check_arity(kernel, out, states):
    """Normalize a kernel's output to a tuple and require one entry per
    state — zip-assignment would otherwise silently truncate."""
    if not isinstance(out, tuple):
        out = (out,)
    if len(out) != len(states):
        raise ValueError(
            f"kernel {kernel.__name__} returned {len(out)} values "
            f"for {len(states)} states"
        )
    return out


def _apply_kernel(kernel, config, states, dyn):
    """Traceable shared body: ``tuple(s + d)`` for the kernel's deltas,
    with the arity check both the per-metric and group paths rely on."""
    deltas = _check_arity(kernel, kernel(*dyn, *config), states)
    return tuple(s + d for s, d in zip(states, deltas))


def _apply_transform(kernel, config, states, dyn):
    """Traceable shared body for transform plans:
    ``states = kernel(states, *dyn, *config)``, arity-checked."""
    return _check_arity(kernel, kernel(states, *dyn, *config), states)


def fused_accumulate(
    kernel: Callable,
    states: Tuple[jax.Array, ...],
    dynamic: Tuple[jax.Array, ...],
    config: Tuple = (),
    *,
    donate: bool = False,
    out_shardings=None,
) -> Tuple[jax.Array, ...]:
    """``tuple(s + d for s, d in zip(states, kernel(*dynamic, *config)))``
    as one jitted dispatch.

    ``config`` entries must be hashable (they key the cache and are baked
    into the trace as compile-time constants). ``kernel`` may return a
    single array (treated as a 1-tuple) or a tuple matching ``states``.

    ``donate=True`` donates the state tuple (``donate_argnums=0``): XLA
    aliases each state's input and output buffer — every ``s + d`` is an
    in-place accumulate, zero realloc per step — and the caller's state
    arrays are CONSUMED (deleted after the call). Callers own the
    aliasing discipline: nothing else may hold those array objects
    (``Metric`` snapshot paths copy; see ``config.update_donation``).

    ``out_shardings`` (a tuple matching the state tuple, hashable —
    ``NamedSharding`` per state) pins the output placement for
    mesh-sharded metric states: without it XLA may resolve a replicated
    output layout and silently gather a distributed state back into a
    full per-device replica (``Metric._mesh_out_shardings``).
    """
    key = (kernel, config, len(states), len(dynamic), donate, out_shardings)
    fn = _CACHE.get(key)
    if fn is None:

        def fused(states, *dyn):
            return _apply_kernel(kernel, config, states, dyn)

        fn = _jit(fused, donate, out_shardings)
        _CACHE[key] = fn
    return fn(states, *dynamic)


def _jit(fused, donate: bool, out_shardings):
    kwargs = {"donate_argnums": (0,) if donate else ()}
    if out_shardings is not None:
        kwargs["out_shardings"] = out_shardings
    return jax.jit(fused, **kwargs)


_TRANSFORM_CACHE: Dict[Any, Callable] = {}


def fused_transform(
    kernel, states, dynamic, config=(), *, donate=False, out_shardings=None
):
    """``kernel(states, *dynamic, *config)`` -> new states, as one jitted
    dispatch — the non-additive sibling of ``fused_accumulate`` (ring
    column writes, running extrema, sharded scatter-routing). Cached per
    (kernel, config, arity); ``donate`` and ``out_shardings`` as in
    ``fused_accumulate`` (a ring-buffer column write becomes a true
    in-place write instead of an O(window) copy)."""
    key = (kernel, config, len(states), len(dynamic), donate, out_shardings)
    fn = _TRANSFORM_CACHE.get(key)
    if fn is None:

        def fused(states, *dyn):
            return _apply_transform(kernel, config, states, dyn)

        fn = _jit(fused, donate, out_shardings)
        _TRANSFORM_CACHE[key] = fn
    return fn(states, *dynamic)


_GROUP_CACHE: Dict[Any, Callable] = {}


def fused_accumulate_group(plans, *, donate=False):
    """Run MANY fusable update plans as ONE jitted dispatch.

    ``plans`` is a sequence of ``(kernel, states, dynamic, config)`` or
    ``(kernel, states, dynamic, config, transform)`` tuples
    (``donate=True`` donates every plan's states — in-place group update). Accumulate
    plans apply ``states += kernel(*dynamic, *config)``; transform plans
    apply ``states = kernel(states, *dynamic, *config)``. Returns the new
    states, one tuple per plan, computed by a single XLA program — the
    collection analogue of the per-metric fusion: an eval loop updating K
    metrics on one batch pays one device round-trip instead of K.

    XLA additionally CSEs work shared between kernels traced into the same
    program (e.g. several classification metrics re-deriving argmax of the
    same logits compute it once).
    """
    kernels = tuple(p[0] for p in plans)
    configs = tuple(p[3] for p in plans)
    kinds = tuple(bool(p[4]) if len(p) > 4 else False for p in plans)
    arity = tuple((len(p[1]), len(p[2])) for p in plans)
    key = (kernels, configs, kinds, arity, donate)
    fn = _GROUP_CACHE.get(key)
    if fn is None:

        def fused(states_group, dynamic_group):
            out = []
            for kernel, config, transform, states, dyn in zip(
                kernels, configs, kinds, states_group, dynamic_group
            ):
                if transform:
                    out.append(
                        _apply_transform(kernel, config, states, dyn)
                    )
                else:
                    out.append(_apply_kernel(kernel, config, states, dyn))
            return tuple(out)

        # donation covers the whole states group: only set when EVERY
        # participating metric follows the snapshot-copy discipline
        # (toolkit.update_collection checks), since a donated buffer is
        # consumed for all of them at once
        fn = jax.jit(fused, donate_argnums=(0,) if donate else ())
        _GROUP_CACHE[key] = fn
    return fn(
        tuple(p[1] for p in plans), tuple(p[2] for p in plans)
    )
