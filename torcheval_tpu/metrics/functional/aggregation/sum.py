"""Weighted sum.

Parity: reference torcheval/metrics/functional/aggregation/sum.py:13-58
(`sum`, `_sum_update`).
"""

from __future__ import annotations

import builtins
from typing import Union

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import resolve_weight, to_jax_float


@jax.jit
def _weighted_total(input: jax.Array, weight: jax.Array) -> jax.Array:
    return jnp.sum(input * weight)


def _sum_update(input, weight: Union[float, int, jax.Array]) -> jax.Array:
    input = to_jax_float(input)
    _, weight_arr = resolve_weight(weight, input, int_clause=True)
    return _weighted_total(input, weight_arr)


def sum(input, weight: Union[float, int, jax.Array] = 1.0) -> jax.Array:
    """Weighted sum: ``sum(weight * input)``.

    Class version: ``torcheval_tpu.metrics.Sum``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import sum
        >>> sum(jnp.array([2., 3.]))
        Array(5., dtype=float32)
        >>> sum(jnp.array([2., 3.]), 0.5)
        Array(2.5, dtype=float32)
    """
    return _sum_update(input, weight)
