#!/usr/bin/env bash
# The static-analysis gate, exactly as CI runs it
# (.github/workflows/pre_commit.yaml `static_analysis` job; rule
# catalogue and suppression syntax in docs/static-analysis.md).
#
#   scripts/run_static_analysis.sh            # lint + concurrency
#                                             #   (jax-free, seconds)
#   scripts/run_static_analysis.sh --full     # + program-verifier smoke
set -euo pipefail
cd "$(dirname "$0")/.."

# AST lint + the ISSUE 15 concurrency verifier (lock discipline,
# lock-order cycles, blocking-under-lock, cross-thread collective
# hazards) in one jax-free pass; the JSON artifact carries both tools'
# findings, suppressed ones flagged with their reasons.
python -m torcheval_tpu.analysis torcheval_tpu examples bench.py scripts \
  --concurrency --report json --output lint-report.json

if [[ "${1:-}" == "--full" ]]; then
  python -m torcheval_tpu.analysis --no-lint --programs \
    --report json --output verifier-report.json
fi
