"""Shared finding/report types for the static-analysis subsystem.

Every analyzer layer (``lint`` — AST house rules, ``program`` — jaxpr/HLO
metric-program verification, ``lockstep`` — cross-rank collective plans)
emits the same :class:`Finding` record, so one JSON schema feeds the CLI,
the CI job, and the conftest failure-forensics hook. Deliberately
stdlib-only: the AST lint must be importable (and runnable in CI) without
pulling jax.

Findings integrate with the observability subsystem (``torcheval_tpu.obs``)
as typed ``AnalysisEvent``s — emitted lazily and only while the recorder is
on, so analysis runs inside an instrumented eval job leave forensics and a
plain lint run stays jax-free.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = [
    "Finding",
    "Report",
    "last_report",
    "set_last_report",
]

REPORT_SCHEMA_VERSION = 1


@dataclass
class Finding:
    """One rule violation (or would-deadlock hazard) at one location.

    ``tool`` names the analyzer layer (``lint`` / ``program`` /
    ``lockstep``), ``rule`` the registry id (docs/static-analysis.md has
    the catalogue). ``path`` is a file for lint findings and a program
    label (e.g. ``MulticlassAccuracy.update``) for verifier findings;
    ``line`` is 1-based (0 = whole-program finding). ``suppressed`` marks
    a finding covered by a ``# tev: disable=<rule> -- reason`` comment —
    kept in the report (with its reason) so suppressions stay auditable,
    but excluded from the pass/fail verdict.
    """

    tool: str
    rule: str
    path: str
    message: str
    line: int = 0
    col: int = 0
    severity: str = "error"  # "error" | "warning"
    suppressed: bool = False
    suppress_reason: str = ""

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        tag = f"[{self.tool}:{self.rule}]"
        sup = (
            f" (suppressed: {self.suppress_reason})" if self.suppressed else ""
        )
        return f"{loc}: {self.severity} {tag} {self.message}{sup}"


@dataclass
class Report:
    """An analyzer run's findings plus enough context to act on them."""

    tool: str
    findings: List[Finding] = field(default_factory=list)
    checked: int = 0  # files (lint) or programs (verifier) examined

    @property
    def ok(self) -> bool:
        """True when no UNSUPPRESSED error-severity finding remains."""
        return not any(
            f.severity == "error" and not f.suppressed for f in self.findings
        )

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def extend(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.checked += other.checked
        return self

    def as_dict(self) -> Dict[str, Any]:
        active = self.active
        return {
            "schema_version": REPORT_SCHEMA_VERSION,
            "tool": self.tool,
            "ok": self.ok,
            "checked": self.checked,
            "counts": {
                "total": len(self.findings),
                "active": len(active),
                "suppressed": len(self.findings) - len(active),
                "errors": sum(
                    1 for f in active if f.severity == "error"
                ),
                "warnings": sum(
                    1 for f in active if f.severity == "warning"
                ),
            },
            "findings": [f.as_dict() for f in self.findings],
        }

    def to_json(self, *, indent: Optional[int] = 2) -> str:
        return json.dumps(self.as_dict(), indent=indent, sort_keys=False)

    def format_text(self, *, include_suppressed: bool = True) -> str:
        lines = [
            f.format()
            for f in self.findings
            if include_suppressed or not f.suppressed
        ]
        counts = self.as_dict()["counts"]
        lines.append(
            f"{self.tool}: {self.checked} checked, "
            f"{counts['errors']} error(s), {counts['warnings']} warning(s), "
            f"{counts['suppressed']} suppressed -> "
            f"{'OK' if self.ok else 'FAIL'}"
        )
        return "\n".join(lines)

    def record_events(self) -> None:
        """Mirror active findings into the observability recorder as
        ``AnalysisEvent``s (no-op — one attribute read — when the
        recorder is off, the same contract as every instrumented site).
        Lazy import: a lint-only process never touches jax.

        Idempotent PER FINDING (an ``_obs_recorded`` marker on the
        record, not a dataclass field): composite verifiers pass the
        same ``Finding`` objects through several ``set_last_report``
        layers (sub-report → extended parent), and each must land in the
        event log exactly once."""
        import sys

        recorder_mod = sys.modules.get("torcheval_tpu.obs.recorder")
        if recorder_mod is None or not recorder_mod.RECORDER.enabled:
            return
        from torcheval_tpu.obs.events import AnalysisEvent

        for f in self.active:
            if getattr(f, "_obs_recorded", False):
                continue
            recorder_mod.RECORDER.record(
                AnalysisEvent(
                    tool=f.tool,
                    rule=f.rule,
                    path=f.path,
                    line=f.line,
                    severity=f.severity,
                    message=f.message,
                )
            )
            f._obs_recorded = True


# The most recent report of any analyzer entry point in this process —
# what the conftest failure-forensics hook attaches next to the obs event
# tail when a test that ran the analyzer fails.
_LAST_REPORT: Optional[Report] = None


def set_last_report(report: Report) -> Report:
    global _LAST_REPORT
    _LAST_REPORT = report
    report.record_events()
    return report


def last_report() -> Optional[Report]:
    return _LAST_REPORT
