"""Binned AUROC class metrics.

Parity: reference torcheval/metrics/classification/binned_auroc.py
(BinaryBinnedAUROC :31 with buffered inputs/targets, MulticlassBinnedAUROC
:153). Returns ``(auroc, threshold)`` from compute.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.classification.auprc import _BufferedPairMetric
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.binned_auroc import (
    DEFAULT_NUM_THRESHOLD,
    _binary_binned_auroc_compute_jit,
    _binary_binned_auroc_param_check,
    _multiclass_binned_auroc_compute_jit,
    _multiclass_binned_auroc_param_check,
)
from torcheval_tpu.metrics.functional.tensor_utils import create_threshold_tensor


class BinaryBinnedAUROC(_BufferedPairMetric):
    """Binned AUROC for binary classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryBinnedAUROC
        >>> metric = BinaryBinnedAUROC(threshold=5)
        >>> metric.update(jnp.array([0.1, 0.5, 0.7, 0.8]),
        ...               jnp.array([0, 0, 1, 1]))
        >>> auroc, thresholds = metric.compute()
    """

    _concat_axis = -1

    _extra_device_attrs = ("threshold",)

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        threshold: Union[int, List[float], jax.Array] = DEFAULT_NUM_THRESHOLD,
        device=None,
    ) -> None:
        super().__init__(device=device)
        threshold = jax.device_put(create_threshold_tensor(threshold), self.device)
        _binary_binned_auroc_param_check(num_tasks, threshold)
        self.num_tasks = num_tasks
        self.threshold = threshold

    def update(self, input, target) -> "BinaryBinnedAUROC":
        input, target = self._input(input), self._input(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        self._append(input, target)
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        # pad-neutral: padded scores are -inf, below every finite threshold
        inputs, targets = self._padded()
        return (
            _binary_binned_auroc_compute_jit(inputs, targets, self.threshold),
            self.threshold,
        )


class MulticlassBinnedAUROC(_BufferedPairMetric):
    """Binned one-vs-rest AUROC for multiclass classification.

    See the functional docstring for the documented divergence from the
    reference's (buggy) class-axis reduction.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassBinnedAUROC
        >>> metric = MulticlassBinnedAUROC(num_classes=3, threshold=5)
        >>> metric.update(jnp.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1],
        ...                  [0.1, 0.2, 0.7], [0.3, 0.5, 0.2]]), jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        (Array(1., dtype=float32), Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32))
    """

    _extra_device_attrs = ("threshold",)

    def __init__(
        self,
        *,
        num_classes: int,
        threshold: Union[int, List[float], jax.Array] = DEFAULT_NUM_THRESHOLD,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        threshold = jax.device_put(create_threshold_tensor(threshold), self.device)
        _multiclass_binned_auroc_param_check(num_classes, threshold, average)
        self.num_classes = num_classes
        self.threshold = threshold
        self.average = average

    def update(self, input, target) -> "MulticlassBinnedAUROC":
        input, target = self._input(input), self._input(target)
        _multiclass_auroc_update_input_check(input, target, self.num_classes)
        self._append(input, target)
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        inputs, targets = self._padded()
        auroc = _multiclass_binned_auroc_compute_jit(
            inputs, targets, self.threshold
        )
        if self.average == "macro":
            return jnp.mean(auroc), self.threshold
        return auroc, self.threshold
