"""docs/metrics.md must equal what the generator renders from docstrings.

Companion to ``tests/test_api_doc.py`` (which guards the symbol table in
docs/api.md): VERDICT r3 missing item 3 asked for rendered per-metric doc
pages; the pages are generated, so the guard is exact text equality —
any docstring edit that is not re-rendered (or hand edit of the output)
fails here with the regeneration command in the message.
"""

from __future__ import annotations

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_metrics_md_is_current():
    import sys

    sys.path.insert(0, os.path.join(REPO, "docs"))
    try:
        from gen_metrics_reference import render
    finally:
        sys.path.pop(0)

    with open(os.path.join(REPO, "docs", "metrics.md")) as f:
        committed = f.read()
    assert committed == render(), (
        "docs/metrics.md is stale — regenerate with "
        "`PYTHONPATH=. python docs/gen_metrics_reference.py`"
    )


def test_metrics_md_covers_every_class():
    import torcheval_tpu.metrics as M

    with open(os.path.join(REPO, "docs", "metrics.md")) as f:
        text = f.read()
    missing = [
        name
        for name in M.__all__
        if name[0].isupper() and f"### `{name}(" not in text
    ]
    assert not missing, f"classes absent from docs/metrics.md: {missing}"


def test_metrics_md_covers_every_functional():
    import torcheval_tpu.metrics.functional as F

    with open(os.path.join(REPO, "docs", "metrics.md")) as f:
        text = f.read()
    missing = [name for name in F.__all__ if f"### `{name}(" not in text]
    assert not missing, f"functions absent from docs/metrics.md: {missing}"
