"""Precision-recall curve class metrics.

Parity: reference torcheval/metrics/classification/precision_recall_curve.py
(Binary :32, Multiclass :125, Multilabel :237) — example-buffering states.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, TypeVar

import jax

from torcheval_tpu.metrics.classification.auprc import _BufferedPairMetric
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_update_input_check,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_update_input_check,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_update_input_check,
)

T = TypeVar("T")


class BinaryPrecisionRecallCurve(_BufferedPairMetric):
    """Precision-recall curve for binary classification.

    ``compute`` returns ``(precision, recall, thresholds)``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryPrecisionRecallCurve
        >>> metric = BinaryPrecisionRecallCurve()
        >>> metric.update(jnp.array([0.1, 0.5, 0.7, 0.8]),
        ...               jnp.array([0, 0, 1, 1]))
    """

    _concat_axis = -1

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)

    def update(self, input, target) -> "BinaryPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        _binary_precision_recall_curve_update_input_check(input, target)
        self._append(input, target)
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        inputs, targets = self._padded()
        return _binary_precision_recall_curve_compute(
            inputs, targets, valid_count=self.num_samples
        )


class MulticlassPrecisionRecallCurve(_BufferedPairMetric):
    """Per-class precision-recall curves for multiclass classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassPrecisionRecallCurve
        >>> metric = MulticlassPrecisionRecallCurve(num_classes=3)
        >>> metric.update(jnp.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1],
        ...                  [0.1, 0.2, 0.7], [0.3, 0.5, 0.2]]), jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        ([Array([0.25      , 0.33333334, 0.5       , 1.        , 1.        ],      dtype=float32), Array([0.5      , 0.6666667, 1.       , 1.       , 1.       ], dtype=float32), Array([0.25, 0.5 , 1.  , 1.  ], dtype=float32)], [Array([1., 1., 1., 1., 0.], dtype=float32), Array([1. , 1. , 1. , 0.5, 0. ], dtype=float32), Array([1., 1., 1., 0.], dtype=float32)], [Array([0.1, 0.2, 0.3, 0.8], dtype=float32), Array([0.1, 0.2, 0.5, 0.7], dtype=float32), Array([0.1, 0.2, 0.7], dtype=float32)])
    """

    def __init__(self, *, num_classes: Optional[int] = None, device=None) -> None:
        super().__init__(device=device)
        self.num_classes = num_classes

    def update(self, input, target) -> "MulticlassPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        _multiclass_precision_recall_curve_update_input_check(
            input, target, self.num_classes
        )
        self._append(input, target)
        return self

    def compute(
        self,
    ) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
        inputs, targets = self._padded()
        num_classes = (
            self.num_classes if self.num_classes is not None
            else inputs.shape[1]
        )
        return _multiclass_precision_recall_curve_compute(
            inputs, targets, num_classes, valid_count=self.num_samples
        )


class MultilabelPrecisionRecallCurve(_BufferedPairMetric):
    """Per-label precision-recall curves for multilabel classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MultilabelPrecisionRecallCurve
        >>> metric = MultilabelPrecisionRecallCurve(num_labels=3)
        >>> metric.update(jnp.array([[0.9, 0.2, 0.8], [0.1, 0.7, 0.3], [0.6, 0.5, 0.4]]), jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 1]]))
        >>> metric.compute()
        ([Array([0.6666667, 1.       , 1.       , 1.       ], dtype=float32), Array([0.33333334, 0.5       , 1.        , 1.        ], dtype=float32), Array([0.6666667, 1.       , 1.       , 1.       ], dtype=float32)], [Array([1. , 1. , 0.5, 0. ], dtype=float32), Array([1., 1., 1., 0.], dtype=float32), Array([1. , 1. , 0.5, 0. ], dtype=float32)], [Array([0.1, 0.6, 0.9], dtype=float32), Array([0.2, 0.5, 0.7], dtype=float32), Array([0.3, 0.4, 0.8], dtype=float32)])
    """

    def __init__(self, *, num_labels: Optional[int] = None, device=None) -> None:
        super().__init__(device=device)
        self.num_labels = num_labels

    def update(self, input, target) -> "MultilabelPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        _multilabel_precision_recall_curve_update_input_check(
            input, target, self.num_labels
        )
        self._append(input, target)
        return self

    def compute(
        self,
    ) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
        inputs, targets = self._padded()
        num_labels = (
            self.num_labels if self.num_labels is not None
            else inputs.shape[1]
        )
        return _multilabel_precision_recall_curve_compute(
            inputs, targets, num_labels, valid_count=self.num_samples
        )
