"""Bandwidth-optimal eager sync: valid-prefix trimming + wire encodings.

Three layers under test, each exactness-pinned against the untrimmed path:

- ``Metric._sync_state_dict`` valid-prefix trimming (buffered power-of-2
  example buffers, pre-wrap ring windows): a sync ships the valid bucket,
  never the full capacity, and the merged result is BIT-identical to
  merging full snapshots;
- ``synclib`` sparse wire encoding: large mostly-zero states (streaming-
  AUROC histograms) travel as (uint32 indices, values) — LOSSLESS, always
  on, bit-exact including -0.0 and NaN payloads via the bit view;
- opt-in bf16 wire compression (``config.sync_compression``): large float
  payloads travel halved; OFF by default — the default sync is
  exactness-preserving.
"""

from __future__ import annotations

import copy

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torcheval_tpu import config as te_config
from torcheval_tpu.distributed import LocalReplicaGroup
from torcheval_tpu.metrics import (
    BinaryAUROC,
    MulticlassAccuracy,
    StreamingBinaryAUROC,
    WindowedBinaryAUROC,
)
from torcheval_tpu.metrics import synclib
from torcheval_tpu.metrics.synclib import (
    _decode_array,
    _encode_array,
    _pack_rank_states,
    _unpack_rank_states,
    metrics_traversal_order,
)
from torcheval_tpu.metrics.toolkit import (
    sync_and_compute,
    sync_and_compute_collection,
)

RNG = np.random.default_rng(7)


def _roundtrip(a: np.ndarray, compression: str = "off") -> np.ndarray:
    entry, chunks = _encode_array(a, compression)
    buf = (
        np.concatenate([c.reshape(-1) for c in chunks])
        if chunks
        else np.zeros(0, np.uint8)
    )
    out, offset = _decode_array(buf, 0, entry)
    assert offset == buf.size
    return out


# ------------------------------------------------------------ wire encodings


def test_sparse_encoding_bit_exact_with_special_values():
    """Sparse zero-suppression must be lossless to the BIT: -0.0 (zero
    value, nonzero bytes) and NaN payloads survive; true zeros restore as
    true zeros."""
    a = np.zeros(4096, dtype=np.float32)
    a[7] = -0.0
    a[100] = np.nan
    a[2000] = 1.5
    a[4095] = -np.inf
    out = _roundtrip(a)
    np.testing.assert_array_equal(
        a.view(np.uint32), out.view(np.uint32)
    )  # bitwise, not just value-wise


def test_sparse_engages_only_when_it_halves_the_wire():
    dense = RNG.normal(size=4096).astype(np.float32)  # no zeros: stays raw
    entry, chunks = _encode_array(dense, "off")
    assert entry[2] is None
    assert sum(c.size for c in chunks) == dense.nbytes

    sparse = np.zeros(4096, dtype=np.float32)
    sparse[:100] = 1.0
    entry, chunks = _encode_array(sparse, "off")
    assert entry[2][0] == "sparse"
    assert sum(c.size for c in chunks) == 100 * (4 + 4)
    np.testing.assert_array_equal(_roundtrip(sparse), sparse)


def test_small_arrays_never_pay_the_nonzero_scan():
    tiny = np.zeros(64, dtype=np.float32)
    entry, chunks = _encode_array(tiny, "off")
    assert entry[2] is None  # below _SPARSE_MIN_BYTES: raw


def test_bf16_compression_opt_in_and_lossy():
    a = (RNG.normal(size=2048).astype(np.float32) + 1.0) * 1e-3
    exact = _roundtrip(a, "off")
    np.testing.assert_array_equal(exact, a)
    lossy = _roundtrip(a, "bf16")
    assert lossy.dtype == np.float32
    np.testing.assert_array_equal(
        lossy, a.astype(jnp.bfloat16).astype(np.float32)
    )
    assert not np.array_equal(lossy, a)  # it IS lossy — hence opt-in


def test_int_and_scalar_states_unchanged_by_compression():
    ints = np.arange(4096, dtype=np.int64)
    entry, chunks = _encode_array(ints, "bf16")
    assert entry[2] is None
    np.testing.assert_array_equal(_roundtrip(ints, "bf16"), ints)


def test_pack_unpack_roundtrip_mixed_collection():
    states = {
        "hist": {"hist": jnp.zeros((1, 2, 8192), jnp.float32).at[0, 0, 5].set(3.0)},
        "counters": {"n": jnp.asarray(4.0), "k": 7},
        "dicty": {"d": {"a": jnp.asarray(1.0), "b": jnp.asarray(2.0)}},
        "listy": {"l": [jnp.arange(3.0), jnp.arange(2.0)]},
    }
    order = metrics_traversal_order(states)
    meta, flat = _pack_rank_states(states, order)
    # the 64 KiB histogram must have travelled sparse
    assert flat.size < 1024, flat.size
    out = _unpack_rank_states(states, order, meta, flat)
    np.testing.assert_array_equal(
        np.asarray(out["hist"]["hist"]), np.asarray(states["hist"]["hist"])
    )
    assert out["counters"]["k"] == 7
    assert float(out["counters"]["n"]) == 4.0
    assert sorted(out["dicty"]["d"]) == ["a", "b"]
    np.testing.assert_array_equal(out["listy"]["l"][1], np.arange(2.0))


# -------------------------------------------------- valid-prefix trimming


def _replicas(factory, world=4, n=100):
    out = []
    for r in range(world):
        m = factory()
        rng = np.random.default_rng(100 + r)
        x = rng.random(n).astype(np.float32)
        t = (rng.random(n) < 0.5).astype(np.float32)
        m.update(jnp.asarray(x), jnp.asarray(t))
        out.append(m)
    return out


def _wire_bytes(metric) -> int:
    payload = {"_m": metric._sync_state_dict()}
    order = metrics_traversal_order(payload)
    _, flat = _pack_rank_states(payload, order)
    return int(flat.size)


def _full_bytes(metric) -> int:
    return int(
        sum(
            np.asarray(v).nbytes
            for v in jax.tree_util.tree_leaves(metric.state_dict())
        )
    )


@pytest.mark.parametrize(
    "name,factory",
    [
        ("streaming", lambda: StreamingBinaryAUROC(num_bins=8192)),
        ("windowed", lambda: WindowedBinaryAUROC(max_num_samples=8192)),
        ("buffered", lambda: BinaryAUROC()),
    ],
)
def test_trimmed_sync_bit_identical_to_merge_oracle(name, factory):
    """The whole point of trimming is that it must NOT be observable in
    the result: synced == eager merge of full replicas, bit for bit."""
    ms = _replicas(factory)
    group = LocalReplicaGroup(jax.devices("cpu")[:1] * 4)
    got = np.asarray(sync_and_compute([copy.deepcopy(m) for m in ms], group))
    oracle = copy.deepcopy(ms[0])
    oracle.merge_state([copy.deepcopy(m) for m in ms[1:]])
    want = np.asarray(oracle.compute())
    np.testing.assert_array_equal(got, want)


def test_streaming_histogram_ships_kilobytes_not_64k():
    """ISSUE acceptance: streaming-AUROC sync payload at 100 valid
    samples drops >= 4x from the r5 bridge figure (65,536 B for the
    (1, 2, 8192) f32 histogram); counter metrics are untouched."""
    (m,) = _replicas(lambda: StreamingBinaryAUROC(num_bins=8192), world=1)
    full = _full_bytes(m)
    wire = _wire_bytes(m)
    assert full == 65536, full  # the published r5 bridge payload
    assert wire * 4 <= full, (wire, full)

    acc = MulticlassAccuracy()
    acc.update(
        jnp.asarray(RNG.uniform(size=(32, 4)).astype(np.float32)),
        jnp.asarray(RNG.integers(0, 4, size=32)),
    )
    assert _wire_bytes(acc) == _full_bytes(acc)  # counters: unchanged


def test_windowed_ring_ships_filled_prefix_only():
    (m,) = _replicas(lambda: WindowedBinaryAUROC(max_num_samples=8192), world=1)
    full = _full_bytes(m)
    wire = _wire_bytes(m)
    assert full > 8192 * 3 * 4  # three preallocated full-window rings
    assert wire <= 100 * 3 * 4 + 64, (wire, full)  # filled prefix + scalars

    # a WRAPPED ring is fully valid and must ship whole
    wrapped = WindowedBinaryAUROC(max_num_samples=64)
    for _ in range(3):
        wrapped.update(
            jnp.asarray(RNG.random(40).astype(np.float32)),
            jnp.asarray((RNG.random(40) < 0.5).astype(np.float32)),
        )
    sd = wrapped._sync_state_dict()
    assert sd["inputs"].shape == (1, 64)


def test_buffered_trim_restores_capacity_invariant():
    """A buffered metric loaded from an over-provisioned snapshot ships
    the covering bucket, not the inherited capacity."""
    m = BinaryAUROC()
    m.update(
        jnp.asarray(RNG.random(100).astype(np.float32)),
        jnp.asarray((RNG.random(100) < 0.5).astype(np.float32)),
    )
    sd = m.state_dict()
    big = dict(sd)
    for name in ("inputs", "targets", "weights"):
        big[name] = jnp.pad(sd[name], (0, 4096 - sd[name].shape[0]),
                            constant_values=0.0)
    m2 = BinaryAUROC()
    m2.load_state_dict(big)
    trimmed = m2._sync_state_dict()
    assert trimmed["inputs"].shape == (128,)  # bucket(100), not 4096
    # and the valid prefix is intact
    np.testing.assert_array_equal(
        np.asarray(trimmed["inputs"][:100]), np.asarray(sd["inputs"][:100])
    )


def test_collection_sync_with_compression_on():
    """End-to-end: a mixed collection syncs under bf16 compression; float
    buffer results are bf16-rounded, counters stay exact."""
    world = 4
    replicas = []
    for r in range(world):
        rng = np.random.default_rng(r)
        acc = MulticlassAccuracy()
        acc.update(
            jnp.asarray(rng.uniform(size=(64, 4)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 4, size=64)),
        )
        auroc = BinaryAUROC()
        auroc.update(
            jnp.asarray(rng.random(600).astype(np.float32)),
            jnp.asarray((rng.random(600) < 0.5).astype(np.float32)),
        )
        replicas.append({"acc": acc, "auroc": auroc})
    group = LocalReplicaGroup(jax.devices("cpu")[:1] * world)
    exact = {
        k: float(v)
        for k, v in sync_and_compute_collection(
            [{k: copy.deepcopy(m) for k, m in c.items()} for c in replicas],
            group,
        ).items()
    }
    with te_config.sync_compression_mode("bf16"):
        lossy = {
            k: float(v)
            for k, v in sync_and_compute_collection(replicas, group).items()
        }
    assert lossy["acc"] == exact["acc"]  # tiny counters: never compressed
    assert abs(lossy["auroc"] - exact["auroc"]) < 0.01  # bf16-rounded
