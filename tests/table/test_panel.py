"""One-intake table panels (ISSUE 17 tentpole): N family columns on ONE
fused key intake. Per-alias values pinned against single-family oracle
tables, one stable program across ragged batches (retrace-proof under
shape bucketing), alias/windowed-member validation, distributed adopt,
state/clone round trips, scrape naming, and the shared admission gate
counting each row once."""

from __future__ import annotations

import numpy as np
import pytest

from torcheval_tpu.metrics import ShardContext
from torcheval_tpu.metrics.toolkit import adopt_synced, clone_metric
from torcheval_tpu.table import (
    AdmissionController,
    MetricTable,
    PanelValues,
    ServingBudget,
    TablePanel,
)
from torcheval_tpu.utils.test_utils import ThreadWorld

RNG = np.random.default_rng(41)
N = 96
KEYS = RNG.integers(0, 40, N)
CLICKS = RNG.integers(0, 2, N).astype(np.float32)
PREDS = RNG.uniform(0.05, 0.95, N).astype(np.float32)
TARGETS = RNG.integers(0, 2, N).astype(np.float32)
WEIGHTS = (RNG.integers(1, 8, N) / 8).astype(np.float32)

MEMBERS = [
    "ctr",
    ("cal", "weighted_calibration"),
    ("ne", "ne", {"from_logits": False}),
    ("conversions", "ctr"),
]
BUNDLE = dict(
    ctr={"clicks": CLICKS, "weights": WEIGHTS},
    cal={"preds": PREDS, "targets": TARGETS, "weights": WEIGHTS},
    ne={"preds": PREDS, "targets": TARGETS, "weights": WEIGHTS},
    conversions={"clicks": TARGETS, "weights": WEIGHTS},
)


def _oracles():
    out = {}
    for alias, family, kwargs in (
        ("ctr", "ctr", {}),
        ("cal", "weighted_calibration", {}),
        ("ne", "ne", {}),
        ("conversions", "ctr", {}),
    ):
        t = MetricTable(family, **kwargs)
        args = BUNDLE[alias]
        t.ingest(KEYS, **args)
        out[alias] = t.compute().as_dict()
    return out


def test_panel_matches_single_family_oracles_bit_exact():
    panel = TablePanel(MEMBERS)
    panel.ingest(KEYS, **BUNDLE)
    values = panel.compute()
    assert isinstance(values, PanelValues)
    assert panel.aliases == ("ctr", "cal", "ne", "conversions")
    got = values.as_dict()
    for alias, want in _oracles().items():
        assert got[alias] == want, alias  # bit-exact, same row kernels


def test_one_intake_means_one_key_set_and_one_program():
    from torcheval_tpu.utils.compile_counter import CompileCounter

    def feed(panel, rng):
        for n in (96, 61, 96, 33):  # ragged sizes
            keys = rng.integers(0, 40, n)
            clicks = rng.integers(0, 2, n).astype(np.float32)
            preds = rng.uniform(0.1, 0.9, n).astype(np.float32)
            tgt = rng.integers(0, 2, n).astype(np.float32)
            panel.ingest(
                keys,
                ctr={"clicks": clicks},
                cal={"preds": preds, "targets": tgt},
                ne={"preds": preds, "targets": tgt},
                conversions={"clicks": tgt},
            )

    panel = TablePanel(MEMBERS)
    panel.ingest(KEYS, **BUNDLE)
    feed(panel, np.random.default_rng(7))  # warm every shape bucket
    with CompileCounter() as warm:
        feed(panel, np.random.default_rng(8))
    assert warm.compiles == 0  # retrace-proof: ONE fused program
    # the intake is shared: one key set, one insert per novel key
    assert int(panel.inserts_total) == int(panel.n_keys)


def test_member_bundles_are_validated():
    panel = TablePanel(MEMBERS)
    with pytest.raises(TypeError, match="per-member keyword arguments"):
        panel.ingest(KEYS, CLICKS)
    with pytest.raises(TypeError, match="missing"):
        panel.ingest(KEYS, ctr={"clicks": CLICKS})
    bad = dict(BUNDLE)
    bad["typo"] = {}
    with pytest.raises(TypeError, match="unexpected"):
        panel.ingest(KEYS, **bad)


def test_alias_and_member_validation():
    with pytest.raises(ValueError, match="at least one member"):
        TablePanel([])
    with pytest.raises(ValueError, match="duplicate panel member alias"):
        TablePanel(["ctr", ("ctr", "ctr")])
    with pytest.raises(ValueError, match="alias"):
        TablePanel([("bad-alias", "ctr")])
    with pytest.raises(ValueError, match="one window size"):
        TablePanel(
            [
                ("a", "windowed_ne", {"window": 4}),
                ("b", "windowed_ne", {"window": 8}),
            ]
        )
    with pytest.raises(ValueError, match="unknown table family"):
        TablePanel(["nope"])


def test_panel_distributed_adopt_matches_world1():
    batches = [
        (
            RNG.integers(0, 40, 32),
            RNG.integers(0, 2, 32).astype(np.float32),
            RNG.uniform(0.1, 0.9, 32).astype(np.float32),
            RNG.integers(0, 2, 32).astype(np.float32),
        )
        for _ in range(4)
    ]

    def bundle(c, p, t):
        return dict(
            ctr={"clicks": c},
            cal={"preds": p, "targets": t},
            ne={"preds": p, "targets": t},
            conversions={"clicks": t},
        )

    def body(g):
        """The panel and its four single-family member tables see the
        same sharded stream; post-adopt the panel's per-alias values
        must be BIT-exact against each member table (same row kernels,
        same merge order — the one-intake fusion changes no math)."""
        panel = TablePanel(MEMBERS, shard=ShardContext(g.rank, 2))
        singles = {
            "ctr": MetricTable("ctr", shard=ShardContext(g.rank, 2)),
            "cal": MetricTable(
                "weighted_calibration", shard=ShardContext(g.rank, 2)
            ),
            "ne": MetricTable("ne", shard=ShardContext(g.rank, 2)),
            "conversions": MetricTable("ctr", shard=ShardContext(g.rank, 2)),
        }
        for i in range(g.rank, len(batches), 2):
            k, c, p, t = batches[i]
            b = bundle(c, p, t)
            panel.ingest(k, **b)
            for alias, table in singles.items():
                table.ingest(k, **b[alias])
        got = adopt_synced(panel, g).compute().as_dict()
        want = {
            alias: adopt_synced(table, g).compute().as_dict()
            for alias, table in singles.items()
        }
        assert got == want
        return got

    results = ThreadWorld(2).run(body)
    assert results[0] == results[1]  # every rank returns the same value


def test_panel_state_and_clone_round_trip():
    panel = TablePanel(MEMBERS)
    panel.ingest(KEYS, **BUNDLE)
    want = panel.compute().as_dict()

    fresh = TablePanel(MEMBERS)
    fresh.load_state_dict(panel.state_dict())
    assert fresh.compute().as_dict() == want

    cloned = clone_metric(panel)  # _MemberView deepcopy regression
    assert cloned.compute().as_dict() == want
    cloned.ingest(KEYS, **BUNDLE)  # the clone is independently usable
    assert panel.compute().as_dict() == want

    merged = clone_metric(fresh)
    merged.merge_state([clone_metric(fresh)])
    doubled = merged.compute().as_dict()
    # ratio families are scale-invariant under a doubled stream
    for alias in ("ctr", "cal", "conversions"):
        for k, v in doubled[alias].items():
            assert v == pytest.approx(want[alias][k], rel=1e-5)


def test_panel_scrape_names_carry_the_alias():
    panel = TablePanel([("a", "ctr"), ("b", "ctr")])
    panel.ingest([3, 4], a={"clicks": np.ones(2, np.float32)},
                 b={"clicks": np.zeros(2, np.float32)})
    values = panel.scrape_values(limit=8)
    assert set(values) == {
        "value_a_3", "value_a_4", "value_b_3", "value_b_4",
    }
    assert values["value_a_3"] == 1.0 and values["value_b_3"] == 0.0


def test_admission_gate_is_shared_by_the_panel_intake():
    panel = TablePanel(
        MEMBERS,
        admission=AdmissionController(ServingBudget(), sample_p=0.3),
    )
    panel.admission_rung = 1
    rng = np.random.default_rng(3)
    n = 600
    keys = rng.integers(0, 3000, n)
    c = rng.integers(0, 2, n).astype(np.float32)
    p = rng.uniform(0.1, 0.9, n).astype(np.float32)
    t = rng.integers(0, 2, n).astype(np.float32)
    panel.ingest(
        keys,
        ctr={"clicks": c},
        cal={"preds": p, "targets": t},
        ne={"preds": p, "targets": t},
        conversions={"clicks": t},
    )
    # each row decided ONCE for all 4 families, not 4x
    assert int(panel.admitted_rows_total) + int(panel.shed_rows_total) == n
    assert 0 < int(panel.shed_rows_total) < n
    # all four aliases report the same (admitted) key set
    got = panel.compute().as_dict()
    keysets = {alias: set(vals) for alias, vals in got.items()}
    assert len(set(map(frozenset, keysets.values()))) == 1
