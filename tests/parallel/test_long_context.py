"""Model-level long-context evaluation: the sequence-sharded LM forward
(ring attention inside) must equal its own dense mode, and the in-program
perplexity counters must match single-device Perplexity on the same data.
"""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 jax keeps it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torcheval_tpu.models import (
    init_long_context_lm,
    long_context_lm,
    perplexity_counters,
)

VOCAB, D_MODEL, HEADS, LAYERS, D_FF = 64, 32, 4, 2, 64
RNG = np.random.default_rng(31)


def _params(max_len):
    return init_long_context_lm(
        jax.random.PRNGKey(0), vocab_size=VOCAB, d_model=D_MODEL,
        n_heads=HEADS, n_layers=LAYERS, d_ff=D_FF, max_len=max_len,
    )


@pytest.mark.parametrize("sp", [2, 8])
def test_sequence_sharded_forward_matches_dense(sp):
    seq = 8 * sp
    params = _params(seq)
    tokens = jnp.asarray(RNG.integers(0, VOCAB, size=(2, seq)))
    mesh = Mesh(np.array(jax.devices("cpu")[:sp]), ("sp",))

    sharded = jax.jit(
        shard_map(
            partial(long_context_lm, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp", None),
        )
    )
    out = sharded(params, tokens)
    dense = long_context_lm(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), atol=2e-4, rtol=2e-4
    )


def test_dp_sp_eval_step_counters_match_perplexity_metric():
    """The full composed eval step — batch over dp, sequence over sp,
    counters psum'd over both axes in-program — must reproduce the
    single-device Perplexity metric exactly."""
    from torcheval_tpu.metrics import Perplexity

    dp, sp = 2, 4
    seq = 8 * sp
    params = _params(seq)
    tokens = jnp.asarray(RNG.integers(0, VOCAB, size=(2 * dp, seq)))
    targets = jnp.asarray(RNG.integers(0, VOCAB, size=(2 * dp, seq)))
    mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(dp, sp), ("dp", "sp"))

    def eval_step(params, tokens, targets):
        logits = long_context_lm(params, tokens, axis_name="sp")
        counters = perplexity_counters(logits, targets)
        return jax.tree.map(lambda c: lax.psum(c, ("dp", "sp")), counters)

    step = jax.jit(
        shard_map(
            eval_step, mesh=mesh,
            in_specs=(P(), P("dp", "sp"), P("dp", "sp")),
            out_specs=P(),
        )
    )
    counters = step(
        params,
        jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp"))),
        jax.device_put(targets, NamedSharding(mesh, P("dp", "sp"))),
    )

    dense_logits = long_context_lm(params, tokens)
    metric = Perplexity()
    metric.update(dense_logits, targets)
    expected = float(metric.compute())

    got = float(
        jnp.exp(counters["sum_log_probs"] / counters["num_total"])
    )
    assert got == pytest.approx(expected, rel=1e-4), (got, expected)
    assert float(counters["num_total"]) == targets.size


def test_positions_are_global_under_sharding():
    """A wrong (local) positional offset is the classic sp bug: degenerate
    check — two devices, position embeddings dominate, block 1 must see
    positions 8..15, not 0..7."""
    seq, sp = 16, 2
    params = _params(seq)
    # make pos embeddings huge so any offset error dwarfs attention noise
    params["pos_embed"] = params["pos_embed"] * 100.0
    tokens = jnp.asarray(RNG.integers(0, VOCAB, size=(1, seq)))
    mesh = Mesh(np.array(jax.devices("cpu")[:sp]), ("sp",))
    sharded = jax.jit(
        shard_map(
            partial(long_context_lm, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp", None),
        )
    )
    np.testing.assert_allclose(
        np.asarray(sharded(params, tokens)),
        np.asarray(long_context_lm(params, tokens)),
        atol=2e-3, rtol=2e-3,
    )
