"""Mergeable on-device input-distribution sketches (data-quality telemetry).

PR 5/8/10 observe the eval's *execution* (latency, retries, stalls);
nothing observes *what the eval is seeing* — input/prediction
distributions, NaN rates, label skew, drift vs a reference window. That
layer is also the prerequisite for the ROADMAP item 2 lossy wire
encodings (EQuARX arXiv:2506.17615, Prime CCL arXiv:2505.14065):
quantized / staleness-tolerant merges only ship safely when per-metric
distribution and error budgets are continuously *measured*, not assumed.

:class:`InputSketch` is a fixed-size, mergeable distribution sketch that
is itself an ordinary :class:`~torcheval_tpu.metrics.metric.Metric` —
its four state families are registered through ``_add_state`` with
declarative merge kinds, so sync, merge, elastic snapshot/restore,
subgroup scoping, and the bucketed masked-twin machinery all apply with
ZERO new protocol code:

- ``hist`` (f32 ``(num_bins,)``, SUM): a log₂ or fixed-edge quantile
  histogram through the PR 6 ``ops.histogram`` kernel (native on the CPU
  lowering, bit-identical XLA twin elsewhere). Fixed-edge mode bins
  values over ``bounds=(lo, hi)``; log₂ mode (the default — no prior
  knowledge of the value range needed) bins ``log2(|x|)`` over an
  exponent range, so ~2x relative resolution everywhere. Counts are
  integer-valued f32 — sums are exact (and therefore merge-order
  invariant) below 2^24 per bin.
- ``counts`` (int32 ``(8,)``, SUM): total / NaN / +Inf / -Inf / zero /
  negative / below-range / above-range counters. Integer adds — exact
  and associative, so every merge order is bit-identical.
- ``moments`` (f32 ``(5,)``, CUSTOM): streaming ``[count, mean, M2,
  min, max]`` over the finite samples. Updates fold each batch's
  two-pass stats into the carried state with Chan's parallel merge; the
  cross-replica merge applies the SAME formula pairwise in ascending
  rank order (:func:`chan_merge`), with the empty-side identities exact
  (``a ⊕ empty`` returns ``a``'s bits verbatim), so a left fold over
  rank-ordered carriers replays the single-stream fold bit-for-bit when
  the carriers partition the stream in rank order.
- ``registers`` (int32 ``(registers,)``, MAX): a deterministic
  register-array distinct-count sketch (Flajolet–Martin / HyperLogLog
  family) over the raw f32 bit patterns, hashed with the murmur3
  finalizer — NOT Python's salted ``hash``, so every rank and every
  restart agrees. MAX merges are idempotent, commutative, associative:
  bit-identical under any merge order, any world change, and double
  counting (the one sketch that is safe under at-least-once delivery).

``update(values)`` is ONE fused transform dispatch (``_fuse.py``) with a
mask-aware twin, so sketches ride shape bucketing and donation like any
counter metric; the fold kernels are shared with
:func:`~torcheval_tpu.obs.quality.watch_inputs`, which fuses the same
accumulation into a *watched* metric's own update program (zero extra
dispatches, zero collectives, zero host syncs — statically verified by
the ``analysis --programs`` sweep).

Cost/exactness contract: nothing here reads the device on the update
path. Reading a sketch (``compute()``, ``summary()``, drift scoring,
Prometheus scrape) is a host readback — scrape-cadence territory, never
step-path (docs/observability.md, "Input quality & drift").
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu._ffi import ffi as _ffi
from torcheval_tpu.metrics.metric import MergeKind, Metric, UpdatePlan
from torcheval_tpu.ops.histogram import histogram as _ops_histogram
from torcheval_tpu.ops.segment import segment_max as _segment_max

__all__ = [
    "InputSketch",
    "SketchConfig",
    "SketchSummary",
    "chan_merge",
    "hll_estimate",
]

# counts lanes (int32 (8,) SUM state)
_CNT_TOTAL = 0
_CNT_NAN = 1
_CNT_POSINF = 2
_CNT_NEGINF = 3
_CNT_ZERO = 4
_CNT_NEG = 5
_CNT_BELOW = 6
_CNT_ABOVE = 7

CNT_FIELDS = (
    "total", "nan", "posinf", "neginf", "zero", "negative", "below", "above",
)


class SketchConfig(NamedTuple):
    """Hashable sketch geometry (keys the fused-kernel jit caches).

    ``log2=True`` bins ``log2(|x|)`` over integer exponent edges
    ``lo..hi`` (one bin per exponent); ``log2=False`` bins values over
    fixed edges ``lo..hi`` with ``num_bins`` equal-width bins.
    ``registers`` is the distinct-sketch register count (power of two).
    """

    num_bins: int
    lo: float
    hi: float
    log2: bool
    registers: int

    @property
    def reg_bits(self) -> int:
        return int(self.registers).bit_length() - 1

    def edges(self) -> np.ndarray:
        """The ``num_bins + 1`` histogram bin edges in VALUE space
        (log₂ mode returns ``2**exponent`` edges of ``|x|``)."""
        e = np.linspace(self.lo, self.hi, self.num_bins + 1)
        return np.exp2(e) if self.log2 else e


class SketchSummary(NamedTuple):
    """``InputSketch.compute()`` result (host-friendly floats)."""

    count: float        # finite samples folded into the moments
    mean: float
    var: float          # population variance (M2 / count)
    min: float
    max: float
    total: int          # every observed sample (incl. NaN/Inf)
    nan: int
    posinf: int
    neginf: int
    zero: int
    negative: int
    below: int          # finite, non-zero-in-log2-mode, under the range
    above: int
    distinct: float     # register-array estimate over raw bit patterns
    hist: Any           # (num_bins,) f32 counts (np.ndarray)


def default_config(
    num_bins: Optional[int] = None,
    bounds: Optional[Tuple[float, float]] = None,
    log2_bounds: Tuple[int, int] = (-24, 24),
    registers: int = 64,
) -> SketchConfig:
    """Normalize the user-facing knobs into a :class:`SketchConfig`.

    ``bounds=(lo, hi)`` selects fixed-edge mode (``num_bins`` defaults
    to 32); ``bounds=None`` selects log₂ mode over integer exponents
    ``log2_bounds`` (one bin per exponent — |x| in [2^-24, 2^24) by
    default; zeros are counted separately, never binned).
    """
    registers = int(registers)
    if registers < 16 or registers & (registers - 1):
        raise ValueError(
            f"registers must be a power of two >= 16, got {registers}"
        )
    if bounds is not None:
        lo, hi = float(bounds[0]), float(bounds[1])
        if not hi > lo:
            raise ValueError(f"bounds must satisfy hi > lo, got ({lo}, {hi})")
        bins = 32 if num_bins is None else int(num_bins)
        if bins < 1:
            raise ValueError(f"num_bins must be >= 1, got {bins}")
        return SketchConfig(bins, lo, hi, False, registers)
    lo_e, hi_e = int(log2_bounds[0]), int(log2_bounds[1])
    if not hi_e > lo_e:
        raise ValueError(
            f"log2_bounds must satisfy hi > lo, got ({lo_e}, {hi_e})"
        )
    bins = (hi_e - lo_e) if num_bins is None else int(num_bins)
    if bins != hi_e - lo_e:
        raise ValueError(
            "log2 mode bins values by INTEGER exponent — one bin per "
            f"exponent (num_bins == hi - lo == {hi_e - lo_e}); widen "
            "log2_bounds or use fixed-edge mode (bounds=) for custom "
            "bin counts"
        )
    return SketchConfig(bins, float(lo_e), float(hi_e), True, registers)


# ------------------------------------------------------------ fold kernels


def _clz32(v: jax.Array) -> jax.Array:
    """Branchless count-leading-zeros of a uint32 (smear + popcount)."""
    v = v | (v >> 1)
    v = v | (v >> 2)
    v = v | (v >> 4)
    v = v | (v >> 8)
    v = v | (v >> 16)
    return 32 - jax.lax.population_count(v).astype(jnp.int32)


def _fmix32(h: jax.Array) -> jax.Array:
    """murmur3's 32-bit finalizer: a deterministic, well-mixed hash of
    the raw value bits (never Python's salted ``hash`` — every rank and
    every restart must agree on register placement)."""
    h = h ^ (h >> 16)
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


@jax.jit
def chan_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Chan's parallel moments merge of two ``[count, mean, M2, min,
    max]`` vectors (Chan, Golub & LeVeque 1979), with EXACT empty-side
    identities: merging with a zero-count side returns the other side's
    bits verbatim, so a left fold over rank-ordered carriers that
    partition the stream replays the single-stream fold bit-for-bit.
    Used by both the fused update (state ⊕ batch, where the jit inlines
    into the fold program) and the cross-replica merge (carrier ⊕
    carrier, ascending rank order, where the jit keeps the eager merge
    one dispatch instead of ~20 — measured 1.4 ms/merge eager on the
    bench box)."""
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    na, nb = a[0], b[0]
    n = na + nb
    safe_n = jnp.maximum(n, 1.0)
    delta = b[1] - a[1]
    mean = a[1] + delta * (nb / safe_n)
    m2 = a[2] + b[2] + delta * delta * (na * (nb / safe_n))
    mean = jnp.where(na == 0, b[1], jnp.where(nb == 0, a[1], mean))
    m2 = jnp.where(na == 0, b[2], jnp.where(nb == 0, a[2], m2))
    return jnp.stack(
        [n, mean, m2, jnp.minimum(a[3], b[3]), jnp.maximum(a[4], b[4])]
    )


def moments_window(live: np.ndarray, ref: np.ndarray) -> np.ndarray:
    """The exact inverse of :func:`chan_merge`: the ``[count, mean, M2,
    min, max]`` of the samples folded AFTER ``ref`` was snapshotted from
    the same stream (drift scoring compares the post-freeze window, not
    the diluted total). min/max cannot be un-merged — the live extrema
    are returned (conservative)."""
    live = np.asarray(live, np.float64)
    ref = np.asarray(ref, np.float64)
    n_w = live[0] - ref[0]
    if n_w <= 0:
        return np.asarray([0.0, 0.0, 0.0, live[3], live[4]], np.float64)
    mean_w = (live[0] * live[1] - ref[0] * ref[1]) / n_w
    delta = mean_w - ref[1]
    m2_w = live[2] - ref[2] - delta * delta * ref[0] * n_w / max(live[0], 1.0)
    return np.asarray(
        [n_w, mean_w, max(m2_w, 0.0), live[3], live[4]], np.float64
    )


def _exponent_of(x: jax.Array) -> jax.Array:
    """``floor(log2(|x|))`` as an INTEGER from the f32 bit pattern —
    biased exponent for normals, mantissa bit length for subnormals.
    No libm, so the native kernel (``ops/native/sketch.cc``) and this
    twin agree bit-for-bit; callers mask out zeros and non-finites."""
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    mag = bits & np.uint32(0x7FFFFFFF)
    eb = (mag >> np.uint32(23)).astype(jnp.int32)
    sub = (32 - _clz32(mag)) - 1 - 149  # bit_length(mag) - 1 - 149
    return jnp.where(eb > 0, eb - 127, sub)


def _seq_sum(values: jax.Array) -> jax.Array:
    """A SEQUENTIAL f32 sum: scatter-add into one segment. XLA:CPU
    lowers scatter-add to an in-order per-element loop (the property
    segment.cc's parity tests pin), so this matches the native kernel's
    ascending-order f32 accumulation bit-for-bit — a plain ``jnp.sum``
    may reduce in vectorized partial sums and differ in the last ulp."""
    return jax.ops.segment_sum(
        values, jnp.zeros(values.shape, jnp.int32), num_segments=1
    )[0]


def _sketch_fold_xla(cfg: SketchConfig, x: jax.Array, w: jax.Array):
    """Pure-XLA twin of the native ``SketchFold`` kernel: returns the
    per-batch deltas ``(hist, counts, stats, regs)``. Bit-identical to
    ``ops/native/sketch.cc`` on CPU (pinned by tests/metrics/
    test_quality.py): integer counters/registers/exponent bins, the
    histogram.cc edge math in fixed mode, and sequential f32 moment
    sums via :func:`_seq_sum`."""
    lo32 = np.float32(cfg.lo)
    hi32 = np.float32(cfg.hi)
    p = cfg.reg_bits
    # anomaly lanes by BIT pattern (float compares are ambiguous for
    # subnormals under XLA's inconsistent flush-to-zero; integer tests
    # match the native kernel deterministically)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    mag = bits & np.uint32(0x7FFFFFFF)
    sign = (bits >> np.uint32(31)) != 0
    is_nan = mag > np.uint32(0x7F800000)
    is_inf = mag == np.uint32(0x7F800000)
    finite = mag < np.uint32(0x7F800000)
    is_zero = finite & (mag == 0)
    nonzero = finite & (mag != 0)
    wb = (w > 0).astype(jnp.float32)  # presence (counter semantics)
    wf = w * finite.astype(jnp.float32)  # moment/histogram weights

    if cfg.log2:
        e = _exponent_of(x)
        below = nonzero & (e < int(cfg.lo))
        above = nonzero & (e >= int(cfg.hi))
    else:
        below = finite & (x < lo32)
        above = finite & (x > hi32)
    xz = jnp.where(wf > 0, x, 0.0)
    # ONE stacked reduction for the counter lanes (integer-valued —
    # exact in any reduce order below 2^24 samples per batch)
    rows = jnp.stack(
        [
            wb,
            wb * is_nan,
            wb * (is_inf & ~sign),
            wb * (is_inf & sign),
            wb * is_zero,
            wb * (nonzero & sign),
            wb * below,
            wb * above,
        ]
    )
    delta_counts = jnp.sum(rows, axis=1).astype(jnp.int32)

    # quantile histogram: fixed mode through ops.histogram (the pinned
    # histogram.cc twin), log2 mode by integer exponent bin scatter
    if cfg.log2:
        ids = jnp.where(
            nonzero & ~below & ~above,
            (_exponent_of(x) - int(cfg.lo)).astype(jnp.int32),
            -1,
        )
        delta_hist = jax.ops.segment_sum(
            wf, ids, num_segments=cfg.num_bins
        )
    else:
        delta_hist = _ops_histogram(
            x, cfg.num_bins, bounds=(cfg.lo, cfg.hi), weights=wf
        )

    # streaming moments: two-pass batch stats, SEQUENTIAL f32 sums
    bc = _seq_sum(wf)
    bmean = _seq_sum(xz * wf) / jnp.maximum(bc, 1.0)
    bm2 = _seq_sum(wf * jnp.square(jnp.where(wf > 0, x - bmean, 0.0)))
    bmin = jnp.min(jnp.where(wf > 0, x, jnp.inf))
    bmax = jnp.max(jnp.where(wf > 0, x, -jnp.inf))
    stats = jnp.stack([bc, bmean, bm2, bmin, bmax])

    # distinct-count registers over the raw bit patterns.
    # ops.segment_max, NOT jax.ops.segment_max: XLA:CPU lowers
    # scatter-max to a per-element update loop (the PR 6 class —
    # measured ~120 µs at n=2048)
    h = _fmix32(jax.lax.bitcast_convert_type(x, jnp.uint32))
    j = (h & np.uint32(cfg.registers - 1)).astype(jnp.int32)
    rho = _clz32(h >> np.uint32(p)) - p + 1
    rho = jnp.where(w > 0, rho, 0).astype(jnp.int32)
    delta_reg = _segment_max(rho, j, cfg.registers, identity=0)
    return delta_hist, delta_counts, stats, delta_reg


def _native_sketch_ready() -> bool:
    from torcheval_tpu.ops import native

    return native.ensure_registered()


def _sketch_fold_deltas(cfg: SketchConfig, x: jax.Array, w: jax.Array):
    """Dispatch one batch's sketch deltas: the fused native kernel
    (``ops/native/sketch.cc`` — TWO data passes instead of ~12 XLA
    reduce loops, measured ~5x on the bench box) on the CPU lowering,
    the bit-identical pure-XLA twin elsewhere (the ``torcheval_tpu.ops``
    fallback contract)."""
    if not (x.size > 0 and _native_sketch_ready()):
        return _sketch_fold_xla(cfg, x, w)

    def native_fn(xv, wv):
        from torcheval_tpu.metrics.functional.tensor_utils import _match_vma

        call = _ffi.ffi_call(
            "torcheval_sketch_fold",
            (
                jax.ShapeDtypeStruct((cfg.num_bins,), jnp.float32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((5,), jnp.float32),
                jax.ShapeDtypeStruct((cfg.registers,), jnp.int32),
            ),
            vmap_method="sequential",
        )
        out = call(
            xv, wv, lo=cfg.lo, hi=cfg.hi, log2_mode=int(cfg.log2)
        )
        return tuple(_match_vma(o, xv) for o in out)

    def xla_fn(xv, wv):
        return _sketch_fold_xla(cfg, xv, wv)

    return jax.lax.platform_dependent(
        x, w, cpu=native_fn, default=xla_fn
    )


@lru_cache(maxsize=None)
def _fold_fns(cfg: SketchConfig):
    """The traceable fold for one sketch geometry:
    ``fold(states4, x, w) -> states4`` where ``states4 = (hist, counts,
    moments, registers)`` and ``w`` is a per-element f32 validity weight
    (bucket-padding masks fold in here — a padded row's w=0 contributes
    exactly zero to every state). Cached per config so repeated updates
    key the same jit entry."""

    def fold(states, x, w):
        hist, counts, moments, registers = states
        x = jnp.asarray(x).astype(jnp.float32)
        w = jnp.broadcast_to(jnp.asarray(w, jnp.float32), x.shape)
        x, w = x.reshape(-1), w.reshape(-1)
        delta_hist, delta_counts, stats, delta_reg = _sketch_fold_deltas(
            cfg, x, w
        )
        return (
            hist + delta_hist,
            counts + delta_counts,
            chan_merge(moments, stats),
            jnp.maximum(registers, delta_reg),
        )

    return fold


@lru_cache(maxsize=None)
def _sketch_kernels(cfg: SketchConfig):
    """(plain, masked) transform kernels for :class:`InputSketch`'s own
    update plan. The masked twin takes the bucket-padded values plus the
    int32 valid-extent vector and rebuilds the row mask inside the fused
    program (the ``_bucket.py`` contract: padded rows contribute exactly
    zero to every state)."""
    fold = _fold_fns(cfg)

    def plain(states, x):
        return fold(states, x, jnp.float32(1.0))

    def masked(states, x, valid):
        n = x.shape[0]
        row = jnp.arange(n, dtype=jnp.int32) < valid[0]
        w = row.astype(jnp.float32).reshape((n,) + (1,) * (x.ndim - 1))
        return fold(states, x, jnp.broadcast_to(w, x.shape))

    plain.__name__ = f"sketch_fold_{cfg.num_bins}"
    masked.__name__ = f"sketch_fold_masked_{cfg.num_bins}"
    return plain, masked


def moment_default() -> jax.Array:
    """The empty moments vector: zero count/mean/M2, inverted extrema
    (the exact identity of :func:`chan_merge`)."""
    return jnp.asarray([0.0, 0.0, 0.0, np.inf, -np.inf], jnp.float32)


def hll_estimate(registers: np.ndarray) -> float:
    """The register-array cardinality estimate (HyperLogLog with the
    standard small-range linear-counting correction). Deterministic host
    math over an int32 register snapshot."""
    regs = np.asarray(registers, np.float64)
    m = regs.size
    if m == 0:
        return 0.0
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(m, 0.7213 / (1 + 1.079 / m))
    est = alpha * m * m / float(np.sum(np.exp2(-regs)))
    zeros = int(np.sum(regs == 0))
    if est <= 2.5 * m and zeros > 0:
        return m * math.log(m / zeros)
    return float(est)


class InputSketch(Metric[SketchSummary]):
    """Fixed-size mergeable distribution sketch of a value stream.

    See the module docstring for the four state families and their
    merge/exactness contracts. ``update(values)`` accepts any array
    (flattened); ``weights`` optionally down-weights/masks elements
    (0/1 masks compose with shape bucketing's padding mask).

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.obs import InputSketch
        >>> sk = InputSketch(bounds=(0.0, 1.0), num_bins=4)
        >>> _ = sk.update(jnp.array([0.1, 0.2, 0.6, 0.9]))
        >>> int(sk.compute().count)
        4
    """

    _bucketed_update = True

    def __init__(
        self,
        *,
        num_bins: Optional[int] = None,
        bounds: Optional[Tuple[float, float]] = None,
        log2_bounds: Tuple[int, int] = (-24, 24),
        registers: int = 64,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self.config = default_config(num_bins, bounds, log2_bounds, registers)
        cfg = self.config
        self._add_state(
            "hist", jnp.zeros((cfg.num_bins,), jnp.float32), merge=MergeKind.SUM
        )
        self._add_state(
            "counts", jnp.zeros((8,), jnp.int32), merge=MergeKind.SUM
        )
        self._add_state("moments", moment_default(), merge=MergeKind.CUSTOM)
        self._add_state(
            "registers",
            jnp.zeros((cfg.registers,), jnp.int32),
            merge=MergeKind.MAX,
        )

    def _update_plan(self, values, weights=None):
        values = self._input(values, dtype=jnp.float32)
        plain, masked = _sketch_kernels(self.config)
        if weights is not None:
            weights = self._input(weights, dtype=jnp.float32)
            if np.shape(weights) != np.shape(values):
                raise ValueError(
                    f"weights shape {np.shape(weights)} != values "
                    f"{np.shape(values)}"
                )
            # weighted updates skip bucketing (the weight IS the mask)
            return UpdatePlan(
                _weighted_kernel(self.config),
                ("hist", "counts", "moments", "registers"),
                (values, weights),
                transform=True,
            )
        return UpdatePlan(
            plain,
            ("hist", "counts", "moments", "registers"),
            (values,),
            transform=True,
            masked_kernel=masked,
            batch_axes=(("batch",),),
        )

    def update(self, values, weights=None) -> "InputSketch":
        return self._apply_update_plan(self._update_plan(values, weights))

    def _merge_custom_state(self, name, mine, theirs):
        if name == "moments":
            # pairwise in carrier (ascending-rank) order: the toolkit
            # merge loop left-folds peers, so this IS Chan's
            # pairwise-in-rank-order merge
            return chan_merge(mine, theirs)
        return super()._merge_custom_state(name, mine, theirs)

    def edges(self) -> np.ndarray:
        """Histogram bin edges in value space (log₂ mode: |x| edges)."""
        return self.config.edges()

    def compute(self) -> SketchSummary:
        """Host-readable summary (forces a device readback — scrape
        cadence, never the step path)."""
        mom = np.asarray(self.moments, np.float64)
        cnt = np.asarray(self.counts)
        count = float(mom[0])
        return SketchSummary(
            count=count,
            mean=float(mom[1]) if count else 0.0,
            var=float(mom[2] / count) if count else 0.0,
            min=float(mom[3]),
            max=float(mom[4]),
            total=int(cnt[_CNT_TOTAL]),
            nan=int(cnt[_CNT_NAN]),
            posinf=int(cnt[_CNT_POSINF]),
            neginf=int(cnt[_CNT_NEGINF]),
            zero=int(cnt[_CNT_ZERO]),
            negative=int(cnt[_CNT_NEG]),
            below=int(cnt[_CNT_BELOW]),
            above=int(cnt[_CNT_ABOVE]),
            distinct=hll_estimate(np.asarray(self.registers)),
            hist=np.asarray(self.hist),
        )

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile from the histogram: the upper edge of
        the bin holding the target sample (conservative — never
        under-reports; within one bin of the truth by construction).
        ``None`` while the histogram is empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        counts = np.asarray(self.hist, np.float64)
        total = float(counts.sum())
        if total <= 0:
            return None
        edges = self.edges()
        target = max(1.0, math.ceil(q * total))
        seen = 0.0
        for i, c in enumerate(counts):
            seen += float(c)
            if seen >= target:
                return float(edges[i + 1])
        return float(edges[-1])


@lru_cache(maxsize=None)
def _weighted_kernel(cfg: SketchConfig):
    fold = _fold_fns(cfg)

    def weighted(states, x, w):
        return fold(states, x, w)

    weighted.__name__ = f"sketch_fold_weighted_{cfg.num_bins}"
    return weighted
