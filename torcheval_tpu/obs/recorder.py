"""The global event recorder: off by default, near-zero-cost when off.

The contract that makes instrumentation safe to leave in the hot paths
(``Metric.update``/``compute``, the toolkit sync entry points, the
resilience retry loop, elastic snapshots):

- **Off by default.** Every instrumented site guards on one attribute read
  (``RECORDER.enabled``) and takes the original code path when False — no
  host sync, no extra collectives, no allocation. Pinned by the
  recorder-ON variants in tests/metrics/test_no_host_sync.py and
  test_sync_collective_counts.py (even ON, the step path adds zero
  host round-trips and zero collectives — recording is a host-side ring
  append).
- **Bounded.** Events land in a thread-safe ring buffer
  (:class:`EventLog`); a forgotten recorder cannot grow without bound —
  old events are dropped (and counted) once ``capacity`` is reached.
- **Composable exporters.** An attached ``export.JsonlWriter`` sees every
  recorded event (async bounded-queue writer — the step path never waits
  for disk unless the queue backs up, which is the backpressure contract
  inherited from the elastic snapshot writer).

Enable via ``config.observability(...)`` (scoped), ``obs.enable()``
(process-wide), or env ``TORCHEVAL_TPU_OBSERVABILITY`` (truthy enables at
import; a value ending in ``.jsonl`` also attaches a JSONL writer at that
path). See docs/observability.md.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

from torcheval_tpu import config
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.obs.events import Event, SpanEvent

__all__ = ["EventLog", "Recorder", "RECORDER", "enable", "disable", "enabled", "recorder", "span"]

DEFAULT_CAPACITY = 4096


class EventLog:
    """Thread-safe bounded ring buffer of :class:`Event`.

    ``capacity`` bounds memory; once full, the oldest events are dropped
    (``dropped`` counts them, ``total`` counts every append ever). Reads
    (:meth:`tail`, iteration) snapshot under the lock, so concurrent
    appends from worker threads (elastic writer, resilience workers)
    never corrupt a reader.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._buf: deque = deque(maxlen=self.capacity)  # tev: guarded-by=_lock
        self._lock = threading.Lock()
        self.total = 0  # tev: guarded-by=_lock
        self.counts: Dict[str, int] = {}  # tev: guarded-by=_lock

    def append(self, event: Event) -> None:
        with self._lock:
            self._buf.append(event)
            self.total += 1
            self.counts[event.kind] = self.counts.get(event.kind, 0) + 1

    @property
    def dropped(self) -> int:
        """Events evicted by the ring bound (``total`` minus retained)."""
        with self._lock:
            return self.total - len(self._buf)

    def tail(self, n: Optional[int] = None) -> List[Event]:
        """The newest ``n`` events, oldest-first (all retained if None)."""
        with self._lock:
            events = list(self._buf)
        return events if n is None else events[-n:]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.total = 0
            self.counts = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def __iter__(self) -> Iterator[Event]:
        return iter(self.tail())


class _Span:
    """Context manager timing one named phase.

    Enters a ``jax.profiler.TraceAnnotation`` so the phase shows up in
    XLA traces (TensorBoard/Perfetto), opens a causal-tracing frame
    (``obs/trace.py`` — nested spans and events recorded inside parent
    to this one), and records a
    :class:`~torcheval_tpu.obs.events.SpanEvent` with the measured wall
    duration on exit.
    """

    def __init__(self, recorder: "Recorder", name: str) -> None:
        self._recorder = recorder
        self.name = name
        self.seconds = 0.0
        self._t0 = 0.0
        self._annotation = None
        self._scope = _trace.Scope(name)
        self.frame = None

    def __enter__(self) -> "_Span":
        import jax

        self._annotation = jax.profiler.TraceAnnotation(self.name)
        self._annotation.__enter__()
        self.frame = self._scope.__enter__()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc_info) -> None:
        self.seconds = time.monotonic() - self._t0
        try:
            self._scope.__exit__(*exc_info)
            self._annotation.__exit__(*exc_info)
        finally:
            frame = self.frame
            self._recorder.record(
                SpanEvent(
                    name=self.name,
                    seconds=self.seconds,
                    trace=frame.trace_id if frame else None,
                    span=frame.span_id if frame else None,
                    parent=frame.parent_id if frame else None,
                )
            )


class Recorder:
    """Process-global event sink (module singleton :data:`RECORDER`).

    ``enabled`` is a plain attribute, not a property: the instrumented hot
    paths read it on every call, and when False that read is the ENTIRE
    observability cost. All other state (log, step cursor, JSONL writer)
    only matters while enabled.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.enabled: bool = False
        self.log = EventLog(capacity)
        self.step_cursor: Optional[int] = None
        self._writer = None  # export.JsonlWriter
        self._compile_sink_installed = False

    # ----------------------------------------------------------- lifecycle

    def enable(
        self,
        *,
        jsonl: Optional[str] = None,
        capacity: Optional[int] = None,
    ) -> "Recorder":
        """Turn recording on (idempotent).

        Args:
            jsonl: optional path — attach an async JSONL writer; every
                recorded event is appended as one JSON line (closed and
                drained by :meth:`disable`).
            capacity: optional new ring-buffer capacity (replaces the
                log, discarding retained events).
        """
        if capacity is not None and capacity != self.log.capacity:
            self.log = EventLog(capacity)
        if jsonl is not None:
            from torcheval_tpu.obs.export import JsonlWriter

            if self._writer is not None:
                self._writer.close()
            self._writer = JsonlWriter(jsonl)
        self._install_compile_sink()
        # the collective flight recorder (obs/flight.py) rides the same
        # switch: recording ON means the sync path's collectives leave
        # per-thread flight rings too. Source-keyed, so an armed stall
        # watchdog keeps flight data when the event recorder turns off.
        from torcheval_tpu.obs.flight import FLIGHT

        FLIGHT.enable("recorder")
        self.enabled = True
        return self

    def disable(self) -> None:
        """Turn recording off; drain and close any attached JSONL writer
        (writer errors ferried by the writer surface here). Releases the
        recorder's flight-recorder enable source (an armed watchdog's
        source, if any, keeps flight recording on)."""
        self.enabled = False
        from torcheval_tpu.obs.flight import FLIGHT

        FLIGHT.disable("recorder")
        writer, self._writer = self._writer, None
        if writer is not None:
            writer.close()

    def _install_compile_sink(self) -> None:
        """Bridge ``utils.CompileCounter``'s jax.monitoring listeners into
        :class:`~torcheval_tpu.obs.events.CompileEvent`s. Installed once;
        the sink itself checks ``enabled`` so a disabled recorder costs
        one attribute read per compile (compiles are rare and expensive)."""
        if self._compile_sink_installed:
            return
        from torcheval_tpu.obs.events import CompileEvent
        from torcheval_tpu.utils import compile_counter

        def sink(what: str, seconds: float) -> None:
            if self.enabled:
                # causal attribution: the innermost open span at compile
                # time NAMES the site that demanded the program (e.g. the
                # update wrapper's "torcheval.update/<Metric>"), and the
                # bucketed dispatch annotates its bucket length on the
                # frame — a retrace is no longer an anonymous event
                frame = _trace.current()
                self.record(
                    CompileEvent(
                        seconds=seconds,
                        cache_hit=(what == "cache_hit"),
                        site=frame.name if frame is not None else "",
                        bucket=(
                            int(frame.annotations.get("bucket", 0))
                            if frame is not None
                            else 0
                        ),
                    )
                )

        compile_counter.add_event_sink(sink)
        self._compile_sink_installed = True

    # ------------------------------------------------------------ recording

    def record(self, event: Event) -> None:
        """Stamp the timing envelope (if unset) and append to the ring;
        forward to the JSONL writer when one is attached. Host-side only:
        no device interaction, no collectives. A DISABLED recorder drops
        the event — the off-by-default contract holds at this choke point
        for every producer, including user ``span()`` phases (not just
        the instrumented sites, which also guard for speed)."""
        if not self.enabled:
            return
        if event.t_mono == 0.0:
            event.t_mono = time.monotonic()
            event.t_wall = time.time()
        if event.step is None:
            event.step = self.step_cursor
        if event.tid is None:
            event.tid = threading.get_ident()
        if event.trace is None:
            # causal stamp: a point event recorded inside an open span
            # inherits its trace and parents to it (duration events set
            # their own span/parent before recording and skip this)
            frame = _trace.current()
            if frame is not None:
                event.trace = frame.trace_id
                if event.span is None and event.parent is None:
                    event.parent = frame.span_id
        self.log.append(event)
        writer = self._writer
        if writer is not None:
            writer.write(event)

    def set_step(self, step: Optional[int]) -> None:
        """Advance the step cursor stamped into subsequent events.
        ``elastic.ElasticSession.step_done`` calls this automatically;
        plain loops call it themselves (docs/observability.md)."""
        self.step_cursor = None if step is None else int(step)

    def span(self, name: str) -> _Span:
        """Time one named phase: ``with RECORDER.span("eval-epoch"): ...``
        records a ``SpanEvent`` AND annotates the XLA trace
        (``jax.profiler.TraceAnnotation``), so the phase is visible both
        in the event log and in a captured device profile."""
        return _Span(self, name)

    def drain(self) -> None:
        """Block until the attached JSONL writer (if any) has flushed
        every queued event; re-raise any ferried writer error."""
        if self._writer is not None:
            self._writer.drain()

    def reset(self) -> None:
        """Clear the ring buffer and step cursor (the enabled flag and
        any attached writer are untouched)."""
        self.log.clear()
        self.step_cursor = None


RECORDER = Recorder()


def recorder() -> Recorder:
    """The process-global :class:`Recorder` singleton."""
    return RECORDER


def enable(*, jsonl: Optional[str] = None, capacity: Optional[int] = None) -> Recorder:
    """Module-level sugar for ``recorder().enable(...)``."""
    return RECORDER.enable(jsonl=jsonl, capacity=capacity)


def disable() -> None:
    """Module-level sugar for ``recorder().disable()``."""
    RECORDER.disable()


def enabled() -> bool:
    """Whether the global recorder is currently recording."""
    return RECORDER.enabled


def span(name: str) -> _Span:
    """Module-level sugar for ``recorder().span(name)``."""
    return RECORDER.span(name)


# Env knob: TORCHEVAL_TPU_OBSERVABILITY. Truthy values enable the recorder
# at import; a value ending in ".jsonl" additionally attaches a JSONL
# writer at that path. Same spelling family as the other config env knobs.
_ENV = os.environ.get("TORCHEVAL_TPU_OBSERVABILITY", "").strip()
if _ENV:
    if _ENV.endswith(".jsonl"):
        RECORDER.enable(jsonl=_ENV)
    elif _ENV.lower() in config._TRUTHY:
        RECORDER.enable()
