"""F1 score (binary / multiclass).

Parity: reference torcheval/metrics/functional/classification/f1_score.py
(multiclass :16-115 with micro/macro/weighted/None averaging and zero-class
masking :196-233; binary :16-119,120-134). Counter extraction uses
``segment_sum``; the reference's data-dependent boolean mask compaction is
replaced by equivalent where-masked arithmetic (masked-out classes contribute
0 to every sum; the macro denominator counts mask entries).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.config import debug_validation_enabled

from torcheval_tpu.metrics.functional.tensor_utils import (
    argmax_last,
    nan_safe_divide,
    valid_mask,
)
from torcheval_tpu.utils.convert import to_jax

_logger: logging.Logger = logging.getLogger(__name__)


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _f1_score_update_jit(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.ndim == 2:
        input = argmax_last(input)
    if average == "micro":
        num_tp = jnp.sum(input == target).astype(jnp.float32)
        num_label = jnp.float32(target.shape[0])
        return num_tp, num_label, num_label
    ones = jnp.ones_like(target, dtype=jnp.float32)
    num_label = jax.ops.segment_sum(ones, target, num_segments=num_classes)
    num_prediction = jax.ops.segment_sum(
        ones, input.astype(target.dtype), num_segments=num_classes
    )
    tp_mask = (input == target).astype(jnp.float32)
    num_tp = jax.ops.segment_sum(tp_mask, target, num_segments=num_classes)
    return num_tp, num_label, num_prediction


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _f1_score_update_masked(
    input: jax.Array,
    target: jax.Array,
    valid_sizes: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Mask-aware twin of ``_f1_score_update_jit`` (shape bucketing)."""
    valid = valid_mask(target.shape[0], valid_sizes[0])
    if input.ndim == 2:
        input = argmax_last(input)
    if average == "micro":
        num_tp = jnp.sum((input == target).astype(jnp.float32) * valid)
        num_label = jnp.sum(valid)
        return num_tp, num_label, num_label
    num_label = jax.ops.segment_sum(valid, target, num_segments=num_classes)
    num_prediction = jax.ops.segment_sum(
        valid, input.astype(target.dtype), num_segments=num_classes
    )
    tp_mask = (input == target).astype(jnp.float32) * valid
    num_tp = jax.ops.segment_sum(tp_mask, target, num_segments=num_classes)
    return num_tp, num_label, num_prediction


@partial(jax.jit, static_argnames=("average",))
def _f1_score_compute_jit(
    num_tp: jax.Array,
    num_label: jax.Array,
    num_prediction: jax.Array,
    average: Optional[str],
) -> jax.Array:
    precision = nan_safe_divide(num_tp, num_prediction)
    recall = nan_safe_divide(num_tp, num_label)
    f1 = 2 * precision * recall / (precision + recall)
    f1 = jnp.nan_to_num(f1)
    if average == "micro":
        return f1
    if average == "macro":
        mask = (num_label != 0) | (num_prediction != 0)
        return jnp.sum(jnp.where(mask, f1, 0.0)) / jnp.maximum(jnp.sum(mask), 1)
    if average == "weighted":
        return jnp.sum(f1 * (num_label / jnp.sum(num_label)))
    return f1


def _f1_score_param_check(num_classes: Optional[int], average: Optional[str]) -> None:
    average_options = ("micro", "macro", "weighted", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"num_classes should be a positive number when average={average}, "
            f"got num_classes={num_classes}."
        )


def _f1_score_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or "
            f"(num_sample, num_classes), got {input.shape}."
        )


def _f1_score_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _f1_score_update_input_check(input, target, num_classes)
    return _f1_score_update_jit(input, target, num_classes, average)


def _f1_score_compute(
    num_tp: jax.Array,
    num_label: jax.Array,
    num_prediction: jax.Array,
    average: Optional[str],
) -> jax.Array:
    if average != "micro" and debug_validation_enabled() and bool(jnp.any(num_label == 0)):
        _logger.warning(
            "Warning: Some classes do not exist in the target. F1 scores for "
            "these classes will be cast to zeros."
        )
    return _f1_score_compute_jit(num_tp, num_label, num_prediction, average)


def multiclass_f1_score(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "micro",
) -> jax.Array:
    """Compute F1 score for multiclass classification.

    Class version: ``torcheval_tpu.metrics.MulticlassF1Score``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import multiclass_f1_score
        >>> multiclass_f1_score(jnp.array([0, 2, 1, 3]), jnp.array([0, 1, 2, 3]))
        Array(0.5, dtype=float32)
    """
    input, target = to_jax(input), to_jax(target)
    _f1_score_param_check(num_classes, average)
    num_tp, num_label, num_prediction = _f1_score_update(
        input, target, num_classes, average
    )
    return _f1_score_compute(num_tp, num_label, num_prediction, average)


@partial(jax.jit, static_argnames=("threshold",))
def _binary_f1_score_update_jit(
    input: jax.Array, target: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    num_tp = jnp.sum(pred * target).astype(jnp.float32)
    num_label = jnp.sum(target).astype(jnp.float32)
    num_prediction = jnp.sum(pred).astype(jnp.float32)
    return num_tp, num_label, num_prediction


@partial(jax.jit, static_argnames=("threshold",))
def _binary_f1_score_update_masked(
    input: jax.Array, target: jax.Array, valid_sizes: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    valid = valid_mask(target.shape[0], valid_sizes[0])
    pred = jnp.where(input < threshold, 0, 1) * valid
    num_tp = jnp.sum(pred * target).astype(jnp.float32)
    num_label = jnp.sum(target * valid).astype(jnp.float32)
    num_prediction = jnp.sum(pred).astype(jnp.float32)
    return num_tp, num_label, num_prediction


def _binary_f1_score_update_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.ndim != 1:
        raise ValueError(
            "input should be a one-dimensional tensor for binary f1 score, "
            f"got shape {input.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            "target should be a one-dimensional tensor for binary f1 score, "
            f"got shape {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )


def _binary_f1_score_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _binary_f1_score_update_input_check(input, target)
    return _binary_f1_score_update_jit(input, target, float(threshold))


def binary_f1_score(input, target, *, threshold: float = 0.5) -> jax.Array:
    """Compute binary F1 score (harmonic mean of precision and recall).

    Class version: ``torcheval_tpu.metrics.BinaryF1Score``.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics.functional import binary_f1_score
        >>> binary_f1_score(jnp.array([0.2, 0.8, 0.6, 0.3]), jnp.array([0, 1, 1, 0]))
        Array(1., dtype=float32)
    """
    input, target = to_jax(input), to_jax(target)
    num_tp, num_label, num_prediction = _binary_f1_score_update(
        input, target, threshold
    )
    return _f1_score_compute_jit(num_tp, num_label, num_prediction, "micro")
