"""StreamingBinaryAUPRC: mergeable histogram-state average precision.

The AUPRC sibling of StreamingBinaryAUROC — same state and update plan
(those legs are exercised by its own MetricClassTester harness here),
with the compute reduction checked against the exact sort-based
BinaryAUPRC and sklearn, including the tie and degenerate-class edges
the descending-Riemann formulation must reproduce.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as skm

from torcheval_tpu.metrics import BinaryAUPRC, StreamingBinaryAUPRC
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
)

RNG = np.random.default_rng(29)
N_UP, BATCH = 8, 64


class TestStreamingBinaryAUPRC(MetricClassTester):
    def test_class_harness(self):
        inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [
            RNG.integers(0, 2, BATCH).astype(np.float32) for _ in range(N_UP)
        ]
        exact = BinaryAUPRC()
        exact.update(
            jnp.asarray(np.concatenate(inputs)),
            jnp.asarray(np.concatenate(targets)),
        )
        self.run_class_implementation_tests(
            metric=StreamingBinaryAUPRC(num_bins=4096),
            state_names={"hist"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=np.float32(float(exact.compute())),
            atol=1e-3,  # bin-resolution error bound
            rtol=1e-3,
        )

    def test_matches_exact_auprc_within_bin_error(self):
        x = RNG.uniform(size=5000).astype(np.float32)
        t = (RNG.random(5000) < 0.3).astype(np.float32)
        exact = BinaryAUPRC()
        exact.update(jnp.asarray(x), jnp.asarray(t))
        stream = StreamingBinaryAUPRC(num_bins=8192)
        stream.update(jnp.asarray(x), jnp.asarray(t))
        np.testing.assert_allclose(
            float(stream.compute()), float(exact.compute()), atol=2e-3
        )

    def test_grid_aligned_scores_are_exact(self):
        # scores on bin centers -> zero binning error vs the exact kernel
        x = (RNG.integers(0, 16, size=400).astype(np.float32) + 0.5) / 16.0
        t = (RNG.random(400) < 0.5).astype(np.float32)
        stream = StreamingBinaryAUPRC(num_bins=16)
        stream.update(jnp.asarray(x), jnp.asarray(t))
        exact = BinaryAUPRC()
        exact.update(jnp.asarray(x), jnp.asarray(t))
        np.testing.assert_allclose(
            float(stream.compute()), float(exact.compute()), rtol=1e-5
        )

    def test_tie_and_degenerate_edges_match_exact_kernel(self):
        # one tie group: precision at the group, like the exact compaction
        m = StreamingBinaryAUPRC(num_bins=8)
        m.update(jnp.asarray([0.5, 0.5, 0.5, 0.5]),
                 jnp.asarray([1.0, 0.0, 1.0, 0.0]))
        assert float(m.compute()) == pytest.approx(0.5)
        # no positives -> 0; all positives -> 1 (exact-kernel semantics)
        neg = StreamingBinaryAUPRC()
        neg.update(jnp.asarray([0.2, 0.7]), jnp.asarray([0.0, 0.0]))
        assert float(neg.compute()) == 0.0
        pos = StreamingBinaryAUPRC()
        pos.update(jnp.asarray([0.2, 0.7]), jnp.asarray([1.0, 1.0]))
        assert float(pos.compute()) == pytest.approx(1.0)

    def test_weighted_and_multitask(self):
        x = RNG.uniform(size=(3, 512)).astype(np.float32)
        t = (RNG.random((3, 512)) < 0.5).astype(np.float32)
        w = RNG.uniform(0.5, 2.0, size=(3, 512)).astype(np.float32)
        m = StreamingBinaryAUPRC(num_tasks=3, num_bins=8192)
        m.update(jnp.asarray(x), jnp.asarray(t), jnp.asarray(w))
        got = np.asarray(m.compute())
        assert got.shape == (3,)
        for i in range(3):
            np.testing.assert_allclose(
                got[i],
                skm.average_precision_score(t[i], x[i], sample_weight=w[i]),
                atol=3e-3,
            )

    def test_merge_equals_pooled_and_rejects_mismatched_bounds(self):
        xs = [RNG.uniform(size=200).astype(np.float32) for _ in range(3)]
        ts = [(RNG.random(200) < 0.4).astype(np.float32) for _ in range(3)]
        parts = []
        for x, t in zip(xs, ts):
            m = StreamingBinaryAUPRC(num_bins=1024)
            m.update(jnp.asarray(x), jnp.asarray(t))
            parts.append(m)
        parts[0].merge_state(parts[1:])
        pooled = StreamingBinaryAUPRC(num_bins=1024)
        pooled.update(
            jnp.asarray(np.concatenate(xs)), jnp.asarray(np.concatenate(ts))
        )
        np.testing.assert_allclose(
            float(parts[0].compute()), float(pooled.compute()), rtol=1e-6
        )
        other = StreamingBinaryAUPRC(num_bins=1024, bounds=(-1.0, 1.0))
        with pytest.raises(ValueError, match="different.*bounds"):
            parts[0].merge_state([other])
