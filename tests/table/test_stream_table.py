"""StreamTable (ISSUE 20 tentpole): decode-step quality keyed by
request id. Pins the acceptance contracts: per-key values bitwise equal
to the standalone streaming-metric oracles fed the same per-request
streams, ZERO fresh programs on a warmed table across ragged (batch,
active-set) shapes, finish/drain retirement into distribution sketches,
ThreadWorld-4 adopt parity under per-request rank affinity, mid-stream
state round trips and a 2->4 elastic world change, admission shedding
that never drops retirement finals, and watch_inputs drift sketches on
the logprob stream."""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from torcheval_tpu.elastic import ElasticSession
from torcheval_tpu.metrics import ShardContext
from torcheval_tpu.metrics.toolkit import adopt_synced, clone_metric
from torcheval_tpu.streaming import (
    StreamingNgramOverlap,
    StreamingPerplexity,
    StreamingTokenAccuracy,
    StreamingTokenEditStats,
)
from torcheval_tpu.table import (
    AdmissionController,
    MetricTable,
    ServingBudget,
    StreamTable,
    TablePanel,
    stream_logprob_family,
)
from torcheval_tpu.utils.compile_counter import CompileCounter
from torcheval_tpu.utils.test_utils import ThreadWorld

ALL_MEMBERS = ("logprob", "token_edit", "token_accuracy", "ngram")


def _streams(n_requests=6, seed=3, max_len=14):
    """Per-request (logprobs, hyp, ref) token streams, ragged lengths."""
    rng = np.random.default_rng(seed)
    out = {}
    for rid in range(n_requests):
        n = int(rng.integers(4, max_len))
        hyp = rng.integers(0, 25, n).astype(np.int32)
        ref = np.where(
            rng.uniform(size=n) < 0.6, hyp, rng.integers(0, 25, n)
        ).astype(np.int32)
        lp = (-rng.uniform(0.05, 3.0, n)).astype(np.float32)
        out[rid] = (lp, hyp, ref)
    return out


def _drive(table, streams):
    """Interleaved decode: at step s every still-active request
    contributes ONE row — ragged active sets, one row per request per
    batch (the decode regime)."""
    horizon = max(len(lp) for lp, _, _ in streams.values())
    for s in range(horizon):
        ids = [r for r, (lp, _, _) in streams.items() if s < len(lp)]
        if not ids:
            continue
        table.ingest(
            np.asarray(ids),
            step_tokens=np.asarray(
                [streams[r][1][s] for r in ids], np.int32
            ),
            logprobs=np.asarray([streams[r][0][s] for r in ids], np.float32),
            ref_tokens=np.asarray(
                [streams[r][2][s] for r in ids], np.int32
            ),
        )
    return table


def test_keyed_values_match_standalone_oracles_bitwise():
    streams = _streams()
    t = _drive(StreamTable(members=ALL_MEMBERS, n_gram=3), streams)
    t.finish(np.asarray(sorted(streams)))
    got = t.compute().as_dict()
    for rid, (lp, hyp, ref) in streams.items():
        ppl = StreamingPerplexity()
        edit = StreamingTokenEditStats()
        acc = StreamingTokenAccuracy()
        ngram = StreamingNgramOverlap(n_gram=3)
        for s in range(len(lp)):
            ppl.update(lp[s : s + 1])
            edit.update(hyp[s : s + 1], ref[s : s + 1])
            acc.update(hyp[s : s + 1], ref[s : s + 1])
            ngram.update(hyp[s : s + 1], ref[s : s + 1])
        ngram.finish()
        assert got["logprob"][rid] == float(ppl.compute()), rid
        assert got["token_edit"][rid] == float(edit.compute().error_rate)
        assert got["token_accuracy"][rid] == float(acc.compute())
        assert got["ngram"][rid] == float(ngram.compute().overlap), rid


def test_single_family_table_equals_panel_member_bitwise():
    """``MetricTable("stream_logprob")`` (registered family) and the
    StreamTable member ride the SAME row kernel — one-intake fusion
    changes no math."""
    streams = _streams(seed=9)
    panel = _drive(StreamTable(members=("logprob",)), streams)
    single = MetricTable("stream_logprob")
    horizon = max(len(lp) for lp, _, _ in streams.values())
    for s in range(horizon):
        ids = [r for r, (lp, _, _) in streams.items() if s < len(lp)]
        single.ingest(
            np.asarray(ids),
            np.asarray([streams[r][0][s] for r in ids], np.float32),
        )
    assert (
        panel.compute().as_dict()["logprob"] == single.compute().as_dict()
    )


def test_warmed_table_is_retrace_proof_across_ragged_active_sets():
    """THE acceptance pin: a warmed StreamTable processes fresh (batch,
    active-set) shapes — including the finish commit and the empty
    decode tail — with zero new compiled programs."""
    keyspace = 400
    t = StreamTable(members=ALL_MEMBERS, n_gram=4)
    rng = np.random.default_rng(0)

    def feed(rng, sizes):
        for n in sizes:
            ids = rng.integers(0, keyspace, n)
            t.ingest(
                ids,
                step_tokens=rng.integers(0, 50, n).astype(np.int32),
                logprobs=(-rng.uniform(0.01, 3.0, n)).astype(np.float32),
                ref_tokens=rng.integers(0, 50, n).astype(np.int32),
            )
            if n > 8:
                t.finish(ids[: n // 3])

    # steady state: admit the whole keyspace, then warm the pow2 buckets
    t.ingest(
        np.arange(keyspace),
        step_tokens=np.zeros(keyspace, np.int32),
        logprobs=np.zeros(keyspace, np.float32),
        ref_tokens=np.zeros(keyspace, np.int32),
    )
    feed(np.random.default_rng(1), (64, 33, 17, 128, 5, 1, 0, 200, 96, 48, 7))
    with CompileCounter() as cc:
        feed(np.random.default_rng(2), (77, 3, 0, 250, 19, 1, 130, 42))
    assert cc.programs == 0


def test_empty_decode_batch_is_a_host_side_noop():
    t = StreamTable(members=("logprob", "token_edit"))
    t.ingest([5], step_tokens=np.array([3]), logprobs=np.array([-0.5]))
    before = t.compute().as_dict()
    with CompileCounter() as cc:
        t.ingest(
            np.zeros(0, np.int64),
            step_tokens=np.zeros(0, np.int32),
            logprobs=np.zeros(0, np.float32),
        )
        t.finish(np.zeros(0, np.int64))
    assert cc.programs == 0
    assert t.compute().as_dict() == before


def test_finish_and_drain_retire_requests_into_sketches():
    streams = _streams(n_requests=5)
    t = _drive(StreamTable(members=("logprob", "token_edit")), streams)
    assert t.active_requests == 5
    t.finish([0, 1, 2])
    assert t.active_requests == 2  # finished streams leave the mirror
    assert int(t.n_keys) == 5  # rows retire at the DRAIN, not at finish
    t._pre_adopt_commit()
    assert int(t.n_keys) == 2
    assert t.counter_source()["finished_requests_total"] == 3
    summ = t.finished_summary()
    assert int(summ["length"]["counts"].sum()) == 3
    assert int(summ["latency"]["counts"].sum()) == 3
    assert int(summ["final_logprob"]["counts"].sum()) == 3
    assert int(summ["final_token_edit"]["counts"].sum()) == 3
    # lengths landed in the right bins: each request's step count
    edges = summ["length"]["edges"]
    for rid in (0, 1, 2):
        n = len(streams[rid][0])
        b = np.searchsorted(edges, n, side="right") - 1
        assert summ["length"]["counts"][b] >= 1
    # double finish is idempotent
    t.finish([0, 1, 2])
    t._pre_adopt_commit()
    assert t.counter_source()["finished_requests_total"] == 3


def test_world4_adopt_matches_world1_under_request_affinity():
    """Decode serving pins a request to one observing rank; under that
    affinity the world-4 adopt is bitwise the world-1 run — same per-key
    float fold, same sketches (latency excluded: wall clock)."""
    batches = []
    for i in range(8):
        rng = np.random.default_rng(100 + i)
        ids = rng.integers(0, 15, 32) * 4 + (i % 4)  # observing rank i%4
        batches.append(
            (
                ids,
                rng.integers(0, 50, 32).astype(np.int32),
                (-rng.uniform(0.1, 2.0, 32)).astype(np.float32),
                rng.integers(0, 50, 32).astype(np.int32),
            )
        )
    fin = {r: np.unique(batches[r][0])[:5] for r in range(4)}

    def run_world1():
        t = StreamTable(members=("logprob", "token_edit"))
        for r in range(4):
            for i in range(r, len(batches), 4):
                k, s, lp, rr = batches[i]
                t.ingest(k, step_tokens=s, logprobs=lp, ref_tokens=rr)
            t.finish(fin[r])
        t._pre_adopt_commit()
        return t

    w1 = run_world1()
    want = w1.compute().as_dict()
    want_hist = {
        k: v["counts"].tolist()
        for k, v in w1.finished_summary().items()
        if k != "latency"
    }

    def body(g):
        t = StreamTable(
            members=("logprob", "token_edit"), shard=ShardContext(g.rank, 4)
        )
        for i in range(g.rank, len(batches), 4):
            k, s, lp, r = batches[i]
            t.ingest(k, step_tokens=s, logprobs=lp, ref_tokens=r)
        t.finish(fin[g.rank])
        merged = adopt_synced(t, g)
        return (
            merged.compute().as_dict(),
            {
                k: v["counts"].tolist()
                for k, v in merged.finished_summary().items()
                if k != "latency"
            },
        )

    results = ThreadWorld(4).run(body)
    assert all(r == results[0] for r in results)
    got, got_hist = results[0]
    assert got == want
    assert got_hist == want_hist


def test_state_round_trip_mid_stream_then_finish():
    """A snapshot taken MID-stream carries the host mirror (ngram tails,
    count planes, span clocks): finishing after the restore produces the
    same finals as finishing the original."""
    streams = _streams(seed=5)
    t = _drive(StreamTable(members=("logprob", "ngram"), n_gram=3), streams)
    sd = t.state_dict()
    fresh = StreamTable(members=("logprob", "ngram"), n_gram=3)
    fresh.load_state_dict(sd)
    assert fresh.active_requests == t.active_requests
    assert fresh.compute().as_dict() == t.compute().as_dict()
    ids = sorted(streams)
    t.finish(ids)
    fresh.finish(ids)
    assert fresh.compute().as_dict() == t.compute().as_dict()
    # clone_metric path (deepcopy of the mirror) stays independent
    c = clone_metric(t)
    c.ingest([999], step_tokens=np.array([1]), logprobs=np.array([-0.1]))
    assert 999 not in [
        k for k in t.compute().as_dict()["logprob"]
    ]


def test_elastic_world_change_2_to_4_mid_stream_bit_identical():
    """Phase 1 streams at world 2, snapshot, resume at world 4 (fresh
    processes), phase 2 streams to completion: per-key values equal the
    world-1 uninterrupted run bitwise. In-flight mirrors rehome through
    the checkpoint; affinity is per phase (id%2 then id%4)."""

    def phase_batches(phase, world):
        out = []
        for i in range(6):
            rng = np.random.default_rng(1000 * phase + i)
            ids = rng.integers(0, 12, 16) * world + (i % world)
            out.append(
                (
                    ids,
                    rng.integers(0, 40, 16).astype(np.int32),
                    (-rng.uniform(0.1, 2.0, 16)).astype(np.float32),
                    rng.integers(0, 40, 16).astype(np.int32),
                )
            )
        return out

    p1 = phase_batches(1, 2)
    p2 = phase_batches(2, 4)
    fin = np.unique(p2[0][0])[:6]

    def feed(t, batches, rank, world):
        for i in range(rank, len(batches), world):
            k, s, lp, r = batches[i]
            t.ingest(k, step_tokens=s, logprobs=lp, ref_tokens=r)

    def world1():
        t = StreamTable(members=("logprob", "ngram"), n_gram=3)
        for r in range(2):
            feed(t, p1, r, 2)
        t._pre_adopt_commit()  # the snapshot drain
        for r in range(4):
            feed(t, p2, r, 4)
        t.finish(fin)
        t._pre_adopt_commit()
        return t.compute().as_dict()

    want = world1()

    with tempfile.TemporaryDirectory() as d:

        def writer(g):
            t = StreamTable(
                members=("logprob", "ngram"),
                n_gram=3,
                shard=ShardContext(g.rank, 2),
            )
            sess = ElasticSession(t, d, process_group=g, interval=10**9)
            feed(t, p1, g.rank, 2)
            sess.snapshot()
            sess.close()

        ThreadWorld(2).run(writer)

        def resume(g):
            t = StreamTable(
                members=("logprob", "ngram"),
                n_gram=3,
                shard=ShardContext(g.rank, 4),
            )
            sess = ElasticSession(t, d, process_group=g, interval=10**9)
            assert sess.restore() is not None
            feed(t, p2, g.rank, 4)
            if g.rank == 0:
                t.finish(fin)
            merged = adopt_synced(t, g)
            sess.close()
            return merged.compute().as_dict()

        results = ThreadWorld(4).run(resume)
    assert all(r == results[0] for r in results)
    assert results[0] == want


def test_admission_sheds_decode_rows_but_never_finals():
    t = StreamTable(
        members=("logprob", "ngram"),
        admission=AdmissionController(ServingBudget(), sample_p=0.25),
    )
    t.admission_rung = 1
    rng = np.random.default_rng(2)
    n = 400
    ids = rng.integers(0, 4000, n)
    t.ingest(
        ids,
        step_tokens=rng.integers(0, 40, n).astype(np.int32),
        logprobs=(-rng.uniform(0.1, 2.0, n)).astype(np.float32),
        ref_tokens=rng.integers(0, 40, n).astype(np.int32),
    )
    shed = int(t.shed_rows_total)
    assert 0 < shed < n  # decode rows carry HT weights through the gate
    assert int(t.admitted_rows_total) + shed == n
    # retirement commits bypass the gate: every finished request's finals
    # land even at a shedding rung (finish rows are one-per-lifetime)
    done = np.unique(ids)[:50]
    t.finish(done)
    assert int(t.shed_rows_total) == shed  # unchanged by the commit
    t._pre_adopt_commit()
    assert t.counter_source()["finished_requests_total"] > 0


def test_watch_inputs_sketches_the_logprob_stream():
    """Output-distribution drift rides the generic quality watch: the
    logprob stream is positional arg 1 of the single-family ingest."""
    from torcheval_tpu.obs import quality

    t = MetricTable("stream_logprob")
    # watched indices address the fused plan's dynamic tuple: the table
    # intake rides 5 leading args (slot/key planes + epoch), so the
    # logprob stream is index 5 on an unarmed table
    watch = quality.watch_inputs(t, args=(5,), log2_bounds=(-8, 8))
    try:
        rng = np.random.default_rng(0)
        for _ in range(4):
            t.ingest(
                rng.integers(0, 9, 16),
                (-rng.uniform(0.05, 2.0, 16)).astype(np.float32),
            )
        (series,) = watch.series
        assert int(watch.sketch(series).compute().count) == 64
    finally:
        watch.close()


def test_member_validation_and_required_kwargs():
    with pytest.raises(ValueError, match="at least one member"):
        StreamTable(members=())
    with pytest.raises(ValueError, match="unknown StreamTable members"):
        StreamTable(members=("logprob", "bleu"))
    with pytest.raises(ValueError, match="duplicate"):
        StreamTable(members=("logprob", "logprob"))
    with pytest.raises(ValueError, match="power of two"):
        StreamTable(members=("ngram",), ngram_buckets=100)
    t = StreamTable(members=("logprob",))
    with pytest.raises(ValueError, match="logprobs"):
        t.ingest([1], step_tokens=np.array([2]))
    t2 = StreamTable(members=("token_edit",))
    with pytest.raises(ValueError, match="step_tokens"):
        t2.ingest([1], logprobs=np.array([-0.1]))


def test_finish_emits_span_events_when_recorder_on():
    from torcheval_tpu import obs

    r = obs.recorder()
    prev = r.enabled
    r.reset()
    r.enable()
    try:
        t = StreamTable(members=("logprob",))
        t.ingest([1, 2], logprobs=np.array([-0.5, -1.0], np.float32))
        t.finish([1, 2])
        spans = [
            e
            for e in r.log.tail()
            if getattr(e, "name", "") == "stream_request"
        ]
        assert len(spans) == 2
        assert all(e.seconds >= 0.0 for e in spans)
    finally:
        r.reset()
        if not prev:
            r.disable()


def test_stream_families_join_mixed_panels_with_windowed_members():
    """Satellite 1 payoff: a streaming family and a WINDOWED family share
    one fused panel intake (one key set, one program, one window clock)."""
    panel = TablePanel(
        [
            ("lp", stream_logprob_family()),
            ("wne", "windowed_ne", {"window": 4}),
        ]
    )
    single_lp = MetricTable("stream_logprob")
    single_ne = MetricTable("windowed_ne", window=4)
    rng = np.random.default_rng(4)
    for _ in range(3):
        keys = rng.integers(0, 10, 24)
        lp = (-rng.uniform(0.05, 2.0, 24)).astype(np.float32)
        preds = rng.uniform(0.1, 0.9, 24).astype(np.float32)
        tgt = rng.integers(0, 2, 24).astype(np.float32)
        panel.ingest(keys, lp=(lp,), wne=(preds, tgt))
        single_lp.ingest(keys, lp)
        single_ne.ingest(keys, preds, tgt)
        panel._pre_adopt_commit()
        single_lp._pre_adopt_commit()
        single_ne._pre_adopt_commit()
    got = panel.compute().as_dict()
    assert got["lp"] == single_lp.compute().as_dict()
    assert got["wne"] == single_ne.compute().as_dict()
