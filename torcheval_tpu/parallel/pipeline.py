"""Pipeline parallelism: GPipe-style microbatched staging over a mesh axis.

Layers are partitioned into S stages, one per device along a ``pp`` mesh
axis; a batch is split into M microbatches that stream through the stages.
Each tick every stage applies its layer block to the microbatch it holds,
then passes the activation one hop down the ring with ``lax.ppermute`` —
the classic (M + S - 1)-tick GPipe schedule, expressed as a ``lax.scan`` so
XLA sees one static program with no data-dependent control flow. The bubble
fraction is (S-1)/(M+S-1); communication is nearest-neighbour over ICI.

The reference has no pipeline parallelism (it is a metrics library;
SURVEY.md section 5.7) — this primitive exists so the *evaluation* stack
(flagship model forward + metric updates, see ``__graft_entry__``) can run
models too deep for one chip, the way the surrounding TPU training stack
does.

Use inside ``shard_map`` over a mesh with a pipeline axis, stage parameters
stacked on a leading axis sharded over it::

    @partial(shard_map, mesh=mesh,
             in_specs=(P("pp"), P()), out_specs=P())
    def run(stage_params, x_microbatches):
        local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        return pipeline_apply(stage_fn, local, x_microbatches,
                              axis_name="pp")
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from torcheval_tpu.utils.vma import pcast_varying, union_vary_axes


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    axis_name: str,
) -> jax.Array:
    """Stream microbatches through the pipeline stages on ``axis_name``.

    Args:
        stage_fn: ``(params, activation) -> activation`` for ONE stage;
            activation shape is preserved.
        stage_params: this device's stage parameters (already indexed out of
            the stacked pytree by the caller).
        x: ``(M, mb, ...)`` microbatched input, replicated across the axis.
        axis_name: the pipeline mesh axis.

    Returns the ``(M, mb, ...)`` pipeline output, replicated (every device
    returns the full result; the last stage's outputs are psum-broadcast).
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    num_micro = x.shape[0]
    is_last = stage == num_stages - 1

    # the scan carry must be varying over the union of the manual axes of
    # x and the stage params, not just the pipeline axis — see
    # utils/vma.py
    vary_axes = union_vary_axes(x, stage_params, axis_name=axis_name)

    def _varying(v):
        return pcast_varying(v, vary_axes)

    # ring neighbours: stage s hands its activation to s+1 (the wrap edge
    # S-1 -> 0 carries retired activations; they are never read)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(carry, t):
        arriving, outputs = carry
        # stage 0 injects microbatch t (clamped: past M it re-reads the
        # last microbatch, whose result never lands in `outputs`)
        fresh = _varying(
            lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, num_micro - 1), axis=0, keepdims=False
            )
        )
        inp = jnp.where(stage == 0, fresh, arriving)
        out = stage_fn(stage_params, inp)
        # the last stage finished microbatch t-(S-1) this tick
        done_idx = t - (num_stages - 1)
        write = is_last & (done_idx >= 0)
        cand = lax.dynamic_update_index_in_dim(
            outputs, out, jnp.clip(done_idx, 0, num_micro - 1), axis=0
        )
        outputs = jnp.where(write, cand, outputs)
        arriving = lax.ppermute(out, axis_name, perm)
        return (arriving, outputs), None

    init = (
        _varying(jnp.zeros_like(x[0])),
        _varying(jnp.zeros_like(x)),
    )
    (_, outputs), _ = lax.scan(
        tick, init, jnp.arange(num_micro + num_stages - 1)
    )
    # only the last stage holds real outputs; broadcast to every stage so
    # the caller can use out_specs=P() (replicated)
    return lax.psum(jnp.where(is_last, outputs, 0), axis_name)


def pipeline_reference(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
) -> jax.Array:
    """Unsharded oracle: apply all S stages sequentially to each microbatch.

    ``stacked_params`` leaves carry the stage axis in front (shape
    ``(S, ...)``); ``x`` is ``(M, mb, ...)`` as in :func:`pipeline_apply`.
    """
    num_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    out = x
    for s in range(num_stages):
        params_s = jax.tree_util.tree_map(lambda a: a[s], stacked_params)
        out = jax.vmap(lambda mb: stage_fn(params_s, mb))(out)
    return out
