"""Sync toolkit tests (reference tests/metrics/test_toolkit.py coverage):
DummySum metrics across 4 replicas, world-size-1 no-op, clone/reset/
to_device, classwise_converter, collection variants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torcheval_tpu.distributed import LocalReplicaGroup, SingleProcessGroup
from torcheval_tpu.metrics import MulticlassAccuracy, Throughput
from torcheval_tpu.metrics.toolkit import (
    classwise_converter,
    clone_metric,
    clone_metrics,
    get_synced_metric,
    get_synced_state_dict,
    reset_metrics,
    sync_and_compute,
    sync_and_compute_collection,
    to_device,
)
from torcheval_tpu.utils.test_utils import (
    DummySumDictStateMetric,
    DummySumListStateMetric,
    DummySumMetric,
)

CPUS = jax.devices("cpu")


def _replicas(metric_cls, n=4):
    group = LocalReplicaGroup(CPUS[:n])
    metrics = [metric_cls(device=CPUS[i]) for i in range(n)]
    return group, metrics


class TestSyncAndCompute:
    def test_tensor_state(self):
        group, ms = _replicas(DummySumMetric)
        for i, m in enumerate(ms):
            m.update(float(i + 1))
        result = sync_and_compute(ms, process_group=group)
        np.testing.assert_allclose(np.asarray(result), 10.0)
        # peers untouched, can keep updating
        np.testing.assert_allclose(np.asarray(ms[1].compute()), 2.0)

    def test_list_state_asymmetric(self):
        group, ms = _replicas(DummySumListStateMetric)
        ms[0].update(jnp.array([1.0, 2.0]))
        ms[1].update(jnp.array([3.0])).update(jnp.array([4.0, 5.0]))
        # ms[2] empty; ms[3] one update
        ms[3].update(jnp.array([10.0]))
        result = sync_and_compute(ms, process_group=group)
        np.testing.assert_allclose(np.asarray(result), 25.0)

    def test_dict_state_disjoint_keys(self):
        group, ms = _replicas(DummySumDictStateMetric)
        ms[0].update("a", 1.0)
        ms[1].update("b", 2.0)
        ms[2].update("a", 3.0).update("c", 4.0)
        result = sync_and_compute(ms, process_group=group)
        assert {k: float(v) for k, v in result.items()} == {
            "a": 4.0,
            "b": 2.0,
            "c": 4.0,
        }

    def test_int_float_states(self):
        group, ms = _replicas(Throughput)
        for i, m in enumerate(ms):
            m.update(32 * (i + 1), elapsed_time_sec=1.0 + i)
        result = sync_and_compute(ms, process_group=group)
        assert result == pytest.approx((32 + 64 + 96 + 128) / 4.0)

    def test_world_size_one_warns_and_returns_input(self, caplog):
        m = DummySumMetric().update(3.0)
        with caplog.at_level("WARNING"):
            result = sync_and_compute(m, process_group=SingleProcessGroup())
        np.testing.assert_allclose(np.asarray(result), 3.0)
        assert any("World size is 1" in r.message for r in caplog.records)

    def test_real_metric_across_replicas(self):
        group, _ = _replicas(lambda device=None: None)  # just the group
        ms = [
            MulticlassAccuracy(device=CPUS[i]) for i in range(4)
        ]
        rng = np.random.default_rng(3)
        all_inputs, all_targets = [], []
        for m in ms:
            x = rng.uniform(size=(8, 3)).astype(np.float32)
            t = rng.integers(0, 3, size=(8,))
            all_inputs.append(x)
            all_targets.append(t)
            m.update(jnp.asarray(x), jnp.asarray(t))
        result = sync_and_compute(ms, process_group=group)
        expected = np.mean(
            np.concatenate([x.argmax(1) for x in all_inputs])
            == np.concatenate(all_targets)
        )
        np.testing.assert_allclose(np.asarray(result), expected, rtol=1e-6)

    def test_replica_count_mismatch_raises(self):
        group = LocalReplicaGroup(CPUS[:4])
        with pytest.raises(ValueError, match="world_size"):
            sync_and_compute([DummySumMetric()], process_group=group)
        with pytest.raises(TypeError, match="per-replica list"):
            sync_and_compute(DummySumMetric(), process_group=group)


class TestCollections:
    def test_sync_collection(self):
        group = LocalReplicaGroup(CPUS[:2])
        colls = []
        for i in range(2):
            colls.append(
                {
                    "sum": DummySumMetric(device=CPUS[i]).update(float(i + 1)),
                    "list": DummySumListStateMetric(device=CPUS[i]).update(
                        jnp.array([float(i)])
                    ),
                }
            )
        result = sync_and_compute_collection(colls, process_group=group)
        np.testing.assert_allclose(np.asarray(result["sum"]), 3.0)
        np.testing.assert_allclose(np.asarray(result["list"]), 1.0)

    def test_synced_state_dict(self):
        group, ms = _replicas(DummySumMetric)
        for i, m in enumerate(ms):
            m.update(float(i))
        sd = get_synced_state_dict(ms, process_group=group)
        np.testing.assert_allclose(np.asarray(sd["sum"]), 6.0)


class TestHelpers:
    def test_clone_metric_independent(self):
        m = DummySumMetric().update(1.0)
        c = clone_metric(m)
        c.update(5.0)
        np.testing.assert_allclose(np.asarray(m.compute()), 1.0)
        np.testing.assert_allclose(np.asarray(c.compute()), 6.0)
        cs = clone_metrics([m, c])
        assert len(cs) == 2

    def test_reset_metrics(self):
        ms = [DummySumMetric().update(1.0), DummySumMetric().update(2.0)]
        reset_metrics(ms)
        assert all(float(m.compute()) == 0.0 for m in ms)

    def test_to_device(self):
        ms = [DummySumMetric(device=CPUS[0]).update(1.0)]
        to_device(ms, CPUS[1])
        assert ms[0].device == CPUS[1]

    def test_classwise_converter(self):
        vals = jnp.array([0.1, 0.2, 0.3])
        out = classwise_converter(vals, "acc")
        assert set(out) == {"acc_0", "acc_1", "acc_2"}
        out = classwise_converter(vals, "acc", labels=["cat", "dog", "fox"])
        assert float(out["acc_dog"]) == pytest.approx(0.2)
        with pytest.raises(ValueError, match="Number of labels"):
            classwise_converter(vals, "acc", labels=["a"])


class TestGetSyncedMetric:
    def test_merged_metric_updatable(self):
        group, ms = _replicas(DummySumMetric)
        for m in ms:
            m.update(1.0)
        merged = get_synced_metric(ms, process_group=group)
        merged.update(6.0)
        np.testing.assert_allclose(np.asarray(merged.compute()), 10.0)
